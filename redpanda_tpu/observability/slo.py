"""SLO evaluation engine: judge the pandaprobe histograms against objectives.

The probe layer (probes.py) has been collecting per-subsystem latency
histograms since PR 2, but nothing *judged* them — a BENCH number or a
/metrics scrape still needed a human to decide whether the broker was
meeting its latency contract. This module closes the loop:

* **Objectives** are declarative: ``{metric, quantile, threshold_ms,
  min_samples, budget_pct}`` — "p99 of kafka_produce_latency_us must stay
  under 250 ms, judged only once 100 samples exist, with at most 1% of
  observations allowed over the threshold". A scenario spec is a named
  list of objectives, loadable from YAML or JSON (``slo_objectives_file``
  config; ``tools/loadgen.py`` scenarios embed theirs).
* **Quantiles are bucket-interpolated**: the HdrHist buckets are
  log-spaced (≈19% worst-case relative error), so the engine linearly
  interpolates the requested rank *inside* its bucket instead of
  reporting the bucket upper bound the raw ``percentile()`` returns.
  A ``+Inf`` overflow bucket (scraped prometheus form) clamps to the
  recorded max, never extrapolates.
* **Windows** come from snapshots: ``snapshot()`` captures every
  histogram's cumulative buckets; ``evaluate(baseline=snap)`` judges only
  the observations recorded since. The admin server exposes named marks
  (``POST /v1/slo/mark`` + ``GET /v1/slo?mark=...``) so an operator — or
  the chaos suite — can bracket an incident window; ``tools/loadgen.py``
  brackets each scenario the same way.
* **Breaches carry trace exemplars**: loading a spec arms each
  objective's threshold on its histogram (probes.arm_exemplar_threshold),
  so the observations that broke the objective link straight to
  ``/v1/trace/slow`` entries by trace id.

Verdicts: ``PASS`` / ``FAIL`` / ``NO_DATA`` (fewer than ``min_samples``
observations in the window — a gate, not a failure: an idle subsystem is
not a breached one). A report passes when nothing FAILed.
"""

from __future__ import annotations

import json
import math
import threading
from dataclasses import dataclass, field

from redpanda_tpu.metrics import registry as default_registry
from redpanda_tpu.observability import probes

_INF = float("inf")


def _hdr_bucket_lower(upper: float) -> float:
    """True lower bound of the HdrHist bucket whose upper bound is
    ``upper``, or 0.0 when the bound doesn't match the HDR layout (generic
    prometheus buckets). Sparse bucket lists only carry OBSERVED bounds,
    so interpolating down to the previous observed bound systematically
    underestimates gapped/bimodal tails — the exact chaos shape; the
    layout knows where the straddling bucket really starts."""
    if not math.isfinite(upper):
        return 0.0
    u = int(upper)
    if u < 1 or u != upper:
        return 0.0
    from redpanda_tpu.utils.hdr import _bucket_of, _bucket_upper

    idx = _bucket_of(u)
    if _bucket_upper(idx) != u:
        return 0.0  # not an HdrHist bound: fall back to the observed one
    return float(_bucket_upper(idx - 1) + 1) if idx > 0 else 1.0


def _is_hdr_layout(buckets: list[tuple[float, int]]) -> bool:
    """True when EVERY finite bound matches the HdrHist layout — only then
    may interpolation trust the layout's bucket lower bounds. A foreign
    (scraped-prometheus) bucket ladder whose bounds are contiguous means
    "previous bound IS the lower bound"; trusting HDR there because one
    small integer coincides (1, 2, 3, 5... are all HDR uppers) would jump
    the interpolation past real mass. All-bounds-match makes a false
    positive require the entire foreign ladder to coincide."""
    return all(
        _hdr_bucket_lower(u) > 0.0 for u, _ in buckets if math.isfinite(u)
    )


# ---------------------------------------------------------------- quantiles
def interpolate_quantile(
    buckets: list[tuple[float, int]], count: int, q: float,
    observed_max: float | None = None,
    hdr_layout: bool | None = None,
) -> float | None:
    """Rank-interpolated quantile from cumulative buckets.

    ``buckets`` is ``[(upper_bound, cumulative_count), ...]`` ascending —
    the HdrHist / prometheus exposition shape. The target rank is placed
    linearly within its straddling bucket: between that bucket's TRUE
    lower bound (from the HDR layout, since sparse lists omit empty
    buckets and the previous observed bound may sit far below) and its
    upper. ``hdr_layout`` says whether the bounds come from our HdrHist:
    True for registry histograms (the SLO engine), False for foreign
    ladders (scraped prometheus — contiguous bounds mean "previous bound
    IS the lower"), None auto-detects (HDR only when every finite bound
    matches the layout). An infinite upper bound (the ``le="+Inf"``
    overflow bucket) clamps to ``observed_max`` when known, else to the
    last finite bound: the histogram genuinely does not know how far the
    tail goes, and inventing a number past the last bound would overstate
    it.
    """
    if count <= 0 or not buckets:
        return None
    if hdr_layout is None:
        hdr_layout = _is_hdr_layout(buckets)
    q = min(max(q, 0.0), 100.0)
    target = q / 100.0 * count
    if target <= 0:
        return 0.0
    prev_upper = 0.0
    prev_cum = 0
    for upper, cum in buckets:
        if cum >= target:
            if math.isinf(upper):
                if observed_max is not None:
                    return float(observed_max)
                return prev_upper
            lo = prev_upper
            if hdr_layout:
                lo = max(lo, _hdr_bucket_lower(upper))
            span = cum - prev_cum
            frac = (target - prev_cum) / span if span > 0 else 1.0
            return lo + (float(upper) - lo) * frac
        if not math.isinf(upper):
            prev_upper = float(upper)
        prev_cum = cum
    return prev_upper


def breach_fraction(
    buckets: list[tuple[float, int]], count: int, threshold: float,
    hdr_layout: bool | None = None,
) -> float:
    """Fraction of observations over ``threshold``, interpolated within the
    straddling bucket (same linearity assumption and ``hdr_layout``
    contract as the quantile)."""
    if count <= 0 or not buckets:
        return 0.0
    if hdr_layout is None:
        hdr_layout = _is_hdr_layout(buckets)
    prev_upper = 0.0
    prev_cum = 0
    for upper, cum in buckets:
        if math.isinf(upper) or upper >= threshold:
            if math.isinf(upper):
                below = float(prev_cum)
            else:
                lo = prev_upper
                if hdr_layout:
                    lo = max(lo, _hdr_bucket_lower(upper))
                span_v = float(upper) - lo
                frac = (threshold - lo) / span_v if span_v > 0 else 1.0
                frac = min(max(frac, 0.0), 1.0)
                below = prev_cum + (cum - prev_cum) * frac
            return max(0.0, min(1.0, (count - below) / count))
        prev_upper = float(upper)
        prev_cum = cum
    return 0.0


# ---------------------------------------------------------------- objectives
@dataclass
class Objective:
    """One latency objective over a registry histogram series."""

    name: str
    metric: str                      # histogram name, e.g. kafka_produce_latency_us
    threshold_ms: float
    quantile: float = 99.0
    min_samples: int = 1
    # allowed % of observations over threshold_ms inside the window (the
    # error budget); default = what the quantile itself implies (p99 ⇒ 1%)
    budget_pct: float | None = None
    labels: dict[str, str] = field(default_factory=dict)

    @property
    def series(self) -> str:
        from redpanda_tpu.metrics import series_key

        return series_key(self.metric, tuple(sorted(self.labels.items())))

    @property
    def effective_budget_pct(self) -> float:
        return (
            self.budget_pct
            if self.budget_pct is not None
            else 100.0 - self.quantile
        )

    @classmethod
    def from_dict(cls, d: dict) -> "Objective":
        try:
            metric = d["metric"]
            threshold_ms = float(d["threshold_ms"])
        except KeyError as e:
            raise ValueError(f"objective missing required field {e}") from e
        quantile = float(d.get("quantile", 99.0))
        if not 0.0 < quantile <= 100.0:
            raise ValueError(f"quantile must be in (0, 100], got {quantile}")
        if threshold_ms <= 0:
            raise ValueError(f"threshold_ms must be positive, got {threshold_ms}")
        return cls(
            name=d.get("name") or f"{metric}_p{quantile:g}",
            metric=metric,
            threshold_ms=threshold_ms,
            quantile=quantile,
            min_samples=int(d.get("min_samples", 1)),
            budget_pct=(
                float(d["budget_pct"]) if d.get("budget_pct") is not None else None
            ),
            labels={str(k): str(v) for k, v in (d.get("labels") or {}).items()},
        )

    def to_dict(self) -> dict:
        out = {
            "name": self.name,
            "metric": self.metric,
            "quantile": self.quantile,
            "threshold_ms": self.threshold_ms,
            "min_samples": self.min_samples,
        }
        if self.budget_pct is not None:
            out["budget_pct"] = self.budget_pct
        if self.labels:
            out["labels"] = dict(self.labels)
        return out


@dataclass
class SloSpec:
    name: str
    objectives: list[Objective]

    @classmethod
    def from_dict(cls, d: dict) -> "SloSpec":
        objs = d.get("objectives")
        if not isinstance(objs, list) or not objs:
            raise ValueError("spec needs a non-empty 'objectives' list")
        return cls(
            name=str(d.get("name", "default")),
            objectives=[Objective.from_dict(o) for o in objs],
        )

    @classmethod
    def load(cls, path: str) -> "SloSpec":
        """YAML or JSON objective file (YAML is a superset of JSON, so one
        loader serves both when pyyaml is present)."""
        with open(path) as f:
            text = f.read()
        try:
            import yaml

            data = yaml.safe_load(text)
        except ImportError:
            data = json.loads(text)
        if not isinstance(data, dict):
            raise ValueError(f"{path}: expected a mapping at top level")
        return cls.from_dict(data)

    def to_dict(self) -> dict:
        return {
            "name": self.name,
            "objectives": [o.to_dict() for o in self.objectives],
        }


# Broker-default objectives: the always-on serving-path histograms judged
# at the tracer's slow-request posture. Deliberately lenient — the broker
# defaults are a health floor, not a benchmark gate; scenarios (loadgen)
# bring their own.
DEFAULT_SPEC = SloSpec(
    name="broker_default",
    objectives=[
        Objective("produce_p99", "kafka_produce_latency_us", 500.0, 99.0, 50),
        Objective("fetch_p99", "kafka_fetch_latency_us", 1000.0, 99.0, 50),
        Objective("append_p99", "storage_append_latency_us", 250.0, 99.0, 50),
        Objective("replicate_p99", "raft_replicate_latency_us", 500.0, 99.0, 50),
        Objective("rpc_p99", "rpc_request_latency_us", 500.0, 99.0, 50),
    ],
)


# ---------------------------------------------------------------- windows
def _hist_window(h) -> dict:
    return {
        "buckets": [(float(u), int(c)) for u, c in h.hist.cumulative_buckets()],
        "count": int(h.hist.count),
        "sum": int(h.hist.sum),
        "max": int(h.hist.max),
    }


def window_delta(after: dict, before: dict | None) -> dict:
    """Observations recorded between two snapshots of ONE series. Buckets
    are cumulative and monotonically growing, so the delta is a per-bound
    subtraction (bounds only ever get added, never removed)."""
    if before is None:
        return after
    base = dict(before["buckets"])
    buckets = []
    removed = 0
    for upper, cum in after["buckets"]:
        prior = base.get(upper, 0)
        removed = max(removed, prior)
        # zero-delta bounds are KEPT: they carry the lower-bound of the
        # next bucket, which the interpolation needs (dropping them would
        # spread a delta bucket's mass down to the previous nonzero bound)
        buckets.append((upper, cum - removed))
    return {
        "buckets": buckets,
        "count": after["count"] - before["count"],
        "sum": after["sum"] - before["sum"],
        # max is high-watermark only; inside a delta window it is an upper
        # bound, honest enough for +Inf clamping
        "max": after["max"],
    }


def judge_objective(
    o: Objective,
    after: dict | None,
    before: dict | None = None,
    *,
    hdr_layout: bool = True,
) -> dict:
    """Judge ONE objective over a snapshot window — the shared core of the
    process-local SloEngine and the cluster federation plane
    (observability/federation.py), which judges the same objectives over a
    merged multi-node scrape. ``after``/``before`` are ``_hist_window``-
    shaped dicts for the objective's series (``after=None`` = metric not
    registered). Returns the report entry WITHOUT exemplars — exemplar
    attachment is a process-local concern (the federation plane has no
    in-process exemplar ring to consult)."""
    if after is None:
        return {
            **o.to_dict(),
            "status": "NO_DATA",
            "samples": 0,
            "detail": "metric not registered",
        }
    w = window_delta(after, before)
    samples = w["count"]
    threshold_us = o.threshold_ms * 1000.0
    if samples < max(1, o.min_samples):
        return {**o.to_dict(), "status": "NO_DATA", "samples": samples}
    observed_us = interpolate_quantile(
        w["buckets"], samples, o.quantile, observed_max=w.get("max"),
        hdr_layout=hdr_layout,
    )
    breach_pct = 100.0 * breach_fraction(
        w["buckets"], samples, threshold_us, hdr_layout=hdr_layout
    )
    budget = o.effective_budget_pct
    # An explicit budget_pct makes the error budget the verdict
    # (e.g. "5% of fetches may long-poll past the bar"); otherwise
    # the interpolated quantile judges the threshold directly.
    if o.budget_pct is not None:
        failed = breach_pct > budget
    else:
        failed = observed_us is not None and observed_us > threshold_us
    return {
        **o.to_dict(),
        "status": "FAIL" if failed else "PASS",
        "samples": samples,
        "observed_ms": (
            round(observed_us / 1000.0, 3) if observed_us is not None else None
        ),
        "mean_ms": round(w["sum"] / samples / 1000.0, 3),
        "max_ms": round((w.get("max") or 0) / 1000.0, 3),
        "breach_pct": round(breach_pct, 4),
        "budget_pct": budget,
    }


def build_report(spec: SloSpec, results: list[dict], window: str,
                 mark: str | None = None) -> dict:
    """The /v1/slo and SLO_r0N.json report envelope around judged
    objectives — shared by the local engine and the federation plane."""
    n_fail = sum(1 for r in results if r["status"] == "FAIL")
    return {
        "scenario": spec.name,
        "pass": n_fail == 0,
        "objectives": results,
        "failed": n_fail,
        "no_data": sum(1 for r in results if r["status"] == "NO_DATA"),
        "window": window,
        **({"mark": mark} if mark else {}),
    }


class SloEngine:
    """Evaluates the active spec over the registry, with named baseline
    marks for windowed judgments. One process-wide instance (``slo``
    below), configured from broker config at app start."""

    def __init__(self, registry=None) -> None:
        self.registry = registry if registry is not None else default_registry
        self._spec = DEFAULT_SPEC
        self._marks: dict[str, dict] = {}
        self._lock = threading.Lock()

    # ------------------------------------------------------------ config
    @property
    def spec(self) -> SloSpec:
        return self._spec

    def configure(self, spec: SloSpec, arm_exemplars: bool = True) -> None:
        self._spec = spec
        if arm_exemplars:
            self.arm_exemplars()

    def configure_from_file(self, path: str) -> None:
        self.configure(SloSpec.load(path))

    def arm_exemplars(self) -> None:
        """Arm each objective's threshold on its histogram so breaching
        observations record trace exemplars (probes.py). Histograms are
        created lazily by their subsystems; unresolved metrics are armed
        on the next evaluate()/arm call instead of erroring."""
        hists = self.registry.histograms()
        for o in self._spec.objectives:
            h = hists.get(o.series)
            if h is not None:
                probes.arm_exemplar_threshold(h, o.threshold_ms * 1000.0)

    # Bounded mark store: marks hold full bucket snapshots, and a cron'd
    # POST /v1/slo/mark with fresh names must not grow broker memory
    # forever — oldest marks fall off past this cap.
    MAX_MARKS = 32

    # ------------------------------------------------------------ marks
    def snapshot(self) -> dict[str, dict]:
        """Cumulative-bucket snapshot of every histogram series, plus a
        ``__meta__`` entry stamping when it was taken (used to scope
        breach exemplars to the window; no histogram can collide with the
        dunder name)."""
        import time as _time

        snap: dict[str, dict] = {
            k: _hist_window(h) for k, h in self.registry.histograms().items()
        }
        snap["__meta__"] = {"ts": _time.time()}
        return snap

    def set_mark(self, name: str = "default") -> int:
        snap = self.snapshot()
        with self._lock:
            self._marks.pop(name, None)  # re-set refreshes insertion order
            self._marks[name] = snap
            while len(self._marks) > self.MAX_MARKS:
                self._marks.pop(next(iter(self._marks)))
        return len(snap) - 1  # __meta__ is not a series

    def mark(self, name: str) -> dict | None:
        with self._lock:
            return self._marks.get(name)

    def marks(self) -> list[str]:
        with self._lock:
            return sorted(self._marks)

    # ------------------------------------------------------------ evaluate
    def evaluate(
        self,
        spec: SloSpec | None = None,
        baseline: dict | None = None,
        mark: str | None = None,
        exemplars: bool = True,
        arm: bool = True,
    ) -> dict:
        """Judge every objective; returns the report dict (the /v1/slo and
        SLO_r0N.json shape). ``baseline`` (a snapshot() result) or ``mark``
        (a named one) restrict the window to observations since then —
        including which breach exemplars are attached (only ones recorded
        inside the window). ``arm=False`` makes the evaluation purely
        read-only (benches judging a registry they don't own)."""
        spec = spec or self._spec
        if mark is not None and baseline is None:
            baseline = self.mark(mark)
            if baseline is None:
                raise KeyError(f"unknown slo mark {mark!r}")
        if arm:
            # re-arm lazily created histograms so late-registered series
            # still produce exemplars for their next breach
            self.arm_exemplars()
        since_ts = (baseline or {}).get("__meta__", {}).get("ts")
        current = self.snapshot()
        results = []
        for o in spec.objectives:
            # hdr_layout=True: these windows come straight from the
            # registry's HdrHists, so the layout's bucket lower bounds are
            # authoritative (no auto-detect ambiguity)
            entry = judge_objective(
                o, current.get(o.series), (baseline or {}).get(o.series),
                hdr_layout=True,
            )
            if entry["status"] == "FAIL" and exemplars:
                entry["exemplars"] = [
                    e for e in probes.exemplars_for(o.series)
                    if since_ts is None or e.get("ts", 0) >= since_ts
                ]
            results.append(entry)
        return build_report(
            spec, results,
            "since_mark" if (baseline or mark) else "process_lifetime",
            mark,
        )


# Process-wide engine over the process-wide registry, like the tracer and
# metrics singletons; app startup loads the operator's objective file.
slo = SloEngine()

__all__ = [
    "DEFAULT_SPEC",
    "Objective",
    "SloEngine",
    "SloSpec",
    "breach_fraction",
    "build_report",
    "interpolate_quantile",
    "judge_objective",
    "slo",
    "window_delta",
]
