"""pandascope metrics federation: scrape every node, merge, judge.

The SLO engine (slo.py) judges the process-local registry — which is
exactly wrong for a cluster: a produce that replicates through raft pays
latency on THREE brokers, and a scenario's offered load is capped by
whatever one process can generate and observe. This module is the
Monarch-style aggregation half of pandascope (PAPERS.md: Monarch for
multi-target metric aggregation; Dapper for the trace half in rpc/wire.py):

* **Scrape** — pull ``/metrics`` from every cluster node's admin API and
  parse the prometheus text back into registry form (histogram cumulative
  buckets + ``_sum``/``_count``, counter/gauge values).
* **Merge** — HdrHist series merge ADDITIVELY bucket-by-bucket: every
  node records into the same bucket layout, so summing per-bound deltas
  and re-accumulating is exact — ``merge(scrape(A), scrape(B))`` yields
  the same quantiles as recording every observation into one registry
  (property-tested in tests/test_federation.py). Series are keyed by
  ``metrics.series_key()``; each node's contribution is preserved under a
  ``node`` label for drill-down.
* **Judge** — the merged window feeds the same ``judge_objective`` /
  ``interpolate_quantile`` path the local engine uses (``hdr_layout=True``
  — the scraped bounds ARE our HdrHist layout), with named marks so a
  federated incident window works like a local one.

Partial scrape caveat: a stale or unreachable node degrades to a partial
merge — the report names the missing nodes and the
``federation_nodes_unreachable`` gauge counts them — never a crash, and
never a silently-complete-looking total.

Also here: cluster trace assembly — fan ``GET /v1/trace/id/<tid>`` out to
every node's admin and merge the per-node span sets into ONE trace (spans
deduped by span id, start times aligned on each tracer's wall epoch), the
backend of ``GET /v1/trace/cluster/<trace_id>`` and
``rpk debug trace --cluster``.
"""

from __future__ import annotations

import asyncio
import math
import re
import time

from redpanda_tpu.metrics import PREFIX, registry, series_key
from redpanda_tpu.observability.slo import (
    SloSpec,
    build_report,
    judge_objective,
    window_delta,
)

SCRAPE_TIMEOUT_S = 5.0
TRACE_FANOUT_TIMEOUT_S = 5.0

# Last scrape's unreachable-node count, exported so dashboards and the SLO
# harness can see a partial merge the moment it happens.
_last_unreachable = 0.0

registry.gauge(
    "federation_nodes_unreachable",
    lambda: _last_unreachable,
    "Nodes the last federated /metrics scrape could not reach "
    "(partial-merge degradation, never a silent total)",
)


# ================================================================ parsing
_SERIES_RE = re.compile(
    r"^(?P<name>[a-zA-Z_:][a-zA-Z0-9_:]*)"
    r"(?:\{(?P<labels>.*)\})?\s+(?P<value>\S+)$"
)
_LABEL_RE = re.compile(r'([a-zA-Z_][a-zA-Z0-9_]*)="((?:[^"\\]|\\.)*)"')


def _unescape(v: str) -> str:
    return v.replace("\\n", "\n").replace('\\"', '"').replace("\\\\", "\\")


def parse_prometheus(text: str, prefix: str = PREFIX + "_") -> dict[str, dict]:
    """Prometheus exposition text → registry-shaped series.

    Returns ``{series_key: entry}`` where histogram entries are
    ``{"kind": "histogram", "buckets": [(upper, cum)...], "sum", "count"}``
    (finite bounds only, ascending — the ``_hist_window`` shape) and
    scalar entries are ``{"kind": "counter"|"gauge", "value": v}``. Only
    series under ``prefix`` are kept; the prefix is stripped so keys join
    with ``registry.histograms()``/``snapshot()`` keys.
    """
    types: dict[str, str] = {}
    hists: dict[str, dict] = {}
    scalars: dict[str, dict] = {}
    for line in text.splitlines():
        line = line.strip()
        if not line:
            continue
        if line.startswith("#"):
            parts = line.split(None, 3)
            if len(parts) >= 4 and parts[1] == "TYPE":
                types[parts[2]] = parts[3].strip()
            continue
        m = _SERIES_RE.match(line)
        if m is None:
            continue
        name = m.group("name")
        if not name.startswith(prefix):
            continue
        short = name[len(prefix):]
        try:
            value = float(m.group("value"))
        except ValueError:
            continue
        labels = {
            k: _unescape(v)
            for k, v in _LABEL_RE.findall(m.group("labels") or "")
        }
        base, comp = short, None
        for suffix in ("_bucket", "_sum", "_count"):
            cand = short[: -len(suffix)] if short.endswith(suffix) else None
            if cand and types.get(f"{prefix}{cand}") == "histogram":
                base, comp = cand, suffix
                break
        if comp is not None:
            le = labels.pop("le", None)
            key = series_key(base, tuple(sorted(labels.items())))
            h = hists.setdefault(
                key, {"kind": "histogram", "raw_buckets": {}, "sum": 0,
                      "count": 0}
            )
            if comp == "_bucket":
                if le is None:
                    continue
                upper = float("inf") if le == "+Inf" else float(le)
                h["raw_buckets"][upper] = value
            elif comp == "_sum":
                h["sum"] = value
            else:
                h["count"] = value
            continue
        key = series_key(short, tuple(sorted(labels.items())))
        kind = types.get(name, "gauge")
        scalars[key] = {"kind": kind, "value": value}
    out: dict[str, dict] = {}
    for key, h in hists.items():
        finite = sorted(
            (u, int(c)) for u, c in h["raw_buckets"].items()
            if math.isfinite(u)
        )
        out[key] = {
            "kind": "histogram",
            "buckets": finite,
            "sum": int(h["sum"]),
            "count": int(h["count"]),
        }
    out.update(scalars)
    return out


# ================================================================ merging
def _bucket_deltas(buckets: list[tuple[float, int]]) -> dict[float, int]:
    """Cumulative → per-bound deltas (the additive form)."""
    deltas: dict[float, int] = {}
    prev = 0
    for upper, cum in buckets:
        deltas[upper] = deltas.get(upper, 0) + (cum - prev)
        prev = cum
    return deltas


def _hist_entry(buckets: list[tuple[float, int]], count: int, total: float) -> dict:
    """snapshot()-shaped series entry. ``max`` is the best bound the scrape
    knows: the highest finite bucket that holds mass — prometheus text
    carries no true max, and the +Inf clamp must not extrapolate past it."""
    mx = 0.0
    prev = 0
    for upper, cum in buckets:
        if cum > prev:
            mx = upper
        prev = cum
    return {
        "buckets": [(float(u), int(c)) for u, c in buckets],
        "count": int(count),
        "sum": int(total),
        "max": mx,
    }


def merge_scrapes(per_node: dict[str, dict[str, dict]]) -> dict:
    """Merge per-node parsed scrapes into ONE federated snapshot.

    Histograms merge additively bucket-by-bucket (counts, _sum, _count);
    counters sum; gauges keep per-node values only (summing a gauge like a
    deadline would be a lie). Every merged series keeps a ``nodes``
    sub-map — the preserved ``node`` label — with each node's own window,
    so a cluster-level breach can be drilled down to the node that caused
    it. The result is ``SloEngine.snapshot()``-shaped (plus ``kind``/
    ``nodes``), so ``window_delta`` and ``judge_objective`` work on it
    unchanged."""
    merged: dict[str, dict] = {}
    for node, series in sorted(per_node.items()):
        for key, s in series.items():
            if s["kind"] == "histogram":
                e = merged.setdefault(
                    key,
                    {"kind": "histogram", "_deltas": {}, "count": 0,
                     "sum": 0, "nodes": {}},
                )
                if e.get("kind") != "histogram":
                    continue  # name collision across kinds: first wins
                for upper, d in _bucket_deltas(s["buckets"]).items():
                    e["_deltas"][upper] = e["_deltas"].get(upper, 0) + d
                e["count"] += s["count"]
                e["sum"] += s["sum"]
                e["nodes"][str(node)] = _hist_entry(
                    s["buckets"], s["count"], s["sum"]
                )
            else:
                e = merged.setdefault(
                    key, {"kind": s["kind"], "value": 0.0, "nodes": {}}
                )
                if "value" not in e:
                    continue
                if s["kind"] == "counter":
                    e["value"] += s["value"]
                else:
                    e["value"] = s["value"]  # gauges: last node's, see nodes
                e["nodes"][str(node)] = s["value"]
    out: dict[str, dict] = {}
    for key, e in merged.items():
        if e.get("kind") == "histogram":
            cum = []
            seen = 0
            for upper in sorted(e["_deltas"]):
                seen += e["_deltas"][upper]
                cum.append((upper, seen))
            entry = _hist_entry(cum, e["count"], e["sum"])
            entry["kind"] = "histogram"
            entry["nodes"] = e["nodes"]
            out[key] = entry
        else:
            out[key] = e
    return out


# ================================================================ scraping
async def _fetch_json(
    base_url: str, path: str, timeout_s: float,
    headers: dict[str, str] | None = None,
):
    from redpanda_tpu.http import HttpClient

    import json as _json

    async with HttpClient(base_url, request_timeout=timeout_s) as c:
        resp = await c.request("GET", path, headers=headers)
        if resp.status != 200:
            raise RuntimeError(f"{base_url}{path} -> {resp.status}")
        return _json.loads(resp.body)


async def _fetch_text(
    base_url: str, path: str, timeout_s: float,
    headers: dict[str, str] | None = None,
) -> str:
    from redpanda_tpu.http import HttpClient

    async with HttpClient(base_url, request_timeout=timeout_s) as c:
        resp = await c.request("GET", path, headers=headers)
        if resp.status != 200:
            raise RuntimeError(f"{base_url}{path} -> {resp.status}")
        return resp.body.decode("utf-8", "replace")


async def scrape_targets(
    targets: list[tuple], timeout_s: float = SCRAPE_TIMEOUT_S,
    headers: dict[str, str] | None = None,
) -> tuple[dict[str, dict[str, dict]], list[str]]:
    """Scrape every target's ``/metrics`` concurrently.

    ``targets`` is ``[(node_id, base_url_or_None), ...]`` (``None`` = the
    node never advertised an admin port). ``headers`` carries the caller's
    peer credentials (the admin's bearer token under auth — see
    ``AdminServer._peer_headers``). Returns ``(per_node_series,
    unreachable_nodes)`` — unreachable nodes degrade the merge to partial
    instead of failing it, and move the ``federation_nodes_unreachable``
    gauge."""
    global _last_unreachable

    async def one(base):
        return parse_prometheus(
            await _fetch_text(base, "/metrics", timeout_s, headers)
        )

    results = await asyncio.gather(
        *(
            one(base) if base else _raise_unreachable()
            for _node, base in targets
        ),
        return_exceptions=True,
    )
    per_node: dict[str, dict[str, dict]] = {}
    unreachable: list[str] = []
    for (node, _base), res in zip(targets, results):
        if isinstance(res, BaseException):
            unreachable.append(str(node))
        else:
            per_node[str(node)] = res
    _last_unreachable = float(len(unreachable))
    return per_node, unreachable


async def _raise_unreachable():
    raise RuntimeError("no admin address advertised")


async def _fan_out_json(
    targets: list[tuple],
    path: str,
    timeout_s: float,
    headers: dict[str, str] | None = None,
) -> tuple[list[tuple[str, dict]], list[str]]:
    """Fetch one admin JSON path from every target concurrently — the
    shared scaffolding of every cluster-assembly fan-out (traces,
    timelines, resources). Returns ``([(node, doc), ...], unreachable)``:
    a target with no advertised admin address or a failing fetch lands in
    ``unreachable`` (partial degradation, never fatal)."""
    results = await asyncio.gather(
        *(
            _fetch_json(base, path, timeout_s, headers)
            if base else _raise_unreachable()
            for _node, base in targets
        ),
        return_exceptions=True,
    )
    docs: list[tuple[str, dict]] = []
    unreachable: list[str] = []
    for (node, _base), res in zip(targets, results):
        if isinstance(res, BaseException):
            unreachable.append(str(node))
        else:
            docs.append((str(node), res))
    return docs, unreachable


async def federated_snapshot(
    targets: list[tuple], timeout_s: float = SCRAPE_TIMEOUT_S,
    headers: dict[str, str] | None = None,
) -> dict:
    """Scrape + merge into one snapshot with a ``__meta__`` entry naming
    which nodes contributed and which were missing."""
    per_node, unreachable = await scrape_targets(targets, timeout_s, headers)
    snap = merge_scrapes(per_node)
    snap["__meta__"] = {
        "ts": time.time(),
        "nodes": sorted(per_node),
        "unreachable": unreachable,
    }
    return snap


# ================================================================ fed SLO
class FederatedSlo:
    """Judge SLO objectives over the federated scrape, with named marks —
    the cluster-wide twin of ``slo.SloEngine``. One instance per admin
    server; ``targets_fn`` supplies the current membership's admin URLs at
    call time (membership changes between calls are picked up free)."""

    MAX_MARKS = 32

    def __init__(self, targets_fn, headers_fn=None) -> None:
        self._targets_fn = targets_fn
        self._headers_fn = headers_fn
        self._marks: dict[str, dict] = {}

    async def snapshot(self) -> dict:
        headers = self._headers_fn() if self._headers_fn else None
        return await federated_snapshot(
            list(self._targets_fn()), headers=headers
        )

    async def set_mark(self, name: str = "default") -> dict:
        snap = await self.snapshot()
        self._marks.pop(name, None)
        self._marks[name] = snap
        while len(self._marks) > self.MAX_MARKS:
            self._marks.pop(next(iter(self._marks)))
        return snap["__meta__"]

    def marks(self) -> list[str]:
        return sorted(self._marks)

    async def _culprit_exemplars(
        self, culprits: set[str], since_ts: float | None
    ) -> dict[str, dict]:
        """Fetch each culprit node's breach-exemplar rings
        (``GET /v1/slo/exemplars``) once, window-filtered. Unreachable
        culprits degrade to an empty map — the breach verdict stands
        either way; the exemplars are forensics, not evidence."""
        targets = {
            str(node): base for node, base in self._targets_fn()
        }
        headers = self._headers_fn() if self._headers_fn else None

        async def one(node: str):
            base = targets.get(node)
            if not base:
                raise RuntimeError("no admin address advertised")
            return await _fetch_json(
                base, "/v1/slo/exemplars", SCRAPE_TIMEOUT_S, headers
            )

        nodes = sorted(culprits)
        results = await asyncio.gather(
            *(one(n) for n in nodes), return_exceptions=True
        )
        out: dict[str, dict] = {}
        for node, res in zip(nodes, results):
            if isinstance(res, BaseException):
                out[node] = {"unreachable": True, "exemplars": {}}
                continue
            ex = {
                series: [
                    e for e in entries
                    if since_ts is None or e.get("ts", 0) >= since_ts
                ]
                for series, entries in (res.get("exemplars") or {}).items()
            }
            out[node] = {
                "unreachable": False,
                "exemplars": {k: v for k, v in ex.items() if v},
            }
        return out

    async def evaluate(
        self,
        spec: SloSpec,
        mark: str | None = None,
        baseline: dict | None = None,
    ) -> dict:
        if mark is not None and baseline is None:
            baseline = self._marks.get(mark)
            if baseline is None:
                raise KeyError(f"unknown federated slo mark {mark!r}")
        current = await self.snapshot()
        results = []
        for o in spec.objectives:
            after = current.get(o.series)
            before = (baseline or {}).get(o.series)
            if after is not None and "buckets" not in after:
                # the objective resolved to a counter/gauge series — a
                # misconfigured spec must read NO_DATA, not crash the plane
                after, before = None, None
            # hdr_layout=True: the scraped bounds are our own HdrHist
            # layout re-parsed, not a foreign prometheus ladder
            entry = judge_objective(o, after, before, hdr_layout=True)
            if after is not None and after.get("nodes"):
                # the preserved node label: each node's own window judged
                # alongside the merged verdict, so a cluster-level breach
                # names the node that caused it
                per_node = {}
                for node, nwin in sorted(after["nodes"].items()):
                    nbefore = ((before or {}).get("nodes") or {}).get(node)
                    per_node[node] = {
                        k: v
                        for k, v in judge_objective(
                            o, nwin, nbefore, hdr_layout=True
                        ).items()
                        if k in ("status", "samples", "observed_ms",
                                 "mean_ms", "max_ms")
                    }
                entry["per_node"] = per_node
                if entry["status"] == "FAIL":
                    # name the culprit(s) on the breach's face: the nodes
                    # whose own window failed the same objective (a
                    # merged-only breach — every node individually under
                    # the bar but the cluster tail over it — names nobody
                    # and says so)
                    entry["culprit_nodes"] = [
                        n for n, v in sorted(per_node.items())
                        if v.get("status") == "FAIL"
                    ]
            results.append(entry)
        # per-node breach exemplars (carried PR 10 follow-on): ONE
        # exemplar fetch per distinct culprit node, then each FAIL entry
        # picks its own series' trace ids out of that node's rings
        since_ts = (baseline or {}).get("__meta__", {}).get("ts")
        culprits = {
            n for r in results for n in r.get("culprit_nodes", ())
        }
        if culprits:
            per_node_ex = await self._culprit_exemplars(culprits, since_ts)
            for r in results:
                if not r.get("culprit_nodes"):
                    continue
                series = series_key(
                    r["metric"],
                    tuple(sorted((r.get("labels") or {}).items())),
                )
                ex = {}
                for n in r["culprit_nodes"]:
                    doc = per_node_ex.get(n) or {}
                    entries = (doc.get("exemplars") or {}).get(series, [])
                    if entries or doc.get("unreachable"):
                        ex[n] = {
                            "unreachable": bool(doc.get("unreachable")),
                            "trace_ids": [e["trace_id"] for e in entries],
                            "exemplars": entries,
                        }
                if ex:
                    r["node_exemplars"] = ex
        meta = current.get("__meta__", {})
        report = build_report(
            spec, results,
            "since_mark" if (baseline or mark) else "scrape_lifetime", mark,
        )
        report["federation"] = {
            "nodes": meta.get("nodes", []),
            "unreachable": meta.get("unreachable", []),
            "partial": bool(meta.get("unreachable")),
            # the node-labeled series backing the drill-down, in
            # series_key() form — proof on the report's face that the
            # verdicts came from a federated scrape, not one registry
            "node_series": sorted(
                series_key(
                    o.metric,
                    tuple(sorted({**o.labels, "node": node}.items())),
                )
                for o in spec.objectives
                for node in (current.get(o.series, {}).get("nodes") or {})
            ),
        }
        return report


# ================================================================ traces
def _merge_trace_docs(trace_id: int, docs: list[dict]) -> dict:
    """Merge per-node ``/v1/trace/id`` documents into one cluster trace.

    Spans dedupe by span id (unique per node — ids are namespaced by the
    tracer's node seed; in-process clusters share one counter), and each
    node's ``start_us`` is re-anchored on its tracer's wall epoch so spans
    from different processes order correctly (same-host clock skew is
    microseconds against millisecond spans; cross-host skew degrades
    ordering, not membership)."""
    by_span: dict = {}
    epoch0 = min(
        (d.get("epoch", 0.0) for d in docs if d.get("spans")), default=0.0
    )
    for d in docs:
        shift_us = int((d.get("epoch", epoch0) - epoch0) * 1e6)
        for s in d.get("spans", []):
            key = s.get("span_id")
            if key is None:
                key = (s.get("node"), s["name"], s["start_us"])
            if key in by_span:
                continue
            span = dict(s)
            span["start_us"] = s["start_us"] + shift_us
            if span.get("node") is None and d.get("node") is not None:
                span["node"] = d["node"]
            by_span[key] = span
    spans = sorted(by_span.values(), key=lambda s: s["start_us"])
    nodes = sorted({s["node"] for s in spans if s.get("node") is not None})
    if spans:
        t0 = min(s["start_us"] for s in spans)
        for s in spans:
            s["start_us"] -= t0
        wall = max(s["start_us"] + s["dur_us"] for s in spans)
    else:
        wall = 0
    return {
        "trace_id": trace_id,
        "wall_us": wall,
        "nodes": nodes,
        "spans": spans,
    }


async def assemble_cluster_trace(
    targets: list[tuple],
    trace_id: int,
    timeout_s: float = TRACE_FANOUT_TIMEOUT_S,
    headers: dict[str, str] | None = None,
) -> dict:
    """Fan ``GET /v1/trace/id/<tid>`` out to every node's admin and merge
    the surviving spans into one cluster-wide trace. Unreachable nodes are
    reported, not fatal — the trace shows what the cluster still knows."""
    docs, unreachable = await _fan_out_json(
        targets, f"/v1/trace/id/{trace_id}", timeout_s, headers
    )
    out = _merge_trace_docs(trace_id, [d for _n, d in docs])
    out["unreachable"] = unreachable
    return out


# ================================================================ timelines
async def assemble_cluster_timeline(
    targets: list[tuple],
    launches: int = 0,
    timeout_s: float = TRACE_FANOUT_TIMEOUT_S,
    headers: dict[str, str] | None = None,
) -> dict:
    """The cluster flight-recorder view: fan ``GET /v1/profile/timeline``
    out to every node's admin and merge the per-node Chrome trace events
    into ONE Perfetto-loadable document.

    Events keep their per-node ``pid`` (each node's spans already carry
    the span-level node stamp, so process tracks separate cleanly) and
    re-anchor ``ts`` on each node's tracer wall epoch exactly like
    ``assemble_cluster_trace``. In-process stacks share one recorder, so
    every fetch returns the same spans — events dedupe by span id (instant
    events by journal seq, metadata by identity key). Unreachable nodes
    are reported, never fatal."""
    docs, unreachable = await _fan_out_json(
        targets, f"/v1/profile/timeline?launches={int(launches)}",
        timeout_s, headers,
    )
    epoch0 = min(
        (d.get("epoch") or 0.0 for _n, d in docs if d.get("traceEvents")),
        default=0.0,
    )
    events: list[dict] = []
    seen: set = set()
    n_launches = 0
    for node, d in docs:
        shift_us = (d.get("epoch", epoch0) or epoch0) - epoch0
        shift_us *= 1e6
        n_launches = max(n_launches, int(d.get("launches") or 0))
        for ev in d.get("traceEvents", []):
            ph = ev.get("ph")
            if ph == "M":
                key = ("M", ev.get("pid"), ev.get("tid"), ev.get("name"),
                       str(ev.get("args")))
            elif ph == "i":
                key = ("i", (ev.get("args") or {}).get("seq"),
                       ev.get("name"), ev.get("ts"))
            elif ph == "C":
                # counter samples have no span/seq identity: pid + track
                # name + sample time IS the identity (in-process stacks
                # share one history ring, so every node's fetch returns
                # the same samples — same dedup rationale as span ids;
                # real multi-process nodes differ by pid and all survive)
                key = ("C", ev.get("pid"), ev.get("name"), ev.get("ts"))
            else:
                sid = (ev.get("args") or {}).get("span_id")
                key = (
                    ("X", sid)
                    if sid is not None
                    else ("X", ev.get("pid"), ev.get("name"), ev.get("ts"),
                          ev.get("dur"))
                )
            if key in seen:
                continue
            seen.add(key)
            ev = dict(ev)
            if "ts" in ev:
                ev["ts"] = ev["ts"] + shift_us
            ev.setdefault("args", {})
            ev["args"].setdefault("src_node", node)
            events.append(ev)
    return {
        "displayTimeUnit": "ms",
        "traceEvents": events,
        "nodes": sorted(n for n, _d in docs),
        "unreachable": unreachable,
        "partial": bool(unreachable),
        "launches": n_launches,
    }


# ================================================================ history
async def assemble_cluster_history(
    targets: list[tuple],
    series: str | None = None,
    limit: int = 0,
    timeout_s: float = SCRAPE_TIMEOUT_S,
    headers: dict[str, str] | None = None,
) -> dict:
    """The cluster trend view: fan ``GET /v1/history`` out to every
    node's admin and return the per-node window rings side by side —
    windows are NOT merged across nodes (each ring rides its own wall
    clock and cadence; a cluster question is "which node's trend broke",
    not a cluster-average that hides the culprit). Per-node EWMA state
    and breach counts ride along; unreachable nodes are reported, never
    fatal — the `rpk debug trend --federated` posture."""
    path = "/v1/history"
    q = []
    if series:
        from urllib.parse import quote

        q.append(f"series={quote(series)}")
    if limit:
        q.append(f"limit={int(limit)}")
    if q:
        path = f"{path}?{'&'.join(q)}"
    docs, unreachable = await _fan_out_json(targets, path, timeout_s, headers)
    nodes = {}
    breaches_total = 0
    for node, body in sorted(docs):
        nodes[str(node)] = body
        breaches_total += int(body.get("breaches_total") or 0)
    return {
        "federated": True,
        "nodes": nodes,
        "node_ids": sorted(n for n, _d in docs),
        "unreachable": unreachable,
        "partial": bool(unreachable),
        "breaches_total": breaches_total,
        **({"series_filter": series} if series else {}),
    }


# ================================================================ resources
_PRESSURE_RANK = {"ok": 0, "warn": 1, "critical": 2}


async def assemble_cluster_resources(
    targets: list[tuple],
    timeout_s: float = SCRAPE_TIMEOUT_S,
    headers: dict[str, str] | None = None,
) -> dict:
    """Merge every node's ``GET /v1/resources`` budget-plane view (the
    read-side half of the federated autotune follow-on, and the occupancy
    column for cluster timelines): per-account ``limit/held/peak`` bytes
    SUM across nodes; ``occupancy`` and the pressure signal report the
    WORST node (summing occupancies would hide one saturated broker
    behind two idle ones). Per-node bodies ride along for drill-down."""
    docs, unreachable = await _fan_out_json(
        targets, "/v1/resources", timeout_s, headers
    )
    nodes: dict[str, dict] = dict(docs)
    accounts: dict[str, dict] = {}
    worst_pressure = "ok"
    worst_node = None
    for node, body in sorted(nodes.items()):
        if not body.get("enabled"):
            continue
        p = str(body.get("pressure", "ok"))
        if _PRESSURE_RANK.get(p, 0) > _PRESSURE_RANK.get(worst_pressure, 0):
            worst_pressure, worst_node = p, node
        for name, acct in (body.get("accounts") or {}).items():
            a = accounts.setdefault(name, {
                "limit_bytes": 0, "held_bytes": 0, "peak_bytes": 0,
                "max_occupancy": 0.0, "max_occupancy_node": None,
                "nodes": {},
            })
            a["limit_bytes"] += int(acct.get("limit_bytes", 0))
            a["held_bytes"] += int(acct.get("held_bytes", 0))
            a["peak_bytes"] += int(acct.get("peak_bytes", 0))
            occ = float(acct.get("occupancy", 0.0))
            if occ >= a["max_occupancy"]:
                a["max_occupancy"] = occ
                a["max_occupancy_node"] = node
            a["nodes"][node] = {
                "held_bytes": acct.get("held_bytes"),
                "peak_bytes": acct.get("peak_bytes"),
                "occupancy": occ,
            }
    return {
        "federated": True,
        "enabled": any(b.get("enabled") for b in nodes.values()),
        "pressure": worst_pressure,
        "pressure_node": worst_node,
        "accounts": accounts,
        "nodes": nodes,
        "unreachable": unreachable,
        "partial": bool(unreachable),
    }


__all__ = [
    "FederatedSlo",
    "assemble_cluster_history",
    "assemble_cluster_resources",
    "assemble_cluster_timeline",
    "assemble_cluster_trace",
    "federated_snapshot",
    "merge_scrapes",
    "parse_prometheus",
    "scrape_targets",
    "window_delta",
]
