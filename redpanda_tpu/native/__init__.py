"""ctypes loader for the native runtime helpers (native/redpanda_native.cc).

Builds on demand with `make` the first time it is imported; all callers must
tolerate `lib is None` (pure numpy fallbacks exist for every entry point).
"""

from __future__ import annotations

import ctypes
import os
import subprocess

import numpy as np

_NATIVE_DIR = os.path.join(os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__)))), "native")
_SO = os.path.join(_NATIVE_DIR, "libredpanda_native.so")


def _pack_paths(paths: list[str]):
    """Paths -> (blob, offsets, lens, k) — the ONE place that defines the
    path-table layout both rp_find_multi and rp_explode_find consume."""
    k = len(paths)
    encoded = [p.encode() for p in paths]
    blob = b"".join(encoded)
    path_off = np.zeros(k, dtype=np.int32)
    path_len = np.fromiter((len(e) for e in encoded), np.int32, k)
    if k:
        np.cumsum(path_len[:-1], out=path_off[1:])
    return blob, path_off, path_len, k


def _gather_dst_cap(lens: np.ndarray, n: int) -> int:
    """Worst-case framed payload size for n records with value lengths
    `lens`: value bytes plus ≤16 bytes of varint framing per record (the
    same margin rp_frame_records has always used)."""
    return int(np.maximum(lens, 0).sum()) + 16 * n + 16


def _take_scratch(out: np.ndarray | None, cap: int) -> np.ndarray:
    """Use the caller's scratch buffer when it fits, else allocate. The
    arena path hands the SAME buffer back launch after launch; a launch
    bigger than everything before it simply allocates fresh."""
    if (
        out is not None
        and out.dtype == np.uint8
        and out.ndim == 1
        and out.nbytes >= cap
        and out.flags["C_CONTIGUOUS"]
    ):
        return out
    return np.empty(max(cap, 1), dtype=np.uint8)


def _check_gather_cols(src_arr, offsets, lens, n: int) -> None:
    """Every (offset, len) span must lie inside src — the C gather memcpys
    unchecked."""
    if n and (
        offsets.min() < 0
        or int((offsets + np.maximum(lens, 0)).max()) > src_arr.nbytes
    ):
        raise ValueError("gather (offset, len) span outside the source blob")


class _NativeLib:
    def __init__(self, dll: ctypes.CDLL):
        self._dll = dll
        dll.rp_crc32c_update.restype = ctypes.c_uint32
        dll.rp_crc32c_update.argtypes = [ctypes.c_uint32, ctypes.c_char_p, ctypes.c_size_t]
        dll.rp_crc32c.restype = ctypes.c_uint32
        dll.rp_crc32c.argtypes = [ctypes.c_char_p, ctypes.c_size_t]
        dll.rp_crc32c_many.restype = None
        dll.rp_crc32c_many.argtypes = [
            ctypes.c_void_p, ctypes.c_size_t, ctypes.c_size_t,
            ctypes.c_void_p, ctypes.c_void_p,
        ]
        dll.rp_pack_rows.restype = ctypes.c_int32
        dll.rp_pack_rows.argtypes = [
            ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p,
            ctypes.c_size_t, ctypes.c_void_p, ctypes.c_size_t,
        ]
        dll.rp_unpack_rows.restype = ctypes.c_int64
        dll.rp_unpack_rows.argtypes = [
            ctypes.c_void_p, ctypes.c_size_t, ctypes.c_void_p,
            ctypes.c_size_t, ctypes.c_void_p,
        ]
        dll.rp_parse_record_values.restype = ctypes.c_int32
        dll.rp_parse_record_values.argtypes = [
            ctypes.c_char_p, ctypes.c_size_t, ctypes.c_int32,
            ctypes.c_void_p, ctypes.c_void_p,
        ]
        dll.rp_frame_records.restype = ctypes.c_int64
        dll.rp_frame_records.argtypes = [
            ctypes.c_void_p, ctypes.c_size_t, ctypes.c_void_p, ctypes.c_void_p,
            ctypes.c_int32, ctypes.c_void_p, ctypes.c_void_p,
        ]
        # Newer symbols bind conditionally: a stale .so (make unavailable,
        # read-only checkout) must degrade to the features it HAS, not
        # disable the whole native layer.
        self.has_parse_many = hasattr(dll, "rp_parse_many")
        if self.has_parse_many:
            dll.rp_parse_many.restype = ctypes.c_int64
            dll.rp_parse_many.argtypes = [
                ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p,
                ctypes.c_void_p, ctypes.c_int32, ctypes.c_void_p,
                ctypes.c_void_p,
            ]
        self.has_explode_find = hasattr(dll, "rp_explode_find")
        if self.has_explode_find:
            dll.rp_explode_find.restype = ctypes.c_int64
            dll.rp_explode_find.argtypes = [
                ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p,
                ctypes.c_void_p, ctypes.c_int32, ctypes.c_char_p,
                ctypes.c_void_p, ctypes.c_void_p, ctypes.c_int32,
                ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p,
                ctypes.c_void_p, ctypes.c_void_p,
            ]
        # Structural-index fused parse + extraction (the pointer-table
        # crossings: payload bytes reach native code without a Python-side
        # b"".join; the joined blob is built in-crossing only when the
        # caller needs it for the zero-copy harvest). The two symbols ship
        # together; the scalar rp_explode_find stays bound as the parity
        # oracle and fallback.
        self.has_structural = hasattr(dll, "rp_explode_find2") and hasattr(
            dll, "rp_extract_cols2"
        )
        if self.has_structural:
            dll.rp_explode_find2.restype = ctypes.c_int64
            dll.rp_explode_find2.argtypes = [
                ctypes.POINTER(ctypes.c_char_p), ctypes.c_void_p,
                ctypes.c_void_p, ctypes.c_int32, ctypes.c_void_p,
                ctypes.c_char_p, ctypes.c_void_p, ctypes.c_void_p,
                ctypes.c_int32, ctypes.c_void_p, ctypes.c_void_p,
                ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p,
            ]
            dll.rp_extract_cols2.restype = None
            dll.rp_extract_cols2.argtypes = [
                ctypes.POINTER(ctypes.c_char_p), ctypes.c_void_p,
                ctypes.c_void_p, ctypes.c_int32, ctypes.c_void_p,
                ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p,
                ctypes.c_void_p, ctypes.c_int32, ctypes.c_void_p,
                ctypes.c_int32, ctypes.POINTER(ctypes.c_void_p),
                ctypes.c_int64, ctypes.c_void_p, ctypes.c_int32,
                ctypes.c_int32, ctypes.c_void_p, ctypes.c_void_p,
            ]
        self.has_project_rows = hasattr(dll, "rp_project_rows")
        if self.has_project_rows:
            dll.rp_project_rows.restype = ctypes.c_int64
            dll.rp_project_rows.argtypes = [
                ctypes.c_void_p, ctypes.c_void_p, ctypes.c_int64,
                ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p,
                ctypes.c_int32, ctypes.c_void_p, ctypes.c_int32,
                ctypes.c_int32, ctypes.c_void_p, ctypes.c_void_p,
            ]
        self.has_find_multi = hasattr(dll, "rp_find_multi")
        if self.has_find_multi:
            dll.rp_find_multi.restype = ctypes.c_int64
            dll.rp_find_multi.argtypes = [
                ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p,
                ctypes.c_int64, ctypes.c_char_p, ctypes.c_void_p,
                ctypes.c_void_p, ctypes.c_int32, ctypes.c_void_p,
                ctypes.c_void_p, ctypes.c_void_p,
            ]
            dll.rp_gather_str.restype = None
            dll.rp_gather_str.argtypes = [
                ctypes.c_void_p, ctypes.c_void_p, ctypes.c_int64,
                ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p,
                ctypes.c_int32, ctypes.c_void_p, ctypes.c_void_p,
            ]
            dll.rp_gather_num.restype = None
            dll.rp_gather_num.argtypes = [
                ctypes.c_void_p, ctypes.c_void_p, ctypes.c_int64,
                ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p,
                ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p,
            ]
        self.has_frame_many = hasattr(dll, "rp_frame_many")
        if self.has_frame_many:
            dll.rp_frame_many.restype = ctypes.c_int64
            dll.rp_frame_many.argtypes = [
                ctypes.c_void_p, ctypes.c_size_t, ctypes.c_void_p,
                ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p,
                ctypes.c_int64, ctypes.c_void_p, ctypes.c_void_p,
                ctypes.c_void_p, ctypes.c_void_p,
            ]
        self.has_frame_many_gather = hasattr(dll, "rp_frame_many_gather")
        if self.has_frame_many_gather:
            dll.rp_frame_gather.restype = ctypes.c_int64
            dll.rp_frame_gather.argtypes = [
                ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p,
                ctypes.c_void_p, ctypes.c_int64, ctypes.c_void_p,
                ctypes.c_void_p,
            ]
            dll.rp_frame_many_gather.restype = ctypes.c_int64
            dll.rp_frame_many_gather.argtypes = [
                ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p,
                ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p,
                ctypes.c_int64, ctypes.c_void_p, ctypes.c_void_p,
                ctypes.c_void_p, ctypes.c_void_p,
            ]
        dll.rp_json_find.restype = ctypes.c_int32
        dll.rp_json_find.argtypes = [
            ctypes.c_char_p, ctypes.c_int64, ctypes.c_char_p, ctypes.c_int32,
            ctypes.c_void_p, ctypes.c_void_p,
        ]
        dll.rp_extract_str.restype = ctypes.c_int64
        dll.rp_extract_str.argtypes = [
            ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p, ctypes.c_int64,
            ctypes.c_char_p, ctypes.c_int32, ctypes.c_int32,
            ctypes.c_void_p, ctypes.c_void_p,
        ]
        dll.rp_extract_num.restype = ctypes.c_int64
        dll.rp_extract_num.argtypes = [
            ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p, ctypes.c_int64,
            ctypes.c_char_p, ctypes.c_int32,
            ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p,
        ]
        dll.rp_extract_exists.restype = ctypes.c_int64
        dll.rp_extract_exists.argtypes = [
            ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p, ctypes.c_int64,
            ctypes.c_char_p, ctypes.c_int32, ctypes.c_void_p,
        ]

    def crc32c_update(self, state: int, data: bytes) -> int:
        return self._dll.rp_crc32c_update(state & 0xFFFFFFFF, data, len(data))

    def crc32c(self, data: bytes) -> int:
        return self._dll.rp_crc32c(data, len(data))

    def crc32c_many(self, rows: np.ndarray, lengths: np.ndarray) -> np.ndarray:
        rows = np.ascontiguousarray(rows, dtype=np.uint8)
        lengths = np.ascontiguousarray(lengths, dtype=np.int32)
        n, stride = rows.shape
        out = np.empty(n, dtype=np.uint32)
        self._dll.rp_crc32c_many(
            rows.ctypes.data, stride, n, lengths.ctypes.data, out.ctypes.data
        )
        return out

    def pack_rows(self, src: bytes, offsets: np.ndarray, sizes: np.ndarray, row_stride: int) -> tuple[np.ndarray, int]:
        offsets = np.ascontiguousarray(offsets, dtype=np.int64)
        sizes = np.ascontiguousarray(sizes, dtype=np.int32)
        n = len(sizes)
        dst = np.empty((n, row_stride), dtype=np.uint8)
        src_arr = np.frombuffer(src, dtype=np.uint8)
        truncated = self._dll.rp_pack_rows(
            src_arr.ctypes.data, offsets.ctypes.data, sizes.ctypes.data,
            n, dst.ctypes.data, row_stride,
        )
        return dst, truncated

    def pack_rows_into(
        self, src: bytes, offsets: np.ndarray, sizes: np.ndarray,
        dst: np.ndarray,
    ) -> int:
        """rp_pack_rows into a CALLER-provided [n, stride] row block — a
        contiguous slice of a larger staging matrix. The pointer-table
        payload staging lane packs each batch's records straight from its
        decompressed payload buffer this way, so no joined blob is ever
        built. The C loop clamps sizes to the stride and zero-fills every
        row tail (byte parity with a whole-launch pack_rows)."""
        offsets = np.ascontiguousarray(offsets, dtype=np.int64)
        sizes = np.ascontiguousarray(sizes, dtype=np.int32)
        n, stride = dst.shape
        if len(offsets) != n or len(sizes) != n:
            raise ValueError("pack_rows_into offsets/sizes/dst mismatch")
        if dst.dtype != np.uint8 or not dst.flags["C_CONTIGUOUS"]:
            raise ValueError("pack_rows_into dst must be contiguous uint8")
        src_arr = np.frombuffer(src, dtype=np.uint8)
        # bounds: the C memcpy is unchecked (sizes clamp to the stride
        # in-crossing, so the effective span is min(max(size,0), stride))
        eff = np.minimum(np.maximum(sizes, 0), stride)
        if n and (
            offsets.min() < 0
            or int((offsets + eff).max()) > src_arr.nbytes
        ):
            raise ValueError("pack span outside the source buffer")
        return self._dll.rp_pack_rows(
            src_arr.ctypes.data, offsets.ctypes.data, sizes.ctypes.data,
            n, dst.ctypes.data, stride,
        )

    def parse_record_values(self, payload: bytes, count: int) -> tuple[np.ndarray, np.ndarray]:
        """Offsets/lengths of each record's value within a batch payload."""
        val_off = np.empty(count, dtype=np.int64)
        val_len = np.empty(count, dtype=np.int32)
        parsed = self._dll.rp_parse_record_values(
            payload, len(payload), count, val_off.ctypes.data, val_len.ctypes.data
        )
        if parsed != count:
            raise ValueError(f"record framing parse failed at record {parsed}/{count}")
        return val_off, val_len

    def frame_records(self, rows: np.ndarray, lens: np.ndarray, keep: np.ndarray) -> tuple[bytes, int]:
        """Frame kept rows as a records payload; returns (payload, kept_count)."""
        rows = np.ascontiguousarray(rows, dtype=np.uint8)
        lens = np.ascontiguousarray(lens, dtype=np.int32)
        keep = np.ascontiguousarray(keep, dtype=np.uint8)
        n, stride = rows.shape
        dst = np.empty(n * (stride + 16) + 16, dtype=np.uint8)
        kept = ctypes.c_int32()
        length = self._dll.rp_frame_records(
            rows.ctypes.data, stride, lens.ctypes.data, keep.ctypes.data,
            n, dst.ctypes.data, ctypes.byref(kept),
        )
        return dst[:length].tobytes(), kept.value

    def find_multi(
        self, joined, offsets: np.ndarray, sizes: np.ndarray, paths: list[str]
    ) -> tuple[np.ndarray, np.ndarray, np.ndarray]:
        """One top-level JSON walk per record locating ALL `paths`
        (single-segment keys). Returns (types[n,k] i8, vs[n,k] i64,
        ve[n,k] i64); type 0 = missing."""
        offsets = np.ascontiguousarray(offsets, dtype=np.int64)
        sizes = np.ascontiguousarray(sizes, dtype=np.int32)
        n = len(sizes)
        blob, path_off, path_len, k = _pack_paths(paths)
        joined_arr = np.frombuffer(joined, dtype=np.uint8)
        types = np.empty((n, k), dtype=np.int8)
        vs = np.empty((n, k), dtype=np.int64)
        ve = np.empty((n, k), dtype=np.int64)
        self._dll.rp_find_multi(
            joined_arr.ctypes.data, offsets.ctypes.data, sizes.ctypes.data, n,
            blob, path_off.ctypes.data, path_len.ctypes.data, k,
            types.ctypes.data, vs.ctypes.data, ve.ctypes.data,
        )
        return types, vs, ve

    def gather_str(
        self, joined, offsets, types_col, vs_col, ve_col, w: int
    ) -> tuple[np.ndarray, np.ndarray]:
        offsets = np.ascontiguousarray(offsets, dtype=np.int64)
        types_col = np.ascontiguousarray(types_col, dtype=np.int8)
        vs_col = np.ascontiguousarray(vs_col, dtype=np.int64)
        ve_col = np.ascontiguousarray(ve_col, dtype=np.int64)
        n = len(offsets)
        joined_arr = np.frombuffer(joined, dtype=np.uint8)
        out = np.empty((n, w), dtype=np.uint8)
        vlen = np.empty(n, dtype=np.int32)
        self._dll.rp_gather_str(
            joined_arr.ctypes.data, offsets.ctypes.data, n,
            types_col.ctypes.data, vs_col.ctypes.data, ve_col.ctypes.data,
            w, out.ctypes.data, vlen.ctypes.data,
        )
        return out, vlen

    def gather_num(
        self, joined, offsets, types_col, vs_col, ve_col
    ) -> tuple[np.ndarray, np.ndarray, np.ndarray]:
        offsets = np.ascontiguousarray(offsets, dtype=np.int64)
        types_col = np.ascontiguousarray(types_col, dtype=np.int8)
        vs_col = np.ascontiguousarray(vs_col, dtype=np.int64)
        ve_col = np.ascontiguousarray(ve_col, dtype=np.int64)
        n = len(offsets)
        joined_arr = np.frombuffer(joined, dtype=np.uint8)
        f32 = np.empty(n, dtype=np.float32)
        i32 = np.empty(n, dtype=np.int32)
        flags = np.empty(n, dtype=np.uint8)
        self._dll.rp_gather_num(
            joined_arr.ctypes.data, offsets.ctypes.data, n,
            types_col.ctypes.data, vs_col.ctypes.data, ve_col.ctypes.data,
            f32.ctypes.data, i32.ctypes.data, flags.ctypes.data,
        )
        return f32, i32, flags

    def frame_many(
        self,
        rows: np.ndarray,
        lens: np.ndarray,
        keep: np.ndarray,
        starts: np.ndarray,
        ends: np.ndarray,
        out: np.ndarray | None = None,
    ) -> tuple[np.ndarray, np.ndarray, np.ndarray, np.ndarray]:
        """Frame many [start, end) record ranges in ONE crossing.

        `out` (optional, uint8 1-D) is reusable caller scratch — see
        frame_many_gather. Returns (dst, payload_off[r], payload_len[r],
        kept[r]); a range's payload is dst[off : off + len].tobytes()."""
        rows = np.ascontiguousarray(rows, dtype=np.uint8)
        lens = np.ascontiguousarray(lens, dtype=np.int32)
        keep = np.ascontiguousarray(keep, dtype=np.uint8)
        starts = np.ascontiguousarray(starts, dtype=np.int64)
        ends = np.ascontiguousarray(ends, dtype=np.int64)
        n, stride = rows.shape
        n_ranges = len(starts)
        # guard the unchecked C walk: out-of-bounds or overlapping ranges
        # must be a ValueError here, not a heap write past dst
        if len(ends) != n_ranges:
            raise ValueError("starts/ends length mismatch")
        if n_ranges and (
            (starts > ends).any()
            or starts.min() < 0
            or ends.max() > n
            or int((ends - starts).sum()) > n
        ):
            raise ValueError("frame_many ranges out of bounds or overlapping")
        dst = _take_scratch(out, n * (stride + 16) + 16)
        out_off = np.empty(n_ranges, dtype=np.int64)
        out_len = np.empty(n_ranges, dtype=np.int64)
        out_kept = np.empty(n_ranges, dtype=np.int32)
        self._dll.rp_frame_many(
            rows.ctypes.data, stride, lens.ctypes.data, keep.ctypes.data,
            starts.ctypes.data, ends.ctypes.data, n_ranges, dst.ctypes.data,
            out_off.ctypes.data, out_len.ctypes.data, out_kept.ctypes.data,
        )
        return dst, out_off, out_len, out_kept

    def frame_gather(
        self,
        src,
        offsets: np.ndarray,
        lens: np.ndarray,
        keep: np.ndarray,
        out: np.ndarray | None = None,
    ) -> tuple[bytes, int]:
        """ZERO-COPY framing of one record range: kept records frame
        straight from `src` via (offset, len) columns — no padded row
        matrix. Returns (payload, kept_count)."""
        offsets = np.ascontiguousarray(offsets, dtype=np.int64)
        lens = np.ascontiguousarray(lens, dtype=np.int32)
        keep = np.ascontiguousarray(keep, dtype=np.uint8)
        n = len(offsets)
        src_arr = np.frombuffer(src, dtype=np.uint8)
        _check_gather_cols(src_arr, offsets, lens, n)
        cap = _gather_dst_cap(lens, n)
        dst = _take_scratch(out, cap)
        kept = ctypes.c_int32()
        length = self._dll.rp_frame_gather(
            src_arr.ctypes.data, offsets.ctypes.data, lens.ctypes.data,
            keep.ctypes.data, n, dst.ctypes.data, ctypes.byref(kept),
        )
        return dst[:length].tobytes(), kept.value

    def frame_many_gather(
        self,
        src,
        offsets: np.ndarray,
        lens: np.ndarray,
        keep: np.ndarray,
        starts: np.ndarray,
        ends: np.ndarray,
        out: np.ndarray | None = None,
    ) -> tuple[np.ndarray, np.ndarray, np.ndarray, np.ndarray]:
        """Gather-frame many [start, end) record ranges in ONE crossing —
        the zero-copy twin of frame_many: records frame straight from
        `src` via per-record (offset, len) columns instead of a padded
        row matrix. `out` (optional, uint8 1-D) is a caller-owned scratch
        buffer (arena reuse across launches); it is grown-by-replacement
        when too small, never written past the returned lengths.

        Returns (dst, payload_off[r], payload_len[r], kept[r]); a range's
        payload is dst[off : off + len].tobytes()."""
        offsets = np.ascontiguousarray(offsets, dtype=np.int64)
        lens = np.ascontiguousarray(lens, dtype=np.int32)
        keep = np.ascontiguousarray(keep, dtype=np.uint8)
        starts = np.ascontiguousarray(starts, dtype=np.int64)
        ends = np.ascontiguousarray(ends, dtype=np.int64)
        n = len(offsets)
        n_ranges = len(starts)
        # same posture as frame_many: the C walk is unchecked, so malformed
        # ranges or out-of-blob (offset, len) spans must be a ValueError
        # here, not a heap read/write
        if len(ends) != n_ranges:
            raise ValueError("starts/ends length mismatch")
        if n_ranges and (
            (starts > ends).any()
            or starts.min() < 0
            or ends.max() > n
            or int((ends - starts).sum()) > n
        ):
            raise ValueError(
                "frame_many_gather ranges out of bounds or overlapping"
            )
        src_arr = np.frombuffer(src, dtype=np.uint8)
        _check_gather_cols(src_arr, offsets, lens, n)
        cap = _gather_dst_cap(lens, n)
        dst = _take_scratch(out, cap)
        out_off = np.empty(n_ranges, dtype=np.int64)
        out_len = np.empty(n_ranges, dtype=np.int64)
        out_kept = np.empty(n_ranges, dtype=np.int32)
        self._dll.rp_frame_many_gather(
            src_arr.ctypes.data, offsets.ctypes.data, lens.ctypes.data,
            keep.ctypes.data, starts.ctypes.data, ends.ctypes.data,
            n_ranges, dst.ctypes.data,
            out_off.ctypes.data, out_len.ctypes.data, out_kept.ctypes.data,
        )
        return dst, out_off, out_len, out_kept

    def parse_many(
        self,
        joined,
        payload_off: np.ndarray,
        payload_len: np.ndarray,
        counts: np.ndarray,
    ) -> tuple[np.ndarray, np.ndarray]:
        """Record value offsets/lengths for MANY batch payloads in one
        crossing; offsets are absolute into `joined`."""
        payload_off = np.ascontiguousarray(payload_off, dtype=np.int64)
        payload_len = np.ascontiguousarray(payload_len, dtype=np.int32)
        counts = np.ascontiguousarray(counts, dtype=np.int32)
        total = int(counts.sum())
        joined_arr = np.frombuffer(joined, dtype=np.uint8)
        val_off = np.empty(total, dtype=np.int64)
        val_len = np.empty(total, dtype=np.int32)
        parsed = self._dll.rp_parse_many(
            joined_arr.ctypes.data, payload_off.ctypes.data,
            payload_len.ctypes.data, counts.ctypes.data, len(counts),
            val_off.ctypes.data, val_len.ctypes.data,
        )
        if parsed != total:
            raise ValueError(f"record framing parse failed at record {parsed}/{total}")
        return val_off, val_len

    def explode_find(
        self,
        joined,
        payload_off: np.ndarray,
        payload_len: np.ndarray,
        counts: np.ndarray,
        paths: list[str],
    ):
        """FUSED explode + find: record framing parse AND the k-path JSON
        walk in one crossing and one cache-hot traversal (the engine's two
        hottest stages). Returns (val_off, val_len, types, vs, ve) with
        the same semantics as parse_many + find_multi."""
        payload_off = np.ascontiguousarray(payload_off, dtype=np.int64)
        payload_len = np.ascontiguousarray(payload_len, dtype=np.int32)
        counts = np.ascontiguousarray(counts, dtype=np.int32)
        total = int(counts.sum())
        blob, path_off, path_len, k = _pack_paths(paths)
        joined_arr = np.frombuffer(joined, dtype=np.uint8)
        val_off = np.empty(total, dtype=np.int64)
        val_len = np.empty(total, dtype=np.int32)
        types = np.empty((total, k), dtype=np.int8)
        vs = np.empty((total, k), dtype=np.int64)
        ve = np.empty((total, k), dtype=np.int64)
        parsed = self._dll.rp_explode_find(
            joined_arr.ctypes.data, payload_off.ctypes.data,
            payload_len.ctypes.data, counts.ctypes.data, len(counts),
            blob, path_off.ctypes.data, path_len.ctypes.data, k,
            val_off.ctypes.data, val_len.ctypes.data,
            types.ctypes.data, vs.ctypes.data, ve.ctypes.data,
        )
        if parsed != total:
            raise ValueError(f"record framing parse failed at record {parsed}/{total}")
        return val_off, val_len, types, vs, ve

    def explode_find_structural(
        self,
        payloads: list[bytes],
        counts: np.ndarray,
        paths: list[str],
        build_joined: bool,
    ):
        """Structural-index fused parse (rp_explode_find2): the payload
        bytes cross the boundary ONCE as a per-batch pointer table — no
        Python-side b"".join. ``build_joined=True`` additionally emits the
        concatenated blob (built in-crossing, parsed cache-hot from the
        copy) for plans whose zero-copy harvest gathers from it; False
        skips the blob entirely (projection plans never read the raw bytes
        again). Returns (joined | None, val_off, val_len, types, vs, ve);
        val_off is absolute into the (possibly virtual) concatenation,
        identical to explode_find's tables."""
        counts = np.ascontiguousarray(counts, dtype=np.int32)
        p_len = np.fromiter((len(p) for p in payloads), np.int32, len(payloads))
        total = int(counts.sum())
        blob, path_off, path_len, k = _pack_paths(paths)
        # bytes -> borrowed char*; the ctypes array retains the objects and
        # the caller holds the payloads list across the call either way
        ptrs = (ctypes.c_char_p * len(payloads))(*payloads)
        joined = (
            np.empty(max(int(p_len.sum()), 1), dtype=np.uint8)
            if build_joined
            else None
        )
        val_off = np.empty(total, dtype=np.int64)
        val_len = np.empty(total, dtype=np.int32)
        types = np.empty((total, k), dtype=np.int8)
        vs = np.empty((total, k), dtype=np.int64)
        ve = np.empty((total, k), dtype=np.int64)
        parsed = self._dll.rp_explode_find2(
            ptrs, p_len.ctypes.data, counts.ctypes.data, len(payloads),
            joined.ctypes.data if joined is not None else None,
            blob, path_off.ctypes.data, path_len.ctypes.data, k,
            val_off.ctypes.data, val_len.ctypes.data,
            types.ctypes.data, vs.ctypes.data, ve.ctypes.data,
        )
        if parsed != total:
            # includes rp_explode_find2's -1 scratch-allocation sentinel
            raise ValueError(f"record framing parse failed at record {parsed}/{total}")
        if joined is not None and int(p_len.sum()) == 0:
            joined = joined[:0]
        return joined, val_off, val_len, types, vs, ve

    def extract_cols2(
        self,
        payloads: list[bytes],
        counts: np.ndarray,
        val_off: np.ndarray,
        val_len: np.ndarray,
        types: np.ndarray,
        vs: np.ndarray,
        ve: np.ndarray,
        pred_descs: np.ndarray,
        n_pad: int,
        proj_descs: np.ndarray | None = None,
        r_out: int = 0,
    ):
        """FUSED extraction (rp_extract_cols2): every predicate column and
        (optionally) the packed projection rows gathered from the span
        tables in ONE record-major crossing, straight from the per-batch
        source buffers — replaces the per-column gather crossings, the
        separate project_rows crossing AND the numpy pad concatenations.
        pred_descs is [n, 4] int32 {kind: 0 num, 1 str, 2 exists; span
        col; w; 0}; proj_descs follows project_rows' desc layout. Returns
        (pred_arrays, proj_rows | None, proj_ok | None); pred_arrays is
        the flat list in desc order (num -> f32, i32, flags; str -> bytes
        [n_pad, w], vlen; exists -> u8) — the _bind_slots input shape."""
        counts = np.ascontiguousarray(counts, dtype=np.int32)
        p_len = np.fromiter((len(p) for p in payloads), np.int32, len(payloads))
        val_off = np.ascontiguousarray(val_off, dtype=np.int64)
        val_len = np.ascontiguousarray(val_len, dtype=np.int32)
        types = np.ascontiguousarray(types, dtype=np.int8)
        vs = np.ascontiguousarray(vs, dtype=np.int64)
        ve = np.ascontiguousarray(ve, dtype=np.int64)
        pred_descs = np.ascontiguousarray(pred_descs, dtype=np.int32)
        n, _k = types.shape
        ptrs = (ctypes.c_char_p * len(payloads))(*payloads)
        arrays: list[np.ndarray] = []
        for kind, _col, w, _ in pred_descs:
            if kind == 0:
                arrays += [
                    np.empty(n_pad, np.float32),
                    np.empty(n_pad, np.int32),
                    np.empty(n_pad, np.uint8),
                ]
            elif kind == 1:
                arrays += [
                    np.empty((n_pad, int(w)), np.uint8),
                    np.empty(n_pad, np.int32),
                ]
            else:
                arrays.append(np.empty(n_pad, np.uint8))
        pred_ptrs = (ctypes.c_void_p * max(len(arrays), 1))(
            *[a.ctypes.data for a in arrays]
        )
        if proj_descs is not None and len(proj_descs):
            proj_descs = np.ascontiguousarray(proj_descs, dtype=np.int32)
            rows = np.empty((n, r_out), dtype=np.uint8)
            ok = np.empty(n, dtype=np.bool_)
            n_proj, rows_ptr, ok_ptr = (
                len(proj_descs), rows.ctypes.data, ok.ctypes.data
            )
            proj_ptr = proj_descs.ctypes.data
        else:
            rows = ok = None
            n_proj, rows_ptr, ok_ptr, proj_ptr = 0, None, None, None
        self._dll.rp_extract_cols2(
            ptrs, p_len.ctypes.data, counts.ctypes.data, len(payloads),
            val_off.ctypes.data, val_len.ctypes.data,
            types.ctypes.data, vs.ctypes.data, ve.ctypes.data, types.shape[1],
            pred_descs.ctypes.data, len(pred_descs), pred_ptrs, n_pad,
            proj_ptr, n_proj, r_out, rows_ptr, ok_ptr,
        )
        return arrays, rows, ok

    def project_rows(
        self,
        joined,
        offsets: np.ndarray,
        types: np.ndarray,
        vs: np.ndarray,
        ve: np.ndarray,
        descs: np.ndarray,
        r_out: int,
    ) -> tuple[np.ndarray, np.ndarray]:
        """FUSED projection: every Int/Float/Str field gathered from the
        span tables straight into packed output rows, one pass per record
        (layout parity with ColumnarPlan.assemble_rows). descs is
        [n_fields, 4] int32 {kind, span col, w, out off}. Returns
        (rows [n, r_out] u8, ok [n] bool)."""
        offsets = np.ascontiguousarray(offsets, dtype=np.int64)
        types = np.ascontiguousarray(types, dtype=np.int8)
        vs = np.ascontiguousarray(vs, dtype=np.int64)
        ve = np.ascontiguousarray(ve, dtype=np.int64)
        descs = np.ascontiguousarray(descs, dtype=np.int32)
        n, k = types.shape
        joined_arr = np.frombuffer(joined, dtype=np.uint8)
        rows = np.empty((n, r_out), dtype=np.uint8)
        # the C side writes 0/1 bytes — valid numpy bool storage, no copy
        ok = np.empty(n, dtype=np.bool_)
        self._dll.rp_project_rows(
            joined_arr.ctypes.data, offsets.ctypes.data, n,
            types.ctypes.data, vs.ctypes.data, ve.ctypes.data, k,
            descs.ctypes.data, len(descs), r_out,
            rows.ctypes.data, ok.ctypes.data,
        )
        return rows, ok

    def json_find(self, value: bytes, path: str) -> tuple[int, int, int]:
        """(type, value_start, value_end) of `path` in one JSON value.

        Mirrors ops.exprs.json_find; types: 0 missing, 1 str, 2 num,
        3 true, 4 false, 5 null, 6 object, 7 array."""
        vs = ctypes.c_int64()
        ve = ctypes.c_int64()
        p = path.encode()
        t = self._dll.rp_json_find(
            value, len(value), p, len(p), ctypes.byref(vs), ctypes.byref(ve)
        )
        return t, vs.value, ve.value

    def extract_str(
        self, joined, offsets: np.ndarray, sizes: np.ndarray, path: str, w: int
    ) -> tuple[np.ndarray, np.ndarray]:
        """String field column: ([n, w] raw bytes, [n] true value length).

        vlen -1 = missing or not a string; bytes are zero-padded/truncated."""
        offsets = np.ascontiguousarray(offsets, dtype=np.int64)
        sizes = np.ascontiguousarray(sizes, dtype=np.int32)
        n = len(sizes)
        joined_arr = np.frombuffer(joined, dtype=np.uint8)
        out = np.empty((n, w), dtype=np.uint8)
        vlen = np.empty(n, dtype=np.int32)
        p = path.encode()
        self._dll.rp_extract_str(
            joined_arr.ctypes.data, offsets.ctypes.data, sizes.ctypes.data, n,
            p, len(p), w, out.ctypes.data, vlen.ctypes.data,
        )
        return out, vlen

    def extract_num(
        self, joined, offsets: np.ndarray, sizes: np.ndarray, path: str
    ) -> tuple[np.ndarray, np.ndarray, np.ndarray]:
        """Numeric field column: ([n] f32, [n] i32, [n] lattice flags u8)."""
        offsets = np.ascontiguousarray(offsets, dtype=np.int64)
        sizes = np.ascontiguousarray(sizes, dtype=np.int32)
        n = len(sizes)
        joined_arr = np.frombuffer(joined, dtype=np.uint8)
        f32 = np.empty(n, dtype=np.float32)
        i32 = np.empty(n, dtype=np.int32)
        flags = np.empty(n, dtype=np.uint8)
        p = path.encode()
        self._dll.rp_extract_num(
            joined_arr.ctypes.data, offsets.ctypes.data, sizes.ctypes.data, n,
            p, len(p), f32.ctypes.data, i32.ctypes.data, flags.ctypes.data,
        )
        return f32, i32, flags

    def extract_exists(
        self, joined, offsets: np.ndarray, sizes: np.ndarray, path: str
    ) -> np.ndarray:
        """Presence column: [n] u8, 1 when the path resolves."""
        offsets = np.ascontiguousarray(offsets, dtype=np.int64)
        sizes = np.ascontiguousarray(sizes, dtype=np.int32)
        n = len(sizes)
        joined_arr = np.frombuffer(joined, dtype=np.uint8)
        out = np.empty(n, dtype=np.uint8)
        p = path.encode()
        self._dll.rp_extract_exists(
            joined_arr.ctypes.data, offsets.ctypes.data, sizes.ctypes.data, n,
            p, len(p), out.ctypes.data,
        )
        return out

    def unpack_rows(self, rows: np.ndarray, sizes: np.ndarray) -> bytes:
        rows = np.ascontiguousarray(rows, dtype=np.uint8)
        sizes = np.ascontiguousarray(sizes, dtype=np.int32)
        n, stride = rows.shape
        total = int(np.minimum(sizes, stride).clip(0).sum())
        dst = np.empty(total, dtype=np.uint8)
        self._dll.rp_unpack_rows(rows.ctypes.data, stride, sizes.ctypes.data, n, dst.ctypes.data)
        return dst.tobytes()


def _build_and_load():
    src = os.path.join(_NATIVE_DIR, "redpanda_native.cc")
    if os.path.exists(src):
        # Let make's own dependency rule decide staleness (cheap no-op when
        # the .so is current); fall back to an existing .so if make fails.
        try:
            subprocess.run(
                ["make", "-C", _NATIVE_DIR],
                check=True,
                capture_output=True,
                timeout=120,
            )
        except Exception:
            pass
    if not os.path.exists(_SO):
        return None
    try:
        return _NativeLib(ctypes.CDLL(_SO))
    except (OSError, AttributeError):
        # AttributeError = a stale .so missing a required symbol; a raising
        # module-level import would evict the module and re-run `make` on
        # every later _native() call
        return None


lib = _build_and_load()
