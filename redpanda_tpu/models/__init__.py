from redpanda_tpu.models.fundamental import NTP, MaterializedNTP, Offset, Term, NodeId
from redpanda_tpu.models.record import (
    Record,
    RecordHeader,
    RecordBatch,
    RecordBatchHeader,
    RecordBatchType,
    Compression,
    TimestampType,
    INTERNAL_HEADER_SIZE,
)
from redpanda_tpu.models.reader import RecordBatchReader, make_memory_reader, make_generator_reader

__all__ = [
    "NTP",
    "MaterializedNTP",
    "Offset",
    "Term",
    "NodeId",
    "Record",
    "RecordHeader",
    "RecordBatch",
    "RecordBatchHeader",
    "RecordBatchType",
    "Compression",
    "TimestampType",
    "INTERNAL_HEADER_SIZE",
    "RecordBatchReader",
    "make_memory_reader",
    "make_generator_reader",
]
