"""Domain identifiers (parity with model/fundamental.h).

``NTP`` = {namespace, topic, partition} — the identity of one partitioned
log replica, used as the routing key everywhere (storage dirs, shard table,
raft groups, coproc inputs). Reference: model/fundamental.h:183.
"""

from __future__ import annotations

from dataclasses import dataclass

Offset = int
Term = int
NodeId = int

DEFAULT_NAMESPACE = "kafka"
INTERNAL_NAMESPACE = "redpanda"
COPROC_INTERNAL_TOPIC = "coprocessor_internal_topic"


@dataclass(frozen=True, order=True)
class NTP:
    ns: str
    topic: str
    partition: int

    def path(self) -> str:
        """Directory path fragment: <ns>/<topic>/<partition>."""
        return f"{self.ns}/{self.topic}/{self.partition}"

    def __str__(self) -> str:
        return f"{{{self.ns}/{self.topic}/{self.partition}}}"

    @staticmethod
    def kafka(topic: str, partition: int) -> "NTP":
        return NTP(DEFAULT_NAMESPACE, topic, partition)


@dataclass(frozen=True)
class MaterializedNTP:
    """A coproc materialized topic: `<source>.$<script>$` convention
    (parity with model::materialized_ntp)."""

    source: NTP
    script: str

    @property
    def ntp(self) -> NTP:
        return NTP(self.source.ns, f"{self.source.topic}.${self.script}$", self.source.partition)

    @staticmethod
    def parse(ntp: NTP) -> "MaterializedNTP | None":
        t = ntp.topic
        if t.endswith("$") and ".$" in t:
            src, script = t[:-1].rsplit(".$", 1)
            return MaterializedNTP(NTP(ntp.ns, src, ntp.partition), script)
        return None
