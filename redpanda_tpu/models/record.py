"""Record / RecordBatch domain model.

Capability parity with the reference's model/record.h:

- ``Record`` — Kafka v2 record: varint-framed {attrs, timestamp_delta,
  offset_delta, key, value, headers}.
- ``RecordBatchHeader`` — the 61-byte packed internal header
  (model/record.h:475-487): little-endian, leading ``header_crc`` (CRC-32C of
  the remaining 57 header bytes, model/record_utils.cc internal_header_only_crc)
  plus the Kafka ``crc`` field (CRC-32C computed per Kafka semantics: header
  fields big-endian from attributes onward, then the records payload —
  model/record_utils.cc:34-91).
- ``RecordBatch`` — header + records, encodable either in the internal
  storage layout or the Kafka wire RecordBatch v2 layout
  (kafka_batch_adapter equivalents live in redpanda_tpu.kafka.protocol.batch).

Design note (TPU-first): batches are kept as contiguous `bytes` payloads so
they can be scattered into fixed-shape device staging buffers without
re-serialization; per-record access lazily parses the payload.
"""

from __future__ import annotations

import enum
import struct
from dataclasses import dataclass, field, replace

from redpanda_tpu.hashing.crc32c import Crc32c, crc32c
from redpanda_tpu.utils.vint import decode_zigzag, encode_zigzag

INTERNAL_HEADER_SIZE = 61  # bytes; model/record.h:475-487


class RecordBatchType(enum.IntEnum):
    """Batch types multiplexed onto logs (parity with model::record_batch_type)."""

    raft_data = 1
    raft_configuration = 2
    controller = 3
    kvstore = 4
    checkpoint = 5
    topic_management_cmd = 6
    ghost_batch = 7
    id_allocator = 8
    tx_prepare = 9
    tx_fence = 10
    tm_update = 11
    user_management_cmd = 12
    acl_management_cmd = 13
    group_prepare_tx = 14
    group_commit_tx = 15
    group_abort_tx = 16
    node_management_cmd = 17
    data_policy_management_cmd = 18
    archival_metadata = 19


class Compression(enum.IntEnum):
    """Codec ids as stored in batch attributes bits 0-2 (Kafka encoding)."""

    none = 0
    gzip = 1
    snappy = 2
    lz4 = 3
    zstd = 4


class TimestampType(enum.IntEnum):
    create_time = 0
    append_time = 1


ATTR_COMPRESSION_MASK = 0x7
ATTR_TIMESTAMP_TYPE = 0x8
ATTR_TRANSACTIONAL = 0x10
ATTR_CONTROL = 0x20


@dataclass(frozen=True)
class RecordHeader:
    key: bytes
    value: bytes | None


@dataclass(frozen=True)
class Record:
    attributes: int = 0
    timestamp_delta: int = 0
    offset_delta: int = 0
    key: bytes | None = None
    value: bytes | None = None
    headers: tuple[RecordHeader, ...] = ()

    def encode(self) -> bytes:
        body = bytearray()
        body += struct.pack("b", self.attributes)
        body += encode_zigzag(self.timestamp_delta)
        body += encode_zigzag(self.offset_delta)
        if self.key is None:
            body += encode_zigzag(-1)
        else:
            body += encode_zigzag(len(self.key))
            body += self.key
        if self.value is None:
            body += encode_zigzag(-1)
        else:
            body += encode_zigzag(len(self.value))
            body += self.value
        body += encode_zigzag(len(self.headers))
        for h in self.headers:
            body += encode_zigzag(len(h.key))
            body += h.key
            if h.value is None:
                body += encode_zigzag(-1)
            else:
                body += encode_zigzag(len(h.value))
                body += h.value
        # bytes + bytearray concatenates to bytes: one copy, not three
        return encode_zigzag(len(body)) + body

    @staticmethod
    def decode(buf, offset: int = 0) -> tuple["Record", int]:
        def take(pos: int, n: int) -> bytes:
            if n < 0 or pos + n > len(buf):
                raise ValueError(f"truncated record: need {n} bytes at {pos}, have {len(buf)}")
            return bytes(buf[pos : pos + n])

        start = offset
        length, n = decode_zigzag(buf, offset)
        offset += n
        end = offset + length
        if end > len(buf):
            raise ValueError(f"truncated record: body ends at {end}, buffer has {len(buf)}")
        attributes = struct.unpack_from("b", take(offset, 1))[0]
        offset += 1
        ts_delta, n = decode_zigzag(buf, offset)
        offset += n
        off_delta, n = decode_zigzag(buf, offset)
        offset += n
        klen, n = decode_zigzag(buf, offset)
        offset += n
        key = None
        if klen >= 0:
            key = take(offset, klen)
            offset += klen
        vlen, n = decode_zigzag(buf, offset)
        offset += n
        value = None
        if vlen >= 0:
            value = take(offset, vlen)
            offset += vlen
        hcount, n = decode_zigzag(buf, offset)
        offset += n
        headers = []
        for _ in range(hcount):
            hklen, n = decode_zigzag(buf, offset)
            offset += n
            hkey = take(offset, hklen)
            offset += hklen
            hvlen, n = decode_zigzag(buf, offset)
            offset += n
            hval = None
            if hvlen >= 0:
                hval = take(offset, hvlen)
                offset += hvlen
            headers.append(RecordHeader(hkey, hval))
        if offset != end:
            raise ValueError(f"record decode mismatch: ended at {offset}, expected {end}")
        return Record(attributes, ts_delta, off_delta, key, value, tuple(headers)), offset - start


@dataclass
class RecordBatchHeader:
    header_crc: int = 0
    size_bytes: int = 0  # header + payload
    base_offset: int = 0
    type: RecordBatchType = RecordBatchType.raft_data
    crc: int = 0  # Kafka CRC-32C (attributes..records)
    attrs: int = 0
    last_offset_delta: int = 0
    first_timestamp: int = 0
    max_timestamp: int = 0
    producer_id: int = -1
    producer_epoch: int = -1
    base_sequence: int = -1
    record_count: int = 0
    # Runtime-only (not part of the 61 packed bytes; parity with
    # record_batch_header::context):
    term: int = -1

    _PACK = "<IiqbiHiqqqhii"  # 61 bytes, little-endian

    @property
    def last_offset(self) -> int:
        return self.base_offset + self.last_offset_delta

    @property
    def compression(self) -> Compression:
        return Compression(self.attrs & ATTR_COMPRESSION_MASK)

    @property
    def is_transactional(self) -> bool:
        return bool(self.attrs & ATTR_TRANSACTIONAL)

    @property
    def is_control(self) -> bool:
        return bool(self.attrs & ATTR_CONTROL)

    def internal_header_only_crc(self) -> int:
        """CRC-32C over the post-header_crc header fields, little-endian
        (model/record_utils.cc internal_header_only_crc)."""
        c = Crc32c()
        c.extend_le(
            "iqbiHiqqqhii",
            self.size_bytes,
            self.base_offset,
            int(self.type),
            _i32(self.crc),
            self.attrs,
            self.last_offset_delta,
            self.first_timestamp,
            self.max_timestamp,
            self.producer_id,
            self.producer_epoch,
            self.base_sequence,
            self.record_count,
        )
        return c.value()

    def kafka_header_crc_prefix(self) -> bytes:
        """The big-endian header-field prefix covered by the Kafka CRC
        (attributes .. record_count), per model/record_utils.cc:34-70."""
        return struct.pack(
            ">hiqqqhii",
            self.attrs,
            self.last_offset_delta,
            self.first_timestamp,
            self.max_timestamp,
            self.producer_id,
            self.producer_epoch,
            self.base_sequence,
            self.record_count,
        )

    def encode(self) -> bytes:
        return struct.pack(
            self._PACK,
            self.header_crc & 0xFFFFFFFF,
            self.size_bytes,
            self.base_offset,
            int(self.type),
            _i32(self.crc),
            self.attrs,
            self.last_offset_delta,
            self.first_timestamp,
            self.max_timestamp,
            self.producer_id,
            self.producer_epoch,
            self.base_sequence,
            self.record_count,
        )

    @staticmethod
    def decode(buf, offset: int = 0) -> "RecordBatchHeader":
        (
            header_crc,
            size_bytes,
            base_offset,
            btype,
            crc,
            attrs,
            last_offset_delta,
            first_timestamp,
            max_timestamp,
            producer_id,
            producer_epoch,
            base_sequence,
            record_count,
        ) = struct.unpack_from(RecordBatchHeader._PACK, buf, offset)
        return RecordBatchHeader(
            header_crc=header_crc,
            size_bytes=size_bytes,
            base_offset=base_offset,
            type=RecordBatchType(btype),
            crc=crc & 0xFFFFFFFF,
            attrs=attrs,
            last_offset_delta=last_offset_delta,
            first_timestamp=first_timestamp,
            max_timestamp=max_timestamp,
            producer_id=producer_id,
            producer_epoch=producer_epoch,
            base_sequence=base_sequence,
            record_count=record_count,
        )


def _i32(v: int) -> int:
    """Clamp an unsigned 32-bit value into the signed range for struct 'i'."""
    v &= 0xFFFFFFFF
    return v - (1 << 32) if v >= (1 << 31) else v


@dataclass
class RecordBatch:
    """Header + raw records payload (possibly compressed).

    ``payload`` is the byte-exact Kafka records section: concatenated
    varint-framed records, or the codec-compressed form when
    header.compression != none.
    """

    header: RecordBatchHeader
    payload: bytes

    # ------------------------------------------------------------ build
    @staticmethod
    def build(
        records: list[Record],
        *,
        base_offset: int = 0,
        type: RecordBatchType = RecordBatchType.raft_data,
        compression: Compression = Compression.none,
        first_timestamp: int = 0,
        max_timestamp: int | None = None,
        producer_id: int = -1,
        producer_epoch: int = -1,
        base_sequence: int = -1,
        transactional: bool = False,
        control: bool = False,
        compressor=None,
    ) -> "RecordBatch":
        payload = b"".join(r.encode() for r in records)
        attrs = int(compression) & ATTR_COMPRESSION_MASK
        if transactional:
            attrs |= ATTR_TRANSACTIONAL
        if control:
            attrs |= ATTR_CONTROL
        if compression != Compression.none:
            if compressor is None:
                from redpanda_tpu.compression import compress as compressor
            payload = compressor(payload, compression)
        hdr = RecordBatchHeader(
            base_offset=base_offset,
            type=type,
            attrs=attrs,
            last_offset_delta=(records[-1].offset_delta if records else 0),
            first_timestamp=first_timestamp,
            max_timestamp=max_timestamp if max_timestamp is not None else first_timestamp,
            producer_id=producer_id,
            producer_epoch=producer_epoch,
            base_sequence=base_sequence,
            record_count=len(records),
        )
        hdr.size_bytes = INTERNAL_HEADER_SIZE + len(payload)
        batch = RecordBatch(hdr, payload)
        batch.reseal()
        return batch

    def reseal(self) -> "RecordBatch":
        """Recompute both CRCs (e.g. after a transform rewrote the payload)."""
        self.header.size_bytes = INTERNAL_HEADER_SIZE + len(self.payload)
        self.header.crc = crc32c(self.header.kafka_header_crc_prefix() + self.payload)
        self.header.header_crc = self.header.internal_header_only_crc()
        return self

    # ------------------------------------------------------------ verify
    def crc_region(self) -> bytes:
        """The byte region covered by the Kafka CRC (header prefix +
        payload) — what the device batch validator hashes
        (kafka_batch_adapter.cc:93 equivalent)."""
        return self.header.kafka_header_crc_prefix() + self.payload

    def verify_kafka_crc(self) -> bool:
        return self.header.crc == crc32c(self.crc_region())

    def verify_header_crc(self) -> bool:
        return self.header.header_crc == self.header.internal_header_only_crc()

    # ------------------------------------------------------------ access
    def records(self, decompressor=None) -> list[Record]:
        payload = self.payload
        if self.header.compression != Compression.none:
            if decompressor is None:
                from redpanda_tpu.compression import uncompress as decompressor
            payload = decompressor(payload, self.header.compression)
        out = []
        offset = 0
        for _ in range(self.header.record_count):
            rec, n = Record.decode(payload, offset)
            out.append(rec)
            offset += n
        return out

    def record_values(self) -> list[bytes]:
        return [r.value or b"" for r in self.records()]

    @property
    def base_offset(self) -> int:
        return self.header.base_offset

    @property
    def last_offset(self) -> int:
        return self.header.last_offset

    @property
    def size_bytes(self) -> int:
        return self.header.size_bytes

    def with_base_offset(self, base_offset: int) -> "RecordBatch":
        hdr = replace(self.header, base_offset=base_offset)
        batch = RecordBatch(hdr, self.payload)
        hdr.header_crc = hdr.internal_header_only_crc()
        return batch

    # ------------------------------------------------------------ storage io
    def encode_internal(self) -> bytes:
        """Internal on-disk layout: 61-byte LE header + payload."""
        return self.header.encode() + self.payload

    @staticmethod
    def peek_size(buf, offset: int = 0) -> int:
        """Total frame length (size_bytes field, 4 bytes in at offset 4)
        without decoding — lets readers grow a bounded window to frame
        boundaries before decode_internal."""
        if len(buf) - offset < 8:
            raise CorruptBatchError("truncated batch header")
        return int(struct.unpack_from("<i", buf, offset + 4)[0])

    @staticmethod
    def decode_internal(buf, offset: int = 0, verify: bool = True) -> tuple["RecordBatch", int]:
        if len(buf) - offset < INTERNAL_HEADER_SIZE:
            raise CorruptBatchError("truncated batch header")
        hdr = RecordBatchHeader.decode(buf, offset)
        if verify and hdr.header_crc != hdr.internal_header_only_crc():
            raise CorruptBatchError(
                f"header_crc mismatch at offset {offset}: "
                f"{hdr.header_crc:#x} != {hdr.internal_header_only_crc():#x}"
            )
        payload_len = hdr.size_bytes - INTERNAL_HEADER_SIZE
        start = offset + INTERNAL_HEADER_SIZE
        payload = bytes(buf[start : start + payload_len])
        if len(payload) != payload_len:
            raise CorruptBatchError("truncated batch payload")
        return RecordBatch(hdr, payload), hdr.size_bytes


class CorruptBatchError(Exception):
    pass
