"""Streaming batch reader abstraction (parity with model/record_batch_reader.h:48).

A ``RecordBatchReader`` yields ``RecordBatch``es asynchronously; consumers
pull with ``read_some``/``consume``. Memory and generator-backed factories
cover the in-process uses (tests, coproc frontend, raft replicate input).
"""

from __future__ import annotations

from typing import AsyncIterator, Awaitable, Callable, Iterable

from redpanda_tpu.models.record import RecordBatch


class RecordBatchReader:
    def __init__(self, gen: AsyncIterator[RecordBatch]):
        self._gen = gen

    def __aiter__(self) -> AsyncIterator[RecordBatch]:
        return self._gen

    async def consume(self, consumer: Callable[[RecordBatch], Awaitable[bool] | bool]):
        """Feed every batch to `consumer`; stop early if it returns False."""
        import inspect

        async for batch in self._gen:
            res = consumer(batch)
            if inspect.isawaitable(res):
                res = await res
            if res is False:
                break
        return consumer

    async def collect(self) -> list[RecordBatch]:
        return [b async for b in self._gen]


def make_memory_reader(batches: Iterable[RecordBatch]) -> RecordBatchReader:
    async def gen():
        for b in batches:
            yield b

    return RecordBatchReader(gen())


def make_generator_reader(agen: AsyncIterator[RecordBatch]) -> RecordBatchReader:
    return RecordBatchReader(agen)
