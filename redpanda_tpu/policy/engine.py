"""Policy execution: device (packed XLA pipeline) + host (pure Python).

The host evaluator is bit-exact with the compiled device pipeline
(ops/transforms.py) by construction — it simulates the same byte-level
semantics (fixed windows, zero padding, 9-digit int bound) rather than
"parsing JSON properly". tests/test_policy.py asserts parity on random
inputs.
"""

from __future__ import annotations

import dataclasses
import struct

import numpy as np

from redpanda_tpu.models.record import Record, RecordBatch
from redpanda_tpu.ops.transforms import (
    Int,
    Str,
    TransformSpec,
    _FilterContains,
    _INT_WINDOW,
    _MapProject,
    _MapUppercase,
    transform_out_width,
)

_NUM_CONT = frozenset(b"0123456789.eE+-")


# ------------------------------------------------------------------ host path
def _find_pattern_py(value: bytes, pat: bytes, require_nonnum_suffix: bool) -> int:
    """First valid start of `pat` in value, else -1 (mirrors _find_pattern)."""
    start = 0
    while True:
        i = value.find(pat, start)
        if i < 0:
            return -1
        if not require_nonnum_suffix:
            return i
        end = i + len(pat)
        if end >= len(value) or value[end] not in _NUM_CONT:
            return i
        start = i + 1


def _parse_int_py(value: bytes, pos: int) -> tuple[int, bool]:
    """Mirror _parse_int_at: 12-byte zero-padded window, <=9 digits, a
    non-digit terminator must appear inside the window."""
    if pos < 0:
        return 0, False
    win = value[pos : pos + _INT_WINDOW].ljust(_INT_WINDOW, b"\x00")
    i = 0
    neg = win[0:1] == b"-"
    if neg:
        i = 1
    digits = 0
    val = 0
    while i < _INT_WINDOW and 48 <= win[i] <= 57:
        val = val * 10 + (win[i] - 48)
        digits += 1
        i += 1
    ok = 0 < digits <= 9 and i < _INT_WINDOW  # terminator seen in-window
    return (-val if neg else val), ok


def evaluate_record(spec: TransformSpec, value: bytes) -> bytes | None:
    """Host-path record transform; None = dropped (keep=False)."""
    if not value:
        return None
    for f in spec.filters:
        assert isinstance(f, _FilterContains)
        hit = _find_pattern_py(value, f.pattern, f.require_nonnum_suffix) >= 0
        if hit if f.negate else not hit:
            return None
    mapper = spec.mapper
    if mapper is None:
        return value
    if isinstance(mapper, _MapUppercase):
        return bytes(b - 32 if 97 <= b <= 122 else b for b in value)
    assert isinstance(mapper, _MapProject)
    parts = []
    for field in mapper.fields:
        if isinstance(field, Int):
            pat = f'"{field.key}":'.encode()
            pos = _find_pattern_py(value, pat, False)
            v, ok = _parse_int_py(value, pos + len(pat) if pos >= 0 else -1)
            if not ok:
                return None
            parts.append(struct.pack("<i", v))
        else:
            assert isinstance(field, Str)
            pat = f'"{field.key}":"'.encode()
            pos = _find_pattern_py(value, pat, False)
            if pos < 0:
                return None
            win = value[pos + len(pat) : pos + len(pat) + field.max_len + 1]
            q = win.find(b'"')
            if q < 0:  # no closing quote within max_len
                return None
            body = win[:q]
            parts.append(struct.pack("<H", q) + body.ljust(field.max_len, b"\x00"))
    return b"".join(parts)


# ------------------------------------------------------------------ engine
class PolicyEngine:
    """Executes a TransformSpec over fetched batches as a read-side view."""

    def __init__(
        self,
        *,
        row_stride: int = 2048,
        min_records_for_device: int = 256,
        force_engine: str | None = None,  # "device" | "host" | None=adaptive
    ):
        self.row_stride = row_stride
        self.min_records_for_device = min_records_for_device
        self.force_engine = force_engine
        self._specs: dict[str, TransformSpec] = {}

    def _spec(self, spec_json: str) -> TransformSpec:
        s = self._specs.get(spec_json)
        if s is None:
            s = self._specs[spec_json] = TransformSpec.from_json(spec_json)
        return s

    def transform_batches(
        self, spec_json: str, batches: list[RecordBatch]
    ) -> list[RecordBatch]:
        """Filter/map records in place of the fetched view. Surviving
        records keep their ORIGINAL offset deltas/timestamps/keys."""
        if not batches:
            return batches
        spec = self._spec(spec_json)
        n_records = sum(b.header.record_count for b in batches)
        engine = self.force_engine or (
            "device" if n_records >= self.min_records_for_device else "host"
        )
        if engine == "device":
            try:
                return self._run_device(spec, batches)
            except Exception:  # device trouble must not fail the fetch
                pass
        return self._run_host(spec, batches)

    # ------------------------------------------------------------ host
    def _run_host(self, spec: TransformSpec, batches: list[RecordBatch]) -> list[RecordBatch]:
        out = []
        for batch in batches:
            kept: list[Record] = []
            changed = False
            for rec in batch.records():
                new_val = evaluate_record(spec, rec.value or b"")
                if new_val is None:
                    changed = True
                    continue
                if new_val != rec.value:
                    changed = True
                    rec = dataclasses.replace(rec, value=new_val)
                kept.append(rec)
            nb = self._rebuild(batch, kept, changed)
            if nb is not None:
                out.append(nb)
        return out

    # ------------------------------------------------------------ device
    def _run_device(self, spec: TransformSpec, batches: list[RecordBatch]) -> list[RecordBatch]:
        from redpanda_tpu.coproc import batch_codec
        from redpanda_tpu.ops.pipeline import IN_META, make_packed_pipeline, unpack_result

        import jax

        fn, r_out = make_packed_pipeline(spec, self.row_stride)
        exploded = batch_codec.explode_batches(batches)
        n = len(exploded.sizes)
        if n == 0:
            return batches
        fits = exploded.sizes <= self.row_stride
        stride = self.row_stride + IN_META
        try:
            from redpanda_tpu.native import lib
        except Exception:
            lib = None
        if lib is not None:
            staged, _ = lib.pack_rows(
                exploded.joined, exploded.offsets, exploded.sizes, stride
            )
        else:
            from redpanda_tpu.ops.packing import pack_rows

            vals = [
                exploded.joined[o : o + min(s, self.row_stride)]
                for o, s in zip(exploded.offsets, exploded.sizes)
            ]
            staged, _ = pack_rows(vals, stride)
        lens = np.where(fits, exploded.sizes, 0).astype("<i4")
        staged[:, self.row_stride : self.row_stride + 4] = lens.view(np.uint8).reshape(n, 4)
        staged[:, self.row_stride + 4 :] = 0
        packed = np.asarray(fn(jax.device_put(staged)))
        out_rows, out_len, keep = unpack_result(packed, r_out)
        keep = keep & fits
        result = []
        for batch, (start, end) in zip(batches, exploded.ranges):
            kept: list[Record] = []
            changed = False
            for i, rec in enumerate(batch.records()):
                j = start + i
                if not keep[j]:
                    changed = True
                    continue
                new_val = out_rows[j, : out_len[j]].tobytes()
                if new_val != rec.value:
                    changed = True
                    rec = dataclasses.replace(rec, value=new_val)
                kept.append(rec)
            nb = self._rebuild(batch, kept, changed)
            if nb is not None:
                result.append(nb)
        return result

    # ------------------------------------------------------------ shared
    @staticmethod
    def _rebuild(batch: RecordBatch, kept: list[Record], changed: bool) -> RecordBatch | None:
        """Reassemble the view batch; None when nothing survives. Original
        offset deltas ride along, so a partially-filtered batch keeps its
        base_offset/last_offset_delta and clients' offset math still works
        (gaps, like compaction)."""
        if not changed:
            return batch
        if not kept:
            return None
        from redpanda_tpu.compression import compress

        payload = b"".join(r.encode() for r in kept)
        codec = batch.header.compression
        if codec != type(codec).none:
            payload = compress(payload, codec)
        hdr = dataclasses.replace(batch.header, record_count=len(kept))
        nb = RecordBatch(hdr, payload)
        nb.reseal()
        return nb
