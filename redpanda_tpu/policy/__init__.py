"""Data-policy execution engine — the TPU-first answer to v8_engine/.

The reference embeds V8 and runs a per-topic JS function over records on
the fetch path (v8_engine/script.h:39-165, wired into the kafka protocol
via application.cc:597,1037; policies replicate through the controller as
create_data_policy_cmd, cluster/commands.h:152-162). A TPU cannot run
arbitrary JS; the idiomatic equivalent is the declarative TransformSpec
DSL already compiled to fused XLA programs for coproc
(redpanda_tpu/ops/transforms.py) — a data policy IS a TransformSpec bound
to a topic.

Two execution engines, same semantics:
- device: pack the fetched records into a staging array and run the
  compiled packed pipeline (one H2D / one D2H) — chosen when a fetch
  carries enough records to amortize the launch.
- host: a pure-Python evaluator of the same DSL (also the parity oracle
  in tests) — chosen for small fetches and when JAX is unavailable.

Unlike coproc (which materializes NEW topics, renumbering records), a
policy is a read-side VIEW: surviving records keep their original
offset_delta/timestamps/keys so client offset arithmetic is unaffected;
filtered records become offset gaps exactly like compacted batches.
"""

from redpanda_tpu.policy.engine import PolicyEngine, evaluate_record
from redpanda_tpu.policy.table import DataPolicy, DataPolicyTable

__all__ = ["DataPolicy", "DataPolicyTable", "PolicyEngine", "evaluate_record"]
