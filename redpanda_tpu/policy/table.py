"""Replicated per-topic data-policy table (v8_engine/data_policy_table)."""

from __future__ import annotations

from dataclasses import dataclass

from redpanda_tpu.cluster.commands import Command, CommandType


@dataclass
class DataPolicy:
    topic: str
    name: str
    spec_json: str


_POLICY_CMDS = [CommandType.create_data_policy, CommandType.delete_data_policy]


class DataPolicyTable:
    """topic -> DataPolicy; fed by controller command replay (clustered)
    or direct application (single-node)."""

    def __init__(self) -> None:
        self._policies: dict[str, DataPolicy] = {}
        self._version = 0

    def attach(self, controller) -> "DataPolicyTable":
        """Plug into the controller mux (data_policy_manager's seat in
        controller_stm.h)."""
        controller.register_applier(_POLICY_CMDS, self.apply_command)
        return self

    def get(self, topic: str) -> DataPolicy | None:
        return self._policies.get(topic)

    def policies(self) -> dict[str, DataPolicy]:
        return dict(self._policies)

    @property
    def version(self) -> int:
        return self._version

    async def apply_command(self, cmd: Command) -> None:
        d = cmd.data
        if cmd.type == CommandType.create_data_policy:
            # validate the spec NOW: a deterministic apply failure must be
            # identical on every node (the controller records apply errors)
            from redpanda_tpu.ops.transforms import TransformSpec

            TransformSpec.from_json(d["spec"])
            self._policies[d["topic"]] = DataPolicy(d["topic"], d["name"], d["spec"])
        elif cmd.type == CommandType.delete_data_policy:
            self._policies.pop(d["topic"], None)
        else:
            raise ValueError(f"not a data-policy command: {cmd.type}")
        self._version += 1
