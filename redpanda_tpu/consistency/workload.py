"""Fault-tolerant workload driver feeding the linearizability checker.

Concurrent writer tasks produce unique values (acks=-1) and reader tasks
fetch the committed suffix, each op recorded with single-process monotonic
invoke/response timestamps (gobekli's workload-driver role). Failures are
recorded as indeterminate ops — never retried with the same value, so the
checker's uniqueness reasoning stays sound. After the run (and after any
injected faults heal), ``final_log()`` reads the full committed log from a
fresh client for ``check_history``.
"""

from __future__ import annotations

import asyncio
import time

from redpanda_tpu.consistency.checker import Op
from redpanda_tpu.kafka.client import KafkaClient


class LogWorkload:
    def __init__(self, bootstrap_fn, topic: str, partition: int = 0):
        """``bootstrap_fn() -> list[(host, port)]`` — re-evaluated on every
        reconnect so killed nodes drop out of the pool."""
        self.bootstrap_fn = bootstrap_fn
        self.topic = topic
        self.partition = partition
        self.history: list[Op] = []
        self._seq = 0

    # ------------------------------------------------------------ clients
    async def _client(self) -> KafkaClient:
        last = None
        for _ in range(40):
            try:
                c = await KafkaClient(self.bootstrap_fn()).connect()
                await c.refresh_metadata([self.topic])
                return c
            except Exception as e:
                last = e
                await asyncio.sleep(0.25)
        raise TimeoutError(f"no broker reachable: {last!r}")

    # ------------------------------------------------------------ ops
    async def writer(self, writer_id: int, n_ops: int, *, op_timeout: float = 8.0):
        c = await self._client()
        try:
            for _ in range(n_ops):
                self._seq += 1
                value = b"w%d-%d" % (writer_id, self._seq)
                op = Op("write", invoke_t=time.monotonic(), value=value)
                self.history.append(op)
                try:
                    off = await asyncio.wait_for(
                        c.produce(self.topic, self.partition, [value], acks=-1),
                        op_timeout,
                    )
                    op.response_t = time.monotonic()
                    op.offset = off
                    op.ok = True
                except Exception:
                    op.response_t = None  # indeterminate; value never reused
                    try:
                        await c.close()
                    except Exception:
                        pass
                    c = await self._client()
                await asyncio.sleep(0)
        finally:
            try:
                await c.close()
            except Exception:
                pass

    async def reader(self, n_ops: int, *, op_timeout: float = 8.0, pause: float = 0.05):
        c = await self._client()
        try:
            for _ in range(n_ops):
                op = Op("read", invoke_t=time.monotonic())
                self.history.append(op)
                try:
                    batches, hw = await asyncio.wait_for(
                        c.fetch(self.topic, self.partition, 0, max_wait_ms=10),
                        op_timeout,
                    )
                    op.response_t = time.monotonic()
                    op.hw = hw
                    op.observed = [
                        (b.header.base_offset + r.offset_delta, r.value)
                        for b in batches
                        for r in b.records()
                    ]
                    op.ok = True
                except Exception:
                    op.response_t = None
                    try:
                        await c.close()
                    except Exception:
                        pass
                    c = await self._client()
                await asyncio.sleep(pause)
        finally:
            try:
                await c.close()
            except Exception:
                pass

    # ------------------------------------------------------------ final state
    async def final_log(self, *, settle_timeout: float = 60.0) -> list[tuple[int, bytes]]:
        """The committed log [offset -> value] once the cluster has healed:
        retries until a leader serves a full read from offset 0."""
        deadline = time.monotonic() + settle_timeout
        last: object = None
        while time.monotonic() < deadline:
            try:
                c = await self._client()
                out: list[tuple[int, bytes]] = []
                offset = 0
                while time.monotonic() < deadline:
                    batches, hw = await c.fetch(
                        self.topic, self.partition, offset, max_wait_ms=10
                    )
                    for b in batches:
                        for r in b.records():
                            out.append(
                                (b.header.base_offset + r.offset_delta, r.value)
                            )
                        offset = b.last_offset + 1
                    if offset >= hw:
                        await c.close()
                        return out
                    if not batches:
                        # hw ahead of what the node serves (recovering
                        # leader): yield instead of spinning hot, and let
                        # the deadline fire
                        last = f"stuck at offset {offset} < hw {hw}"
                        await asyncio.sleep(0.2)
                await c.close()
            except Exception as e:
                last = e
                await asyncio.sleep(0.5)
        raise TimeoutError(f"cluster never healed for the final read: {last!r}")
