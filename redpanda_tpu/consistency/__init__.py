"""Consistency testing: linearizability checking for the partitioned log.

The gobekli analogue (reference: src/consistency-testing/gobekli —
LinearizabilityRegisterChecker, gobekli/consensus.py:65, plus the chaostest
fault campaigns). The reference checks a kv register built ON raft
(kvelldb); here the object under test is what this system actually
guarantees: the partition IS a linearizable append-only register sequence,
so the checker validates client-observed histories of produce/fetch against
the log model. See checker.py for the model and workload.py for the
fault-driving workload used by tests/chaos/test_linearizability.py.
"""

from redpanda_tpu.consistency.checker import (  # noqa: F401
    CheckResult,
    Op,
    check_history,
)
from redpanda_tpu.consistency.workload import LogWorkload  # noqa: F401
