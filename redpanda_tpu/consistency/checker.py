"""Linearizability checker for the append-only log register.

Model: one topic partition is a sequence register. A produce(v, acks=-1)
that RETURNS offset o asserts "v is durably at position o, committed". A
fetch observing high watermark h asserts "positions [0, h) are immutable
and v at o < h is readable". Linearizability over this model means the
log's committed prefix behaves like a single atomic object in real time:

  W1. Every acked write's value sits at its acked offset in the final log,
      exactly once (no lost acked writes, no duplication of an acked op,
      no offset reuse). Failed/timed-out writes are indeterminate: they
      may appear at most once anywhere.
  W2. Real-time write order: if write A completed before write B was
      invoked, then offset(A) < offset(B).
  R1. A read's observed records match the final log at those offsets
      byte-for-byte (committed data is immutable).
  R2. Recency: a read invoked after write W completed must observe
      high watermark > offset(W) — the committed write cannot disappear
      or be hidden from later readers.
  R3. Real-time hw monotonicity: if read R1 completed before R2 was
      invoked, hw(R1) <= hw(R2) (the register never rolls back).

This is the same guarantee gobekli's LinearizabilityRegisterChecker
(reference src/consistency-testing/gobekli/gobekli/consensus.py:65)
enforces for its kv register, specialized to the log's offset order — the
total order is given by offsets, so checking is O(n log n) rather than a
search over permutations.

Clock note: invocation/response timestamps come from ONE test process
(time.monotonic), so real-time comparisons are exact, not approximations.
"""

from __future__ import annotations

from dataclasses import dataclass, field


@dataclass
class Op:
    kind: str  # "write" | "read"
    invoke_t: float
    response_t: float | None = None  # None = never returned (indeterminate)
    ok: bool = False  # acked / completed successfully
    # write fields
    value: bytes | None = None
    offset: int | None = None  # acked offset
    # read fields
    hw: int | None = None
    observed: list[tuple[int, bytes]] = field(default_factory=list)

    @property
    def determinate(self) -> bool:
        return self.ok and self.response_t is not None


@dataclass
class CheckResult:
    ok: bool
    violations: list[str]
    n_ops: int
    n_acked_writes: int

    def __bool__(self) -> bool:
        return self.ok


def check_history(history: list[Op], final_log: list[tuple[int, bytes]]) -> CheckResult:
    """Validate a client-observed history against the final committed log.

    ``final_log``: [(offset, value)] read from offset 0 to the high
    watermark after the workload (and after recovery from any faults).
    """
    violations: list[str] = []
    log = dict(final_log)
    offsets_sorted = sorted(log)
    writes = [op for op in history if op.kind == "write"]
    reads = [op for op in history if op.kind == "read"]
    acked = [w for w in writes if w.determinate]

    # --- W1: acked writes present at their offsets, exactly once
    value_locations: dict[bytes, list[int]] = {}
    for off, v in final_log:
        value_locations.setdefault(v, []).append(off)
    for w in acked:
        locs = value_locations.get(w.value, [])
        if w.offset is None:
            violations.append(f"acked write {w.value!r} returned no offset")
            continue
        if w.offset not in locs:
            got = log.get(w.offset)
            violations.append(
                f"LOST ACKED WRITE: {w.value!r} acked at offset {w.offset} "
                f"but log has {got!r} there (value found at {locs})"
            )
        elif len(locs) > 1:
            violations.append(
                f"acked write {w.value!r} duplicated at offsets {locs}"
            )
    # indeterminate writes: at most once
    for w in writes:
        if not w.determinate and w.value is not None:
            locs = value_locations.get(w.value, [])
            if len(locs) > 1:
                violations.append(
                    f"indeterminate write {w.value!r} duplicated at {locs}"
                )

    # --- W2: real-time order between acked writes. Offsets are the total
    # order, so the check is a sweep: walking writes by invocation time,
    # any write whose offset is <= the max offset of writes ALREADY
    # completed before it began violates real time (an op that completed
    # strictly earlier cannot be ordered after one invoked later).
    placed = [w for w in acked if w.offset is not None]
    by_completion = sorted(placed, key=lambda w: w.response_t)
    max_done_off = -1
    max_done_val = None
    ci = 0
    for w in sorted(placed, key=lambda w: w.invoke_t):
        while ci < len(by_completion) and by_completion[ci].response_t < w.invoke_t:
            if by_completion[ci].offset > max_done_off:
                max_done_off = by_completion[ci].offset
                max_done_val = by_completion[ci].value
            ci += 1
        if w.offset <= max_done_off:
            violations.append(
                f"REAL-TIME ORDER: write {w.value!r} got offset {w.offset} "
                f"but {max_done_val!r} already completed at offset "
                f"{max_done_off} before it was invoked"
            )

    # --- R1: observed records match the final log. Fetch only serves
    # COMMITTED data (<= hw), so ANY observed offset missing from the final
    # log — including past its end — is committed data that vanished.
    for r in reads:
        if not r.determinate:
            continue
        for off, v in r.observed:
            if off in log:
                if log[off] != v:
                    violations.append(
                        f"IMMUTABILITY: read observed {v!r} at offset {off}, "
                        f"final log has {log[off]!r}"
                    )
            else:
                violations.append(
                    f"COMMITTED DATA LOST: read observed offset {off} "
                    f"({v!r}) absent from the final log"
                )

    # --- R2: recency — reads see every write completed before they began
    for r in reads:
        if not r.determinate or r.hw is None:
            continue
        for w in acked:
            if w.offset is not None and w.response_t < r.invoke_t:
                if r.hw <= w.offset:
                    violations.append(
                        f"STALE READ: hw {r.hw} but write {w.value!r} at "
                        f"offset {w.offset} completed before the read began"
                    )
                    break  # one witness per read keeps the report readable

    # --- R3: hw never moves backwards in real time (same completion sweep
    # as W2: walk by invocation, track the max hw of reads already done)
    done_reads = sorted(
        (r for r in reads if r.determinate and r.hw is not None),
        key=lambda r: r.response_t,
    )
    prior_hw = -1
    ri = 0
    for r in sorted(done_reads, key=lambda r: r.invoke_t):
        while ri < len(done_reads) and done_reads[ri].response_t < r.invoke_t:
            prior_hw = max(prior_hw, done_reads[ri].hw)
            ri += 1
        if r.hw < prior_hw:
            violations.append(
                f"HW ROLLBACK: read observed hw {r.hw} after an earlier "
                f"read completed with hw {prior_hw}"
            )

    return CheckResult(
        ok=not violations,
        violations=violations,
        n_ops=len(history),
        n_acked_writes=len(acked),
    )
