"""Per-shard durable key-value store (parity with storage/kvstore.h:61-91).

Small metadata only — raft voted_for/terms, log start offsets, coproc
offsets — exactly the uses the reference lists. In-memory dict + WAL file
of CRC-framed ops + periodic snapshot; recovery = snapshot + WAL replay.
Keys are namespaced by ``KeySpace``.
"""

from __future__ import annotations

import enum
import os
import struct

from redpanda_tpu.hashing.crc32c import crc32c
from redpanda_tpu.storage import file_sanitizer
from redpanda_tpu.storage.snapshot import SnapshotManager, SnapshotError


class KeySpace(enum.IntEnum):
    testing = 0
    consensus = 1
    storage = 2
    controller = 3
    offset_translator = 4
    coproc = 5


_OP = struct.Struct("<IBBI")  # crc, keyspace, op, key_len  (value_len follows for puts)


class KvStore:
    SNAPSHOT_THRESHOLD = 1 << 20  # snapshot + truncate WAL at 1 MiB

    def __init__(self, dir_path: str):
        self.dir = dir_path
        os.makedirs(dir_path, exist_ok=True)
        self._data: dict[tuple[int, bytes], bytes] = {}
        self._snap = SnapshotManager(dir_path, "kvstore.snapshot")
        self._wal_path = os.path.join(dir_path, "kvstore.wal")
        self._wal = None

    # ------------------------------------------------------------ lifecycle
    def start(self) -> "KvStore":
        snap = None
        try:
            snap = self._snap.read()
        except SnapshotError:
            snap = None  # corrupt snapshot: fall back to WAL-only replay
        if snap:
            _, payload = snap
            self._load_payload(payload)
        self._replay_wal()
        self._wal = file_sanitizer.maybe_wrap(
            open(self._wal_path, "ab"), self._wal_path
        )
        return self

    def stop(self):
        if self._wal is None:
            return  # never started: don't clobber on-disk state with nothing
        self._do_snapshot()
        self._wal.close()
        self._wal = None

    # ------------------------------------------------------------ ops
    def get(self, space: KeySpace, key: bytes) -> bytes | None:
        return self._data.get((int(space), bytes(key)))

    def put(self, space: KeySpace, key: bytes, value: bytes) -> None:
        key, value = bytes(key), bytes(value)
        self._data[(int(space), key)] = value
        self._log_op(space, 0, key, value)

    def remove(self, space: KeySpace, key: bytes) -> None:
        key = bytes(key)
        self._data.pop((int(space), key), None)
        self._log_op(space, 1, key, b"")

    def keys(self, space: KeySpace) -> list[bytes]:
        s = int(space)
        return [k for (sp, k) in self._data if sp == s]

    # ------------------------------------------------------------ internals
    def _log_op(self, space: KeySpace, op: int, key: bytes, value: bytes):
        if self._wal is None:
            raise RuntimeError("kvstore not started")
        body = struct.pack("<BBI", int(space), op, len(key)) + key
        if op == 0:
            body += struct.pack("<I", len(value)) + value
        frame = struct.pack("<I", crc32c(body)) + body
        self._wal.write(struct.pack("<I", len(frame)) + frame)
        self._wal.flush()
        os.fsync(self._wal.fileno())
        if self._wal.tell() >= self.SNAPSHOT_THRESHOLD:
            self._do_snapshot()

    def _replay_wal(self):
        try:
            with open(self._wal_path, "rb") as f:
                blob = f.read()
        except FileNotFoundError:
            return
        at = 0
        while at + 4 <= len(blob):
            (flen,) = struct.unpack_from("<I", blob, at)
            frame = blob[at + 4 : at + 4 + flen]
            if len(frame) != flen or flen < 4:
                break  # torn tail
            (crc,) = struct.unpack_from("<I", frame)
            body = frame[4:]
            if crc32c(body) != crc:
                break
            space, op, klen = struct.unpack_from("<BBI", body)
            key = body[6 : 6 + klen]
            if op == 0:
                (vlen,) = struct.unpack_from("<I", body, 6 + klen)
                value = body[10 + klen : 10 + klen + vlen]
                self._data[(space, key)] = value
            else:
                self._data.pop((space, key), None)
            at += 4 + flen

    def _payload(self) -> bytes:
        out = bytearray()
        for (space, key), value in sorted(self._data.items()):
            out += struct.pack("<BII", space, len(key), len(value))
            out += key
            out += value
        return bytes(out)

    def _load_payload(self, payload: bytes):
        at = 0
        while at + 9 <= len(payload):
            space, klen, vlen = struct.unpack_from("<BII", payload, at)
            at += 9
            key = payload[at : at + klen]
            at += klen
            value = payload[at : at + vlen]
            at += vlen
            self._data[(space, key)] = value

    def _do_snapshot(self):
        self._snap.write(b"kvstore-v1", self._payload())
        if self._wal:
            self._wal.close()
        with open(self._wal_path, "wb"):
            pass  # truncate
        self._wal = file_sanitizer.maybe_wrap(
            open(self._wal_path, "ab"), self._wal_path
        )
