"""Positioned-reader cache for sequential fetch continuation.

Reference: storage/readers_cache.h:36 — `disk_log_impl` keeps a per-log
cache of live `log_reader`s keyed by their next read position; a fetch
whose start offset matches a cached reader's position adopts it instead of
re-opening and re-seeking, and truncation/compaction/start-offset moves
evict affected readers.

Our readers are not long-lived objects (each DiskLog.read decodes from a
file position), so the cached thing is the *cursor*: next_offset →
(segment base, exact file position just past the last decoded frame).
A continuation read seeks straight there, skipping the sparse-index lookup
and the decode-and-skip scan from the index point. Cursors at the log tail
stay valid across appends — the next frame lands exactly at the cached
position, so steady-state sequential consumers never re-scan.

Invalidation (DiskLog mirrors its batch-cache hooks):
- truncate(offset): drop cursors with next_offset > offset (their position
  may now be past EOF or point into rewritten bytes)
- prefix_truncate(offset): drop cursors below the new start offset
- compaction (in-place segment rewrite): drop the log's cursors entirely
- close/remove: drop the log's cursors entirely
"""

from __future__ import annotations

from collections import OrderedDict
from dataclasses import dataclass


@dataclass(frozen=True)
class ReadCursor:
    segment_base: int  # base offset of the segment the position lies in
    file_pos: int  # byte position of the next frame within that segment


class ReadersCache:
    """Process-wide LRU of read cursors, shared by all managed logs."""

    def __init__(self, max_entries: int = 256):
        self.max_entries = max_entries
        # (log_key, next_offset) -> ReadCursor, oldest first
        self._lru: "OrderedDict[tuple[int, int], ReadCursor]" = OrderedDict()
        self.hits = 0
        self.misses = 0

    def get(self, log_key: int, next_offset: int) -> ReadCursor | None:
        cur = self._lru.get((log_key, next_offset))
        if cur is None:
            self.misses += 1
            return None
        self._lru.move_to_end((log_key, next_offset))
        self.hits += 1
        return cur

    def put(self, log_key: int, next_offset: int, cursor: ReadCursor) -> None:
        key = (log_key, next_offset)
        self._lru.pop(key, None)
        self._lru[key] = cursor
        while len(self._lru) > self.max_entries:
            self._lru.popitem(last=False)

    def invalidate(
        self,
        log_key: int,
        *,
        from_offset: int | None = None,
        below_offset: int | None = None,
    ) -> None:
        """No range args = drop every cursor for the log."""
        doomed = []
        for (lk, off) in self._lru:
            if lk != log_key:
                continue
            if from_offset is not None:
                # a cursor at exactly `from_offset` points at the first
                # truncated byte — the position is stale too; drop >= hence
                if off >= from_offset:
                    doomed.append((lk, off))
            elif below_offset is not None:
                if off < below_offset:
                    doomed.append((lk, off))
            else:
                doomed.append((lk, off))
        for key in doomed:
            del self._lru[key]

    def stats(self) -> dict[str, int]:
        return {"hits": self.hits, "misses": self.misses, "entries": len(self._lru)}
