"""In-memory log backend for tests (parity with storage/mem_log_impl.cc).

Same surface as DiskLog, no files. Used by raft/cluster/kafka tests where
disk behavior is not under test.
"""

from __future__ import annotations

from dataclasses import replace

from redpanda_tpu.models.fundamental import NTP
from redpanda_tpu.models.record import RecordBatch
from redpanda_tpu.storage.log import AppendResult, LogOffsets


class MemLog:
    def __init__(self, ntp: NTP, start_offset: int = 0):
        self.ntp = ntp
        self._batches: list[RecordBatch] = []
        self._start_offset = start_offset
        self._term = 0

    @property
    def term(self) -> int:
        return self._term

    def offsets(self) -> LogOffsets:
        dirty = self._batches[-1].last_offset if self._batches else self._start_offset - 1
        return LogOffsets(self._start_offset, dirty, dirty)

    async def append(self, batches, *, term=None, assign_offsets: bool = True) -> AppendResult:
        if term is not None:
            self._term = max(self._term, term)
        off = self.offsets()
        next_offset = off.dirty_offset + 1
        first = None
        size = 0
        for batch in batches:
            if assign_offsets:
                batch = batch.with_base_offset(next_offset)
                batch.header.term = self._term
            elif batch.header.term < 0:
                batch.header.term = self._term
            else:
                # Follower-path append keeps the replicated term (MemLog has
                # no segments, so the term survives only in the header).
                self._term = batch.header.term
            if first is None:
                first = batch.base_offset
            self._batches.append(batch)
            size += batch.size_bytes
            next_offset = batch.last_offset + 1
        last = next_offset - 1
        return AppendResult(first if first is not None else last + 1, last, size)

    async def read(self, start_offset, max_bytes=1 << 20, *, max_offset=None, type_filter=None):
        out = []
        taken = 0
        for b in self._batches:
            if b.last_offset < start_offset or b.last_offset < self._start_offset:
                continue
            if max_offset is not None and b.base_offset > max_offset:
                break
            if type_filter is not None and b.header.type not in type_filter:
                continue
            out.append(b)
            taken += b.size_bytes
            if taken >= max_bytes:
                break
        return out

    async def flush(self):
        pass

    async def truncate(self, offset: int):
        self._batches = [b for b in self._batches if b.last_offset < offset]

    async def prefix_truncate(self, offset: int):
        self._start_offset = max(self._start_offset, offset)
        self._batches = [b for b in self._batches if b.last_offset >= self._start_offset]

    async def timequery(self, ts: int):
        for b in self._batches:
            if b.header.max_timestamp >= ts:
                return b.base_offset
        return None

    async def close(self):
        pass

    async def remove(self):
        self._batches.clear()
