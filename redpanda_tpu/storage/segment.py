"""Log segment: one append-only data file + a sparse offset index.

Capability parity with the reference's storage/segment.h +
segment_appender.h (chunked buffered writes, background flush) +
segment_index.h (sparse index sampled every `index_step` bytes). The
on-disk payload is the internal batch layout (61-byte LE header + payload,
models/record.py), so a recovery scan is a straight walk of
[header][payload] frames whose CRCs can be validated in one batched device
kernel (see recovery.py).

File naming: <base_offset>-<term>-v1.log / .index under the ntp directory.
"""

from __future__ import annotations

import os
import struct
from dataclasses import dataclass

from redpanda_tpu.models.record import (
    INTERNAL_HEADER_SIZE,
    CorruptBatchError,
    RecordBatch,
    RecordBatchHeader,
)
from redpanda_tpu.storage import file_sanitizer

INDEX_STEP = 32 * 1024
_INDEX_ENTRY = struct.Struct("<IQq")  # rel_offset u32, file_pos u64, ts i64
_INDEX_MAGIC = b"RPXI\x02"
_INDEX_FOOTER = struct.Struct("<qq")  # dirty_offset i64, max_timestamp i64


@dataclass
class IndexEntry:
    rel_offset: int
    file_pos: int
    timestamp: int


class SegmentIndex:
    """Sparse offset -> file position index, rebuilt on demand if missing."""

    def __init__(self, path: str, base_offset: int):
        self.path = path
        self.base_offset = base_offset
        self.entries: list[IndexEntry] = []
        self._acc_bytes = 0

    def maybe_track(self, batch_header: RecordBatchHeader, file_pos: int):
        self._acc_bytes += batch_header.size_bytes
        if not self.entries or self._acc_bytes >= INDEX_STEP:
            self.entries.append(
                IndexEntry(
                    batch_header.base_offset - self.base_offset,
                    file_pos,
                    batch_header.first_timestamp,
                )
            )
            self._acc_bytes = 0

    def lookup(self, offset: int) -> int:
        """Largest indexed file position whose batch base_offset <= offset."""
        rel = offset - self.base_offset
        pos = 0
        for e in self.entries:
            if e.rel_offset <= rel:
                pos = e.file_pos
            else:
                break
        return pos

    def lookup_time(self, ts: int) -> int:
        pos = 0
        for e in self.entries:
            if e.timestamp <= ts:
                pos = e.file_pos
            else:
                break
        return pos

    def persist(self, dirty_offset: int = -1, max_timestamp: int = -1):
        with open(self.path, "wb") as f:
            f.write(_INDEX_MAGIC)
            f.write(_INDEX_FOOTER.pack(dirty_offset, max_timestamp))
            for e in self.entries:
                f.write(_INDEX_ENTRY.pack(e.rel_offset, e.file_pos, e.timestamp))

    def load(self) -> tuple[int, int] | None:
        """Returns (dirty_offset, max_timestamp) on success, else None."""
        try:
            with open(self.path, "rb") as f:
                blob = f.read()
        except FileNotFoundError:
            return None
        hdr = len(_INDEX_MAGIC) + _INDEX_FOOTER.size
        if not blob.startswith(_INDEX_MAGIC) or len(blob) < hdr:
            return None
        dirty, max_ts = _INDEX_FOOTER.unpack_from(blob, len(_INDEX_MAGIC))
        self.entries = []
        body = blob[hdr:]
        if len(body) % _INDEX_ENTRY.size:
            return None
        for i in range(0, len(body), _INDEX_ENTRY.size):
            rel, pos, ts = _INDEX_ENTRY.unpack_from(body, i)
            self.entries.append(IndexEntry(rel, pos, ts))
        return dirty, max_ts

    def truncate_at_pos(self, file_pos: int):
        self.entries = [e for e in self.entries if e.file_pos < file_pos]


class Segment:
    """One data file; open for append only when it is the active segment."""

    APPEND_BUF_LIMIT = 1 << 20  # flush the write buffer at 1 MiB

    def __init__(self, dir_path: str, base_offset: int, term: int):
        self.dir = dir_path
        self.base_offset = base_offset
        self.term = term
        stem = f"{base_offset}-{term}-v1"
        self.data_path = os.path.join(dir_path, stem + ".log")
        self.index = SegmentIndex(os.path.join(dir_path, stem + ".index"), base_offset)
        self._file = None
        self._buf = bytearray()
        self.size_bytes = 0
        self.dirty_offset = base_offset - 1  # highest appended offset
        self.max_timestamp = -1

    # ------------------------------------------------------------ lifecycle
    def create(self):
        self._file = file_sanitizer.maybe_wrap(
            open(self.data_path, "wb"), self.data_path
        )
        return self

    def open_existing(self, writable: bool):
        self.size_bytes = os.path.getsize(self.data_path)
        if writable:
            self._file = file_sanitizer.maybe_wrap(
                open(self.data_path, "ab"), self.data_path
            )
        loaded = self.index.load()
        if loaded is None:
            self.rebuild_index()
        else:
            self.dirty_offset, self.max_timestamp = loaded
            if self.dirty_offset < self.base_offset:
                # stale/pre-footer index: derive state from the data file
                self.rebuild_index()
        return self

    @property
    def writable(self) -> bool:
        return self._file is not None

    # ------------------------------------------------------------ append
    def append(self, batch: RecordBatch) -> None:
        assert self._file is not None, "segment not writable"
        encoded = batch.encode_internal()
        # this batch's file position == bytes appended so far (incl. buffered)
        self.index.maybe_track(batch.header, self.size_bytes)
        self._buf += encoded
        self.size_bytes += len(encoded)
        self.dirty_offset = batch.last_offset
        self.max_timestamp = max(self.max_timestamp, batch.header.max_timestamp)
        if len(self._buf) >= self.APPEND_BUF_LIMIT:
            self.flush_buffer()

    def flush_buffer(self):
        if self._buf and self._file:
            self._file.write(self._buf)
            self._buf.clear()

    def fsync(self):
        self.flush_buffer()
        if self._file:
            self._file.flush()
            os.fsync(self._file.fileno())

    def release_appender(self):
        """Close for writing (segment roll); persists the index."""
        if self._file:
            self.flush_buffer()
            self._file.flush()
            os.fsync(self._file.fileno())
            self._file.close()
            self._file = None
        self.index.persist(self.dirty_offset, self.max_timestamp)

    def close(self):
        self.release_appender()

    # ------------------------------------------------------------ read
    def read_from(self, file_pos: int, max_len: int | None = None) -> bytes:
        self.flush_buffer()
        if self._file:
            self._file.flush()
        with open(self.data_path, "rb") as f:
            f.seek(file_pos)
            return f.read() if max_len is None else f.read(max_len)

    def scan(
        self,
        start_offset: int,
        max_bytes: int,
        *,
        type_filter=None,
        max_offset: int | None = None,
        start_pos: int | None = None,
    ) -> tuple[list[RecordBatch], int]:
        """Batches overlapping [start_offset, max_offset], bounded by size,
        with cursor support (readers_cache.h continuation).

        `start_pos` is an exact file position of a frame boundary (from a
        cached read cursor) — when given, the sparse-index lookup and the
        decode-and-skip scan up to `start_offset` are bypassed. Returns
        (batches, next_file_pos) where next_file_pos is the byte position
        just past the last KEPT batch (or the scan start when nothing was
        kept) — the cursor for the follow-up read at
        `batches[-1].last_offset + 1`. Frames consumed but filtered out
        AFTER the last kept batch are deliberately not covered by the
        cursor, so a continuation under a different type_filter re-scans
        them instead of silently skipping.
        """
        pos = start_pos if start_pos is not None else self.index.lookup(start_offset)
        # bounded chunked reads (ONE handle, window trimmed as frames are
        # consumed): a sequential consumer with a cursor reads ~max_bytes
        # per call instead of slurping the segment tail
        chunk = max(min(max_bytes * 2, 8 << 20), 1 << 16)
        out: list[RecordBatch] = []
        taken = 0
        kept_end = pos  # file offset just past the last KEPT batch
        for batch, end_pos in self._frames_from(pos, chunk):
            if max_offset is not None and batch.base_offset > max_offset:
                break  # NOT consumed: cursor stays before this frame
            if batch.last_offset < start_offset:
                continue
            if type_filter is not None and batch.header.type not in type_filter:
                continue
            # Runtime term context comes from the segment (the packed
            # header carries no term; the reference derives it the same
            # way, from the raft configuration tracking / segment naming)
            batch.header.term = self.term
            out.append(batch)
            kept_end = end_pos
            taken += batch.size_bytes
            if taken >= max_bytes:
                break
        return out, kept_end

    def _frames_from(self, pos: int, chunk: int):
        """Yield (batch, end_file_pos) for each frame from file position
        `pos`, reading the file in `chunk`-sized windows trimmed as frames
        are consumed. A frame cut at EOF raises CorruptBatchError: appends
        are whole-frame and recovery truncates torn tails at open, so a
        partial frame is corruption, never a legitimate state."""
        self.flush_buffer()
        if self._file:
            self._file.flush()
        with open(self.data_path, "rb") as f:
            f.seek(pos)
            blob = bytearray(f.read(chunk))
            base = pos  # file offset of blob[0]
            at = 0  # decode position within blob
            while True:
                if at >= chunk:
                    del blob[:at]
                    base += at
                    at = 0
                if at + INTERNAL_HEADER_SIZE > len(blob):
                    more = f.read(chunk)
                    if not more:
                        if at < len(blob):
                            raise CorruptBatchError(
                                f"partial batch header at EOF ({self.data_path}"
                                f" pos {base + at})"
                            )
                        return
                    blob += more
                    continue
                frame_len = RecordBatch.peek_size(blob, at)
                if at + frame_len > len(blob):
                    more = f.read(chunk)
                    if not more:
                        raise CorruptBatchError(
                            f"batch frame overruns EOF ({self.data_path} pos "
                            f"{base + at}, size_bytes={frame_len})"
                        )
                    blob += more
                    continue
                batch, consumed = RecordBatch.decode_internal(blob, at)
                at += consumed
                yield batch, base + at

    def first_offset_with_ts(self, ts: int) -> int | None:
        """First batch offset whose max_timestamp >= ts (index-accelerated).

        Bounded chunked reads via the shared frame iterator: a timequery
        that resolves near the index point must not slurp the rest of the
        segment file; corruption raises loudly like every read path."""
        pos = self.index.lookup_time(ts)
        for batch, _end in self._frames_from(pos, 1 << 20):
            if batch.header.max_timestamp >= ts:
                return batch.base_offset
        return None

    def rebuild_index(self, blob: bytes | None = None):
        """Recreate the sparse index (and dirty/max_ts) by scanning the data."""
        self.index.entries = []
        self.index._acc_bytes = 0
        self.dirty_offset = self.base_offset - 1
        self.max_timestamp = -1
        if blob is None:
            blob = self.read_from(0)
        at = 0
        while at + INTERNAL_HEADER_SIZE <= len(blob):
            try:
                batch, consumed = RecordBatch.decode_internal(blob, at)
            except Exception:
                break
            self.index.maybe_track(batch.header, at)
            self.dirty_offset = batch.last_offset
            self.max_timestamp = max(self.max_timestamp, batch.header.max_timestamp)
            at += consumed

    def truncate_to_file_pos(self, file_pos: int, new_dirty: int, new_max_ts: int = -1):
        self.flush_buffer()
        was_writable = self._file is not None
        if self._file:
            self._file.close()
        with open(self.data_path, "r+b") as f:
            f.truncate(file_pos)
        self.size_bytes = file_pos
        self.dirty_offset = new_dirty
        self.max_timestamp = new_max_ts
        self.index.truncate_at_pos(file_pos)
        if was_writable:
            self._file = file_sanitizer.maybe_wrap(
                open(self.data_path, "ab"), self.data_path
            )

    def remove(self):
        self.release_appender()
        for p in (self.data_path, self.index.path):
            try:
                os.remove(p)
            except FileNotFoundError:
                pass

    def __repr__(self):
        return f"Segment(base={self.base_offset}, term={self.term}, size={self.size_bytes})"
