"""Global LRU batch cache fronting segment reads.

Reference: storage/batch_cache.h:99 — one process-wide LRU of decoded
record batches with a byte budget, integrated with the memory reclaimer;
readers check it before touching segment files (batch_cache_index per log).
Here the budget is a plain byte cap (the asyncio runtime has no Seastar
reclaimer; the kafka layer's MemoryBudget guards request memory
separately), eviction is LRU, and each DiskLog holds an index keyed by
batch base offset with bisect range lookup.

Invalidation rules (all enforced by DiskLog calling ``invalidate``):
- suffix truncate(offset): drop every cached batch with last_offset >= offset
- prefix_truncate(offset): drop every batch below the new start
- compaction rewrites a segment in place: drop the log's whole index
- close/remove: drop the log's whole index
"""

from __future__ import annotations

from bisect import bisect_right, insort
from collections import OrderedDict

from redpanda_tpu.models.record import RecordBatch


class BatchCache:
    """Process-wide LRU over decoded batches, byte-budgeted."""

    def __init__(self, max_bytes: int = 64 << 20):
        self.max_bytes = max_bytes
        self._bytes = 0
        # (log_key, base_offset) -> RecordBatch, in LRU order (oldest first)
        self._lru: "OrderedDict[tuple[int, int], RecordBatch]" = OrderedDict()
        # log_key -> sorted [base_offset]
        self._index: dict[int, list[int]] = {}
        self.hits = 0
        self.misses = 0

    # ------------------------------------------------------------ lookup
    def get(self, log_key: int, offset: int) -> RecordBatch | None:
        """The cached batch COVERING `offset`, else None."""
        bases = self._index.get(log_key)
        if not bases:
            self.misses += 1
            return None
        i = bisect_right(bases, offset) - 1
        if i < 0:
            self.misses += 1
            return None
        key = (log_key, bases[i])
        b = self._lru.get(key)
        if b is None or b.last_offset < offset:
            self.misses += 1
            return None
        self._lru.move_to_end(key)
        self.hits += 1
        return b

    # ------------------------------------------------------------ insert
    def put(self, log_key: int, batch: RecordBatch) -> None:
        if batch.size_bytes > self.max_bytes:
            return
        key = (log_key, batch.header.base_offset)
        old = self._lru.pop(key, None)
        if old is not None:
            self._bytes -= old.size_bytes
        self._lru[key] = batch
        if old is None:
            insort(self._index.setdefault(log_key, []), batch.header.base_offset)
        self._bytes += batch.size_bytes
        while self._bytes > self.max_bytes and self._lru:
            (lk, base), evicted = self._lru.popitem(last=False)
            self._bytes -= evicted.size_bytes
            bases = self._index.get(lk)
            if bases:
                i = bisect_right(bases, base) - 1
                if i >= 0 and bases[i] == base:
                    bases.pop(i)
                if not bases:
                    del self._index[lk]

    # ------------------------------------------------------------ invalidate
    def invalidate(
        self,
        log_key: int,
        *,
        from_offset: int | None = None,
        below_offset: int | None = None,
    ) -> None:
        """Drop cached batches of one log: everything (no bounds), the
        suffix with last_offset >= from_offset, or the prefix with
        base_offset < below_offset."""
        bases = self._index.get(log_key)
        if not bases:
            return
        keep: list[int] = []
        for base in bases:
            key = (log_key, base)
            b = self._lru.get(key)
            if b is None:
                continue
            drop = True
            if from_offset is not None:
                drop = b.last_offset >= from_offset
            elif below_offset is not None:
                drop = base < below_offset
            if drop:
                del self._lru[key]
                self._bytes -= b.size_bytes
            else:
                keep.append(base)
        if keep:
            self._index[log_key] = keep
        else:
            self._index.pop(log_key, None)

    # ------------------------------------------------------------ stats
    @property
    def bytes_used(self) -> int:
        return self._bytes

    def stats(self) -> dict:
        return {
            "hits": self.hits,
            "misses": self.misses,
            "bytes_used": self._bytes,
            "max_bytes": self.max_bytes,
            "batches": len(self._lru),
        }
