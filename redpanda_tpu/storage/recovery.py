"""Crash-recovery CRC scan of a segment tail (parity with storage/
log_replayer.h and the header-CRC validation in storage/parser.cc:159-173).

The scan walks [header][payload] frames; each header's header_crc and each
batch's Kafka CRC must verify. The host path validates with the native CRC;
the device path packs every frame of the segment into one [N, R] staging
array and validates all CRCs in a single batched kernel — the first
internal consumer of the produce-path validator (SURVEY §7 step 2).

The segment is truncated at the first corrupt frame (everything after a
torn write is discarded, as the reference does).
"""

from __future__ import annotations

import numpy as np

from redpanda_tpu.models.record import (
    INTERNAL_HEADER_SIZE,
    CorruptBatchError,
    RecordBatch,
    RecordBatchHeader,
)


def scan_valid_prefix_host(blob: bytes) -> tuple[int, int]:
    """Returns (valid_byte_length, last_valid_offset)."""
    at = 0
    last_offset = -1
    n = len(blob)
    while at + INTERNAL_HEADER_SIZE <= n:
        try:
            batch, consumed = RecordBatch.decode_internal(blob, at, verify=True)
        except CorruptBatchError:
            break
        if not batch.verify_kafka_crc():
            break
        last_offset = batch.last_offset
        at += consumed
    return at, last_offset


def scan_valid_prefix_device(blob: bytes, row_stride: int = 4096) -> tuple[int, int]:
    """Device-batched variant: frame boundaries come from the headers (host,
    cheap), every frame's Kafka CRC validates in one kernel launch."""
    frames: list[tuple[int, RecordBatchHeader]] = []
    at = 0
    n = len(blob)
    while at + INTERNAL_HEADER_SIZE <= n:
        try:
            hdr = RecordBatchHeader.decode(blob, at)
        except Exception:
            break
        if hdr.size_bytes < INTERNAL_HEADER_SIZE or at + hdr.size_bytes > n:
            break
        if hdr.header_crc != hdr.internal_header_only_crc():
            break
        if hdr.size_bytes - INTERNAL_HEADER_SIZE + 40 > row_stride:
            # frame too large for the staging row: fall back to host CRC
            return scan_valid_prefix_host(blob)
        frames.append((at, hdr))
        at += hdr.size_bytes
    if not frames:
        return 0, -1
    from redpanda_tpu.ops.crc32c_device import make_crc_fn

    rows = np.zeros((len(frames), row_stride), dtype=np.uint8)
    lens = np.zeros(len(frames), dtype=np.int32)
    claimed = np.zeros(len(frames), dtype=np.uint32)
    for i, (pos, hdr) in enumerate(frames):
        prefix = hdr.kafka_header_crc_prefix()
        payload = blob[pos + INTERNAL_HEADER_SIZE : pos + hdr.size_bytes]
        row = prefix + payload
        rows[i, : len(row)] = np.frombuffer(row, dtype=np.uint8)
        lens[i] = len(row)
        claimed[i] = hdr.crc
    got = np.asarray(make_crc_fn(row_stride)(rows, lens))
    ok = got == claimed
    bad = ~ok
    valid = int(np.argmax(bad)) if bad.any() else len(frames)
    if valid == 0:
        return 0, -1
    end_pos, last_hdr = frames[valid - 1]
    return end_pos + last_hdr.size_bytes, last_hdr.base_offset + last_hdr.last_offset_delta


def recover_segment(seg, *, use_device: bool = False) -> None:
    """Truncate `seg` after its last intact batch and rebuild its index.

    Single read: the blob is scanned once for CRC validity and the surviving
    prefix is handed to rebuild_index (which also resets dirty_offset and
    max_timestamp — crucial when the whole tail is corrupt and the stale
    index footer would otherwise claim offsets that no longer exist)."""
    blob = seg.read_from(0)
    if use_device:
        valid_len, _last_offset = scan_valid_prefix_device(blob)
    else:
        valid_len, _last_offset = scan_valid_prefix_host(blob)
    if valid_len < len(blob):
        with open(seg.data_path, "r+b") as f:
            f.truncate(valid_len)
        seg.size_bytes = valid_len
        blob = blob[:valid_len]
    seg.rebuild_index(blob)
