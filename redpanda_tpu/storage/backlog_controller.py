"""Backlog-driven compaction pacing.

Parity with the reference's compaction_controller/backlog_controller
(storage/backlog_controller.h, configured in application.cc:445-489): a
proportional controller samples the compaction backlog each housekeeping
tick and converts the error against a setpoint into scheduling pressure.
The reference actuates Seastar scheduling-group shares; this runtime's
actuator is the compaction cadence — idle logs are visited lazily at
`max_interval_s`, and as backlog grows past the setpoint the interval
shrinks toward `min_interval_s` so compaction keeps up with produce rate
instead of letting closed segments pile up.
"""

from __future__ import annotations

from dataclasses import dataclass


@dataclass
class BacklogController:
    setpoint_bytes: int = 64 << 20  # backlog we tolerate before pressure
    kp: float = 2.0  # proportional gain on the backlog ratio
    min_interval_s: float = 0.5
    max_interval_s: float = 10.0
    last_backlog: int = 0
    last_interval: float = 0.0

    def update(self, backlog_bytes: int) -> float:
        """Next compaction-pass interval for the measured backlog."""
        self.last_backlog = backlog_bytes
        error = (backlog_bytes - self.setpoint_bytes) / max(self.setpoint_bytes, 1)
        if error <= 0:
            interval = self.max_interval_s
        else:
            # pressure grows with the backlog ratio; clamped to the floor
            interval = max(
                self.min_interval_s, self.max_interval_s / (1.0 + self.kp * error)
            )
        self.last_interval = interval
        return interval
