"""Debug file-handle sanitizer.

Parity with the reference's file_io_sanitizer (utils/file_sanitizer.h:51,
armed by the `storage::debug_sanitize_files` knob on log_config/kvstore
config, application.cc:418,429): in debug runs, long-lived storage file
handles are wrapped so misuse — writing or fsyncing a closed handle,
closing twice, leaking an open handle at shutdown — raises at the misuse
site with the original open() location attached, instead of surfacing
later as silent data loss or an EBADF on an unrelated fd.

Process-global arm/disarm mirrors the reference's config knob; wrapping is
zero-cost when disarmed (`maybe_wrap` returns the raw handle).
"""

from __future__ import annotations

import os
import traceback

_enabled = False
_open_files: dict[int, "SanitizedFile"] = {}


class FileSanitizerError(RuntimeError):
    pass


def enable() -> None:
    global _enabled
    _enabled = True


def disable() -> None:
    global _enabled
    _enabled = False
    _open_files.clear()


def enabled() -> bool:
    return _enabled


class SanitizedFile:
    """Wraps a file object; every op checks liveness first."""

    def __init__(self, f, path: str):
        self._f = f
        self._path = path
        self._closed = False
        self._opened_at = "".join(traceback.format_stack(limit=8)[:-1])
        _open_files[id(self)] = self

    def _check(self, op: str) -> None:
        if self._closed:
            raise FileSanitizerError(
                f"{op} on closed file {self._path!r}\nopened at:\n{self._opened_at}"
            )

    def write(self, data):
        self._check("write")
        return self._f.write(data)

    def flush(self):
        self._check("flush")
        return self._f.flush()

    def fileno(self):
        self._check("fileno")
        return self._f.fileno()

    def close(self):
        if self._closed:
            raise FileSanitizerError(
                f"double close of {self._path!r}\nopened at:\n{self._opened_at}"
            )
        self._closed = True
        _open_files.pop(id(self), None)
        return self._f.close()

    def __getattr__(self, name):
        # reads/seeks pass through but still require a live handle
        self._check(name)
        return getattr(self._f, name)


def maybe_wrap(f, path: str):
    """Wrap when armed, return the raw handle otherwise."""
    return SanitizedFile(f, path) if _enabled else f


def verify_all_closed(prefix: str | None = None) -> list[str]:
    """Shutdown check: paths of handles never closed (leaks), cleared from
    the registry as they are reported.

    The arm knob is process-global (matching the reference's debug-build
    flag), but MULTIPLE storage instances can coexist in one process
    (in-process multi-node fixtures) — pass `prefix` (a base directory) so
    one instance's shutdown only reports and clears its own handles
    instead of wiping another instance's live ones."""
    if prefix is not None:
        # path-separator boundary: '<tmp>/d' must not claim '<tmp>/d2'
        prefix = prefix.rstrip(os.sep) + os.sep
    doomed = [
        key
        for key, sf in _open_files.items()
        if prefix is None or sf._path.startswith(prefix)
    ]
    leaked = [_open_files.pop(key)._path for key in doomed]
    return leaked
