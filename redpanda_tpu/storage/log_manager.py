"""Log manager + storage API facade.

Parity with storage/api.h:20 (`storage::api` = log_manager + kvstore) and
log_manager.h:171 (`manage(ntp)` creates/opens the per-ntp log, housekeeping
applies retention).
"""

from __future__ import annotations

import asyncio
import logging
import os
import time

from redpanda_tpu.models.fundamental import NTP
from redpanda_tpu.observability import probes
from redpanda_tpu.storage.kvstore import KvStore
from redpanda_tpu.storage.log import DiskLog, LogConfig


class LogManager:
    def __init__(self, config: LogConfig, *, batch_cache_bytes: int = 64 << 20):
        from redpanda_tpu.storage.batch_cache import BatchCache
        from redpanda_tpu.storage.readers_cache import ReadersCache

        self.config = config
        self._logs: dict[NTP, DiskLog] = {}
        self._housekeeping_task: asyncio.Task | None = None
        self._compaction_task: asyncio.Task | None = None
        # ONE cache across every managed log (batch_cache.h:99 is a global
        # LRU): hot partitions naturally take budget from cold ones
        self.batch_cache = BatchCache(batch_cache_bytes)
        # positioned read cursors for sequential fetch (readers_cache.h:36)
        self.readers_cache = ReadersCache()
        # set by start_housekeeping; pacing state exported as metrics
        self.backlog_controller = None

    async def manage(self, ntp: NTP, *, overrides: LogConfig | None = None) -> DiskLog:
        if ntp in self._logs:
            return self._logs[ntp]
        log = await DiskLog.open(ntp, overrides or self.config)
        log.batch_cache = self.batch_cache
        log.readers_cache = self.readers_cache
        self._logs[ntp] = log
        return log

    def get(self, ntp: NTP) -> DiskLog | None:
        return self._logs.get(ntp)

    def logs(self) -> dict[NTP, DiskLog]:
        return dict(self._logs)

    async def shutdown(self, ntp: NTP):
        log = self._logs.pop(ntp, None)
        if log:
            await log.close()

    async def remove(self, ntp: NTP):
        log = self._logs.pop(ntp, None)
        if log:
            await log.remove()

    def compaction_backlog(self) -> int:
        """Total compaction backlog across managed logs (controller PV)."""
        return sum(log.compaction_backlog() for log in self._logs.values())

    async def start_housekeeping(
        self, interval_s: float = 10.0, compaction_interval_s: float | None = None
    ):
        """Retention + compaction fibers (log_manager housekeeping). The
        compaction cadence is backlog-driven: `compaction_interval_s` (or
        `interval_s`) is the controller's lazy ceiling, and the pass rate
        rises as closed un-compacted bytes pile past the setpoint
        (compaction_controller/backlog_controller.h posture)."""
        from redpanda_tpu.storage.backlog_controller import BacklogController

        ceiling = (
            compaction_interval_s if compaction_interval_s is not None else interval_s
        )
        self.backlog_controller = BacklogController(
            max_interval_s=ceiling, min_interval_s=min(0.5, ceiling)
        )

        async def housekeep_once(log) -> None:
            t0 = time.perf_counter()
            policy = log.config.cleanup_policy
            if "delete" in policy:
                await log.apply_retention()
            probes.observe_us(probes.storage_housekeeping_hist, t0)

        async def loop():
            while True:
                await asyncio.sleep(interval_s)
                for log in list(self._logs.values()):
                    try:
                        await housekeep_once(log)
                    except Exception:
                        pass

        async def compaction_loop():
            while True:
                # one backlog sample drives both the interval and the order
                backlogs = {
                    log: log.compaction_backlog() for log in self._logs.values()
                }
                await asyncio.sleep(
                    self.backlog_controller.update(sum(backlogs.values()))
                )
                # biggest backlog first, so pressure relieves fastest
                for log in sorted(backlogs, key=backlogs.get, reverse=True):
                    if not log.is_compacted:
                        continue
                    try:
                        t0 = time.perf_counter()
                        await log.compact()
                        probes.observe_us(probes.storage_housekeeping_hist, t0)
                    except Exception:
                        pass

        self._housekeeping_task = asyncio.create_task(loop())
        self._compaction_task = asyncio.create_task(compaction_loop())

    async def stop(self):
        for task_attr in ("_housekeeping_task", "_compaction_task"):
            task = getattr(self, task_attr, None)
            if task:
                task.cancel()
                try:
                    await task
                except asyncio.CancelledError:
                    pass
                setattr(self, task_attr, None)
        for log in self._logs.values():
            await log.close()
        self._logs.clear()


class StorageApi:
    """storage::api equivalent: one kvstore + one log_manager per shard."""

    def __init__(self, base_dir: str, log_config: LogConfig | None = None, shard: int = 0):
        self.base_dir = base_dir
        cfg = log_config or LogConfig(base_dir=os.path.join(base_dir, "data"))
        self.log_mgr = LogManager(cfg)
        self.kvs = KvStore(os.path.join(base_dir, f"kvstore-{shard}"))

    async def start(self) -> "StorageApi":
        self.kvs.start()
        return self

    async def stop(self):
        await self.log_mgr.stop()
        self.kvs.stop()
        from redpanda_tpu.storage import file_sanitizer

        if file_sanitizer.enabled():
            # scope to this instance's tree: another StorageApi in the same
            # process (multi-node fixtures) keeps its live handles
            leaked = file_sanitizer.verify_all_closed(prefix=self.base_dir)
            if leaked:
                logging.getLogger("rptpu.storage").warning(
                    "file sanitizer: %d handle(s) leaked at shutdown: %s",
                    len(leaked), leaked,
                )
