from redpanda_tpu.storage.log import DiskLog, LogConfig, AppendResult, LogOffsets
from redpanda_tpu.storage.log_manager import LogManager, StorageApi
from redpanda_tpu.storage.kvstore import KvStore, KeySpace
from redpanda_tpu.storage.snapshot import SnapshotManager, write_snapshot, read_snapshot
from redpanda_tpu.storage.mem_log import MemLog

__all__ = [
    "DiskLog",
    "LogConfig",
    "AppendResult",
    "LogOffsets",
    "LogManager",
    "StorageApi",
    "KvStore",
    "KeySpace",
    "SnapshotManager",
    "write_snapshot",
    "read_snapshot",
    "MemLog",
]
