"""Per-NTP append-only segmented log.

Capability parity with the reference's storage/disk_log_impl.h behind the
storage/log.h pimpl interface: append / read / flush / truncate /
prefix-truncate (eviction) / timequery / segment roll / retention, with
recovery via a CRC scan of the tail segment (log_replayer.h) that can run
as one batched device kernel.

Design note (TPU-first): the log keeps batches byte-contiguous on disk in
the internal layout so recovery and compaction hashing feed the device CRC
kernel without re-framing; readers return RecordBatch views whose payloads
slice directly out of the read blob.
"""

from __future__ import annotations

import asyncio
import os
import time
from dataclasses import dataclass, field

from redpanda_tpu.finjector import honey_badger
from redpanda_tpu.models.fundamental import NTP
from redpanda_tpu.models.record import RecordBatch
from redpanda_tpu.observability import probes
from redpanda_tpu.observability.trace import tracer
from redpanda_tpu.storage.segment import Segment
from redpanda_tpu.storage.recovery import recover_segment

# storage failure probes (reference storage/failure_probes.h:24
# log_failure_probes {append, roll, truncate}, driven over the admin
# honey-badger API like tests/rptest services/honey_badger.py)
honey_badger.register_probe("storage", "log_append", "log_roll", "log_truncate")


@dataclass
class LogConfig:
    base_dir: str = "/tmp/redpanda_tpu_data"
    max_segment_size: int = 128 * 1024 * 1024
    segment_age_s: float = float("inf")
    retention_bytes: int | None = None
    retention_ms: int | None = None
    fsync_on_append: bool = False
    use_device_recovery: bool = False  # batch CRC scan on the TPU
    # cleanup.policy: "delete", "compact", or "compact,delete"
    cleanup_policy: str = "delete"
    # debug file-handle sanitizer (storage::debug_sanitize_files)
    sanitize_files: bool = False
    delete_retention_ms: int | None = 86_400_000  # tombstone retention
    compaction_max_keys_in_memory: int = 128 * 1024  # key-index spill bound


@dataclass
class AppendResult:
    base_offset: int
    last_offset: int
    byte_size: int


@dataclass
class LogOffsets:
    start_offset: int
    dirty_offset: int  # highest appended
    committed_offset: int  # highest fsynced


class DiskLog:
    def __init__(self, ntp: NTP, config: LogConfig):
        self.ntp = ntp
        self.config = config
        self.dir = os.path.join(config.base_dir, ntp.path())
        self.segments: list[Segment] = []
        self._start_offset = 0
        self._committed = -1
        self._active_created_at = 0.0
        self._lock = asyncio.Lock()
        self._term = 0
        # sync callables (type, base_offset, last_offset) fired per appended
        # batch under the log lock; truncation listeners get (offset)
        self.append_listeners: list = []
        self.truncate_listeners: list = []
        # global LRU fronting segment reads (batch_cache.h:99); assigned by
        # the LogManager, None in bare/standalone usage
        self.batch_cache = None
        # positioned-cursor cache for sequential fetch continuation
        # (readers_cache.h:36); assigned by the LogManager like batch_cache
        self.readers_cache = None

    def _cache_put(self, batch: RecordBatch) -> None:
        if self.batch_cache is not None:
            self.batch_cache.put(id(self), batch)

    def _cache_invalidate(self, **kw) -> None:
        if self.batch_cache is not None:
            self.batch_cache.invalidate(id(self), **kw)
        if self.readers_cache is not None:
            self.readers_cache.invalidate(id(self), **kw)

    # ------------------------------------------------------------ lifecycle
    @classmethod
    async def open(cls, ntp: NTP, config: LogConfig) -> "DiskLog":
        if config.sanitize_files:
            from redpanda_tpu.storage import file_sanitizer

            file_sanitizer.enable()
        log = cls(ntp, config)
        # The segment scan + tail CRC recovery is pure disk work on an
        # object nothing else references yet; inline it and a node restart
        # with many partitions would stall every other recovery on the loop.
        await asyncio.to_thread(log._open_sync)
        return log

    def _open_sync(self) -> None:
        os.makedirs(self.dir, exist_ok=True)
        stems = sorted(
            (f for f in os.listdir(self.dir) if f.endswith(".log")),
            key=lambda f: int(f.split("-")[0]),
        )
        for i, fname in enumerate(stems):
            base, term, _ = fname.split("-", 2)
            seg = Segment(self.dir, int(base), int(term))
            last = i == len(stems) - 1
            seg.open_existing(writable=False)
            if last:
                # CRC-scan the tail (crash recovery), truncating at the
                # first corrupt frame, then reopen for append.
                recover_segment(seg, use_device=self.config.use_device_recovery)
                seg._file = open(seg.data_path, "ab")
            self.segments.append(seg)
            self._term = max(self._term, seg.term)
        if self.segments:
            self._start_offset = self.segments[0].base_offset
            self._committed = self.segments[-1].dirty_offset
            self._active_created_at = time.monotonic()

    async def close(self):
        async with self._lock:
            self._cache_invalidate()
            for seg in self.segments:
                seg.close()

    async def remove(self):
        async with self._lock:
            self._cache_invalidate()
            for seg in self.segments:
                seg.remove()
            self.segments.clear()
            try:
                os.removedirs(self.dir)
            except OSError:
                pass

    # ------------------------------------------------------------ offsets
    def offsets(self) -> LogOffsets:
        dirty = self.segments[-1].dirty_offset if self.segments else self._start_offset - 1
        return LogOffsets(self._start_offset, dirty, self._committed)

    @property
    def term(self) -> int:
        return self._term

    # ------------------------------------------------------------ append
    async def append(
        self, batches: list[RecordBatch], *, term: int | None = None, assign_offsets: bool = True
    ) -> AppendResult:
        """Append sealed batches; assigns monotone base offsets by default."""
        if not batches:
            off = self.offsets()
            return AppendResult(off.dirty_offset + 1, off.dirty_offset, 0)
        # storage account (resource_mgmt budget plane): append-buffer bytes
        # inflight through this call. Waiting (not shedding) is correct
        # here — every producer of appends sits behind an admission gate
        # (kafka produce, coproc submit, rpc dispatch), so the wait is
        # bounded backpressure and peak occupancy never breaches the
        # account. Plane-less processes skip both branches.
        from redpanda_tpu.resource_mgmt import budgets as _budgets

        acct = _budgets.account_or_none("storage")
        reserved = 0
        if acct is not None:
            reserved = await acct.acquire(
                sum(b.size_bytes for b in batches)
            )
        t_probe = time.perf_counter()
        try:
            with tracer.span("storage.append"):
                return await self._append_locked(batches, term, assign_offsets)
        finally:
            if acct is not None:
                acct.release(reserved)
            probes.observe_us(probes.storage_append_hist, t_probe)

    async def _append_locked(
        self, batches: list[RecordBatch], term: int | None, assign_offsets: bool
    ) -> AppendResult:
        async with self._lock:
            honey_badger.inject_sync("storage", "log_append")
            if term is not None and term > self._term:
                self._term = term
            seg = self._active_segment_for_append()
            next_offset = seg.dirty_offset + 1
            first = None
            size = 0
            for batch in batches:
                if assign_offsets:
                    batch = batch.with_base_offset(next_offset)
                    batch.header.term = self._term
                elif batch.header.term < 0:
                    batch.header.term = self._term
                else:
                    # Follower-path append: batches arrive with the leader's
                    # term already stamped; adopt it (terms may also go DOWN
                    # after a divergent suffix was truncated).
                    self._term = batch.header.term
                if first is None:
                    first = batch.base_offset
                # The segment filename is the durable term record (the packed
                # header has no term field), so the active segment's term must
                # match every batch written into it.
                seg = self._segment_for_term(seg, batch.header.term)
                seg = self._maybe_roll(seg)
                seg.append(batch)
                # hot tail into the cache: fetch-after-produce never touches
                # the segment file (batch_cache put-on-append)
                self._cache_put(batch)
                size += batch.size_bytes
                next_offset = batch.last_offset + 1
                for fn in self.append_listeners:
                    fn(batch.header.type, batch.base_offset, batch.last_offset)
            if self.config.fsync_on_append:
                seg.fsync()
                self._committed = seg.dirty_offset
            last = next_offset - 1
            return AppendResult(first if first is not None else last + 1, last, size)

    def _active_segment_for_append(self) -> Segment:
        if not self.segments or not self.segments[-1].writable:
            base = self.offsets().dirty_offset + 1
            seg = Segment(self.dir, base, self._term).create()
            self.segments.append(seg)
            self._active_created_at = time.monotonic()
            return seg
        return self.segments[-1]

    def _segment_for_term(self, seg: Segment, term: int) -> Segment:
        """Roll (or, if still empty, replace) the active segment so its
        filename term matches `term`."""
        if seg.term == term:
            return seg
        if seg.size_bytes == 0:
            # Nothing written yet: replace it so no batch is ever mislabeled.
            base = seg.base_offset
            seg.remove()
            self.segments.pop()
        else:
            base = seg.dirty_offset + 1
            seg.release_appender()
        new = Segment(self.dir, base, term).create()
        self.segments.append(new)
        self._active_created_at = time.monotonic()
        return new

    def _maybe_roll(self, seg: Segment) -> Segment:
        too_big = seg.size_bytes >= self.config.max_segment_size
        too_old = (
            seg.size_bytes > 0
            and (time.monotonic() - self._active_created_at) >= self.config.segment_age_s
        )
        if too_big or too_old:
            honey_badger.inject_sync("storage", "log_roll")
            seg.release_appender()
            new = Segment(self.dir, seg.dirty_offset + 1, self._term).create()
            self.segments.append(new)
            self._active_created_at = time.monotonic()
            return new
        return seg

    async def flush(self):
        async with self._lock:
            if self.segments:
                self.segments[-1].fsync()
                self._committed = self.segments[-1].dirty_offset

    # ------------------------------------------------------------ read
    async def read(
        self,
        start_offset: int,
        max_bytes: int = 1 << 20,
        *,
        max_offset: int | None = None,
        type_filter=None,
    ) -> list[RecordBatch]:
        async with self._lock:
            start = max(start_offset, self._start_offset)
            cached = self._read_cached(start, max_bytes, max_offset, type_filter)
            if cached is not None:
                return cached
            out: list[RecordBatch] = []
            taken = 0
            # adopt a cached read cursor for the first touched segment: the
            # scan seeks straight to the frame boundary instead of going
            # through the sparse index (readers_cache.h continuation)
            cursor = (
                self.readers_cache.get(id(self), start)
                if self.readers_cache is not None
                else None
            )
            end_seg = end_pos = None
            for seg in self.segments:
                if seg.dirty_offset < start:
                    continue
                if max_offset is not None and seg.base_offset > max_offset:
                    break
                start_pos = None
                if cursor is not None and cursor.segment_base == seg.base_offset:
                    start_pos = cursor.file_pos
                cursor = None  # only valid for the first segment touched
                batches, next_pos = seg.scan(
                    start,
                    max_bytes - taken,
                    type_filter=type_filter,
                    max_offset=max_offset,
                    start_pos=start_pos,
                )
                end_seg, end_pos = seg, next_pos
                for b in batches:
                    out.append(b)
                    self._cache_put(b)
                    taken += b.size_bytes
                if taken >= max_bytes:
                    break
                if out:
                    start = out[-1].last_offset + 1
            if self.readers_cache is not None and out and end_seg is not None:
                from redpanda_tpu.storage.readers_cache import ReadCursor

                self.readers_cache.put(
                    id(self),
                    out[-1].last_offset + 1,
                    ReadCursor(end_seg.base_offset, end_pos),
                )
            return out

    def _read_cached(self, start, max_bytes, max_offset, type_filter):
        """Serve the read purely from the batch cache, or None.

        Only a COMPLETE answer counts: the cached chain must run unbroken
        from `start` to the dirty offset / max_offset / byte budget —
        a mid-range miss falls back to the segment scan (which re-populates
        the cache), so callers never see a silently shortened read."""
        if self.batch_cache is None or not self.segments:
            return None
        end = self.segments[-1].dirty_offset
        if max_offset is not None:
            end = min(end, max_offset)
        out: list[RecordBatch] = []
        taken = 0
        cur = start
        key = id(self)
        while cur <= end and taken < max_bytes:
            b = self.batch_cache.get(key, cur)
            if b is None:
                return None  # chain broken: not a complete answer
            if type_filter is None or b.header.type in type_filter:
                out.append(b)
                taken += b.size_bytes
            cur = b.last_offset + 1
        return out

    async def timequery(self, ts: int) -> int | None:
        """First offset with max_timestamp >= ts (storage timequery)."""
        async with self._lock:
            for seg in self.segments:
                if seg.max_timestamp >= ts:
                    off = seg.first_offset_with_ts(ts)
                    if off is not None:
                        return off
            return None

    # ------------------------------------------------------------ truncate
    async def truncate(self, offset: int):
        """Drop everything at and after `offset` (suffix truncation)."""
        async with self._lock:
            honey_badger.inject_sync("storage", "log_truncate")
            self._cache_invalidate(from_offset=offset)
            keep: list[Segment] = []
            for seg in self.segments:
                if seg.dirty_offset < offset:
                    keep.append(seg)
                    continue
                if seg.base_offset >= offset:
                    seg.remove()
                    continue
                # partial: find the file position of the first batch >= offset
                blob = seg.read_from(0)
                at = 0
                new_dirty = seg.base_offset - 1
                new_max_ts = -1
                from redpanda_tpu.models.record import INTERNAL_HEADER_SIZE

                while at + INTERNAL_HEADER_SIZE <= len(blob):
                    batch, consumed = RecordBatch.decode_internal(blob, at)
                    if batch.last_offset >= offset:
                        break
                    new_dirty = batch.last_offset
                    new_max_ts = max(new_max_ts, batch.header.max_timestamp)
                    at += consumed
                seg.truncate_to_file_pos(at, new_dirty, new_max_ts)
                keep.append(seg)
            self.segments = keep
            self._committed = min(self._committed, self.offsets().dirty_offset)
            for fn in self.truncate_listeners:
                fn(offset)

    async def prefix_truncate(self, offset: int):
        """Evict whole segments below `offset` (retention / raft snapshot)."""
        async with self._lock:
            self._cache_invalidate(below_offset=offset)
            while self.segments and self.segments[0].dirty_offset < offset and (
                len(self.segments) > 1 or not self.segments[0].writable
            ):
                self.segments.pop(0).remove()
            self._start_offset = max(self._start_offset, offset)

    # ------------------------------------------------------------ compaction
    @property
    def is_compacted(self) -> bool:
        return "compact" in self.config.cleanup_policy

    def compaction_backlog(self) -> int:
        """Closed-segment bytes accumulated SINCE the last compaction pass —
        the controller's process variable (backlog_controller.h). Measured
        against the post-compaction closed-bytes baseline so steady trickle
        appends into the active segment read as zero backlog (total closed
        bytes would keep the controller pinned at max pressure forever)."""
        if not self.is_compacted:
            return 0
        if getattr(self, "_compacted_through", None) == self.offsets().dirty_offset:
            return 0
        closed = sum(s.size_bytes for s in self.segments if not s.writable)
        return max(0, closed - getattr(self, "_compacted_closed_bytes", 0))

    async def compact(self) -> tuple[int, int]:
        """Self-compact all closed segments (storage/compaction.py); no-op
        until new data has arrived since the previous pass."""
        offs = self.offsets()
        if getattr(self, "_compacted_through", None) == offs.dirty_offset:
            return 0, 0
        from redpanda_tpu.storage.compaction import compact_log

        result = await compact_log(
            self,
            delete_retention_ms=self.config.delete_retention_ms,
            max_keys_in_memory=self.config.compaction_max_keys_in_memory,
        )
        # compaction rewrote segment contents in place: cached batches for
        # dropped keys would resurrect them on a cache-served fetch
        self._cache_invalidate()
        self._compacted_through = offs.dirty_offset
        # baseline for the backlog measure: closed bytes as they stand
        # post-rewrite, so only NEW closed data counts as backlog
        self._compacted_closed_bytes = sum(
            s.size_bytes for s in self.segments if not s.writable
        )
        return result

    # ------------------------------------------------------------ retention
    async def apply_retention(self):
        cfg = self.config
        if cfg.retention_bytes is not None:
            total = sum(s.size_bytes for s in self.segments)
            while len(self.segments) > 1 and total > cfg.retention_bytes:
                seg = self.segments[0]
                total -= seg.size_bytes
                await self.prefix_truncate(seg.dirty_offset + 1)
        if cfg.retention_ms is not None:
            cutoff = int(time.time() * 1000) - cfg.retention_ms
            while len(self.segments) > 1 and self.segments[0].max_timestamp < cutoff and self.segments[0].max_timestamp >= 0:
                await self.prefix_truncate(self.segments[0].dirty_offset + 1)
