"""Log compaction: key-index build (with disk spill) + segment rewrite.

Behavior parity with the reference's storage compaction stack
(segment_utils.cc:517 self_compact_segment, spill_key_index.cc,
compaction_reducers.h), redesigned for this engine's storage layout:

- A whole-log key index maps record key -> the highest log offset holding
  that key. It is built oldest->newest in one pass; when it outgrows the
  in-memory bound it spills sorted runs to disk and stream-merges them
  (the reference's spill_key_index writes compacted-index files for the
  same reason: bounded memory over unbounded key spaces).
- Every CLOSED segment is rewritten in place (atomic tmp+rename): a data
  record survives only if it is the latest occurrence of its key.
  Offsets are immutable — surviving records keep their original
  offset_delta, batch headers keep base_offset and last_offset_delta, so
  compaction only ever creates gaps, never renumbers (Kafka semantics).
- Non-data batches (raft config, control markers, tx markers) pass through
  verbatim: compaction applies to the Kafka data plane only.
- The final batch of each segment is never dropped outright (it shrinks to
  record_count=0 if everything in it is shadowed) so the segment's dirty
  offset — and with it the log's next-offset accounting — is preserved.
- Tombstones (null value) survive while they are the latest write for
  their key and are dropped once older than delete_retention_ms, matching
  delete.retention.ms semantics.

The per-record hot work (key extraction, re-framing) rides the existing
native record codecs; compaction itself is IO-bound and stays host-side by
design (SURVEY §7: Python per batch, C per record, TPU per byte).
"""

from __future__ import annotations

import heapq
import logging
import os
import struct
import tempfile
import time

from redpanda_tpu.models.record import (
    INTERNAL_HEADER_SIZE,
    Compression,
    Record,
    RecordBatch,
    RecordBatchType,
)

logger = logging.getLogger("rptpu.storage.compaction")

# Keys held in memory before a sorted run spills to disk.
DEFAULT_MAX_KEYS_IN_MEMORY = 128 * 1024


class KeyLatestIndex:
    """key bytes -> highest offset, with sorted-run spill above a bound."""

    def __init__(self, max_keys_in_memory: int = DEFAULT_MAX_KEYS_IN_MEMORY):
        self._mem: dict[bytes, int] = {}
        self._max = max_keys_in_memory
        self._runs: list[str] = []
        self._spill_dir: str | None = None

    def put(self, key: bytes, offset: int) -> None:
        cur = self._mem.get(key)
        if cur is None or offset > cur:
            self._mem[key] = offset
        if len(self._mem) >= self._max:
            self._spill()

    def _spill(self) -> None:
        if self._spill_dir is None:
            self._spill_dir = tempfile.mkdtemp(prefix="rptpu-compact-")
        path = os.path.join(self._spill_dir, f"run-{len(self._runs)}.idx")
        with open(path, "wb") as f:
            for key in sorted(self._mem):
                f.write(struct.pack("<Iq", len(key), self._mem[key]))
                f.write(key)
        self._runs.append(path)
        self._mem.clear()

    @staticmethod
    def _iter_run(path: str):
        with open(path, "rb") as f:
            while True:
                hdr = f.read(12)
                if len(hdr) < 12:
                    return
                klen, off = struct.unpack("<Iq", hdr)
                yield f.read(klen), off

    def finish(self) -> dict[bytes, int]:
        """Merge memory + spilled runs into the final latest-offset map."""
        if not self._runs:
            return self._mem
        merged: dict[bytes, int] = dict(self._mem)
        for key, off in heapq.merge(*(self._iter_run(p) for p in self._runs)):
            cur = merged.get(key)
            if cur is None or off > cur:
                merged[key] = off
        self.cleanup()
        return merged

    def cleanup(self) -> None:
        for p in self._runs:
            try:
                os.remove(p)
            except OSError:
                pass
        self._runs.clear()
        if self._spill_dir is not None:
            try:
                os.rmdir(self._spill_dir)
            except OSError:
                pass
            self._spill_dir = None


def _iter_batches(blob: bytes):
    at = 0
    while at + INTERNAL_HEADER_SIZE <= len(blob):
        batch, consumed = RecordBatch.decode_internal(blob, at)
        yield batch
        at += consumed


def build_key_index(
    segments, *, max_keys_in_memory: int = DEFAULT_MAX_KEYS_IN_MEMORY
) -> dict[bytes, int]:
    """Latest offset per key over the given segments (oldest -> newest)."""
    idx = KeyLatestIndex(max_keys_in_memory)
    for seg in segments:
        for batch in _iter_batches(seg.read_from(0)):
            if batch.header.type != RecordBatchType.raft_data or batch.header.is_control:
                continue
            base = batch.base_offset
            for rec in batch.records():
                if rec.key is not None:
                    idx.put(rec.key, base + rec.offset_delta)
    return idx.finish()


def self_compact_segment(
    seg,
    key_index: dict[bytes, int],
    *,
    tombstone_cutoff_ms: int | None = None,
) -> tuple[int, int]:
    """Rewrite one closed segment keeping only live records.

    Returns (bytes_before, bytes_after). The caller holds the log lock.
    """
    assert not seg.writable, "only closed segments are compacted"
    blob = seg.read_from(0)
    out = bytearray()
    batches = list(_iter_batches(blob))
    for i, batch in enumerate(batches):
        is_final = i == len(batches) - 1
        if batch.header.type != RecordBatchType.raft_data or batch.header.is_control:
            out += batch.encode_internal()
            continue
        base = batch.base_offset
        kept: list[Record] = []
        for rec in batch.records():
            if rec.key is None:
                kept.append(rec)  # keyless records cannot be compacted
                continue
            off = base + rec.offset_delta
            if key_index.get(rec.key, off) > off:
                continue  # shadowed by a newer write of the same key
            if (
                rec.value is None
                and tombstone_cutoff_ms is not None
                and batch.header.max_timestamp < tombstone_cutoff_ms
            ):
                continue  # expired tombstone
            kept.append(rec)
        if len(kept) == batch.header.record_count:
            out += batch.encode_internal()
            continue
        if not kept and not is_final:
            continue  # fully shadowed: drop the batch (offset gap, like Kafka)
        # shrink in place: original offset deltas + last_offset_delta keep
        # the offset math identical for readers and for the next append
        hdr = batch.header
        payload = b"".join(r.encode() for r in kept)
        attrs = hdr.attrs
        codec = hdr.compression
        if codec != Compression.none and payload:
            from redpanda_tpu.compression import compress

            payload = compress(payload, codec)
        elif not payload:
            attrs &= ~0x07  # empty batches are stored uncompressed
        import dataclasses

        new_hdr = dataclasses.replace(
            hdr, attrs=attrs, record_count=len(kept), size_bytes=0
        )
        nb = RecordBatch(new_hdr, payload)
        nb.reseal()
        out += nb.encode_internal()
    before = seg.size_bytes
    if len(out) == before:
        return before, before
    tmp = seg.data_path + ".compact.tmp"
    with open(tmp, "wb") as f:
        f.write(out)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, seg.data_path)
    seg.size_bytes = len(out)
    seg.rebuild_index(bytes(out))
    seg.index.persist(seg.dirty_offset, seg.max_timestamp)
    return before, len(out)


async def compact_log(
    log,
    *,
    delete_retention_ms: int | None = None,
    max_keys_in_memory: int = DEFAULT_MAX_KEYS_IN_MEMORY,
) -> tuple[int, int]:
    """Compact every closed segment of a log. Returns (bytes_before, after).

    The key index spans the WHOLE log including the active segment, so a
    record in a closed segment is dropped when a newer write exists even if
    that write is still in the active head (self-compaction with whole-log
    shadowing, one pass).
    """
    async with log._lock:
        closed = [s for s in log.segments if not s.writable]
        if not closed:
            return 0, 0
        key_index = build_key_index(
            log.segments, max_keys_in_memory=max_keys_in_memory
        )
        cutoff = (
            int(time.time() * 1000) - delete_retention_ms
            if delete_retention_ms is not None
            else None
        )
        before = after = 0
        for seg in closed:
            b, a = self_compact_segment(seg, key_index, tombstone_cutoff_ms=cutoff)
            before += b
            after += a
        if before != after:
            logger.info(
                "compacted %s: %d -> %d bytes (%d closed segments)",
                log.ntp, before, after, len(closed),
            )
        return before, after
