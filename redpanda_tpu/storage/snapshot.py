"""General snapshot file format (parity with storage/snapshot.h).

Layout: magic(4) | version(1) | metadata_len(u32) | metadata_crc(u32) |
metadata | payload_crc(u32) | payload. Both CRCs are CRC-32C. Writes go
through a temp file + atomic rename; `SnapshotManager` keeps the
last-good snapshot per directory.
"""

from __future__ import annotations

import os
import struct

from redpanda_tpu.hashing.crc32c import crc32c

_MAGIC = b"RPSN"
_VERSION = 1
_HDR = struct.Struct("<4sBII")


class SnapshotError(Exception):
    pass


def write_snapshot(path: str, metadata: bytes, payload: bytes) -> None:
    tmp = path + ".partial"
    with open(tmp, "wb") as f:
        f.write(_HDR.pack(_MAGIC, _VERSION, len(metadata), crc32c(metadata)))
        f.write(metadata)
        f.write(struct.pack("<I", crc32c(payload)))
        f.write(payload)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)


def read_snapshot(path: str) -> tuple[bytes, bytes]:
    with open(path, "rb") as f:
        blob = f.read()
    if len(blob) < _HDR.size:
        raise SnapshotError("snapshot too short")
    magic, version, mlen, mcrc = _HDR.unpack_from(blob)
    if magic != _MAGIC or version != _VERSION:
        raise SnapshotError("bad snapshot magic/version")
    meta_end = _HDR.size + mlen
    metadata = blob[_HDR.size : meta_end]
    if len(metadata) != mlen or crc32c(metadata) != mcrc:
        raise SnapshotError("snapshot metadata corrupt")
    (pcrc,) = struct.unpack_from("<I", blob, meta_end)
    payload = blob[meta_end + 4 :]
    if crc32c(payload) != pcrc:
        raise SnapshotError("snapshot payload corrupt")
    return metadata, payload


class SnapshotManager:
    """Named snapshot in a directory with atomic replacement."""

    def __init__(self, dir_path: str, name: str = "snapshot"):
        self.dir = dir_path
        self.path = os.path.join(dir_path, name)
        os.makedirs(dir_path, exist_ok=True)

    def exists(self) -> bool:
        return os.path.exists(self.path)

    def write(self, metadata: bytes, payload: bytes) -> None:
        write_snapshot(self.path, metadata, payload)

    def read(self) -> tuple[bytes, bytes] | None:
        if not self.exists():
            return None
        return read_snapshot(self.path)

    def remove(self) -> None:
        try:
            os.remove(self.path)
        except FileNotFoundError:
            pass
