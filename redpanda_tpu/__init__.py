"""redpanda_tpu — a TPU-native streaming framework.

A brand-new implementation of the capabilities of the reference streaming
platform (Kafka-compatible partitioned logs, Raft replication, consumer
groups, inline record transforms, tiered storage, REST proxy / schema
registry), re-designed TPU-first:

- The host runtime (storage, raft, RPC, Kafka protocol, control plane) is an
  asyncio-based broker with a native extension for the hot byte paths.
- The per-batch data plane — CRC32c validation, (de)compression staging, and
  user map/filter transforms — executes as batched XLA/Pallas kernels over a
  ``[partition, batch, record]`` axis on TPU, fed through a device bridge
  (``redpanda_tpu.bridge``), with shardings laid over a ``jax.sharding.Mesh``
  for multi-chip scale-out (``redpanda_tpu.parallel``).

Layer map (mirrors SURVEY.md §1 of the reference analysis):

    utils/ hashing/ compression/ models/   foundation (bytes, CRC, codecs,
                                           record-batch domain model)
    ops/ parallel/ bridge/                 device data plane (TPU kernels,
                                           mesh shardings, host<->device)
    storage/                               segmented log + kvstore + snapshots
    rpc/ raft/                             internal RPC + consensus
    cluster/                               controller, topic table, allocator
    kafka/                                 wire protocol server + client
    coproc/                                inline transform engine (TPU-backed)
    security/ config/ admin/ proxy/        SASL/ACL, config store, admin API,
    archival/ cli/                         REST proxy + schema registry,
                                           tiered storage, operator CLI
"""

__version__ = "0.1.0"
