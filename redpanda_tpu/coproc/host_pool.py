"""Host-stage worker pool: per-core sharding of the engine's record stages.

BENCH_r05 made the bottleneck explicit: with the device predicate leg down
to ~2% of stage wall time, the engine is bound by SINGLE-THREADED host
stages — ``t_explode_find`` alone is ~57% and projection extraction another
~26%. Every one of those stages is a ctypes crossing (GIL released) or a
bulk numpy pass over **disjoint record ranges**, which is the classic
vectorized-execution sharding setup (MonetDB/X100 style) and the per-core
analogue of the reference's per-shard pacemaker fibers
(coproc/pacemaker.h:41-145): partition a launch's batches into contiguous
shards, run every per-record stage per shard on a small thread pool, and
merge index tables by rebasing.

This module owns only the generic machinery — the pool itself and the
contiguous, record-count-balanced batch partitioner. What runs per shard
(explode/find, column extraction, projection, framing) is the engine's
business (engine._dispatch_sharded / _Launch._framed_sharded).

Sizing: ``coproc_host_workers`` (config/properties.py), default
``min(4, os.cpu_count())``; ``0`` (or 1) keeps today's inline path — the
pool only exists at >= 2 workers. Observability: every task ticks the
``coproc_host_pool_busy_workers`` gauge (observability/probes.py) and the
engine records ``coproc_shard_rows`` per shard, so traceview and /metrics
show the fan-out.
"""

from __future__ import annotations

import os
import threading
import time
from concurrent.futures import ThreadPoolExecutor

import numpy as np

from redpanda_tpu.observability import probes


def default_host_workers() -> int:
    """The config default: one worker per core, capped at 4 (beyond that
    the merge/serial residue dominates before memory bandwidth does)."""
    return min(4, os.cpu_count() or 1)


# The measured sharded/inline ratio must clear this margin before the
# engine pins the pool on (see TpuEngine._calibrate_host_pool): a real
# 2-core box shards the explode stage ~1.8x faster; a quota-limited box
# advertising CPUs it doesn't have measures <= 1.0 with scheduler-thrash
# tails. Requiring a real win also keeps borderline boxes (whose burst
# capacity comes and goes) on the predictable inline path.
PROBE_MARGIN = 1.25


def measure_parallel_capacity(workers: int = 2) -> dict:
    """Diagnostic: do GIL-releasing numpy tasks actually run concurrently
    here? ``os.cpu_count()`` lies on quota-limited boxes, so
    tools/microbench.py reports this next to the pool-scaling numbers.
    NOTE this synthetic answer is context only — the engine calibrates on
    its REAL explode stage (burstable hosts can pass a millisecond-scale
    synthetic probe and still thrash on sustained parsing work).
    Returns {'speedup', 'workers'}; best-of-3 on both sides."""
    workers = max(2, int(workers))

    def task() -> None:
        x = np.arange(200_000, dtype=np.float64)
        for _ in range(4):
            x = np.sqrt(x * 1.0001 + 1.0)

    ex = ThreadPoolExecutor(max_workers=workers)
    try:
        task()  # warm numpy + the allocator
        serial = parallel = float("inf")
        for _ in range(3):
            t0 = time.perf_counter()
            for _ in range(workers):
                task()
            serial = min(serial, time.perf_counter() - t0)
            t0 = time.perf_counter()
            futs = [ex.submit(task) for _ in range(workers)]
            for f in futs:
                f.result()
            parallel = min(parallel, time.perf_counter() - t0)
    finally:
        ex.shutdown(wait=False)
    speedup = serial / parallel if parallel > 0 else 1.0
    return {"speedup": round(speedup, 3), "workers": workers}


def partition_counts(counts: list[int], n_shards: int) -> list[tuple[int, int]]:
    """Contiguous [start, end) slices over ``counts`` (per-batch record
    counts), balanced by total records per shard.

    Contiguity is the invariant everything downstream leans on: shard i's
    records form one contiguous record range, so merged offset/size/span
    tables are plain concatenations with rebased indices and the framed
    per-batch outputs concatenate back in input order byte-identically.
    Never returns empty slices; may return fewer than ``n_shards`` when
    there are fewer batches than shards.
    """
    n = len(counts)
    if n == 0 or n_shards <= 1:
        return [(0, n)] if n else []
    n_shards = min(n_shards, n)
    total = sum(counts)
    target = total / n_shards
    cuts = [0]
    acc = 0
    for i, c in enumerate(counts):
        acc += c
        # cut when this shard reached its share, leaving enough batches
        # for the remaining shards to be non-empty
        remaining_shards = n_shards - len(cuts)
        if (
            remaining_shards > 0
            and acc >= target * len(cuts)
            and (n - (i + 1)) >= remaining_shards
            and i + 1 > cuts[-1]
        ):
            cuts.append(i + 1)
            if len(cuts) == n_shards:
                break
    cuts.append(n)
    return [(cuts[i], cuts[i + 1]) for i in range(len(cuts) - 1) if cuts[i + 1] > cuts[i]]


class HostStagePool:
    """A named thread pool for the engine's per-shard host stages.

    Threads, not processes: the sharded stages spend their time inside
    ctypes calls (GIL dropped for the whole crossing), zlib/lz4
    decompression, or wide numpy kernels — real parallelism without
    pickling record payloads across a process boundary.

    The executor is created lazily (an engine configured with workers but
    never fed a shardable launch costs nothing) and torn down by
    interpreter exit like any ThreadPoolExecutor; engines are long-lived
    process singletons in the broker (one per CoprocApi).
    """

    def __init__(self, workers: int):
        from redpanda_tpu.coproc import lockwatch

        self.workers = int(workers)
        self._executor: ThreadPoolExecutor | None = None
        self._lock = lockwatch.wrap(threading.Lock(), "HostStagePool._lock")

    def _ensure_executor(self) -> ThreadPoolExecutor:
        # locked check-then-create: concurrent first launches must not
        # each build (and leak) an executor
        with self._lock:
            if self._executor is None:
                self._executor = ThreadPoolExecutor(
                    max_workers=self.workers,
                    thread_name_prefix="rptpu-host-stage",
                )
            return self._executor

    def run(self, fns: list) -> list:
        """Run thunks concurrently; returns results in input order.

        The first exception (in input order) propagates to the caller —
        the engine's per-script error policy handles it exactly as it
        handles an inline-stage failure. Remaining tasks still run to
        completion (they share no mutable state by construction; the
        SHD6xx pandalint rules keep it that way).
        """
        if len(fns) == 1:
            return [self._tracked(fns[0])]
        ex = self._ensure_executor()
        futures = [ex.submit(self._tracked, fn) for fn in fns]
        results = []
        first_exc: BaseException | None = None
        for f in futures:
            try:
                results.append(f.result())
            except BaseException as e:  # noqa: BLE001  # pandalint: disable=EXC901 -- collected, not swallowed: the first failure re-raises after every task completes
                results.append(None)
                if first_exc is None:
                    first_exc = e
        if first_exc is not None:
            raise first_exc
        return results

    @staticmethod
    def _tracked(fn):
        probes.host_pool_task_started()
        try:
            return fn()
        finally:
            probes.host_pool_task_finished()

    def shutdown(self) -> None:
        with self._lock:
            if self._executor is not None:
                self._executor.shutdown(wait=False)
                self._executor = None
