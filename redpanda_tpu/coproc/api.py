"""Coproc API: event listener + script dispatcher + pacemaker + engine.

Parity with coproc/api.h (api.cc:19-49 owns pacemaker + event listener),
wasm/event_listener (event_listener.cc:139-156 polls the internal topic),
and script_dispatcher.cc:166 enable_coprocessors (register with the engine
AND the pacemaker). The reference's listener is an in-proc kafka::client
over loopback; running inside the broker process, this listener reads the
internal topic's partition directly — same log, no socket hop.

Deploy surface (used by the CLI's `wasm deploy` and tests): produce a
validated deploy/remove event to ``coprocessor_internal_topic``; the
listener reconciles events in log order on every node that hosts it.
"""

from __future__ import annotations

import asyncio
import logging

from redpanda_tpu.coproc import faults, wasm_event
from redpanda_tpu.coproc.engine import EnableResponseCode, TpuEngine
from redpanda_tpu.coproc.pacemaker import Pacemaker
from redpanda_tpu.models.fundamental import COPROC_INTERNAL_TOPIC, NTP
from redpanda_tpu.cluster.topic_table import TopicConfig

logger = logging.getLogger("rptpu.coproc.api")


class CoprocApi:
    def __init__(self, broker, config=None) -> None:
        self.broker = broker

        def _knob(name, default):
            return getattr(config, name, default) if config is not None else default

        max_batch = _knob("coproc_max_batch_size", 32 * 1024)
        inflight_bytes = _knob("coproc_max_inflight_bytes", 10 * 1024 * 1024)
        flush_ms = _knob("coproc_offset_flush_interval_ms", 300_000)
        # budget plane (resource_mgmt): installed on the broker by the
        # application; bare brokers (unit harnesses) run plane-less, which
        # keeps admission off and the historical semantics
        plane = getattr(broker, "budget_plane", None)
        if _knob("coproc_lockwatch", False):
            # must flip BEFORE the engine is built: per-object locks bind
            # their recorder (or lack of one) at construction
            from redpanda_tpu.coproc import lockwatch

            lockwatch.enable()
        if _knob("coproc_leakwatch", False):
            # same contract: the engine's admission controller and arena
            # bind their balance recorder (or lack of one) at construction
            from redpanda_tpu.coproc import leakwatch

            leakwatch.enable()
        # None -> the engine resolves min(4, cores); the property default
        # matches, so an unset config and a default config agree
        self.engine = TpuEngine(
            host_workers=_knob("coproc_host_workers", None),
            host_pool_probe=_knob("coproc_host_pool_probe", True),
            host_pool_recal_launches=_knob(
                "coproc_host_pool_recal_launches", None
            ),
            gather_frame=_knob("coproc_gather_frame", True),
            structural_parse=_knob("coproc_structural_parse", None),
            device_column_cache_mb=_knob(
                "coproc_device_column_cache_mb", 32
            ),
            mesh_devices=_knob("coproc_mesh_devices", 0) or None,
            mesh_backend=_knob("coproc_mesh_backend", "") or None,
            mesh_probe=_knob("coproc_mesh_probe", True),
            device_deadline_ms=_knob("coproc_device_deadline_ms", None),
            launch_retries=_knob("coproc_launch_retries", None),
            retry_backoff_ms=_knob("coproc_retry_backoff_ms", None),
            breaker_threshold=_knob("coproc_breaker_threshold", None),
            breaker_cooldown_ms=_knob("coproc_breaker_cooldown_ms", None),
            adaptive_deadline=_knob("coproc_adaptive_deadline", None),
            adaptive_deadline_margin=_knob(
                "coproc_adaptive_deadline_margin", None
            ),
            governor_journal_capacity=_knob(
                "coproc_governor_journal_capacity", None
            ),
            budget_plane=plane,
        )
        # close the autotune loop: the governor's ADMISSION domain owns
        # the dynamic group_ticks/launch_depth verdicts, driven by the
        # success-only dispatch-leg histogram and the plane's occupancy
        group_ticks = _knob("coproc_group_ticks_per_launch", 1)
        launch_depth = _knob("coproc_launch_depth", 4)
        self.engine.governor.configure_autotune(
            enabled=_knob("coproc_autotune_launch", True),
            group_ticks=group_ticks,
            group_ticks_cap=_knob("coproc_group_ticks_max", 8),
            launch_depth=launch_depth,
            launch_depth_cap=_knob("coproc_launch_depth_max", 8),
            pressure_fn=(
                (lambda: (plane.pressure(), plane.max_occupancy()[1]))
                if plane is not None
                else None
            ),
        )
        self.pacemaker = Pacemaker(
            broker, self.engine,
            max_batch_size=max_batch,
            group_ticks_per_launch=group_ticks,
            launch_depth=launch_depth,
            # the byte budget bounds concurrent reads: each read holds at
            # most max_batch_size bytes (configuration.h:57-61 semantics)
            max_inflight_reads=max(1, inflight_bytes // max(max_batch, 1)),
            offset_flush_interval_s=flush_ms / 1000.0,
            # the tick backstop sits ABOVE the engine's own retry envelope
            # (a few device legs per tick, each up to one full envelope) —
            # it only fires when the in-engine machinery itself is wedged
            tick_deadline_s=max(
                60.0, 4 * self.engine._fault_policy.envelope_s()
            ),
        )
        self._listener_task: asyncio.Task | None = None
        self._listen_offset = 0
        self._active: dict[str, wasm_event.WasmEvent] = {}
        self.poll_interval_s = 0.05

    # ------------------------------------------------------------ lifecycle
    async def start(self) -> "CoprocApi":
        await self.pacemaker.start()
        # topic creation happens inside the listener loop with retries:
        # at startup the cluster may not have a quorum of REGISTERED nodes
        # yet (replication = default factor needs them), and blocking app
        # start on cluster formation would deadlock — every node is doing
        # the same thing
        self._listener_task = asyncio.create_task(self._listen_loop())
        return self

    async def _ensure_internal_topic(self) -> bool:
        if self.broker.topic_table.contains(COPROC_INTERNAL_TOPIC):
            return True
        try:
            # replicated to the default factor: every broker's listener
            # reads its LOCAL raft replica of the event log, so deploys
            # reconcile cluster-wide without a client hop
            await self.broker.create_topic(
                TopicConfig(
                    COPROC_INTERNAL_TOPIC, 1,
                    self.broker.config.default_replication,
                )
            )
            return True
        except ValueError:
            return True  # lost a concurrent create: it exists
        except Exception as e:  # pandalint: disable=EXC901 -- startup poll: the topic is not creatable until a controller leader exists; retried every 0.5s, not a fault
            logger.debug("coproc internal topic not creatable yet: %s", e)
            return False

    async def stop(self) -> None:
        if self._listener_task is not None:
            self._listener_task.cancel()
            try:
                await self._listener_task
            except asyncio.CancelledError:
                pass
            self._listener_task = None
        await self.pacemaker.stop()
        # stop the engine's background machinery LAST: the pacemaker's
        # final ticks may still be harvesting (engine.shutdown joins the
        # harvester off-loop; it can block up to a drain, so thread it)
        await asyncio.to_thread(self.engine.shutdown)

    # ------------------------------------------------------------ deploy surface
    async def deploy(self, name: str, spec_json: str, input_topics: list[str]) -> None:
        from redpanda_tpu.models.fundamental import MaterializedNTP

        for t in input_topics:
            if not self.broker.topic_table.contains(t):
                raise ValueError(f"input topic does not exist: {t}")
            # one canonical predicate: internal topics and materialized
            # topics (MaterializedNTP convention) cannot be inputs
            if self.broker.is_internal_topic(t) or MaterializedNTP.parse(NTP("kafka", t, 0)):
                raise ValueError(f"invalid input topic: {t}")
        await self._produce_event(
            wasm_event.make_deploy_record(name, spec_json, input_topics)
        )

    async def remove(self, name: str) -> None:
        await self._produce_event(wasm_event.make_remove_record(name))

    async def _produce_event(self, rec) -> None:
        # topic creation is deferred to the listener loop (cluster
        # formation); a deploy right after start must drive it itself
        deadline = asyncio.get_event_loop().time() + 10.0
        p = self.broker.get_partition(COPROC_INTERNAL_TOPIC, 0)
        while p is None and asyncio.get_event_loop().time() < deadline:
            await self._ensure_internal_topic()
            await asyncio.sleep(0.05)
            p = self.broker.get_partition(COPROC_INTERNAL_TOPIC, 0)
        if p is None:
            raise RuntimeError("coproc internal topic missing")
        await p.replicate([wasm_event.deploy_batch([rec])], 0)

    # ------------------------------------------------------------ listener
    async def _listen_loop(self) -> None:
        """do_ingest (event_listener.cc:139): poll, validate, reconcile,
        dispatch enable/disable to engine + pacemaker."""
        created = False
        while True:
            try:
                if not created:
                    created = await self._ensure_internal_topic()
                await self._ingest_once()
            except asyncio.CancelledError:
                raise
            except Exception as exc:
                # classified: a broker that can no longer ingest deploys is
                # degraded even though this loop survives to retry
                faults.note_failure("wasm_ingest", exc)
                logger.exception("coproc event ingest failed")
            await asyncio.sleep(self.poll_interval_s if created else 0.5)

    async def _ingest_once(self) -> None:
        p = self.broker.get_partition(COPROC_INTERNAL_TOPIC, 0)
        if p is None:
            return
        hwm = p.high_watermark
        if self._listen_offset >= hwm:
            return
        events = []
        next_offset = self._listen_offset
        while next_offset < hwm:
            batches = await p.make_reader(next_offset, 1 << 20, max_offset=hwm - 1)
            if not batches:
                break
            for b in batches:
                for rec in b.records():
                    ev = wasm_event.parse_event(rec)
                    if ev is not None:
                        events.append(ev)
                    else:
                        logger.warning("ignoring malformed coproc event")
                next_offset = b.last_offset + 1
        # dispatch BEFORE advancing the cursor, but isolate per event: a
        # POISON event — the script itself is bad (SandboxViolation from
        # validation, ValueError from a malformed event body) — is logged
        # and skipped, otherwise one bad deploy would wedge every later
        # deploy/remove on every broker forever. Anything else is a
        # TRANSIENT infrastructure failure (partition moving, engine
        # mid-restart): re-raise WITHOUT advancing the cursor so the whole
        # chunk retries on the next poll — swallowing it would silently
        # diverge script state across the cluster (this broker skips a
        # deploy its peers applied). Retried events are idempotent:
        # _enable dedupes unchanged redeploys by checksum and _disable of
        # an inactive name is a no-op.
        from redpanda_tpu.coproc.sandbox import SandboxViolation

        for name, ev in wasm_event.reconcile(events).items():
            try:
                if ev.action == wasm_event.DEPLOY:
                    await self._enable(ev)
                else:
                    await self._disable(name)
            except asyncio.CancelledError:
                raise
            except (SandboxViolation, ValueError) as exc:
                faults.note_failure("wasm_event", exc)
                logger.exception("poison coproc event %r skipped", name)
        self._listen_offset = next_offset

    async def _enable(self, ev: wasm_event.WasmEvent) -> None:
        """script_dispatcher::enable_coprocessors: engine first, then the
        pacemaker source (script_dispatcher.cc:166)."""
        if ev.name in self._active and self._active[ev.name].checksum == ev.checksum:
            return  # unchanged redeploy
        if ev.name in self._active:
            await self._disable(ev.name)
        if ev.py_source:
            # sandboxed python transform: restricted-AST validation runs
            # inside enable_py_sandboxed on THIS broker before registration
            from redpanda_tpu.coproc.engine import ErrorPolicy

            codes = [self.engine.enable_py_sandboxed(
                ev.script_id, ev.py_source, ev.input_topics,
                ErrorPolicy.deregister if ev.policy == "deregister"
                else ErrorPolicy.skip_on_failure,
            )]
        else:
            codes = self.engine.enable_coprocessors(
                [(ev.script_id, ev.spec_json, ev.input_topics)]
            )
        if codes[0] != EnableResponseCode.success:
            logger.error("enable %s failed: %s", ev.name, codes[0].name)
            return
        await self.pacemaker.add_source(ev.name, ev.script_id, ev.input_topics)
        self._active[ev.name] = ev
        logger.info("coprocessor %s enabled on %s", ev.name, list(ev.input_topics))

    async def _disable(self, name: str) -> None:
        ev = self._active.pop(name, None)
        if ev is None:
            return
        await self.pacemaker.remove_script(name)
        self.engine.disable_coprocessors([ev.script_id])
        logger.info("coprocessor %s disabled", name)

    # ------------------------------------------------------------ views
    def active_scripts(self) -> list[str]:
        return sorted(self._active)
