"""coproc leakwatch: the runtime half of the pandaleak cross-check.

With ``coproc_leakwatch=true`` the broker's budget accounts, admission
controllers, inflight gates, and arenas are wrapped in a BALANCE
recorder: every acquire/release is attributed to its repo-relative call
site (file:line of the caller) and netted per resource. The record is
what a dynamic leak detector would build; here its job is to VALIDATE
the static analyzer — the chaos parity suite runs the fault matrix
(including cancellation injection) under leakwatch and asserts (a) every
balance nets to zero at end of test and (b) every observed acquire SITE
is a statement pandalint's lifecycle model knows about
(tools/pandalint/lifecycle.model_sites), so the analyzer's vocabulary
blind spots surface as test failures instead of silent false-green
gates.

Zero cost when off — the same contract lockwatch pins:

- ``wrap(obj, name)`` returns the RAW object untouched unless leakwatch
  was enabled before the owning object was constructed; the steady-state
  broker carries plain accounts/gates/arenas and pays one flag check per
  resource CONSTRUCTION, nothing per acquisition.
- ``enable()`` flips the flag; construction sites (BudgetPlane,
  pacemaker, engine admission/arena, rpc server) pick the wrapper up
  when built afterwards — CoprocApi/broker app do this off the config
  knob before building anything.

Balance accounting per wrapper kind:

- accounts/admission/gates net GRANTED amounts (refusals — 0 grants,
  ``None`` slots — are not acquisitions); a net going NEGATIVE (more
  released than acquired) is an imbalance the moment it happens, bumps
  ``coproc_leakwatch_imbalance_total`` and journals under the governor
  ``leakwatch`` domain.
- arenas track buffer IDENTITY, not counts: the grown-by-replacement
  scratch contract means a callee may hand back a replacement for the
  ``out=`` buffer it consumed, so releasing a buffer this wrapper never
  issued is ADOPTION (legal, ignored), while an issued buffer never
  released is the leak.

Like lockwatch, the recorder's own lock stays a leaf: the journal and
counter are taken OUTSIDE ``_state_lock``.
"""

from __future__ import annotations

import os
import sys
import threading

_enabled = False
_state_lock = threading.Lock()
# resource name -> net outstanding (bytes/slots) or, for arenas, buffer count
_balance: dict[str, int] = {}
# (resource name, "rel/path.py:line") -> [acquires, releases]
_sites: dict[tuple[str, str], list] = {}
_imbalances: int = 0

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def enabled() -> bool:
    return _enabled


def _caller_site(depth: int = 2) -> str:
    """repo-relative file:line of the frame that called the wrapper."""
    try:
        f = sys._getframe(depth)
    except ValueError:  # pragma: no cover - shallow stack
        return "?:0"
    path = f.f_code.co_filename
    try:
        rel = os.path.relpath(path, _REPO_ROOT)
    except ValueError:  # pragma: no cover - different drive (windows)
        rel = path
    return f"{rel.replace(os.sep, '/')}:{f.f_lineno}"


def _note(name: str, site: str, delta: int, acquire: bool) -> None:
    global _imbalances
    new_site = False
    negative = None
    with _state_lock:
        counts = _sites.get((name, site))
        if counts is None:
            counts = _sites[(name, site)] = [0, 0]
            new_site = acquire
        counts[0 if acquire else 1] += 1
        bal = _balance.get(name, 0) + delta
        _balance[name] = bal
        if bal < 0:
            _imbalances += 1
            negative = bal
    # outside _state_lock: journal/counter take their own locks
    if negative is not None:
        from redpanda_tpu.coproc import governor
        from redpanda_tpu.observability import probes

        probes.coproc_leakwatch_imbalance.inc()
        governor.journal_record(
            governor.LEAKWATCH,
            "imbalance",
            f"resource {name} balance went negative ({negative}) at "
            f"{site}: released more than was ever acquired — a "
            f"double-release or an adoption the recorder cannot pair",
            {"resource": name, "balance": negative, "site": site},
        )
    elif new_site:
        from redpanda_tpu.coproc import governor

        governor.journal_record(
            governor.LEAKWATCH,
            "site",
            f"first acquire of {name} from {site}; the static lifecycle "
            f"model must contain this statement",
            {"resource": name, "site": site},
        )


class _Proxy:
    """Forwarding base: everything not intercepted hits the raw object,
    so identity-free callers (gauges, pressure recompute, snapshots)
    behave exactly as without leakwatch."""

    # __weakref__: the budget plane's gauge registration weakrefs its
    # accounts — the proxy must be weakref-able like the raw object
    __slots__ = ("_raw", "_lw_name", "__weakref__")

    def __init__(self, raw, name: str):
        object.__setattr__(self, "_raw", raw)
        object.__setattr__(self, "_lw_name", name)

    def __getattr__(self, attr):
        return getattr(object.__getattribute__(self, "_raw"), attr)

    def __setattr__(self, attr, value):
        if attr in ("_raw", "_lw_name", "_lw_out"):  # pragma: no cover
            object.__setattr__(self, attr, value)
        else:
            setattr(object.__getattribute__(self, "_raw"), attr, value)


class WatchedAccount(_Proxy):
    """MemoryAccount balance recorder (also fits anything with the
    try_acquire/acquire/release byte vocabulary, e.g. MemoryBudget)."""

    __slots__ = ()

    def try_acquire(self, n: int) -> int:
        got = self._raw.try_acquire(n)
        if got:
            _note(self._lw_name, _caller_site(), got, True)
        return got

    async def acquire(self, n: int) -> int:
        site = _caller_site()  # capture BEFORE suspension
        got = await self._raw.acquire(n)
        if got:
            _note(self._lw_name, site, got, True)
        return got

    def release(self, n: int) -> None:
        if n:
            _note(self._lw_name, _caller_site(), -n, False)
        self._raw.release(n)


class WatchedAdmission(_Proxy):
    """AdmissionController recorder: try_admit returns (reserved,
    retry_ms); zero reserved is a shed, not an acquisition."""

    __slots__ = ()

    def try_admit(self, n: int):
        reserved, retry_ms = self._raw.try_admit(n)
        if reserved:
            _note(self._lw_name, _caller_site(), reserved, True)
        return reserved, retry_ms

    def admit(self, n: int) -> int:
        reserved = self._raw.admit(n)
        if reserved:
            _note(self._lw_name, _caller_site(), reserved, True)
        return reserved

    def release(self, reserved: int) -> None:
        if reserved:
            _note(self._lw_name, _caller_site(), -reserved, False)
        self._raw.release(reserved)


class WatchedGate(_Proxy):
    """InflightGate recorder: try_enter returns the reserved byte count
    or None on refusal; leave gives the bytes back."""

    __slots__ = ()

    def try_enter(self, nbytes: int):
        reserved = self._raw.try_enter(nbytes)
        if reserved is not None:
            _note(self._lw_name, _caller_site(), reserved, True)
        return reserved

    def leave(self, reserved: int) -> None:
        _note(self._lw_name, _caller_site(), -reserved, False)
        self._raw.leave(reserved)


class WatchedArena(_Proxy):
    """Arena recorder: identity accounting for the grown-by-replacement
    contract. Issued buffers are tracked by id(); releasing a buffer the
    arena never issued through this wrapper is ADOPTION (the callee grew
    the out= scratch and handed ownership of its replacement back) and
    is forwarded without touching the balance."""

    __slots__ = ("_lw_out",)

    def __init__(self, raw, name: str):
        super().__init__(raw, name)
        object.__setattr__(self, "_lw_out", set())

    def acquire(self, nbytes: int):
        buf = self._raw.acquire(nbytes)
        out = object.__getattribute__(self, "_lw_out")
        with _state_lock:
            out.add(id(buf))
        _note(self._lw_name, _caller_site(), 1, True)
        return buf

    def release(self, buf) -> None:
        out = object.__getattribute__(self, "_lw_out")
        issued = False
        with _state_lock:
            if id(buf) in out:
                out.discard(id(buf))
                issued = True
        if issued:
            _note(self._lw_name, _caller_site(), -1, False)
        self._raw.release(buf)


def wrap(obj, name: str):
    """The ONE construction-time hook: returns `obj` untouched when
    leakwatch is off (zero steady-state overhead, no proxy installed),
    a duck-typed balance recorder when on."""
    if not _enabled:
        return obj
    if hasattr(obj, "try_enter"):
        return WatchedGate(obj, name)
    if hasattr(obj, "try_admit"):
        return WatchedAdmission(obj, name)
    if hasattr(obj, "try_acquire") or hasattr(obj, "release") and hasattr(obj, "acquire"):
        # arenas release BUFFERS, accounts release COUNTS: arenas have
        # no try_acquire and no held/occupancy vocabulary
        if hasattr(obj, "try_acquire"):
            return WatchedAccount(obj, name)
        return WatchedArena(obj, name)
    return obj  # pragma: no cover - unknown vocabulary: leave it alone


def balances() -> dict[str, int]:
    with _state_lock:
        return dict(sorted(_balance.items()))


def sites() -> dict[tuple[str, str], tuple[int, int]]:
    """(resource, 'rel/path.py:line') -> (acquires, releases)."""
    with _state_lock:
        return {k: (v[0], v[1]) for k, v in sorted(_sites.items())}


def acquire_sites() -> set[tuple[str, int]]:
    """Observed acquire sites as (relpath, line) — the set the chaos
    parity test checks against the static lifecycle model."""
    with _state_lock:
        out = set()
        for (_name, site), (acq, _rel) in _sites.items():
            if not acq:
                continue
            rel, _colon, line = site.rpartition(":")
            out.add((rel, int(line)))
        return out


def snapshot() -> dict:
    with _state_lock:
        outstanding = {k: v for k, v in sorted(_balance.items()) if v}
        return {
            "enabled": _enabled,
            "resources": len(_balance),
            "sites": len(_sites),
            "outstanding": outstanding,
            "imbalances": _imbalances,
        }


def reset() -> None:
    global _imbalances
    with _state_lock:
        _balance.clear()
        _sites.clear()
        _imbalances = 0


def enable() -> None:
    """Flip leakwatch on. Call BEFORE constructing the budget plane /
    engine / rpc server: wrappers bind at construction."""
    global _enabled
    with _state_lock:
        _enabled = True


def disable() -> None:
    """Stop wrapping new constructions. Objects built while enabled keep
    their (still-recording but cheap) proxies."""
    global _enabled
    with _state_lock:
        _enabled = False
