"""Coproc fault domains: deadlines, bounded retry, and the device breaker.

The engine's device interactions (dispatch, mask fetch, harvest) share one
failure physics: a healthy link answers in microseconds-to-milliseconds, a
flaky link answers late or throws, and a wedged link never answers at all —
it HANGS inside the fetch rather than raising (see
engine._probe_columnar_backend, which met this first). This module turns
that physics into policy, in one place:

- ``FaultPolicy`` — per-attempt deadline + bounded retries with
  exponential backoff and jitter (``coproc_device_deadline_ms``,
  ``coproc_launch_retries``, ``coproc_retry_backoff_ms``).
- ``fetch_with_deadline`` — runs a device leg on a reusable *abandonable*
  daemon worker: on deadline the caller walks away and the worker, if it
  ever finishes, discards the stale result and returns ITSELF to the free
  pool (no thread growth across completed-late fetches; a truly wedged
  fetch strands at most its one worker).
- ``retry_call`` — the two combined; programming errors never retry.
- ``CircuitBreaker`` — per-engine closed → open → half-open machine:
  ``threshold`` consecutive device failures demote the engine to host
  execution; after ``cooldown_s`` ONE half-open probe launch is admitted
  and its outcome re-closes or re-opens the breaker.
- ``note_failure`` — classified failure accounting: every swallowed
  exception lands in ``coproc_failures_total{domain,kind}`` and logs once
  per (domain, kind) at WARNING (DEBUG after), so no degradation is
  invisible; programming errors optionally re-raise instead.

The honey-badger probe points (finjector.py) for the coproc fault domains
are registered here; every injectable site calls ``inject(<domain>)``,
which is a no-op attribute check unless the badger was armed (the
breaker_overhead microbench gates the closed-breaker + disabled-badger
cost at <1% of the launch path).
"""

from __future__ import annotations

import logging
import queue
import random
import threading
import time
from dataclasses import dataclass

from redpanda_tpu.finjector import ProbeTriggered, honey_badger
from redpanda_tpu.observability import probes

logger = logging.getLogger("rptpu.coproc.faults")

# ------------------------------------------------------------ fault domains
MODULE = "coproc"
DEVICE_DISPATCH = "device_dispatch"
MASK_FETCH = "mask_fetch"
HARVEST = "harvest"
SHARD_WORKER = "shard_worker"
SANDBOX_COMPILE = "sandbox_compile"
# multi-chip sharded predicate launch (coproc/meshrunner.py): its own
# domain so a flaky mesh path demotes MESH launches to the bit-identical
# single-device path while plain dispatch keeps its own breaker
MESH_DISPATCH = "mesh_dispatch"

honey_badger.register_probe(
    MODULE, DEVICE_DISPATCH, MASK_FETCH, HARVEST, SHARD_WORKER,
    SANDBOX_COMPILE, MESH_DISPATCH,
)


def inject(probe: str) -> None:
    """Honey-badger probe site for a coproc fault domain (sync paths)."""
    honey_badger.inject_sync(MODULE, probe)


class DeadlineExceeded(Exception):
    """A device leg outlived its per-attempt deadline (wedged link)."""


# Failures that indicate a bug in OUR code, not a degraded environment:
# retrying or falling back would mask the bug, so they always propagate.
PROGRAMMING_ERRORS = (AssertionError, NameError, UnboundLocalError)


def kind_of(exc: BaseException) -> str:
    if isinstance(exc, DeadlineExceeded):
        return "deadline"
    if isinstance(exc, ProbeTriggered):
        return "injected"
    return type(exc).__name__


# warn-once registry: the first failure of a (domain, kind) pair is loud,
# repeats are DEBUG — a flapping link must not flood the log, but neither
# may any class of degradation stay invisible (the counter sees them all).
_warned: set[tuple[str, str]] = set()
_warned_lock = threading.Lock()


def reset_warned() -> None:
    """Test hook: forget which (domain, kind) pairs have warned."""
    with _warned_lock:
        _warned.clear()


def note_failure(
    domain: str, exc: BaseException, *, reraise_programming: bool = False
) -> str:
    """Account one classified failure; returns the kind label.

    With ``reraise_programming=True`` (device legs: our code between the
    probe site and the device), PROGRAMMING_ERRORS re-raise after being
    counted. User-code boundaries (script fns, spec compilation) keep the
    default: a user TypeError is a script failure, not an engine bug.
    """
    kind = kind_of(exc)
    probes.coproc_failure_counter(domain, kind).inc()
    with _warned_lock:
        first = (domain, kind) not in _warned
        if first:
            _warned.add((domain, kind))
    if first:
        logger.warning(
            "coproc fault domain %r degraded: %s [%s] "
            "(repeats log at DEBUG; coproc_failures_total counts all)",
            domain, exc, kind,
        )
    else:
        logger.debug("coproc fault domain %r: %s [%s]", domain, exc, kind)
    if reraise_programming and isinstance(exc, PROGRAMMING_ERRORS):
        raise exc
    return kind


# ------------------------------------------------------------ fault policy
@dataclass(frozen=True)
class FaultPolicy:
    """Deadline + bounded-retry envelope for one device interaction."""

    deadline_s: float = 30.0
    retries: int = 2
    backoff_s: float = 0.05
    backoff_cap_s: float = 2.0

    def backoff(self, attempt: int) -> float:
        """Exponential backoff with jitter (50-100% of the step): retrying
        launches from many scripts must not re-converge on the device in
        lockstep after a shared blip."""
        step = min(self.backoff_cap_s, self.backoff_s * (2 ** attempt))
        return step * (0.5 + random.random() * 0.5)

    def envelope_s(self) -> float:
        """Worst-case wall time of ONE full retried interaction: every
        attempt runs to its deadline, every backoff takes its full step.
        Anything that waits ON such an interaction (a caller waiting for
        the harvester's verdict, the tick backstop, the breaker's stale-
        probe release) must wait at least this long, or it declares the
        interaction dead while it is legitimately mid-envelope."""
        backoffs = sum(
            min(self.backoff_cap_s, self.backoff_s * (2 ** a))
            for a in range(self.retries)
        )
        return (self.retries + 1) * self.deadline_s + backoffs


# ----------------------------------------------- abandonable fetch workers
# A wedged device fetch cannot be cancelled — only abandoned. Workers are
# plain daemon threads (concurrent.futures joins its workers at interpreter
# exit, which would hang shutdown on a wedge) that are REUSED: a worker
# whose fetch completes goes back to the free list, including one that
# completes AFTER its caller timed out — the late result is discarded and
# the thread reclaimed, so completed-late fetches never grow the pool.


class _Job:
    __slots__ = ("fn", "state", "result", "exc", "event")

    def __init__(self, fn):
        self.fn = fn
        self.state = "pending"  # pending -> done | abandoned
        self.result = None
        self.exc: BaseException | None = None
        self.event = threading.Event()


_pool_lock = threading.Lock()
_free_workers: list["_FetchWorker"] = []
_workers_created = 0


class _FetchWorker(threading.Thread):
    def __init__(self, idx: int):
        super().__init__(name=f"rptpu-fault-fetch-{idx}", daemon=True)
        self._jobs: "queue.Queue[_Job]" = queue.Queue()  # pandalint: disable=BPR1401 -- one job per worker by construction: a _FetchWorker is checked out of the free list per fetch and holds exactly one job until it completes or is abandoned
        self.start()

    def submit(self, job: _Job) -> None:
        self._jobs.put(job)

    def run(self) -> None:
        while True:
            job = self._jobs.get()
            try:
                res, exc = job.fn(), None
            except BaseException as e:  # noqa: BLE001 — delivered to caller
                res, exc = None, e
            with _pool_lock:
                if job.state == "abandoned":
                    # late completion: drain-or-discard the stale result
                    # (it may pin a device buffer) and reclaim this thread
                    job.result = job.exc = None
                    job.fn = None
                    _free_workers.append(self)
                    continue
                job.state = "done"
                job.result, job.exc = res, exc
            job.event.set()


def fetch_pool_stats() -> dict:
    """{'created', 'free'} — the no-thread-growth regression test's view."""
    with _pool_lock:
        return {"created": _workers_created, "free": len(_free_workers)}


def fetch_with_deadline(fn, deadline_s: float | None):
    """Run ``fn()`` on an abandonable worker; raise DeadlineExceeded after
    ``deadline_s``. ``None`` runs inline (no deadline, no thread)."""
    global _workers_created
    if deadline_s is None:
        return fn()
    with _pool_lock:
        worker = _free_workers.pop() if _free_workers else None
        if worker is None:
            _workers_created += 1
            idx = _workers_created
    if worker is None:
        worker = _FetchWorker(idx)
    job = _Job(fn)
    worker.submit(job)
    finished = job.event.wait(deadline_s)
    with _pool_lock:
        if not finished and job.state == "done":
            finished = True  # completion raced the timeout: take the result
        if finished:
            _free_workers.append(worker)
        else:
            job.state = "abandoned"
    if not finished:
        raise DeadlineExceeded(
            f"device leg exceeded its {deadline_s:.3f}s deadline"
        )
    if job.exc is not None:
        raise job.exc
    return job.result


def retry_call(fn, policy: FaultPolicy, domain: str, *, count=None):
    """``fn()`` under the policy's per-attempt deadline, retried with
    backoff+jitter up to ``policy.retries`` times. The last failure
    propagates (callers decide the fallback); programming errors and
    SystemExit (honey-badger terminate) never retry. ``count`` is the
    engine's ``_stat_add`` so retries land in stats()/BENCH."""
    last: BaseException | None = None
    for attempt in range(policy.retries + 1):
        try:
            return fetch_with_deadline(fn, policy.deadline_s)
        except PROGRAMMING_ERRORS:
            raise
        except Exception as exc:
            last = exc
            if attempt < policy.retries:
                probes.coproc_retries_total.inc()
                if count is not None:
                    count("n_retries", 1.0)
                logger.debug(
                    "retrying %s after %s [attempt %d/%d]",
                    domain, kind_of(exc), attempt + 1, policy.retries,
                )
                time.sleep(policy.backoff(attempt))
    assert last is not None
    raise last


# ------------------------------------------------------------ circuit breaker
STATE_CLOSED = "closed"
STATE_OPEN = "open"
STATE_HALF_OPEN = "half_open"
STATE_NUM = {STATE_CLOSED: 0.0, STATE_OPEN: 1.0, STATE_HALF_OPEN: 2.0}


class CircuitBreaker:
    """Per-engine device circuit breaker.

    closed --[threshold consecutive failures]--> open
    open --[cooldown elapsed]--> half_open (admits ONE probe launch)
    half_open --[probe success]--> closed / --[probe failure]--> open

    While not closed, ``allow_device()`` answers False and the engine runs
    every stage on the exact host path — output is identical, only slower.
    ``clock`` is injectable so the state machine is testable without
    sleeping through cooldowns.
    """

    def __init__(
        self,
        threshold: int = 5,
        cooldown_s: float = 30.0,
        clock=time.monotonic,
        probe_timeout_s: float | None = None,
        name: str = "",
        listener=None,
    ) -> None:
        self.threshold = max(1, int(threshold))
        self.cooldown_s = float(cooldown_s)
        # identity + transition hook for the governor's decision journal:
        # ``listener(name, old_state, new_state, info)`` fires AFTER the
        # breaker's lock is released (a listener that re-enters breaker
        # state, or appends to a locked journal, must not deadlock here)
        self.name = name
        self._listener = listener
        # how long an admitted half-open probe may run before its slot is
        # presumed abandoned. MUST exceed the probe launch's own retry
        # envelope (FaultPolicy.envelope_s) or a legitimately-slow probe
        # gets a second probe stacked onto the same struggling device.
        self.probe_timeout_s = (
            float(probe_timeout_s) if probe_timeout_s is not None
            else self.cooldown_s
        )
        self._clock = clock
        from redpanda_tpu.coproc import lockwatch

        self._lock = lockwatch.wrap(threading.Lock(), "CircuitBreaker._lock")
        self._state = STATE_CLOSED
        self._consecutive = 0
        self._opened_at = 0.0
        self._probe_inflight = False
        self._probe_started_at = 0.0
        self.trips = 0

    def _tick_locked(self) -> None:
        if (
            self._state == STATE_OPEN
            and self._clock() - self._opened_at >= self.cooldown_s
        ):
            self._state = STATE_HALF_OPEN
            self._probe_inflight = False
        elif (
            self._state == STATE_HALF_OPEN
            and self._probe_inflight
            and self._clock() - self._probe_started_at >= self.probe_timeout_s
        ):
            # stale probe: the admitted launch never reported a verdict
            # (e.g. it degraded on a HOST-side fault before touching the
            # device, which is no verdict on the device at all). Without
            # this, _probe_inflight would wedge the breaker in half_open
            # forever and the engine would stay demoted until restart.
            self._probe_inflight = False

    def _tick_event_locked(self, events: list) -> None:
        """Run _tick_locked and capture its transition (if any) while the
        lock is STILL held — the (old, new, reason, info) tuple must be a
        consistent snapshot of one transition, not a re-read after other
        threads may have moved the state again."""
        old = self._state
        self._tick_locked()
        if self._state != old:
            events.append((
                old, self._state,
                "cooldown elapsed; half-open probe slot available",
                self._info_locked(),
            ))

    def _info_locked(self) -> dict:
        return {"consecutive_failures": self._consecutive, "trips": self.trips}

    def _fire(self, events: list) -> None:
        """Deliver captured transitions OUTSIDE the lock (the listener
        appends to the governor's journal, which takes its own locks)."""
        if self._listener is None:
            return
        for old, new, reason, info in events:
            try:
                self._listener(self.name, old, new, reason, info)
            except Exception:  # pragma: no cover - observability must not kill the data path
                logger.exception("breaker transition listener failed")

    @property
    def state(self) -> str:
        events: list = []
        with self._lock:
            self._tick_event_locked(events)
            state = self._state
        self._fire(events)
        return state

    def allow_device(self) -> bool:
        """May the next launch touch the device? Half-open admits exactly
        one probe at a time; everyone else stays on the host fallback until
        that probe's verdict lands."""
        events: list = []
        with self._lock:
            self._tick_event_locked(events)
            if self._state == STATE_CLOSED:
                allowed = True
            elif self._state == STATE_HALF_OPEN and not self._probe_inflight:
                self._probe_inflight = True
                self._probe_started_at = self._clock()
                allowed = True
            else:
                allowed = False
        self._fire(events)
        return allowed

    def record_success(self) -> None:
        events: list = []
        with self._lock:
            self._tick_event_locked(events)
            self._consecutive = 0
            if self._state == STATE_HALF_OPEN:
                logger.info(
                    "coproc breaker %s re-closed after successful half-open "
                    "probe", self.name or "(unnamed)",
                )
                old = self._state
                self._state = STATE_CLOSED
                self._probe_inflight = False
                events.append((
                    old, STATE_CLOSED,
                    "half-open probe succeeded; device re-admitted",
                    self._info_locked(),
                ))
        self._fire(events)

    def record_failure(self) -> None:
        events: list = []
        with self._lock:
            self._tick_event_locked(events)
            self._consecutive += 1
            tripped = False
            if self._state == STATE_HALF_OPEN:
                tripped = True  # probe failed: straight back to open
            elif (
                self._state == STATE_CLOSED
                and self._consecutive >= self.threshold
            ):
                tripped = True
            if tripped:
                old = self._state
                self._state = STATE_OPEN
                self._opened_at = self._clock()
                self._probe_inflight = False
                self.trips += 1
                probes.coproc_breaker_trips.inc()
                logger.warning(
                    "coproc breaker %s OPEN after %d consecutive device "
                    "failures (trip #%d); domain demoted to host execution, "
                    "re-probe in %.1fs",
                    self.name or "(unnamed)", self._consecutive, self.trips,
                    self.cooldown_s,
                )
                events.append((
                    old, STATE_OPEN,
                    f"{self._consecutive} consecutive failure(s) against "
                    f"threshold {self.threshold}",
                    self._info_locked(),
                ))
        self._fire(events)

    def snapshot(self) -> dict:
        events: list = []
        with self._lock:
            self._tick_event_locked(events)
            out = {
                "state": self._state,
                "consecutive_failures": self._consecutive,
                "trips": self.trips,
                "threshold": self.threshold,
                "cooldown_ms": round(self.cooldown_s * 1000.0),
            }
            if self.name:
                out["domain"] = self.name
        self._fire(events)
        return out
