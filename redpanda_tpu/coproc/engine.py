"""The TPU transform engine — replacement for the reference's Node.js sidecar.

The reference ships record batches over RPC to a Node.js process that runs
user JS per record (ProcessBatchServer, src/js/modules/rpc/server.ts:79,
applyCoprocessor :244-266). Here the "supervisor" is a JAX engine: deploys
carry a declarative TransformSpec (redpanda_tpu.ops.transforms) compiled once
per (script, row-stride) into a fused XLA program.

Data-path architecture (why it looks the way it does): the link between the
broker runtime and the device charges per *round trip*, not per byte — a
synchronous launch over the axon tunnel costs ~66 ms while the actual
compute for a 64-partition tick is ~3 ms. The engine therefore never blocks
per call:

  * ``submit()`` packs every record of a request into ONE staging array
    (lengths ride in trailing metadata columns — exactly one H2D), issues
    the launch, and immediately queues an async device→host copy of the ONE
    packed result array. It returns a :class:`Ticket` without synchronizing.
  * ``submit_group()`` goes further and fuses MANY requests into one launch
    per script, amortizing the H2D round trip across all of them.
  * ``Ticket.result()`` materializes the reply; by the time a pipelined
    caller harvests, the async copy has landed and the call is host-speed.
  * ``process_batch()`` is the synchronous compatibility wrapper
    (submit + result), matching the supervisor RPC schema (coproc/gen.json):
    enable_coprocessors / disable_coprocessors / disable_all /
    process_batch / heartbeat.

Error policies mirror the public SDK (Coprocessor.ts:21-24):
SkipOnFailure drops the failing batch but keeps the script; Deregister
removes the script on first failure.
"""

from __future__ import annotations

import enum
import threading
from dataclasses import dataclass, field

import numpy as np

from redpanda_tpu.hashing.xx import xxhash64
from redpanda_tpu.models.fundamental import NTP
from redpanda_tpu.models.record import Compression, RecordBatch
from redpanda_tpu.ops.pipeline import IN_META, make_packed_pipeline, unpack_result
from redpanda_tpu.ops.transforms import TransformSpec
from redpanda_tpu.coproc import batch_codec


class EnableResponseCode(enum.IntEnum):
    success = 0
    internal_error = 1
    script_id_already_exists = 2
    script_contains_invalid_topic = 3
    script_contains_no_topics = 4


class DisableResponseCode(enum.IntEnum):
    success = 0
    internal_error = 1
    script_id_does_not_exist = 2


class ErrorPolicy(enum.IntEnum):
    skip_on_failure = 0
    deregister = 1


@dataclass
class ScriptHandle:
    script_id: int
    spec: TransformSpec
    input_topics: tuple[str, ...]
    policy: ErrorPolicy = ErrorPolicy.skip_on_failure
    checksum: int = 0


@dataclass
class ProcessBatchItem:
    script_id: int
    ntp: NTP
    batches: list[RecordBatch]


@dataclass
class ProcessBatchRequest:
    items: list[ProcessBatchItem] = field(default_factory=list)


@dataclass
class ProcessBatchReplyItem:
    script_id: int
    source: NTP
    batches: list[RecordBatch]  # transformed output (may be empty)


@dataclass
class ProcessBatchReply:
    items: list[ProcessBatchReplyItem] = field(default_factory=list)
    deregistered: list[int] = field(default_factory=list)


def _bucket_rows(n: int) -> int:
    """Round the row count up so jit sees few distinct shapes."""
    b = 128
    while b < n:
        b *= 2
    return b


class _Launch:
    """One device launch for one script, possibly spanning many requests."""

    __slots__ = ("script_id", "policy", "r_out", "ranges", "fits", "_packed_dev",
                 "_mat", "_lock")

    def __init__(self, script_id: int, policy: ErrorPolicy):
        self.script_id = script_id
        self.policy = policy
        self.r_out = 0
        self.ranges: list[tuple[int, int]] = []
        self.fits: np.ndarray | None = None
        self._packed_dev = None
        self._mat = None
        self._lock = threading.Lock()

    def materialize(self):
        """(out, out_len, keep) host arrays; fetch happens at most once.

        Locked: tickets of one submit_group share this launch and may be
        harvested from different threads (the pacemaker harvests via
        run_in_executor)."""
        with self._lock:
            if self._mat is None:
                if self._packed_dev is None:  # zero-record launch
                    self._mat = (
                        np.zeros((0, self.r_out), np.uint8),
                        np.zeros(0, np.int32),
                        np.zeros(0, bool),
                    )
                else:
                    packed = np.asarray(self._packed_dev)
                    self._packed_dev = None
                    out, out_len, keep = unpack_result(packed, self.r_out)
                    n = len(self.fits)
                    self._mat = (out[:n], out_len[:n], keep[:n] & self.fits)
            return self._mat


# Per-slot dispositions inside a Ticket.
_UNKNOWN, _EMPTY, _DEREGISTERED, _LAUNCHED = range(4)


class Ticket:
    """Handle for an in-flight engine request; ``result()`` materializes it."""

    def __init__(self, engine: "TpuEngine"):
        self._engine = engine
        # (disposition, item, launch, [batch range indices])
        self._slots: list[tuple] = []

    def result(self) -> ProcessBatchReply:
        reply = ProcessBatchReply()
        dereg: set[int] = set()
        failed_scripts: set[int] = set()
        for disp, item, launch, rng in self._slots:
            if disp == _UNKNOWN or disp == _EMPTY:
                reply.items.append(ProcessBatchReplyItem(item.script_id, item.ntp, []))
            elif disp == _DEREGISTERED:
                dereg.add(item.script_id)
            else:
                if launch.script_id in failed_scripts:
                    if launch.policy != ErrorPolicy.deregister:
                        reply.items.append(
                            ProcessBatchReplyItem(item.script_id, item.ntp, [])
                        )
                    continue
                try:
                    out_batches = self._rebuild(item, launch, rng)
                    reply.items.append(
                        ProcessBatchReplyItem(item.script_id, item.ntp, out_batches)
                    )
                except Exception:
                    failed_scripts.add(launch.script_id)
                    if launch.policy == ErrorPolicy.deregister:
                        self._engine.disable_coprocessors([launch.script_id])
                        dereg.add(launch.script_id)
                        reply.items = [
                            ri for ri in reply.items if ri.script_id != launch.script_id
                        ]
                    else:
                        reply.items.append(
                            ProcessBatchReplyItem(item.script_id, item.ntp, [])
                        )
        reply.deregistered = sorted(dereg)
        return reply

    def _rebuild(self, item: ProcessBatchItem, launch: _Launch, rng) -> list[RecordBatch]:
        out, out_len, keep = launch.materialize()
        e = self._engine
        item_out: list[RecordBatch] = []
        for batch, ridx in zip(item.batches, rng):
            start, end = launch.ranges[ridx]
            rebuilt = batch_codec.rebuild_batch(
                batch,
                out[start:end],
                out_len[start:end],
                keep[start:end],
                compress_threshold=e._compress_threshold,
                codec=e._output_codec,
            )
            if rebuilt is not None:
                item_out.append(rebuilt)
        return item_out


class TpuEngine:
    """HandleTable + batched async device execution."""

    def __init__(
        self,
        *,
        row_stride: int = 1024,
        compress_threshold: int = 512,
        output_codec: Compression = Compression.zstd,
    ):
        self._handles: dict[int, ScriptHandle] = {}
        self._row_stride = row_stride
        self._compress_threshold = compress_threshold
        self._output_codec = output_codec
        self._pipelines: dict[int, tuple] = {}  # script_id -> (fn, r_out)

    # ------------------------------------------------------------ control
    def enable_coprocessors(
        self, scripts: list[tuple[int, str, tuple[str, ...]]]
    ) -> list[EnableResponseCode]:
        """scripts: [(script_id, spec_json, input_topics)]."""
        out = []
        for script_id, spec_json, topics in scripts:
            if script_id in self._handles:
                out.append(EnableResponseCode.script_id_already_exists)
                continue
            if not topics:
                out.append(EnableResponseCode.script_contains_no_topics)
                continue
            if any(t.startswith("__") or ".$" in t for t in topics):
                out.append(EnableResponseCode.script_contains_invalid_topic)
                continue
            try:
                spec = TransformSpec.from_json(spec_json)
                self._pipelines[script_id] = make_packed_pipeline(spec, self._row_stride)
            except Exception:
                out.append(EnableResponseCode.internal_error)
                continue
            self._handles[script_id] = ScriptHandle(
                script_id, spec, tuple(topics), checksum=xxhash64(spec_json)
            )
            out.append(EnableResponseCode.success)
        return out

    def disable_coprocessors(self, script_ids: list[int]) -> list[DisableResponseCode]:
        out = []
        for sid in script_ids:
            if sid in self._handles:
                del self._handles[sid]
                self._pipelines.pop(sid, None)
                out.append(DisableResponseCode.success)
            else:
                out.append(DisableResponseCode.script_id_does_not_exist)
        return out

    def disable_all_coprocessors(self) -> int:
        n = len(self._handles)
        self._handles.clear()
        self._pipelines.clear()
        return n

    def heartbeat(self) -> int:
        """Returns the number of registered scripts (liveness probe)."""
        return len(self._handles)

    @property
    def scripts(self) -> dict[int, ScriptHandle]:
        return dict(self._handles)

    # ------------------------------------------------------------ data path
    def process_batch(self, req: ProcessBatchRequest) -> ProcessBatchReply:
        """Synchronous wrapper: one submit, one harvest."""
        return self.submit(req).result()

    def submit(self, req: ProcessBatchRequest) -> Ticket:
        return self.submit_group([req])[0]

    def submit_group(self, reqs: list[ProcessBatchRequest]) -> list[Ticket]:
        """Fuse many requests into ONE launch per script.

        All records of all requests targeting a script are packed into a
        single staging array: one H2D transfer, one device program, one
        async D2H — the round-trip cost of the device link is paid once per
        group instead of once per request.
        """
        tickets = [Ticket(self) for _ in reqs]
        # script_id -> list of (ticket, slot_idx, item)
        by_script: dict[int, list[tuple]] = {}
        for ticket, req in zip(tickets, reqs):
            for item in req.items:
                if item.script_id not in self._handles:
                    ticket._slots.append((_UNKNOWN, item, None, None))
                else:
                    slot_idx = len(ticket._slots)
                    ticket._slots.append(None)  # placeholder, filled below
                    by_script.setdefault(item.script_id, []).append(
                        (ticket, slot_idx, item)
                    )
        for script_id, entries in by_script.items():
            handle = self._handles[script_id]
            launch = _Launch(script_id, handle.policy)
            try:
                self._dispatch(script_id, launch, entries)
                ridx = 0
                for ticket, slot_idx, item in entries:
                    rng = list(range(ridx, ridx + len(item.batches)))
                    ridx += len(item.batches)
                    ticket._slots[slot_idx] = (_LAUNCHED, item, launch, rng)
            except Exception as exc:
                if handle.policy == ErrorPolicy.deregister:
                    self.disable_coprocessors([script_id])
                    for ticket, slot_idx, item in entries:
                        ticket._slots[slot_idx] = (_DEREGISTERED, item, None, None)
                else:
                    for ticket, slot_idx, item in entries:
                        ticket._slots[slot_idx] = (_EMPTY, item, None, None)
        return tickets

    def _dispatch(self, script_id: int, launch: _Launch, entries: list[tuple]) -> None:
        """Pack all entries' records and issue the (async) device launch."""
        import jax

        fn, r_out = self._pipelines[script_id]
        launch.r_out = r_out
        all_batches = [b for _, _, item in entries for b in item.batches]
        exploded = batch_codec.explode_batches(all_batches)
        launch.ranges = exploded.ranges
        n = len(exploded.sizes)
        launch.fits = exploded.sizes <= self._row_stride
        if n == 0:
            return
        n_pad = _bucket_rows(n)
        staged = self._pack_staged(exploded, n_pad)
        dev = jax.device_put(staged)
        packed = fn(dev)
        packed.copy_to_host_async()
        launch._packed_dev = packed

    def _pack_staged(self, exploded, n_pad: int) -> np.ndarray:
        """[n_pad, row_stride + IN_META] uint8: record bytes then LE32 length.

        Records wider than the staging row cannot be transformed faithfully:
        their length is staged as 0 here and their keep bit is cleared after
        the launch via ``launch.fits`` (the reference bounds record size
        upstream via coproc_max_batch_size; truncating would corrupt data
        silently).
        """
        r = self._row_stride
        stride = r + IN_META
        n = len(exploded.sizes)
        offsets = exploded.offsets
        sizes = exploded.sizes
        if n_pad != n:
            offsets = np.concatenate([offsets, np.zeros(n_pad - n, np.int64)])
            sizes = np.concatenate([sizes, np.zeros(n_pad - n, np.int32)])
        fits = sizes <= r
        lens = np.where(fits, sizes, 0).astype("<i4")
        try:
            from redpanda_tpu.native import lib
        except Exception:
            lib = None
        if lib is not None:
            staged, _ = lib.pack_rows(exploded.joined, offsets, sizes, stride)
        else:
            from redpanda_tpu.ops.packing import pack_rows

            vals = [
                exploded.joined[o : o + s] for o, s in zip(offsets, np.minimum(sizes, r))
            ]
            staged, _ = pack_rows(vals, stride)
        staged[:, r : r + 4] = lens.view(np.uint8).reshape(n_pad, 4)
        staged[:, r + 4 :] = 0
        return staged
