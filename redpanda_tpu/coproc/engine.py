"""The TPU transform engine — replacement for the reference's Node.js sidecar.

The reference ships record batches over RPC to a Node.js process that runs
user JS per record (ProcessBatchServer, src/js/modules/rpc/server.ts:79,
applyCoprocessor :244-266). Here the "supervisor" is a JAX engine: deploys
carry a declarative TransformSpec (redpanda_tpu.ops.transforms) compiled once
per (script, row-stride) into a fused XLA program; process_batch packs every
record of every input batch into one [N, R] staging array, runs a single
device launch, and reassembles output batches natively.

The RPC surface mirrors the supervisor schema (coproc/gen.json):
enable_coprocessors / disable_coprocessors / disable_all / process_batch /
heartbeat — so the engine can sit in-process (hermetic fixtures, the
reference's supervisor_test_fixture.h pattern) or behind the rpc server.

Error policies mirror the public SDK (Coprocessor.ts:21-24):
SkipOnFailure drops the failing batch but keeps the script; Deregister
removes the script on first failure.
"""

from __future__ import annotations

import enum
from dataclasses import dataclass, field

import numpy as np

from redpanda_tpu.hashing.xx import xxhash64
from redpanda_tpu.models.fundamental import NTP
from redpanda_tpu.models.record import Compression, RecordBatch
from redpanda_tpu.ops.pipeline import make_record_pipeline
from redpanda_tpu.ops.transforms import TransformSpec
from redpanda_tpu.coproc import batch_codec


class EnableResponseCode(enum.IntEnum):
    success = 0
    internal_error = 1
    script_id_already_exists = 2
    script_contains_invalid_topic = 3
    script_contains_no_topics = 4


class DisableResponseCode(enum.IntEnum):
    success = 0
    internal_error = 1
    script_id_does_not_exist = 2


class ErrorPolicy(enum.IntEnum):
    skip_on_failure = 0
    deregister = 1


@dataclass
class ScriptHandle:
    script_id: int
    spec: TransformSpec
    input_topics: tuple[str, ...]
    policy: ErrorPolicy = ErrorPolicy.skip_on_failure
    checksum: int = 0


@dataclass
class ProcessBatchItem:
    script_id: int
    ntp: NTP
    batches: list[RecordBatch]


@dataclass
class ProcessBatchRequest:
    items: list[ProcessBatchItem] = field(default_factory=list)


@dataclass
class ProcessBatchReplyItem:
    script_id: int
    source: NTP
    batches: list[RecordBatch]  # transformed output (may be empty)


@dataclass
class ProcessBatchReply:
    items: list[ProcessBatchReplyItem] = field(default_factory=list)
    deregistered: list[int] = field(default_factory=list)


class TpuEngine:
    """HandleTable + batched device execution."""

    def __init__(
        self,
        *,
        row_stride: int = 1024,
        compress_threshold: int = 512,
        output_codec: Compression = Compression.zstd,
    ):
        self._handles: dict[int, ScriptHandle] = {}
        self._row_stride = row_stride
        self._compress_threshold = compress_threshold
        self._output_codec = output_codec
        self._pipelines: dict[int, tuple] = {}  # script_id -> (fn, r_out)

    # ------------------------------------------------------------ control
    def enable_coprocessors(
        self, scripts: list[tuple[int, str, tuple[str, ...]]]
    ) -> list[EnableResponseCode]:
        """scripts: [(script_id, spec_json, input_topics)]."""
        out = []
        for script_id, spec_json, topics in scripts:
            if script_id in self._handles:
                out.append(EnableResponseCode.script_id_already_exists)
                continue
            if not topics:
                out.append(EnableResponseCode.script_contains_no_topics)
                continue
            if any(t.startswith("__") or ".$" in t for t in topics):
                out.append(EnableResponseCode.script_contains_invalid_topic)
                continue
            try:
                spec = TransformSpec.from_json(spec_json)
                self._pipelines[script_id] = make_record_pipeline(spec, self._row_stride)
            except Exception:
                out.append(EnableResponseCode.internal_error)
                continue
            self._handles[script_id] = ScriptHandle(
                script_id, spec, tuple(topics), checksum=xxhash64(spec_json)
            )
            out.append(EnableResponseCode.success)
        return out

    def disable_coprocessors(self, script_ids: list[int]) -> list[DisableResponseCode]:
        out = []
        for sid in script_ids:
            if sid in self._handles:
                del self._handles[sid]
                self._pipelines.pop(sid, None)
                out.append(DisableResponseCode.success)
            else:
                out.append(DisableResponseCode.script_id_does_not_exist)
        return out

    def disable_all_coprocessors(self) -> int:
        n = len(self._handles)
        self._handles.clear()
        self._pipelines.clear()
        return n

    def heartbeat(self) -> int:
        """Returns the number of registered scripts (liveness probe)."""
        return len(self._handles)

    @property
    def scripts(self) -> dict[int, ScriptHandle]:
        return dict(self._handles)

    # ------------------------------------------------------------ data path
    def process_batch(self, req: ProcessBatchRequest) -> ProcessBatchReply:
        """One device launch per script, not per (script, ntp): every record
        of every partition's batches is packed into a single [N, R] staging
        array — the [partition, batch, record] batching the engine exists
        for. Items of unknown scripts get empty replies so callers resync."""
        reply = ProcessBatchReply()
        by_script: dict[int, list[ProcessBatchItem]] = {}
        for item in req.items:
            if item.script_id not in self._handles:
                reply.items.append(ProcessBatchReplyItem(item.script_id, item.ntp, []))
            else:
                by_script.setdefault(item.script_id, []).append(item)
        for script_id, items in by_script.items():
            handle = self._handles[script_id]
            try:
                outputs = self._run_script_group(script_id, items)
                for item, out_batches in zip(items, outputs):
                    reply.items.append(
                        ProcessBatchReplyItem(script_id, item.ntp, out_batches)
                    )
            except Exception:
                if handle.policy == ErrorPolicy.deregister:
                    self.disable_coprocessors([script_id])
                    reply.deregistered.append(script_id)
                else:  # skip_on_failure: ack every batch with no output
                    for item in items:
                        reply.items.append(ProcessBatchReplyItem(script_id, item.ntp, []))
        return reply

    def _run_script_group(
        self, script_id: int, items: list[ProcessBatchItem]
    ) -> list[list[RecordBatch]]:
        from redpanda_tpu.native import lib

        all_batches = [b for item in items for b in item.batches]
        exploded = batch_codec.explode_batches(all_batches)
        n = len(exploded.sizes)
        if n == 0:
            return [[] for _ in items]
        if lib is not None:
            rows, _ = lib.pack_rows(
                exploded.joined, exploded.offsets, exploded.sizes, self._row_stride
            )
        else:
            vals = [
                exploded.joined[o : o + s]
                for o, s in zip(exploded.offsets, exploded.sizes)
            ]
            from redpanda_tpu.ops.packing import pack_rows

            rows, _ = pack_rows(vals, self._row_stride)
        # Records wider than the staging row cannot be transformed faithfully:
        # drop them (the reference bounds record size upstream via
        # coproc_max_batch_size; truncating would corrupt data silently).
        fits = exploded.sizes <= self._row_stride
        lens = np.where(fits, exploded.sizes, 0).astype(np.int32)
        fn, _r_out = self._pipelines[script_id]
        out, out_len, keep, _out_crc = fn(rows, lens)
        out = np.asarray(out)
        out_len = np.asarray(out_len)
        keep = np.asarray(keep) & fits
        results: list[list[RecordBatch]] = []
        range_it = iter(exploded.ranges)
        for item in items:
            item_out: list[RecordBatch] = []
            for batch in item.batches:
                start, end = next(range_it)
                rebuilt = batch_codec.rebuild_batch(
                    batch,
                    out[start:end],
                    out_len[start:end],
                    keep[start:end],
                    compress_threshold=self._compress_threshold,
                    codec=self._output_codec,
                )
                if rebuilt is not None:
                    item_out.append(rebuilt)
            results.append(item_out)
        return results
