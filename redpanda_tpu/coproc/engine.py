"""The TPU transform engine — replacement for the reference's Node.js sidecar.

The reference ships record batches over RPC to a Node.js process that runs
user JS per record (ProcessBatchServer, src/js/modules/rpc/server.ts:79,
applyCoprocessor :244-266). Here the "supervisor" is a JAX engine: deploys
carry a declarative TransformSpec (redpanda_tpu.ops.transforms) compiled once
per script into an execution plan (coproc/column_plan.py).

Data-path architecture (why it looks the way it does): the link between the
broker runtime and the device charges per round trip AND per byte, and both
are expensive over a tunnel (tools/link_probe.py measured ~70 ms per
synchronous op, H2D ~15-70 MB/s, D2H ~3-14 MB/s, while a 64-partition tick
needs only ~3 ms of device compute). The engine therefore ships as little
as possible and never blocks per call:

  * **columnar plans** (v2 ``where`` specs) ship per-field columns — a few
    bytes per record — and fetch ONE BIT per record back (packed); the
    device evaluates the whole predicate tree. Projections are assembled
    host-side from columns the native columnarizer already extracted.
  * **payload plans** (v1 raw-byte specs) stage full records; correct
    everywhere, fast only on wide links (co-located PCIe/ICI).
  * **host plans** (identity / uppercase / py_transform escape hatch) have
    no device stage; they run in the engine's host stage with the same
    interface.
  * ``submit_group()`` fuses MANY requests into one launch per script;
    ``Ticket.result()`` materializes replies after the async D2H lands.
  * ``process_batch()`` is the synchronous compatibility wrapper
    (submit + result), matching the supervisor RPC schema (coproc/gen.json):
    enable_coprocessors / disable_coprocessors / disable_all /
    process_batch / heartbeat.

Per-stage wall time and link bytes accumulate in ``stats()`` so the bench
(and the engine's own mode decisions) argue from data.

Error policies mirror the public SDK (Coprocessor.ts:21-24):
SkipOnFailure drops the failing batch but keeps the script; Deregister
removes the script on first failure.
"""

from __future__ import annotations

import enum
import logging
import queue
import threading
import time
import weakref
from collections import defaultdict
from dataclasses import dataclass, field

import numpy as np

from redpanda_tpu.hashing.xx import xxhash64
from redpanda_tpu.models.fundamental import NTP
from redpanda_tpu.models.record import Compression, RecordBatch
from redpanda_tpu.observability import probes
from redpanda_tpu.observability.trace import tracer
from redpanda_tpu.ops.pipeline import IN_META, make_packed_pipeline, unpack_result

logger = logging.getLogger("rptpu.coproc.engine")
from redpanda_tpu.ops.transforms import TransformSpec
from redpanda_tpu.coproc import (
    batch_codec,
    colcache,
    faults,
    governor,
    host_pool,
    leakwatch,
    lockwatch,
    meshrunner,
)
from redpanda_tpu.coproc.column_plan import ColumnarPlan, HostPlan, PayloadPlan, plan_spec
from redpanda_tpu.resource_mgmt import admission as rm_admission
from redpanda_tpu.resource_mgmt import budgets as rm_budgets


class EnableResponseCode(enum.IntEnum):
    success = 0
    internal_error = 1
    script_id_already_exists = 2
    script_contains_invalid_topic = 3
    script_contains_no_topics = 4


class DisableResponseCode(enum.IntEnum):
    success = 0
    internal_error = 1
    script_id_does_not_exist = 2


class ErrorPolicy(enum.IntEnum):
    skip_on_failure = 0
    deregister = 1


@dataclass
class ScriptHandle:
    script_id: int
    spec: TransformSpec
    input_topics: tuple[str, ...]
    policy: ErrorPolicy = ErrorPolicy.skip_on_failure
    checksum: int = 0


@dataclass
class ProcessBatchItem:
    script_id: int
    ntp: NTP
    batches: list[RecordBatch]


@dataclass
class ProcessBatchRequest:
    items: list[ProcessBatchItem] = field(default_factory=list)
    # pandaprobe trace id: executor threads don't inherit the caller's task
    # context, so the ambient id rides the request object across the hop
    # (pacemaker tick → engine submit → harvester thread).
    trace_id: int | None = None


@dataclass
class ProcessBatchReplyItem:
    script_id: int
    source: NTP
    batches: list[RecordBatch]  # transformed output (may be empty)


@dataclass
class ProcessBatchReply:
    items: list[ProcessBatchReplyItem] = field(default_factory=list)
    deregistered: list[int] = field(default_factory=list)


def _bucket_rows(n: int) -> int:
    """Round the row count up so jit sees few distinct shapes."""
    b = 128
    while b < n:
        b *= 2
    return b


# serializes _mask_state transitions between the harvester, timed-out
# callers claiming their still-queued mask, and sharded-launch abandonment
# (transitions are rare and O(1); one process-wide lock is plenty)
_mask_claim_lock = threading.Lock()


class _MaskSlot:
    """One shard's predicate mask in flight (host-evaluated or device).

    Field names deliberately mirror _Launch's mask fields
    (``_mask_dev``/``_mask_np``/``_mask_event``/``trace_id``/``_enq_t``):
    the harvester loop serves either shape without caring which it got.
    """

    __slots__ = ("n", "_mask_dev", "_mask_np", "_mask_event",
                 "trace_id", "_enq_t", "_cols", "_mask_state")

    def __init__(self, n: int):
        self.n = n
        self._mask_dev = None
        self._mask_np = None
        self._mask_event: threading.Event | None = None
        self.trace_id: int | None = None
        self._enq_t = 0.0
        # extracted predicate columns, retained while a device mask is in
        # flight: the exact numpy fallback re-evaluates over these if the
        # D2H fetch dies (faults.MASK_FETCH domain)
        self._cols = None
        # claim protocol (guarded by _mask_claim_lock): "idle" -> "queued"
        # on enqueue; the harvester CASes queued -> "harvesting" on
        # dequeue; a caller that timed out while its mask was still QUEUED
        # (harvester busy on an earlier wedged mask) CASes queued ->
        # "claimed" and fetches itself; a degraded sharded launch marks
        # its orphans "abandoned". The harvester skips claimed/abandoned
        # without a fetch or a breaker verdict — one mask, one envelope,
        # one verdict, no matter how deep the harvest queue is.
        self._mask_state = "idle"


class _HostShard:
    """One contiguous record-range shard of a launch's host stages.

    Everything here is produced by exactly one pool worker and read only
    after the fan-in barrier (pool.run returns) — shard workers never
    touch each other's state (pandalint SHD6xx enforces the discipline).
    """

    __slots__ = ("n", "ranges", "exploded", "proj_data", "proj_ok",
                 "mask", "stages")

    def __init__(self):
        self.n = 0
        self.ranges: list[tuple[int, int]] = []
        self.exploded = None
        self.proj_data = None
        self.proj_ok = None
        self.mask: _MaskSlot | None = None
        self.stages: dict[str, float] = {}


class _Launch:
    """One device launch for one script, possibly spanning many requests.

    ``materialize()`` yields (out_rows, out_len, keep) host arrays with one
    row per input record; mode decides where they come from:

    - payload: the fetched packed device result (full transformed rows).
    - columnar: keep = device mask bits & host projection-ok; rows are
      host-assembled projection columns (or packed input values for
      passthrough specs).
    - host: computed synchronously from the exploded inputs at harvest.

    When the engine's host-stage pool sharded the launch (``_shards`` set),
    the columnar harvest side assembles and frames per shard instead of
    launch-wide; the framed list is the in-order concatenation of the
    shards' framed lists, byte-identical to the inline path.
    """

    __slots__ = ("script_id", "policy", "mode", "r_out", "ranges", "fits",
                 "engine", "n", "_packed_dev", "_mask_dev", "_mask_np",
                 "_mask_event", "_proj_data", "_proj_ok", "_plan",
                 "_exploded", "_mat", "_gather_mat", "_framed", "_lock",
                 "_shards", "trace_id", "_enq_t", "_cols", "_staged_np",
                 "_mask_state", "_pending_slots")

    def __init__(self, script_id: int, policy: ErrorPolicy):
        self.script_id = script_id
        self.policy = policy
        self.trace_id: int | None = None
        self._enq_t = 0.0
        self.mode = "payload"
        self.r_out = 0
        self.ranges: list[tuple[int, int]] = []
        self.fits: np.ndarray | None = None
        self.engine = None
        self.n = 0
        self._packed_dev = None
        self._mask_dev = None
        self._mask_np = None
        self._mask_event: threading.Event | None = None
        self._proj_data = None
        self._proj_ok = None
        self._plan = None
        self._exploded = None
        self._mat = None
        self._gather_mat = None
        self._framed = None
        self._lock = lockwatch.wrap(threading.Lock(), "_Launch._lock")
        self._shards: list[_HostShard] | None = None
        # fault-domain fallbacks: predicate columns / staged payload rows
        # retained until their device result lands, so an exhausted device
        # retry can re-execute the stage host-side with exact output
        self._cols = None
        # see _MaskSlot._mask_state: same claim protocol, same harvester
        self._mask_state = "idle"
        # per-shard _MaskSlots this launch has enqueued to the harvester
        # (appended under self._lock by shard workers): a sharded launch
        # that degrades to the inline path abandons these so orphan masks
        # cost no envelopes and feed no stale verdicts to the breaker
        self._pending_slots: list[_MaskSlot] = []
        self._staged_np = None


    def _mat_payload(self):
        if self._packed_dev is None:  # zero-record launch
            return (
                np.zeros((0, self.r_out), np.uint8),
                np.zeros(0, np.int32),
                np.zeros(0, bool),
            )
        t0 = time.perf_counter()
        dev = self._packed_dev
        eng = self.engine
        if isinstance(dev, np.ndarray) or eng is None:
            # host-fallback result (already materialized) / bare test launch
            packed = np.asarray(dev)
        elif not eng.governor.breaker_for(faults.HARVEST).allow_device():
            # open harvest breaker: fetches are demoted straight to the
            # exact host fallback without spending a retry envelope
            packed = self._payload_host_fallback()
        else:
            def leg():
                faults.inject(faults.HARVEST)
                return np.asarray(dev)

            packed = eng._try_device_leg(faults.HARVEST, leg)
            if packed is None:
                packed = self._payload_host_fallback()
            else:
                eng.governor.breaker_for(faults.HARVEST).record_success()
        self._stat("t_fetch", t0)
        self._packed_dev = None
        self._staged_np = None
        out, out_len, keep = unpack_result(packed, self.r_out)
        n = len(self.fits)
        return out[:n], out_len[:n], keep[:n] & self.fits

    def _payload_host_fallback(self) -> np.ndarray:
        """Fail closed per-launch: re-run the packed pipeline on the CPU
        backend over the retained staged rows — the same program over the
        same bytes, so output is exact; only the executor changed. Raises
        when nothing was retained (the launch then follows ErrorPolicy,
        exactly like any unrecoverable script failure)."""
        import jax

        staged = self._staged_np  # pandalint: disable=RAC1102 -- the unlocked caller is _dispatch_payload, which runs BEFORE the launch is published to tickets (thread-local construction phase); every harvest-time caller reaches here under _Launch._lock via _materialize_locked
        eng = self.engine
        if staged is None or eng is None:
            raise RuntimeError(
                "payload host fallback impossible: staged rows not retained"
            )
        fn, _ = eng._pipelines[self.script_id]
        cpu = jax.local_devices(backend="cpu")[0]
        with jax.default_device(cpu):
            packed = np.asarray(fn(jax.device_put(staged, cpu)))
        eng._count_fallback(self.n)
        return packed

    def _resolve_keep(self, slot, n: int) -> np.ndarray:
        """Resolve a keep mask from a mask holder — the launch itself or a
        per-shard _MaskSlot (same field shape by design): no predicate,
        host-evaluated bits, or device fetch via the async-harvest event.
        The D2H discipline is subtle, so exactly ONE copy of it exists."""
        if slot._mask_dev is None and slot._mask_np is None:
            return np.ones(n, dtype=bool)  # no predicate: keep all present
        if slot._mask_dev is None:
            # host-evaluated mask (columnar_host ablation): already on host
            keep = np.unpackbits(slot._mask_np)[:n].astype(bool)
            slot._mask_np = None
            return keep
        t0 = time.perf_counter()
        eng = self.engine
        # wait out the harvester's WHOLE retry envelope, not one attempt's
        # deadline: timing out mid-envelope would start a duplicate
        # concurrent fetch of the same array and double-count the failure.
        # Sized off the governor's envelope BOUND (the max deadline ever
        # issued, = the static envelope until an adaptive raise happens),
        # and RE-READ before the second wait below: the harvester derives
        # its own deadline concurrently, and it publishes any raise into
        # the bound before fetching, so the re-reading waiter can never
        # end up shorter than the fetch it is waiting on
        wait_s = (
            eng.governor.envelope_bound_s(faults.HARVEST) + 1.0
            if eng is not None
            else 30.0
        )
        if slot._mask_event is not None:
            # harvester thread pays the link round trip concurrently
            # with the caller's host work; worst case we fetch ourselves.
            # Keep OUR fetch in a local — the harvester may still write
            # _mask_np (even None, on its own failure) after a timeout.
            finished = slot._mask_event.wait(timeout=wait_s)
            bits = slot._mask_np
            if bits is None:
                if finished:
                    # the harvester ran the FULL retry envelope on this
                    # mask and definitively failed (its breaker verdict is
                    # already recorded): re-running the same doomed fetch
                    # here would double-count the failure and double the
                    # dead-link wait — go straight to the exact fallback
                    bits = self._mask_host_fallback(slot)
                else:
                    # mask still QUEUED? The single harvester is busy on
                    # earlier (wedged) masks. Claim it — the harvester
                    # will skip the claimed slot, so this stays ONE fetch
                    # envelope and ONE breaker verdict per mask at any
                    # queue depth.
                    with _mask_claim_lock:
                        claimed = slot._mask_state == "queued"
                        if claimed:
                            slot._mask_state = "claimed"
                    if claimed:
                        bits = self._fetch_mask_bits(slot)
                    else:
                        # the harvester is ACTIVELY harvesting this mask:
                        # one more envelope bounds its verdict. Re-read
                        # the bound — the harvester published any adaptive
                        # raise into it before starting its fetch
                        if eng is not None:
                            wait_s = (
                                eng.governor.envelope_bound_s(faults.HARVEST)
                                + 1.0
                            )
                        finished = slot._mask_event.wait(timeout=wait_s)
                        bits = slot._mask_np
                        if bits is None:
                            # verdict recorded -> exact fallback; still
                            # nothing -> the thread itself is stuck, pay
                            # the fetch ourselves (genuinely exceptional)
                            bits = (
                                self._mask_host_fallback(slot)
                                if finished
                                else self._fetch_mask_bits(slot)
                            )
        else:
            bits = self._fetch_mask_bits(slot)
        self._stat("t_fetch", t0)
        slot._mask_dev = None
        slot._mask_np = None
        slot._cols = None
        return np.unpackbits(bits)[:n].astype(bool)

    def _fetch_mask_bits(self, slot) -> np.ndarray:
        """Deadline-bounded, retried D2H mask fetch with the EXACT numpy
        fallback: on exhausted retries the predicate re-evaluates over the
        retained extracted columns (faults.MASK_FETCH domain), so a dead
        link changes where the bits come from, never what they are."""
        eng = self.engine
        dev = slot._mask_dev
        if eng is None:  # bare launch in tests: old synchronous behavior
            return np.asarray(dev)
        fetch_breaker = eng.governor.breaker_for(faults.MASK_FETCH)
        if not fetch_breaker.allow_device():
            # open mask-fetch breaker: this domain is demoted — go straight
            # to the exact numpy fallback over the retained columns instead
            # of burning a full retry envelope on a known-dead D2H path.
            # Dispatch keeps its own breaker; launches stay on-device.
            return self._mask_host_fallback(slot)

        def leg():
            faults.inject(faults.MASK_FETCH)
            return np.asarray(dev)

        bits = eng._try_device_leg(faults.MASK_FETCH, leg)
        if bits is None:
            bits = self._mask_host_fallback(slot)
        else:
            fetch_breaker.record_success()
        return bits

    def _mask_host_fallback(self, slot) -> np.ndarray:
        """Exact numpy re-evaluation of the predicate over the retained
        extracted columns (same expression tree, same column bytes).
        Raises when nothing was retained — the launch then follows the
        script's ErrorPolicy like any unrecoverable failure."""
        cols = slot._cols
        if cols is None:
            raise RuntimeError(
                "mask host fallback impossible: predicate columns not retained"
            )
        bits = self._plan.eval_host_mask(cols)
        self.engine._count_fallback(slot.n)
        return bits

    def _mat_columnar(self):
        n = self.n
        if n == 0:
            return (
                np.zeros((0, max(self.r_out, 1)), np.uint8),
                np.zeros(0, np.int32),
                np.zeros(0, bool),
            )
        keep = self._resolve_keep(self, n)
        keep &= self._proj_ok
        t0 = time.perf_counter()
        plan: ColumnarPlan = self._plan
        if plan.passthrough:
            # Output = input value bytes of kept records (empty values are
            # legal and kept when the predicate says so — host_eval is the
            # normative semantics, unlike v1's drop-empty payload rule).
            ex = self._exploded
            stride = max(int(ex.sizes.max()) if n else 1, 1)
            rows, lens = _pack_values(ex, stride)
        else:
            rows, lens = plan.assemble_rows(self._proj_data, n)
        self._stat("t_assemble", t0)
        self._proj_data = None
        self._exploded = None
        return rows, lens, keep

    def _mat_host(self):
        plan: HostPlan = self._plan
        ex = self._exploded
        n = self.n
        if n == 0:
            return (
                np.zeros((0, 1), np.uint8),
                np.zeros(0, np.int32),
                np.zeros(0, bool),
            )
        t0 = time.perf_counter()
        if plan.kind == "python":
            outs = []
            for i in range(n):
                o = int(ex.offsets[i])
                val = ex.joined[o : o + int(ex.sizes[i])]
                try:
                    outs.append(plan.fn(val))
                except Exception as exc:
                    if self.policy == ErrorPolicy.deregister:
                        # propagate: Ticket._result_impl applies the policy
                        # and unloads the script (wasm_event.h Deregister)
                        raise
                    # user-code boundary: a script TypeError is a script
                    # failure, not an engine bug — never re-raise, but
                    # count it (skip_on_failure drops silently otherwise)
                    faults.note_failure("host_plan", exc)
                    outs.append(None)
            keep = np.array([o is not None for o in outs], dtype=bool)
            stride = max((len(o) for o in outs if o is not None), default=1)
            stride = max(stride, 1)
            rows = np.zeros((n, stride), dtype=np.uint8)
            lens = np.zeros(n, dtype=np.int32)
            for i, o in enumerate(outs):
                if o is not None:
                    rows[i, : len(o)] = np.frombuffer(o, np.uint8)
                    lens[i] = len(o)
        else:
            stride = max(int(ex.sizes.max()), 1)
            rows, lens = _pack_values(ex, stride)
            keep = ex.sizes > 0
            if plan.kind == "uppercase":
                is_lower = (rows >= ord("a")) & (rows <= ord("z"))
                rows = np.where(is_lower, rows - 32, rows)
        self._stat("t_assemble", t0)
        self._exploded = None
        return rows, lens, keep

    def framed(self) -> list[tuple[bytes, int]]:
        """Per-range (payload, kept), framed launch-wide in ONE native
        crossing the first time any ticket rebuilds. Locked: tickets of one
        submit_group share this launch and may harvest from different
        threads (the pacemaker harvests via run_in_executor).

        Byte-identity transforms take the ZERO-COPY gather path: kept
        records frame straight from the joined blob via the (offset, len)
        columns the explode stage already produced — the padded row matrix
        the padded path packs just to copy from never exists. Output is
        bit-identical either way (the gather parity suite pins it)."""
        with self._lock:
            if self._framed is None:
                if self._shards is not None:
                    self._framed = self._framed_sharded()
                else:
                    gv = self._gather_view()
                    arena = self.engine._arena if self.engine is not None else None
                    if gv is not None:
                        ex, keep = gv
                        t0 = time.perf_counter()
                        self._framed = batch_codec.frame_ranges_gather(
                            ex.joined, ex.offsets, ex.sizes, keep,
                            self.ranges, arena=arena,
                        )
                        self._stat("t_frame_gather", t0)
                        self._count_frame("n_frame_gather")
                        self._exploded = None
                        self._gather_mat = None
                    else:
                        out, out_len, keep = self._materialize_locked()
                        t0 = time.perf_counter()
                        self._framed = batch_codec.frame_ranges(
                            out, out_len, keep, self.ranges, arena=arena
                        )
                        self._stat("t_rebuild", t0)
                        self._count_frame("n_frame_padded")
            return self._framed

    def _gather_view(self):
        """(exploded, keep) when this launch's output bytes are an
        (offset, len) view into the joined blob — byte-identity plans
        (columnar passthrough, host identity) with the exploded table
        still in hand; None sends the launch down the padded path.

        The resolved view is CACHED (like _materialize_locked's _mat):
        _resolve_keep consumes the mask slot, so an uncached re-entry
        after a framing failure would read an empty slot as "no
        predicate" and silently emit keep-all output on retry."""
        if self._gather_mat is not None:
            return self._gather_mat
        eng = self.engine
        if eng is None or not eng._gather_frame:
            return None
        plan = self._plan
        if plan is None or not getattr(plan, "byte_identity", False):
            return None
        ex = self._exploded
        if ex is None:
            return None
        if self.mode == "columnar":
            keep = self._resolve_keep(self, self.n) & self._proj_ok
        elif self.mode == "host":
            # identity's normative keep rule: drop empty values (matches
            # _mat_host's `ex.sizes > 0`)
            keep = ex.sizes > 0
        else:
            return None
        self._gather_mat = (ex, keep)
        return self._gather_mat

    def _count_frame(self, key: str) -> None:
        eng = self.engine
        if eng is None:
            return
        eng._stat_add(key, 1.0)
        # decision-plane bookkeeping: which framing path this launch took.
        # record_mode journals only on CHANGE (first engagement or a mode
        # flip); the steady-state cost is one lock + one compare per launch
        mode = "gather" if key == "n_frame_gather" else "padded"
        eng.governor.record_mode(
            governor.HARVEST_PATH,
            mode,
            "byte-identity plan framed zero-copy from the joined blob"
            if mode == "gather"
            else "byte-mutating plan framed via the padded row matrix",
            {"script_id": self.script_id, "mode": self.mode},
            # dedupe per SCRIPT: the framing path is a property of the
            # script's plan, and a mixed gather+padded workload must not
            # flip-flop the journal on every alternating launch
            key=self.script_id,
        )

    def _shard_keep(self, shard: _HostShard) -> np.ndarray:
        """Resolve one shard's keep mask via the shared _resolve_keep."""
        if shard.n == 0:
            return np.zeros(0, dtype=bool)
        if shard.mask is None:
            return np.ones(shard.n, dtype=bool) & shard.proj_ok
        return self._resolve_keep(shard.mask, shard.n) & shard.proj_ok

    def _frame_shard(self, shard: _HostShard, keep: np.ndarray):
        """Assemble + frame ONE shard's record range (pool worker body —
        touches only its own shard, see SHD6xx). Byte-identity plans
        gather-frame straight from the shard's exploded table (same
        zero-copy rule as the inline path)."""
        plan: ColumnarPlan = self._plan
        eng = self.engine
        arena = eng._arena if eng is not None else None
        ex = shard.exploded
        if (
            eng is not None
            and eng._gather_frame
            and getattr(plan, "byte_identity", False)
            and ex is not None
            and shard.n > 0
        ):
            t0 = time.perf_counter()
            framed = batch_codec.frame_ranges_gather(
                ex.joined, ex.offsets, ex.sizes, keep, shard.ranges,
                arena=arena,
            )
            self._stat("t_shard_frame_gather", t0)
            self._count_frame("n_frame_gather")
            return framed
        t0 = time.perf_counter()
        if shard.n == 0:
            rows = np.zeros((0, max(self.r_out, 1)), np.uint8)
            lens = np.zeros(0, np.int32)
        elif plan.passthrough:
            stride = max(int(ex.sizes.max()), 1)
            rows, lens = _pack_values(ex, stride)
        else:
            rows, lens = plan.assemble_rows(shard.proj_data, shard.n)
        # t_shard_* keys: concurrent per-shard CPU-seconds, kept apart from
        # the launch-wall t_assemble/t_rebuild of the inline path (the
        # fan-out's wall time is t_sharded_frame)
        self._stat("t_shard_assemble", t0)
        t0 = time.perf_counter()
        framed = batch_codec.frame_ranges(
            rows, lens, keep, shard.ranges, arena=arena
        )
        self._stat("t_shard_rebuild", t0)
        self._count_frame("n_frame_padded")
        return framed

    def _framed_sharded(self) -> list[tuple[bytes, int]]:
        """Sharded harvest: per-shard masks resolved in shard order, then
        assembly + framing fan out over the host pool; the concatenated
        framed lists are byte-identical to the launch-wide path because
        shards are contiguous record ranges in input order."""
        shards = self._shards
        keeps = [self._shard_keep(shard) for shard in shards]
        thunks = [
            (lambda s=shard, k=keep: self._frame_shard(s, k))
            for shard, keep in zip(shards, keeps)
        ]
        pool = self.engine._host_pool if self.engine is not None else None
        t0 = time.perf_counter()
        parts = pool.run(thunks) if pool is not None else [t() for t in thunks]
        self._stat("t_sharded_frame", t0)
        for shard in shards:
            shard.proj_data = None
            shard.exploded = None
        return [item for part in parts for item in part]

    def _materialize_locked(self):
        """(out, out_len, keep) host arrays; fetch happens at most once.
        Caller holds self._lock."""
        if self._mat is None:
            if self.mode == "payload":
                self._mat = self._mat_payload()
            elif self.mode == "columnar":
                self._mat = self._mat_columnar()
            else:
                self._mat = self._mat_host()
        return self._mat

    def _stat(self, key: str, t0: float):
        # harvest-side stage (fetch/assemble/frame/seal): runs on whatever
        # thread materializes, so the launch's explicit trace id carries
        # the pulse slice (no ambient there); _stat_stage owns the single
        # clock read + stat/probe/timeline fan-out
        if self.engine is not None:
            self.engine._stat_stage(key, t0, trace_id=self.trace_id)
        else:
            tracer.record(
                "coproc.stage." + key[2:],
                (time.perf_counter() - t0) * 1e6,
                self.trace_id,
                start_perf=t0,
            )


def _pack_values(ex, stride: int):
    """Pack exploded record values into [n, stride] rows + lens."""
    try:
        from redpanda_tpu.native import lib
    except Exception as exc:
        # expected degradation: no native build on this box — the Python
        # packer is exact, only slower; counted so the demotion is visible
        faults.note_failure("native_lib", exc)
        lib = None
    sizes = np.minimum(ex.sizes, stride).astype(np.int32)
    if lib is not None:
        rows, _ = lib.pack_rows(ex.joined, ex.offsets, sizes, stride)
    else:
        from redpanda_tpu.ops.packing import pack_rows

        vals = [
            ex.joined[o : o + s] for o, s in zip(ex.offsets, sizes)
        ]
        rows, _ = pack_rows(vals, stride)
    return rows, sizes


def _fit_cols(cols, n_pad: int) -> list:
    """Pad/trim host predicate columns to a row bucket. Rows beyond the
    shard's real record count are padding whose predicate bits are
    discarded ([:n] at unpack), so zero-fill is always safe."""
    out = []
    for a in cols:
        if len(a) == n_pad:
            out.append(a)
        elif len(a) > n_pad:
            out.append(a[:n_pad])
        else:
            pad = np.zeros((n_pad - len(a),) + a.shape[1:], dtype=a.dtype)
            out.append(np.concatenate([a, pad]))
    return out


def _explode_shard(batches):
    """One payload/host-plan explode shard on a pool worker (the
    shard_worker fault domain covers every dispatch-side worker body)."""
    faults.inject(faults.SHARD_WORKER)
    return batch_codec.explode_batches(batches)


# Per-slot dispositions inside a Ticket.
_UNKNOWN, _EMPTY, _DEREGISTERED, _LAUNCHED = range(4)

# "resolve the trace id from the ambient contextvar" sentinel for
# _stat_stage (None is a real value there: "caller had no trace").
_AMBIENT = object()

# Sharding threshold: below this many records the pool's fan-out/merge
# overhead (thread handoff, per-shard native-call fixed costs) eats the
# win, so small launches keep the inline path.
_SHARD_MIN_ROWS = 2048

# Harvest-side seal sharding threshold: below this many output batches the
# pool's thread handoff costs more than the recompress+CRC it spreads.
_SEAL_MIN_BATCHES = 8

# Columnar backend probe: don't pin the process-wide device-vs-host choice
# on a batch too small to represent steady state, and bound the device leg
# (first TPU compile is ~20-40s; a wedged tunnel hangs forever).
_PROBE_MIN_ROWS = 1024
_PROBE_DEVICE_TIMEOUT_S = 120.0
# The probe times only the synchronous predicate leg; the device path
# additionally pays per-launch costs the probe cannot see (async harvester
# handoff + GIL contention between the fetch thread and host assembly,
# dispatch bookkeeping). Bench measurement: with the probe leg favoring
# the device 3.2x, END-TO-END host columnar still won 1.5x — an unmeasured
# overhead factor of ~5. The device must therefore beat the host leg by
# this margin to be picked; on co-located TPU it wins by orders of
# magnitude, on a tunneled link it loses outright, so the margin only
# decides the gray zone in between.
_PROBE_DEVICE_MARGIN = 4.0


class Ticket:
    """Handle for an in-flight engine request; ``result()`` materializes it."""

    def __init__(self, engine: "TpuEngine"):
        self._engine = engine
        self.trace_id: int | None = None
        # (disposition, item, launch, [batch range indices])
        self._slots: list[tuple] = []
        # bytes reserved from the coproc memory account at submit (0 when
        # admission is off); released exactly once when result() returns
        # OR raises — leaking them would starve every later submit
        self._admitted: int = 0

    def result(self) -> ProcessBatchReply:
        try:
            with tracer.span("coproc.harvest", trace_id=self.trace_id):
                return self._result_impl()
        finally:
            self._engine._release_admission(self)

    def _result_impl(self) -> ProcessBatchReply:
        reply = ProcessBatchReply()
        dereg: set[int] = set()
        failed_scripts: set[int] = set()
        # Phase 1: frame every launch and collect the recompress+seal jobs
        # REPLY-WIDE, so the seal can fan out over the host pool in one
        # batch instead of serially per item — the harvest-side analogue
        # of submit_group's launch fusion. Jobs are independent
        # (build_output_batch is pure per batch) and merge in input order,
        # so offsets/CRCs are bit-identical to the serial loop.
        seal_jobs: list[tuple] = []  # (source batch, payload, kept)
        slot_plans: list = []  # per slot: list[int] | Exception | None
        framing_failed: set[int] = set()
        for disp, item, launch, rng in self._slots:
            if disp != _LAUNCHED or launch.script_id in framing_failed:
                # a later slot of a script whose framing already failed is
                # resolved by phase 2's failed_scripts bookkeeping (the
                # failing slot precedes it in slot order)
                slot_plans.append(None)
                continue
            try:
                framed = launch.framed()  # one crossing per launch
                idxs = []
                for batch, ridx in zip(item.batches, rng):
                    payload, kept = framed[ridx]
                    idxs.append(len(seal_jobs))
                    seal_jobs.append((batch, payload, kept))
                slot_plans.append(idxs)
            except Exception as exc:  # pandalint: disable=EXC901 -- held for phase 2: delivered as a value to the ErrorPolicy boundary, which classifies it via note_failure("rebuild")
                # held for phase 2: the script error policy is applied in
                # slot order there, exactly like the old per-slot loop
                slot_plans.append(exc)
                framing_failed.add(launch.script_id)
        sealed = self._engine._seal_jobs(seal_jobs, trace_id=self.trace_id)
        # Phase 2: assemble the reply in slot order under the script's
        # ErrorPolicy — this is the policy boundary (deregister failures
        # ride through here), so programming errors must not bypass it.
        for (disp, item, launch, rng), plan in zip(self._slots, slot_plans):
            if disp == _UNKNOWN or disp == _EMPTY:
                reply.items.append(ProcessBatchReplyItem(item.script_id, item.ntp, []))
            elif disp == _DEREGISTERED:
                dereg.add(item.script_id)
            else:
                if launch.script_id in failed_scripts:
                    if launch.policy != ErrorPolicy.deregister:
                        reply.items.append(
                            ProcessBatchReplyItem(item.script_id, item.ntp, [])
                        )
                    continue
                exc = plan if isinstance(plan, Exception) else next(
                    (
                        sealed[i]
                        for i in plan
                        if isinstance(sealed[i], BaseException)
                    ),
                    None,
                )
                if exc is None:
                    out_batches = [
                        sealed[i] for i in plan if sealed[i] is not None
                    ]
                    reply.items.append(
                        ProcessBatchReplyItem(item.script_id, item.ntp, out_batches)
                    )
                    continue
                faults.note_failure("rebuild", exc)
                failed_scripts.add(launch.script_id)
                if launch.policy == ErrorPolicy.deregister:
                    self._engine.disable_coprocessors([launch.script_id])
                    dereg.add(launch.script_id)
                    reply.items = [
                        ri for ri in reply.items if ri.script_id != launch.script_id
                    ]
                else:
                    reply.items.append(
                        ProcessBatchReplyItem(item.script_id, item.ntp, [])
                    )
        reply.deregistered = sorted(dereg)
        return reply


class TpuEngine:
    """HandleTable + batched async device execution.

    ``mesh``: optional jax.sharding.Mesh with a 'p' axis; columnar launches
    then run SPMD with record rows sharded over the mesh (the per-shard
    pacemaker-fiber analogue of coproc/pacemaker.h:41-145 — one engine, all
    chips). ``force_mode`` pins every script to one execution mode
    ("payload" forces the full-row staging path, "columnar_host" pins the
    numpy predicate, "columnar_device" pins the device predicate; used by
    the bench to measure each half).

    Where the columnar predicate runs is a MEASURED decision (same policy
    as ops/crc_backend.pick and the LZ4 keep-or-kill): the first columnar
    launch probes device vs numpy over the same extracted columns and the
    process keeps the winner. On locally-attached TPU the device wins; on
    a high-RTT tunneled link numpy does — the probe, not an assumption,
    decides (see BENCH vs_host_columnar for both halves on record).
    """

    # process-wide probed decision: the link physics don't change per
    # engine instance ("device" | "host" | None = not yet probed).
    # Two locks with distinct jobs (pandaraces RAC1101 fix): the RUN lock
    # serializes probe EXECUTION — two concurrent first columnar launches
    # used to BOTH run the expensive device probe (the PR-3 duplicate-
    # jit-trace shape); the loser blocks here and adopts the winner's
    # pick. The short field lock guards the two-field backend/record
    # write and every read — it is never held across the probe itself,
    # so stats()/status readers cannot hang behind a wedged 120s device
    # leg.
    _columnar_backend: str | None = None
    _columnar_probe: dict | None = None
    _columnar_probe_run_lock = threading.Lock()
    _columnar_probe_lock = threading.Lock()

    def __init__(
        self,
        *,
        row_stride: int = 1024,
        compress_threshold: int = 512,
        output_codec: Compression = Compression.zstd,
        mesh=None,
        force_mode: str | None = None,
        host_workers: int | None = None,
        host_pool_probe: bool = True,
        host_pool_recal_launches: int | None = None,
        gather_frame: bool = True,
        structural_parse: bool | None = None,
        structural_probe: bool = True,
        device_column_cache_mb: int | None = None,
        mesh_devices: int | None = None,
        mesh_backend: str | None = None,
        mesh_probe: bool = True,
        device_deadline_ms: int | None = None,
        launch_retries: int | None = None,
        retry_backoff_ms: int | None = None,
        breaker_threshold: int | None = None,
        breaker_cooldown_ms: int | None = None,
        adaptive_deadline: bool | None = None,
        adaptive_deadline_margin: float | None = None,
        governor_journal_capacity: int | None = None,
        budget_plane=None,
    ):
        self._handles: dict[int, ScriptHandle] = {}
        # fault domains: every device interaction runs under this envelope
        # (per-attempt deadline, bounded retry + backoff). The static
        # deadline is the FLOOR: the governor derives per-domain effective
        # deadlines from the observed stage p99.9 and may only raise them
        # (coproc/governor.py; config coproc_device_deadline_ms etc.)
        self._fault_policy = faults.FaultPolicy(
            deadline_s=(
                device_deadline_ms if device_deadline_ms is not None else 30_000
            ) / 1000.0,
            retries=launch_retries if launch_retries is not None else 2,
            backoff_s=(
                retry_backoff_ms if retry_backoff_ms is not None else 50
            ) / 1000.0,
        )
        _threshold = breaker_threshold if breaker_threshold is not None else 5
        _cooldown_s = (
            breaker_cooldown_ms if breaker_cooldown_ms is not None else 30_000
        ) / 1000.0
        # The governor owns the decision plane: ONE per-domain breaker per
        # device fault domain (a flaky mask-fetch path demotes fetches
        # while dispatch stays on-device), adaptive per-domain deadlines,
        # and the decision journal every adaptive choice appends to.
        if governor_journal_capacity is not None:
            governor.journal.configure(governor_journal_capacity)
        self.governor = governor.Governor(
            fault_policy=self._fault_policy,
            breaker_threshold=_threshold,
            breaker_cooldown_s=_cooldown_s,
            # a legitimate half-open probe runs a full retry envelope; the
            # stale-probe release must outwait it or a slow probe gets a
            # second probe stacked onto the same struggling device. The
            # envelope here uses the static floor; adaptive growth is
            # bounded by the governor's cap, and the max() keeps the
            # cooldown as the operator-visible lower bound either way.
            breaker_probe_timeout_s=max(
                _cooldown_s, 2.0 * self._fault_policy.envelope_s()
            ),
            adaptive_deadline=(
                adaptive_deadline if adaptive_deadline is not None else True
            ),
            deadline_margin=(
                adaptive_deadline_margin
                if adaptive_deadline_margin is not None
                else 4.0
            ),
        )
        self.governor.set_config_snapshot({
            "device_deadline_ms": round(self._fault_policy.deadline_s * 1e3),
            "launch_retries": self._fault_policy.retries,
            "retry_backoff_ms": round(self._fault_policy.backoff_s * 1e3),
            "breaker_threshold": _threshold,
            "breaker_cooldown_ms": round(_cooldown_s * 1e3),
            "force_mode": force_mode,
            "gather_frame": bool(gather_frame),
            "adaptive_deadline": (
                adaptive_deadline if adaptive_deadline is not None else True
            ),
        })
        # the dispatch-domain breaker doubles as the engine-level handle
        # (dispatch is the domain every launch crosses first)
        self._breaker = self.governor.breaker_for(faults.DEVICE_DISPATCH)
        self._row_stride = row_stride
        self._compress_threshold = compress_threshold
        self._output_codec = output_codec
        self._mesh = mesh
        self._force_mode = force_mode
        # host-stage worker pool (coproc/host_pool.py): None = config
        # default min(4, cores); 0 or 1 = the inline single-thread path
        if host_workers is None:
            host_workers = host_pool.default_host_workers()
        self._host_workers = max(0, int(host_workers))
        self._host_pool = (
            host_pool.HostStagePool(self._host_workers)
            if self._host_workers >= 2
            else None
        )
        # Pool on/off is a MEASURED per-process decision, exactly like the
        # columnar device-vs-host probe: the first shardable launch times
        # its own explode stage inline vs sharded and pins the winner
        # (quota-limited boxes advertise CPUs that thrash instead of
        # scale). host_pool_probe=False pins "sharded" unmeasured — bench
        # scaling runs and parity tests need the fan-out deterministically.
        self._pool_decision: str | None = None if host_pool_probe else "sharded"
        self.governor.update_config_snapshot(host_workers=self._host_workers)
        if not host_pool_probe:
            # config pin, not a measurement — posture only, no journal
            # entry (a decision the operator made is not an adaptive one)
            self.governor.note_posture(governor.HOST_POOL, "sharded")
        self._pool_decision_lock = lockwatch.wrap(
            threading.Lock(), "TpuEngine._pool_decision_lock"
        )
        # set while a periodic re-calibration is pending, so the next
        # calibration journals itself as a recal rather than a first probe
        self._recal_pending = False
        self._host_pool_probe: dict | None = None
        self._host_pool_probe_prev: dict | None = None
        # Periodic re-calibration (config coproc_host_pool_recal_launches):
        # burstable boxes gain/lose capacity over time, so a pinned on/off
        # decision re-measures every N shardable launches. 0 pins forever;
        # an explicit host_pool_probe=False pin is never re-measured.
        self._probe_enabled = bool(host_pool_probe)
        self._recal_interval = (
            512
            if host_pool_recal_launches is None
            else max(0, int(host_pool_recal_launches))
        )
        self._launches_since_cal = 0
        # Zero-copy harvest: byte-identity transforms gather-frame straight
        # from the joined blob (gather_frame=False is the bench ablation /
        # operator escape hatch), and framing scratch reuses across
        # launches through the arena (reset_arenas() for tests).
        self._gather_frame = bool(gather_frame)
        self._arena = leakwatch.wrap(batch_codec.Arena(), "engine.arena")
        # Structural-index parse path (native rp_explode_find2 +
        # rp_extract_cols2): fused-vs-staged is a MEASURED per-engine
        # decision with the host-pool posture — the first representative
        # columnar launch times BOTH full ladders on its own batches and
        # the winner pins (PROBE_MARGIN; the scalar staged ladder is the
        # known path, so structural must show a real win). config
        # coproc_structural_parse=False pins staged outright;
        # structural_probe=False pins structural unmeasured (bench
        # ablations / parity tests need the fused lane deterministically).
        self._structural_enabled = (
            True if structural_parse is None else bool(structural_parse)
        )
        self._parse_probe_enabled = bool(structural_probe)
        self._parse_probe: dict | None = None
        if not self._structural_enabled:
            self._parse_decision: str | None = "staged"
            # operator pin, not a measurement: posture only
            self.governor.note_posture(governor.PARSE_PATH, "staged")
        elif not self._parse_probe_enabled:
            self._parse_decision = "structural"
            self.governor.note_posture(governor.PARSE_PATH, "structural")
        else:
            self._parse_decision = None
        self._parse_decision_lock = lockwatch.wrap(
            threading.Lock(), "TpuEngine._parse_decision_lock"
        )
        # serializes calibration EXECUTION only (see _parse_path): never
        # held while publishing or reading the decision fields
        self._parse_probe_run_lock = lockwatch.wrap(
            threading.Lock(), "TpuEngine._parse_probe_run_lock"
        )
        self.governor.update_config_snapshot(
            structural_parse=self._structural_enabled
        )
        # Device-resident column cache (coproc/colcache.py): repeat
        # scripts over unchanged batch windows skip the whole host ladder
        # and the H2D replay. 0/None disables it — the BROKER default is
        # 32 MB via config coproc_device_column_cache_mb (CoprocApi), but
        # a bare-constructed engine keeps the uncached semantics so fault/
        # parity harnesses that replay one request still exercise the
        # machinery they are pointed at.
        _cache_mb = (
            0 if device_column_cache_mb is None
            else max(0, int(device_column_cache_mb))
        )
        self._colcache = (
            colcache.DeviceColumnCache(_cache_mb << 20) if _cache_mb else None
        )
        self.governor.update_config_snapshot(
            device_column_cache_mb=_cache_mb
        )
        # Multi-chip sharded engine (coproc/meshrunner.py): the partition
        # axis pjit/shard_map-sharded over an N-device mesh, per-device
        # sub-launches over the host-pool range shard. None/0/1 keeps the
        # single-device engine (config coproc_mesh_devices wires the
        # broker knob). mesh_probe=False pins "mesh" unmeasured — parity
        # tests and bench ablations need the mesh lane deterministically;
        # True runs the measured mesh-vs-single calibration on the first
        # representative launch (PROBE_MARGIN posture, journaled).
        self._meshrunner: meshrunner.MeshRunner | None = None
        if mesh_devices is not None and int(mesh_devices) >= 2:
            try:
                self._meshrunner = meshrunner.MeshRunner(
                    n_devices=int(mesh_devices), backend=mesh_backend,
                    probe=mesh_probe,
                )
            except Exception as exc:
                # fewer devices than asked for (or no jax backend): the
                # engine runs single-device; classified so the demotion
                # is visible on /metrics rather than silent
                faults.note_failure("mesh_init", exc)
                logger.warning("meshrunner unavailable: %s", exc)
        self.governor.update_config_snapshot(
            mesh_devices=(
                self._meshrunner.n_devices if self._meshrunner else 0
            )
        )
        # Budget plane (resource_mgmt): staged rows acquire from the
        # 'coproc' account BEFORE any dispatch — exhaustion sheds the
        # whole submit with a retriable ShedError (the pacemaker backs
        # off and re-reads the same offsets: nothing lost, nothing
        # duplicated, never silent queue growth). Bytes release when the
        # ticket harvests (Ticket.result's finally — the
        # leak-on-exception tests pin it). Plane-less engines (bare
        # test/bench constructions) admit everything, the historical
        # semantics. The pressure listener is weakref-bound: the
        # process-wide plane must not pin dead engines.
        self._budget_plane = budget_plane
        self._admission: rm_admission.AdmissionController | None = None
        self._pressure_listener = None
        if budget_plane is not None:
            acct = budget_plane.accounts.get("coproc")
            if acct is not None:
                self._admission = leakwatch.wrap(
                    rm_admission.AdmissionController(
                        acct, "coproc", warn_pct=budget_plane.warn_pct
                    ),
                    "engine.admission",
                )
            _ref = weakref.ref(self)

            def _pressure_listener(level, snap, _ref=_ref):
                eng = _ref()
                if eng is not None:
                    eng._on_memory_pressure(level, snap)

            self._pressure_listener = _pressure_listener
            budget_plane.add_pressure_listener(_pressure_listener)
        self.governor.update_config_snapshot(
            admission=self._admission is not None
        )
        # per-shard stage splits of the most recent sharded launch (bench
        # artifact + debugging aid; overwritten per launch under the lock)
        self.last_launch_shards: list[dict] | None = None
        self._pipelines: dict[int, tuple] = {}  # payload: script_id -> (fn, r_out)
        self._plans: dict[int, object] = {}  # script_id -> execution plan
        self._stats: dict[str, float] = defaultdict(float)
        self._stats_lock = lockwatch.wrap(
            threading.Lock(), "TpuEngine._stats_lock"
        )
        # mask harvester: one daemon thread pays the D2H confirmation round
        # trip per launch while the caller keeps doing host work (~10 ms of
        # tunnel RTT per harvest otherwise lands on the critical path)
        self._harvest_q: "queue.Queue[_Launch]" = queue.Queue()  # pandalint: disable=BPR1401 -- bounded upstream: at most launch_depth launches are in flight (pacemaker gate) and each holds coproc-account bytes admitted at submit_group
        self._harvester: threading.Thread | None = None

    def _ensure_harvester(self) -> threading.Thread:
        # locked: concurrent dispatchers must not each spawn a permanent
        # thread (check-then-create race)
        with self._stats_lock:
            if self._harvester is None or not self._harvester.is_alive():
                self._harvester = threading.Thread(
                    target=self._harvest_loop, name="rptpu-mask-harvester",
                    daemon=True,
                )
                self._harvester.start()
            return self._harvester

    def shutdown(self) -> None:
        """Stop the engine's background machinery: the mask-harvester
        thread (sentinel + join) and the host-stage pool. In-flight
        launches drain first (the sentinel queues behind them). A daemon
        harvester pins the whole engine — plans, jit executables, staged
        arrays — for the life of the process otherwise, which long-lived
        embedders (and test suites creating many engines) cannot afford.
        The engine must not process batches after shutdown."""
        with self._stats_lock:
            t, self._harvester = self._harvester, None
        if t is not None and t.is_alive():
            self._harvest_q.put(None)
            t.join(timeout=60.0)
        if self._host_pool is not None:
            self._host_pool.shutdown()
        with self._stats_lock:  # concurrent shutdowns: swap-then-remove once
            listener, self._pressure_listener = self._pressure_listener, None
        if self._budget_plane is not None and listener is not None:
            # the plane is process-wide and outlives this engine: leave
            # the dead closure behind and every later pressure transition
            # still walks it (the weakref makes it a no-op, not free)
            self._budget_plane.remove_pressure_listener(listener)

    def _harvest_loop(self) -> None:
        while True:
            launch = self._harvest_q.get()
            if launch is None:  # shutdown sentinel
                return
            with _mask_claim_lock:
                if launch._mask_state in ("claimed", "abandoned"):
                    # claimed: its caller gave up waiting and is fetching
                    # the mask itself; abandoned: a degraded sharded launch
                    # orphaned it. Either way a fetch here would be a
                    # duplicate envelope and a stale breaker verdict.
                    continue
                launch._mask_state = "harvesting"
            t_get = time.perf_counter()
            dev = launch._mask_dev
            harvest_breaker = self.governor.breaker_for(faults.HARVEST)
            try:
                if dev is not None and not harvest_breaker.allow_device():
                    # open harvest breaker: skip the doomed fetch without
                    # spending an envelope or a verdict — the woken caller
                    # takes the exact host fallback (demoted fetches, while
                    # dispatch's own breaker decides dispatch separately)
                    launch._mask_np = None
                elif dev is not None:
                    def leg(dev=dev):
                        t0 = time.perf_counter()
                        faults.inject(faults.HARVEST)
                        # the fetch worker pays the D2H sync; this thread
                        # only coordinates, so a wedged link can no longer
                        # freeze every later launch's mask behind it
                        out = np.asarray(dev)
                        # success-only adaptive-deadline sample (a raise
                        # or abandonment never reaches this line)
                        self.governor.observe_leg(
                            faults.HARVEST, time.perf_counter() - t0
                        )
                        return out

                    launch._mask_np = faults.retry_call(
                        leg, self.governor.policy_for(faults.HARVEST),
                        faults.HARVEST, count=self._stat_add,
                    )
                    harvest_breaker.record_success()
            except Exception as exc:
                launch._mask_np = None  # materialize() falls back
                # classified, never fatal: this daemon serves every launch
                # and _resolve_keep owns the per-launch fallback decision.
                # The verdict lands BEFORE the event below: a caller woken
                # by the event must observe the breaker state this failure
                # produced, not a stale snapshot. A PROGRAMMING error is
                # counted but gives no breaker verdict — a bug in our code
                # must not quietly demote the engine to host forever (and
                # re-raising would kill the daemon every launch depends on).
                faults.note_failure(faults.HARVEST, exc)
                if not isinstance(exc, faults.PROGRAMMING_ERRORS):
                    harvest_breaker.record_failure()
            finally:
                t_done = time.perf_counter()
                # device-time span: the fetch completes the async D2H, so
                # its wall time is the post-block_until_ready device leg;
                # queue_us is how long the launch waited for this thread.
                tracer.record(
                    "coproc.device_harvest",
                    (t_done - t_get) * 1e6,
                    launch.trace_id,
                    start_perf=t_get,
                    queue_us=int((t_get - launch._enq_t) * 1e6),
                    device_us=int((t_done - t_get) * 1e6),
                )
                launch._mask_event.set()

    # ------------------------------------------------------------ control
    def enable_coprocessors(
        self, scripts: list[tuple[int, str, tuple[str, ...]]]
    ) -> list[EnableResponseCode]:
        """scripts: [(script_id, spec_json, input_topics)]."""
        out = []
        for script_id, spec_json, topics in scripts:
            if script_id in self._handles:
                out.append(EnableResponseCode.script_id_already_exists)
                continue
            if not topics:
                out.append(EnableResponseCode.script_contains_no_topics)
                continue
            if any(t.startswith("__") or ".$" in t for t in topics):
                out.append(EnableResponseCode.script_contains_invalid_topic)
                continue
            try:
                spec = TransformSpec.from_json(spec_json)
                plan = plan_spec(spec)  # validates the expr tree + constants
                if (
                    self._force_mode == "payload"
                    and plan.mode != "payload"
                    and spec.where is None
                ):
                    # v1-expressible specs only: where-specs have no payload
                    # compilation and keep their columnar plan.
                    plan = PayloadPlan(spec)
                if plan.mode == "payload":
                    self._pipelines[script_id] = make_packed_pipeline(
                        spec, self._row_stride
                    )
                self._plans[script_id] = plan
            except Exception as exc:
                # bad spec from the wire, not a broker fault: refuse the
                # registration and account the rejection
                faults.note_failure("enable", exc)
                out.append(EnableResponseCode.internal_error)
                continue
            self._handles[script_id] = ScriptHandle(
                script_id, spec, tuple(topics), checksum=xxhash64(spec_json)
            )
            out.append(EnableResponseCode.success)
        return out

    def enable_py_transform(
        self,
        script_id: int,
        fn,
        topics: tuple[str, ...],
        policy: ErrorPolicy = ErrorPolicy.skip_on_failure,
    ) -> EnableResponseCode:
        """Escape hatch: an arbitrary python callable(value) -> value | None
        run in the engine's host stage with the standard engine interface —
        for transforms the declarative DSL cannot express (the analogue of
        the reference's arbitrary Coprocessor.apply(), SimpleTransform.ts:18).
        In-process trust only; the WIRE-deployable form is
        enable_py_sandboxed.
        """
        if script_id in self._handles:
            return EnableResponseCode.script_id_already_exists
        if not topics:
            return EnableResponseCode.script_contains_no_topics
        spec = TransformSpec(name=f"py:{getattr(fn, '__name__', 'fn')}")
        self._plans[script_id] = plan_spec(spec, py_fn=fn)
        self._handles[script_id] = ScriptHandle(
            script_id, spec, tuple(topics), policy=policy
        )
        return EnableResponseCode.success

    def enable_py_sandboxed(
        self,
        script_id: int,
        source: str,
        topics: tuple[str, ...],
        policy: ErrorPolicy = ErrorPolicy.skip_on_failure,
    ) -> EnableResponseCode:
        """Wire-deployable arbitrary transform: restricted-AST python
        validated HERE (on every consuming broker) before registration —
        a malicious blob never reaches execution (coproc/sandbox.py; the
        reference's analogue is the V8 supervisor boundary)."""
        from redpanda_tpu.coproc.sandbox import SandboxViolation, compile_transform

        if script_id in self._handles:
            return EnableResponseCode.script_id_already_exists
        if not topics:
            return EnableResponseCode.script_contains_no_topics
        try:
            fn = compile_transform(source, script_id=script_id)
        except SandboxViolation as exc:
            faults.note_failure(faults.SANDBOX_COMPILE, exc)
            return EnableResponseCode.internal_error
        except Exception as exc:
            # any other compile-time blowup is a bad script, not a broker
            # fault — refuse registration rather than poison the caller
            faults.note_failure(faults.SANDBOX_COMPILE, exc)
            logger.exception("sandboxed script %d failed to compile", script_id)
            return EnableResponseCode.internal_error
        return self.enable_py_transform(script_id, fn, topics, policy)

    def disable_coprocessors(self, script_ids: list[int]) -> list[DisableResponseCode]:
        out = []
        for sid in script_ids:
            if sid in self._handles:
                del self._handles[sid]
                self._pipelines.pop(sid, None)
                self._plans.pop(sid, None)
                self.invalidate_columns(sid)
                out.append(DisableResponseCode.success)
            else:
                out.append(DisableResponseCode.script_id_does_not_exist)
        return out

    def disable_all_coprocessors(self) -> int:
        n = len(self._handles)
        self._handles.clear()
        self._pipelines.clear()
        self._plans.clear()
        self.invalidate_columns()
        return n

    # ------------------------------------------------------------ colcache
    def invalidate_columns(self, script_id: int | None = None) -> int:
        """Drop cached device/host columns (every script when script_id is
        None); returns entries dropped. The cache key is content-addressed
        (a changed batch window misses by construction), so this hook is a
        MEMORY contract, not a correctness one: the pacemaker calls it
        when a script's input offsets advance (streaming never re-reads,
        the bytes are dead weight) and script unload drops its entries."""
        if self._colcache is None:
            return 0
        return self._colcache.invalidate(script_id)

    def reset_column_cache(self) -> None:
        """Test/bench hook: drop all cached columns AND zero the cache
        counters so hit-rate accounting is deterministic per run."""
        if self._colcache is not None:
            self._colcache.reset()

    # ------------------------------------------------------------ metrics
    def stats(self) -> dict:
        """Accumulated per-stage wall seconds and link bytes, plus the
        pool size and (once probed) the columnar-backend probe record.
        Numeric stage keys are floats; ``columnar_backend``/``columnar_probe``
        are a string and a dict — consumers formatting stages should key on
        the ``t_``/``n_``/``bytes_`` prefixes."""
        with self._stats_lock:
            out = dict(self._stats)
        out["host_workers"] = float(self._host_workers)
        # "breaker" keeps its historical engine-level shape (worst state,
        # summed counts); "breakers" is the per-domain split and
        # "governor" the decision-plane snapshot (posture + journal summary)
        out["breaker"] = self.governor.aggregate_breaker_snapshot()
        out["breakers"] = self.governor.breakers_snapshot()
        out["governor"] = self.governor.snapshot()
        out["arena"] = self._arena.stats()
        if lockwatch.enabled():
            # debug mode only: the observed lock-order edge count rides
            # stats() into /v1/coproc/status, rpk debug coproc and BENCH
            out["lockwatch"] = lockwatch.snapshot()
        if leakwatch.enabled():
            # same posture: outstanding balances + imbalance count ride
            # stats() into the status/debug surfaces
            out["leakwatch"] = leakwatch.snapshot()
        with self._parse_decision_lock:
            out["parse_path"] = self._parse_decision
            if self._parse_probe is not None:
                out["parse_probe"] = dict(self._parse_probe)
        if self._colcache is not None:
            out["colcache"] = self._colcache.stats()
        if self._admission is not None:
            out["admission"] = self._admission.snapshot()
        if self._meshrunner is not None:
            out["mesh"] = self._meshrunner.stats()
        if self._host_pool_probe is not None:
            out["host_pool_probe"] = dict(self._host_pool_probe)
        if self._host_pool_probe_prev is not None:
            out["host_pool_probe_prev"] = dict(self._host_pool_probe_prev)
        if self._host_pool is not None:
            out["host_pool_recal"] = {
                "interval": self._recal_interval if self._probe_enabled else 0,
                "launches_since": self._launches_since_cal,
            }
        with TpuEngine._columnar_probe_lock:  # coherent two-field snapshot
            backend = TpuEngine._columnar_backend
            probe = TpuEngine._columnar_probe
        if probe is not None:
            out["columnar_backend"] = backend
            out["columnar_probe"] = dict(probe)
        return out

    @classmethod
    def sticky_columnar_backend(cls) -> str | None:
        """The process-wide probed backend, read under the probe lock —
        call sites take ONE coherent snapshot instead of re-reading the
        class attribute around a concurrent probe's two-field write."""
        with cls._columnar_probe_lock:
            return cls._columnar_backend

    @classmethod
    def reset_columnar_probe(cls) -> None:
        """Forget the process-wide columnar backend probe so the next
        columnar launch re-probes. The probed pick is deliberately sticky
        (link physics don't change per engine), but bench ablations and
        tests that construct engines under a different ``force_mode`` or a
        different link must be able to re-measure instead of inheriting a
        stale decision."""
        with cls._columnar_probe_lock:
            cls._columnar_backend = None
            cls._columnar_probe = None

    def _release_admission(self, ticket: "Ticket") -> None:
        """Return a ticket's reserved coproc-account bytes. Idempotent AND
        atomic: the zero-swap runs under the stats lock because an
        abandonment path may release from the loop while the executor
        thread's ``result()`` finally races the same ticket — an unlocked
        double-read would free the bytes twice and overcommit the
        account."""
        with self._stats_lock:
            n, ticket._admitted = ticket._admitted, 0
        if n and self._admission is not None:
            self._admission.release(n)

    def _on_memory_pressure(self, level: str, snap: dict) -> None:
        """Budget-plane pressure transition (fired by BudgetPlane on level
        CHANGE, from whatever thread moved the occupancy). CRITICAL sheds
        reclaimable memory: the arena free-list is trimmed and the column
        cache evicts down to half its budget; OK restores the full cache
        budget. WARN only journals — the admission and autotune layers own
        the load response. Each transition is one ADMISSION-domain journal
        entry (level changes are rare by the plane's hysteresis)."""
        trims = evicted = 0
        if level == rm_budgets.PRESSURE_CRITICAL:
            trims = self._arena.trim()
            if self._colcache is not None:
                evicted = self._colcache.set_pressure(True)
            self._stat_add("n_pressure_trims", 1.0)
            if evicted:
                self._stat_add("n_pressure_evictions", float(evicted))
        elif level == rm_budgets.PRESSURE_OK and self._colcache is not None:
            self._colcache.set_pressure(False)
        self.governor.record(
            governor.ADMISSION, level,
            f"memory pressure {level}: arena buffers freed {trims}, "
            f"colcache entries evicted {evicted}",
            {
                "arena_freed": trims,
                "colcache_evicted": evicted,
                "max_occupancy": snap.get("max_occupancy"),
                "account": snap.get("max_occupancy_account"),
            },
        )

    def reset_arenas(self) -> None:
        """Swap in a fresh harvest scratch arena. The arena is deliberately
        long-lived (buffer reuse across launches is the point), but tests
        and bench ablations need deterministic alloc/reuse accounting —
        and an engine parked after a giant launch can use this to return
        the held buffers to the allocator."""
        self._arena = leakwatch.wrap(batch_codec.Arena(), "engine.arena")

    def reset_stats(self) -> None:
        with self._stats_lock:
            self._stats.clear()

    def _stat_add(self, key: str, v: float) -> None:
        # Harvests may run on executor threads concurrently with dispatch.
        # The probe mirror records UNDER the same lock: HdrHist.record is a
        # read-modify-write, and concurrent harvest threads would lose
        # samples recorded outside it. Per-launch cadence, so the lock is
        # off the per-record path. Stage wall times become
        # coproc_stage_latency_us{stage=...}; link traffic becomes the
        # device-transfer counters.
        with self._stats_lock:
            self._stats[key] += v
            if key.startswith("t_"):
                probes.coproc_stage_hist(key[2:]).record(int(v * 1e6))
            elif key == "bytes_h2d":
                probes.coproc_h2d_bytes.inc(v)
            elif key == "bytes_d2h":
                probes.coproc_d2h_bytes.inc(v)
            elif key == "n_frame_gather":
                probes.coproc_harvest_gather.inc(v)
            elif key == "n_frame_padded":
                probes.coproc_harvest_padded.inc(v)

    def _stat_stage(self, key: str, t0: float, trace_id=_AMBIENT) -> float:
        """Close one stage timer: ONE clock read, stat + probe mirror via
        ``_stat_add``, and the same duration mirrored as a pandapulse
        lifecycle span (so timeline slices sum to the ``t_*`` splits by
        construction — both sides see the identical ``dt``). Submit-side
        call sites run inside the ``coproc.dispatch`` span, so the ambient
        trace id resolves on the dispatching thread; pool/mesh workers
        pass the launch's trace id explicitly (no ambient there). Tracer
        off → ``tracer.record`` is a cheap early return."""
        dt = time.perf_counter() - t0
        self._stat_add(key, dt)
        if tracer.enabled:
            tid = tracer.current_trace() if trace_id is _AMBIENT else trace_id
            if tid is not None:
                # "coproc.stage." namespace: stage slices must not collide
                # with the wrapper spans (t_dispatch vs the coproc.dispatch
                # span around the whole submit fan-out)
                tracer.record(
                    "coproc.stage." + key[2:], dt * 1e6, tid, start_perf=t0
                )
        return dt

    def _count_fallback(self, n: int) -> None:
        """Account records whose stages re-executed on the pure-host
        fallback (exhausted device retries or an open breaker)."""
        self._stat_add("n_fallback_rows", float(n))
        probes.coproc_fallback_rows.inc(n)

    def _seal_jobs(self, jobs: list[tuple], trace_id: int | None = None) -> list:
        """Recompress + seal framed payloads into output batches
        (batch_codec.build_output_batch), sharded over the host pool when
        the measured pool decision is on and the reply is big enough.
        Jobs are independent (build_output_batch is pure per batch) and
        chunks merge in input order, so offsets/CRCs are bit-identical to
        the serial loop. A per-job failure comes back AS the exception
        instance (the caller owns the script error policy); a pool
        machinery failure degrades the whole list to the inline loop."""
        if not jobs:
            return []

        def seal_one(src, payload, kept):
            try:
                return batch_codec.build_output_batch(
                    src, payload, kept,
                    compress_threshold=self._compress_threshold,
                    codec=self._output_codec,
                )
            except Exception as exc:  # pandalint: disable=EXC901 -- delivered as a value to the ErrorPolicy boundary (note_failure("rebuild") classifies it there)
                return exc

        pool = self._host_pool
        with self._pool_decision_lock:  # coherent read vs concurrent recal
            decision = self._pool_decision
        if (
            pool is not None
            and decision == "sharded"
            and len(jobs) >= _SEAL_MIN_BATCHES
        ):
            # chunks balance by payload bytes: recompression cost tracks
            # size, and one fat batch must not serialize a whole chunk
            # behind it (+1 keeps zero-length payloads partitionable)
            parts = host_pool.partition_counts(
                [len(p) + 1 for _, p, _ in jobs], pool.workers
            )
            if len(parts) >= 2:
                def run_chunk(s: int, e: int) -> list:
                    t0 = time.perf_counter()
                    out = [seal_one(*jobs[i]) for i in range(s, e)]
                    # per-chunk CPU-seconds; the fan-out wall time is
                    # t_sharded_seal (same split discipline as t_shard_*).
                    # Explicit trace id: chunks run on pool workers where
                    # no ambient trace is set.
                    self._stat_stage("t_shard_seal", t0, trace_id=trace_id)
                    return out

                t0 = time.perf_counter()
                try:
                    chunks = pool.run([
                        (lambda s=s, e=e: run_chunk(s, e)) for s, e in parts
                    ])
                except Exception as exc:
                    faults.note_failure(
                        faults.SHARD_WORKER, exc, reraise_programming=True
                    )
                else:
                    self._stat_stage("t_sharded_seal", t0)
                    # journaled only once the fan-out COMMITTED: a pool-
                    # machinery failure falls through to the inline loop
                    # below, and recording "sharded" first would both lie
                    # and flip-flop the dedupe into flooding the ring
                    self.governor.record_mode(
                        governor.SHARDED_SEAL,
                        "sharded",
                        f"reply-wide seal fan-out engaged: {len(jobs)} jobs "
                        f">= {_SEAL_MIN_BATCHES} over {len(parts)} chunks",
                        {"jobs": len(jobs), "chunks": len(parts)},
                    )
                    return [b for chunk in chunks for b in chunk]
        if len(jobs) >= _SEAL_MIN_BATCHES:
            # only an ELIGIBLE reply sealing inline is a decision (pool off
            # or degraded); small replies below the threshold are trivia,
            # and journaling them would flip-flop the ring on workloads
            # whose reply sizes oscillate around _SEAL_MIN_BATCHES
            self.governor.record_mode(
                governor.SHARDED_SEAL,
                "inline",
                "serial seal despite an eligible reply: pool off, measured "
                "inline decision, or pool-machinery degradation",
                {"jobs": len(jobs)},
            )
        t0 = time.perf_counter()
        out = [seal_one(*j) for j in jobs]
        self._stat_stage("t_seal", t0)
        return out

    def _abandon_pending_masks(self, launch: _Launch) -> None:
        """Mark a degraded sharded launch's still-queued shard masks
        abandoned (the harvester skips them: no fetch, no verdict). A mask
        already being harvested keeps its in-flight verdict — that device
        interaction genuinely happened."""
        with launch._lock:
            slots, launch._pending_slots = launch._pending_slots, []
        with _mask_claim_lock:
            for slot in slots:
                if slot._mask_state == "queued":
                    slot._mask_state = "abandoned"

    def _try_device_leg(self, domain: str, leg):
        """One device leg under the engine's fault envelope: the DOMAIN's
        per-attempt deadline (adaptive, governor-derived) + bounded retry
        (faults.retry_call), classified failure accounting, and a failure
        verdict on the DOMAIN's breaker at exhaustion. Returns the leg's
        value, or None after exhausted retries — the call site supplies
        its exact host fallback and, where the leg's success IS the device
        verdict (harvest/fetch legs), records the success. Every leg
        returns an array, so None is an unambiguous sentinel. This is THE
        shape of a fault-tolerant device interaction; keeping it in one
        place keeps the breaker verdicts exhaustive.

        Each SUCCESSFUL attempt's wall time feeds the governor's
        success-only device-leg histogram — the adaptive-deadline source.
        The timing wraps the leg itself, so a failed or abandoned attempt
        records nothing (a wedge that completes late on its abandoned
        worker still records its true wall time — an honest, rare
        completion, not a timeout artifact)."""
        gov = self.governor

        def timed_leg():
            t0 = time.perf_counter()
            out = leg()
            gov.observe_leg(domain, time.perf_counter() - t0)
            return out

        try:
            return faults.retry_call(
                timed_leg, gov.policy_for(domain), domain,
                count=self._stat_add,
            )
        except Exception as exc:
            faults.note_failure(domain, exc, reraise_programming=True)
            gov.breaker_for(domain).record_failure()
            return None

    def heartbeat(self) -> int:
        """Returns the number of registered scripts (liveness probe)."""
        return len(self._handles)

    @property
    def scripts(self) -> dict[int, ScriptHandle]:
        return dict(self._handles)

    # ------------------------------------------------------------ data path
    def process_batch(self, req: ProcessBatchRequest) -> ProcessBatchReply:
        """Synchronous wrapper: one submit, one harvest."""
        return self.submit(req).result()

    def submit(self, req: ProcessBatchRequest) -> Ticket:
        return self.submit_group([req])[0]

    def submit_group(self, reqs: list[ProcessBatchRequest]) -> list[Ticket]:
        """Fuse many requests into ONE launch per script.

        All records of all requests targeting a script are packed into a
        single staging array: one H2D transfer, one device program, one
        async D2H — the round-trip cost of the device link is paid once per
        group instead of once per request.

        Admission (resource_mgmt budget plane): every request's payload
        bytes reserve from the 'coproc' account BEFORE anything dispatches,
        all-or-nothing per group — a shed submit raises ``ShedError``
        having dispatched NOTHING (shed-before-ack: no offsets move, no
        materialized write can exist). Reserved bytes release when each
        ticket harvests, or here on any submit-path exception.
        """
        admitted: list[int] = []
        if self._admission is not None:
            ctrl = self._admission
            for req in reqs:
                nbytes = sum(
                    len(b.payload) for item in req.items for b in item.batches
                )
                reserved, retry_ms = ctrl.try_admit(nbytes)
                if nbytes > 0 and reserved == 0:
                    for r in admitted:
                        ctrl.release(r)
                    acct = ctrl.account
                    self.governor.note_shed(
                        "coproc", retry_ms,
                        {"requested_bytes": nbytes, "held_bytes": acct.held,
                         "limit_bytes": acct.limit},
                    )
                    self._stat_add("n_shed_submits", 1.0)
                    raise rm_admission.ShedError(
                        "coproc", retry_ms, f"{nbytes} staged bytes"
                    )
                admitted.append(reserved)
            if any(admitted):
                # a zero-byte submit is not evidence the account recovered
                self.governor.note_admitted("coproc")
        try:
            return self._submit_group_admitted(reqs, admitted)
        except BaseException:
            # nothing was handed back: the caller cannot harvest, so the
            # reservations must not outlive the failed submit
            if self._admission is not None:
                for r in admitted:
                    self._admission.release(r)
            raise

    def _submit_group_admitted(
        self, reqs: list[ProcessBatchRequest], admitted: list[int]
    ) -> list[Ticket]:
        tickets = [Ticket(self) for _ in reqs]
        for t, r in zip(tickets, admitted):
            t._admitted = r
        # script_id -> list of (ticket, slot_idx, item)
        by_script: dict[int, list[tuple]] = {}
        for ticket, req in zip(tickets, reqs):
            ticket.trace_id = req.trace_id
            for item in req.items:
                if item.script_id not in self._handles:
                    ticket._slots.append((_UNKNOWN, item, None, None))
                else:
                    slot_idx = len(ticket._slots)
                    ticket._slots.append(None)  # placeholder, filled below
                    by_script.setdefault(item.script_id, []).append(
                        (ticket, slot_idx, item)
                    )
        for script_id, entries in by_script.items():
            handle = self._handles[script_id]
            launch = _Launch(script_id, handle.policy)
            # a fused launch serves many requests; the first requester's
            # trace adopts it (the pacemaker submits one request per tick)
            launch.trace_id = entries[0][0].trace_id
            try:
                with tracer.span("coproc.dispatch", trace_id=launch.trace_id):
                    self._dispatch(script_id, launch, entries)
                ridx = 0
                for ticket, slot_idx, item in entries:
                    rng = list(range(ridx, ridx + len(item.batches)))
                    ridx += len(item.batches)
                    ticket._slots[slot_idx] = (_LAUNCHED, item, launch, rng)
            except Exception as exc:
                # classified: a dispatch blow-up emptying a launch's output
                # must never be invisible (a swallowed AttributeError here
                # once surfaced only as empty replies); programming errors
                # re-raise — the tick fails loudly and retries, instead of
                # the script silently dropping every record
                faults.note_failure("dispatch", exc, reraise_programming=True)
                if handle.policy == ErrorPolicy.deregister:
                    self.disable_coprocessors([script_id])
                    for ticket, slot_idx, item in entries:
                        ticket._slots[slot_idx] = (_DEREGISTERED, item, None, None)
                else:
                    for ticket, slot_idx, item in entries:
                        ticket._slots[slot_idx] = (_EMPTY, item, None, None)
        return tickets

    def _dispatch(self, script_id: int, launch: _Launch, entries: list[tuple]) -> None:
        """Explode all entries' records and issue the (async) device launch."""
        plan = self._plans[script_id]
        launch.engine = self
        launch.mode = plan.mode
        launch._plan = plan
        all_batches = [b for _, _, item in entries for b in item.batches]
        # Multi-chip lane (coproc/meshrunner.py): partition axis sharded
        # over the device mesh, per-device sub-launches, ONE SPMD
        # predicate program. Declines (single-device decision, open mesh
        # breaker, small launch) fall through to the standard path —
        # output is bit-identical either way, which is what the
        # test_meshrunner parity matrix pins.
        if plan.mode == "columnar" and self._meshrunner is not None:
            if self._dispatch_mesh(launch, plan, all_batches):
                return
        # Device-resident column cache: a repeat launch over an unchanged
        # batch window skips the WHOLE host ladder (decompress, parse,
        # find, extract) and — when the predicate ran on-device — the H2D
        # replay (the cached cols are device-resident). The key is
        # content-addressed (colcache.fingerprint), so an append produces
        # a clean miss by construction. Sharded launches consult and
        # populate the cache PER SHARD inside their workers (the old
        # second-miss inline self-route is gone), so this launch-wide
        # lookup serves the inline path and full-launch repeat windows.
        store_key = None
        if (
            plan.mode == "columnar"
            and self._colcache is not None
            and self._mesh is None
            and all_batches
        ):
            key = (script_id, colcache.fingerprint(all_batches))
            entry = self._colcache.lookup(key)
            if entry is not None:
                self._count_colcache(True)
                self._dispatch_columnar_cached(launch, plan, entry)
                return
            self._count_colcache(False)
            store_key = key
        if self._dispatch_sharded(launch, plan, all_batches):
            return
        # decide the parse ladder BEFORE the stage timer starts: the first
        # representative launch runs the fused-vs-staged calibration here,
        # and its four ladder passes must not masquerade as that launch's
        # t_explode_find* stage time
        parse = (
            self._parse_path(plan, all_batches)
            if plan.mode == "columnar"
            else "staged"
        )
        t0 = time.perf_counter()
        cache = None
        if plan.mode == "columnar":
            paths = plan.flat_paths()
            sp = None
            if parse == "structural":
                # STRUCTURAL fused lane: payload bytes cross the native
                # boundary once as a pointer table (no Python-side join;
                # the blob is built in-crossing only for passthrough
                # plans, whose zero-copy harvest gathers from it), parsed
                # by the two-stage structural-index kernel
                sp = batch_codec.explode_find_structural(
                    all_batches, paths, need_joined=plan.byte_identity
                )
            if sp is not None:
                self._stat_stage("t_explode_find2", t0)
                launch.ranges = sp.ranges
                n = sp.n
                launch.n = n
                self._stat_add("n_records", n)
                self._stat_add("n_launches", 1)
                with self._stats_lock:
                    probes.coproc_launch_rows_hist.record(n)
                self._dispatch_columnar_fused(launch, plan, sp, store_key)
                return
            # STAGED lane: framing parse + k-path JSON walk in one scalar
            # native crossing (rp_explode_find) — the parity oracle, and
            # the measured pick on boxes where structural doesn't win
            fused = batch_codec.explode_and_find(all_batches, paths)
            if fused is not None:
                exploded, types, vs, ve = fused
                cache = plan.make_cache_from_tables(exploded, paths, types, vs, ve)
                self._stat_stage("t_explode_find", t0)
            else:
                exploded = batch_codec.explode_batches(all_batches)
                self._stat_stage("t_explode", t0)
        else:
            if plan.mode == "payload":
                # POINTER-TABLE staging lane (ROADMAP item 1 follow-on b):
                # record (offset, len) parse straight off the decompressed
                # per-batch payload buffers and staging packs from the
                # same buffers — the joined blob (and its b"".join copy,
                # plus _pack_staged's second cache-cold pass over it)
                # never exists. Bit-identical to the classic lane (the
                # _pack_staged parity test pins it).
                pe = batch_codec.explode_ptrs(all_batches)
                if pe is not None:
                    self._stat_stage("t_explode_ptrs", t0)
                    launch.ranges = pe.ranges
                    n = len(pe.sizes)
                    launch.n = n
                    self._stat_add("n_records", n)
                    self._stat_add("n_launches", 1)
                    with self._stats_lock:
                        probes.coproc_launch_rows_hist.record(n)
                    self._dispatch_payload_ptrs(launch, pe, n)
                    return
            exploded = batch_codec.explode_batches(all_batches)
            self._stat_stage("t_explode", t0)
        launch.ranges = exploded.ranges
        n = len(exploded.sizes)
        launch.n = n
        self._stat_add("n_records", n)
        self._stat_add("n_launches", 1)
        with self._stats_lock:  # concurrent submits: HdrHist isn't thread-safe
            probes.coproc_launch_rows_hist.record(n)
        if plan.mode == "payload":
            self._dispatch_payload(launch, exploded, n)
        elif plan.mode == "columnar":
            self._dispatch_columnar(launch, plan, exploded, n, cache, store_key)
        else:  # host: materialized lazily at harvest
            launch._exploded = exploded

    # ------------------------------------------------------ parse-path probe
    def _parse_path(self, plan, all_batches) -> str:
        """Which parse ladder this launch runs: the measured per-engine
        fused-vs-staged decision, gated by plan eligibility (nested paths
        or general projections keep the staged ladder regardless). Until
        a representative launch has probed, small launches take the known
        staged path without pinning anything.

        Same two-lock discipline as the columnar-backend probe: the RUN
        lock serializes calibration EXECUTION (concurrent first launches
        must not measure against each other's load), while the short
        decision lock guards only the field — stats() readers never wait
        behind the four ladder passes a calibration runs."""
        if not self._structural_enabled or not plan.structural_eligible():
            return "staged"
        with self._parse_decision_lock:
            decision = self._parse_decision
        if decision is not None:
            return decision
        n = sum(b.header.record_count for b in all_batches)
        if n < _PROBE_MIN_ROWS:
            return "staged"
        with self._parse_probe_run_lock:
            with self._parse_decision_lock:
                decision = self._parse_decision
            if decision is None:
                self._calibrate_parse_path(plan, all_batches)
                with self._parse_decision_lock:
                    decision = self._parse_decision
        return decision

    def _measure_parse_ratio(self, plan, all_batches) -> tuple[float, float]:
        """(t_staged, t_structural) for this launch's REAL parse+extract
        ladders, each best-of-2 — the same measure-the-true-workload
        posture as _measure_pool_ratio."""
        paths = plan.flat_paths()
        n = sum(b.header.record_count for b in all_batches)
        n_pad = _bucket_rows(n)

        def staged():
            fused = batch_codec.explode_and_find(all_batches, paths)
            if fused is None:
                raise RuntimeError("staged native ladder unavailable")
            ex, types, vs, ve = fused
            cache = plan.make_cache_from_tables(ex, paths, types, vs, ve)
            if plan.dev_cols:
                plan.extract_device_inputs(
                    ex.joined, ex.offsets, ex.sizes, n_pad, cache
                )
            if not plan.passthrough:
                plan.extract_projection(ex.joined, ex.offsets, ex.sizes, cache)

        def structural():
            sp = batch_codec.explode_find_structural(
                all_batches, paths, need_joined=plan.byte_identity
            )
            if sp is None:
                raise RuntimeError("structural native ladder unavailable")
            plan.extract_fused(sp, n_pad)

        t_staged = t_structural = float("inf")
        for _ in range(2):
            t0 = time.perf_counter()
            staged()
            t_staged = min(t_staged, time.perf_counter() - t0)
            t0 = time.perf_counter()
            structural()
            t_structural = min(t_structural, time.perf_counter() - t0)
        return t_staged, t_structural

    def _calibrate_parse_path(self, plan, all_batches) -> None:
        """One-shot engine-sticky fused-vs-staged pin off the first
        representative columnar launch. Caller holds the probe RUN lock;
        the decision fields publish under the short decision lock."""
        try:
            t_staged, t_structural = self._measure_parse_ratio(
                plan, all_batches
            )
        except Exception as exc:
            # a box whose probe blows up runs the known staged ladder
            # forever — classified so the demotion is visible on /metrics
            faults.note_failure("parse_calibration", exc)
            logger.exception("parse-path calibration failed; keeping staged")
            with self._parse_decision_lock:
                self._parse_decision = "staged"
            self.governor.record(
                governor.PARSE_PATH,
                "staged",
                f"calibration FAILED ({faults.kind_of(exc)}); keeping the "
                "scalar staged ladder",
                {"error": faults.kind_of(exc)},
            )
            return
        ratio = t_staged / t_structural if t_structural > 0 else 0.0
        decision = "structural" if ratio >= host_pool.PROBE_MARGIN else "staged"
        probe = {
            "t_staged_ms": round(t_staged * 1e3, 3),
            "t_structural_ms": round(t_structural * 1e3, 3),
            "speedup": round(ratio, 3),
            "chosen": decision,
        }
        with self._parse_decision_lock:
            self._parse_decision = decision
            self._parse_probe = probe
        logger.info("parse-path calibration: %s", probe)
        self.governor.record(
            governor.PARSE_PATH,
            decision,
            f"measured parse+extract ladders: staged {t_staged * 1e3:.3f} ms"
            f" vs structural {t_structural * 1e3:.3f} ms (structural must "
            f"win {host_pool.PROBE_MARGIN}x; engine-sticky)",
            dict(probe),
        )

    # ------------------------------------------------------ pool calibration
    def _measure_pool_ratio(self, plan, all_batches, counts) -> tuple[float, float]:
        """(t_inline, t_sharded) for this launch's REAL explode stage, each
        best-of-2. Measuring the true workload, not a synthetic spin: on
        burstable virtualized hosts a millisecond-scale synthetic probe can
        show phantom 2-3x thread scaling while sustained parsing thrashes."""
        pool = self._host_pool
        parts = host_pool.partition_counts(counts, pool.workers)
        paths = plan.flat_paths() if plan.mode == "columnar" else None

        def explode(batches):
            if paths:
                got = batch_codec.explode_and_find(batches, paths)
                if got is not None:
                    return got
            return batch_codec.explode_batches(batches)

        t_inline = t_sharded = float("inf")
        for _ in range(2):
            t0 = time.perf_counter()
            explode(all_batches)
            t_inline = min(t_inline, time.perf_counter() - t0)
            t0 = time.perf_counter()
            pool.run([
                (lambda s=s, e=e: explode(all_batches[s:e])) for s, e in parts
            ])
            t_sharded = min(t_sharded, time.perf_counter() - t0)
        return t_inline, t_sharded

    def _calibrate_host_pool(self, plan, all_batches, counts) -> None:
        """One-shot, process-sticky pool on/off decision off the first
        shardable launch (the same measure-first posture as
        _probe_columnar_backend: never assume the cores are real). The
        ~4 extra explode passes cost one launch a few ms, once."""
        recal = self._recal_pending
        self._recal_pending = False
        why = (
            "periodic recalibration (coproc_host_pool_recal_launches)"
            if recal
            else "first shardable launch calibration"
        )
        try:
            t_inline, t_sharded = self._measure_pool_ratio(
                plan, all_batches, counts
            )
        except Exception as exc:
            # classified: a box whose calibration keeps blowing up runs
            # inline forever, which must be visible on /metrics
            faults.note_failure("pool_calibration", exc)
            logger.exception("host pool calibration failed; keeping inline path")
            self._pool_decision = "inline"
            self.governor.record(
                governor.HOST_POOL,
                "inline",
                f"{why} FAILED ({faults.kind_of(exc)}); keeping inline path",
                {"error": faults.kind_of(exc), "workers": self._host_workers},
            )
        else:
            ratio = t_inline / t_sharded if t_sharded > 0 else 0.0
            self._pool_decision = (
                "sharded" if ratio >= host_pool.PROBE_MARGIN else "inline"
            )
            self._host_pool_probe = {
                "t_inline_ms": round(t_inline * 1e3, 3),
                "t_sharded_ms": round(t_sharded * 1e3, 3),
                "speedup": round(ratio, 3),
                "workers": self._host_workers,
                "chosen": self._pool_decision,
            }
            logger.info("host pool calibration: %s", self._host_pool_probe)
            self.governor.record(
                governor.HOST_POOL,
                self._pool_decision,
                f"{why}: measured explode speedup {ratio:.3f}x vs margin "
                f"{host_pool.PROBE_MARGIN} at {self._host_workers} workers",
                dict(self._host_pool_probe, recalibration=recal),
            )
        if self._pool_decision == "inline":
            self._host_pool.shutdown()  # threads idle forever otherwise

    # ------------------------------------------------------ sharded dispatch
    def _dispatch_sharded(self, launch: _Launch, plan, all_batches) -> bool:
        """Shard the launch's host stages over the worker pool.

        Returns False when this launch should take the inline path: no
        pool, too small, SPMD mesh, or a columnar plan whose device-vs-host
        probe has not run yet (the first columnar launch probes inline and
        pins the backend; every later launch shards).
        """
        pool = self._host_pool
        if pool is None or len(all_batches) < 2:
            return False
        counts = [b.header.record_count for b in all_batches]
        if sum(counts) < _SHARD_MIN_ROWS:
            return False
        parts = host_pool.partition_counts(counts, pool.workers)
        if len(parts) < 2:
            # skewed batches can collapse to a single shard; never CALIBRATE
            # on such a launch either — a 1-thunk pool.run executes on the
            # caller thread, so t_sharded ~= t_inline and the pool would be
            # demoted process-wide off a meaningless measurement
            return False
        # ONE locked region owns the recal counter, the calibrate-once
        # double-check AND the decision read this launch acts on: the old
        # shape re-read self._pool_decision unlocked after the calibrate
        # block (pandaraces RAC1101 — a concurrent recal archiving the
        # probe could flip the value between the calibration and its use).
        # Serializing concurrent first submits here also keeps them from
        # calibrating against each other's measurement load, which would
        # depress the sharded ratio below PROBE_MARGIN on boxes where the
        # pool truly wins.
        with self._pool_decision_lock:
            decision = self._pool_decision
            if (
                self._probe_enabled
                and self._recal_interval > 0
                and decision is not None
            ):
                # periodic re-calibration: after N shardable launches the
                # pinned decision is archived and THIS launch re-measures —
                # burstable hosts that gained (or lost) capacity re-pin
                self._launches_since_cal += 1
                if self._launches_since_cal >= self._recal_interval:
                    if self._host_pool_probe is not None:
                        self._host_pool_probe_prev = dict(
                            self._host_pool_probe
                        )
                    decision = self._pool_decision = None
                    self._launches_since_cal = 0
                    # the calibration this triggers journals itself as
                    # a recal (read + cleared in _calibrate_host_pool)
                    self._recal_pending = True
            if decision is None:
                self._calibrate_host_pool(plan, all_batches, counts)
                decision = self._pool_decision
        if decision != "sharded":
            return False  # calibration: no real win on this box
        use_host = None
        if plan.mode == "columnar" and plan.dev_cols:
            if self._mesh is not None:
                return False  # SPMD predicate stays one launch over the mesh
            backend = TpuEngine.sticky_columnar_backend()
            if self._force_mode == "columnar_host":
                use_host = True
            elif self._force_mode == "columnar_device":
                use_host = False
            elif backend is not None:
                use_host = backend == "host"
                self.governor.note_posture(
                    governor.COLUMNAR_BACKEND, backend
                )
            else:
                return False
        breaker_demoted_rows = 0
        if plan.mode == "columnar" and plan.dev_cols and use_host is False:
            if not self._breaker.allow_device():
                # open breaker demotes the whole sharded launch to the
                # exact numpy predicate (identical bits per shard). Rows
                # are COUNTED only after the fan-out commits: a shard
                # fault degrades this launch to the inline path, which
                # counts its own demotion — counting here too would
                # double n_fallback_rows for the same records.
                use_host = True
                breaker_demoted_rows = sum(counts)
        if plan.mode == "columnar":
            if use_host is False:
                # compile in THIS thread before fan-out: plan._fn_cache is
                # a plain dict and first-touch jit takes seconds — shard
                # workers must find the function already cached
                plan.compile_device(None)
            paths = plan.flat_paths()
            # parse ladder decided ONCE per launch (may probe, inline, on
            # the first representative launch) — shard workers must not
            # race the calibration or mix ladders within a launch
            structural = self._parse_path(plan, all_batches) == "structural"
            t0 = time.perf_counter()
            try:
                shards = pool.run([
                    (
                        lambda i=i, s=s, e=e: self._run_columnar_shard(
                            i, launch, plan, all_batches[s:e], paths,
                            use_host, structural
                        )
                    )
                    for i, (s, e) in enumerate(parts)
                ])
            except Exception as exc:
                # fail closed per-launch: a faulted shard worker degrades
                # this launch to the inline path, which re-executes every
                # stage launch-wide from the original batches (exact output,
                # nothing lost or duplicated — nothing was emitted yet).
                # Sibling shards may have already enqueued device masks:
                # abandon them, or each orphan costs the harvester a full
                # envelope and feeds the breaker verdicts for a launch
                # that no longer exists.
                faults.note_failure(
                    faults.SHARD_WORKER, exc, reraise_programming=True
                )
                self._abandon_pending_masks(launch)
                return False
            self._stat_stage("t_sharded_dispatch", t0)
            if breaker_demoted_rows:
                self._count_fallback(breaker_demoted_rows)
            launch._shards = shards
            launch.r_out = plan.r_out
            n = 0
            ranges: list[tuple[int, int]] = []
            for shard in shards:
                ranges.extend((a + n, b + n) for a, b in shard.ranges)
                n += shard.n
            launch.ranges = ranges
            launch.n = n
        else:
            # payload/host plans: only explode is per-record host work at
            # dispatch; shard it and merge back into one launch-wide table
            # (merge_exploded rebases offsets/ranges) so the existing
            # device staging / host materialize paths run unchanged.
            t0 = time.perf_counter()
            try:
                exploded = batch_codec.merge_exploded(
                    pool.run([
                        (lambda s=s, e=e: _explode_shard(all_batches[s:e]))
                        for s, e in parts
                    ])
                )
            except Exception as exc:
                faults.note_failure(
                    faults.SHARD_WORKER, exc, reraise_programming=True
                )
                return False  # degrade this launch to the inline path
            self._stat_stage("t_explode", t0)
            launch.ranges = exploded.ranges
            n = len(exploded.sizes)
            launch.n = n
            if plan.mode == "payload":
                self._dispatch_payload(launch, exploded, n)
            else:
                launch._exploded = exploded
        self._stat_add("n_records", n)
        self._stat_add("n_launches", 1)
        self._stat_add("n_sharded_launches", 1)
        with self._stats_lock:  # HdrHist isn't thread-safe
            probes.coproc_launch_rows_hist.record(n)
            if plan.mode == "columnar":
                for shard in launch._shards:
                    probes.coproc_shard_rows_hist.record(shard.n)
                self.last_launch_shards = [
                    {"rows": shard.n, **shard.stages} for shard in launch._shards
                ]
            else:
                for s, e in parts:
                    probes.coproc_shard_rows_hist.record(sum(counts[s:e]))
        return True

    def _count_colcache(self, hit: bool) -> None:
        if hit:
            self._stat_add("n_colcache_hit", 1.0)
            probes.coproc_colcache_hits.inc()
        else:
            self._stat_add("n_colcache_miss", 1.0)
            probes.coproc_colcache_misses.inc()

    def _shard_cache_key(self, script_id: int, batches) -> tuple | None:
        """Per-shard column-cache key (cross-launch cache for the sharded
        path, ROADMAP item 1 follow-on c): the SAME content fingerprint as
        the launch-wide key, over the shard's batch slice. Contiguous
        range shards of a repeating launch produce identical slices, so
        every shard of the second identical launch hits."""
        if self._colcache is None or not batches:
            return None
        return (script_id, colcache.fingerprint(batches))

    def _shard_cache_entry(
        self, shard: _HostShard, plan: ColumnarPlan, cols, n_pad: int,
        structural: bool,
    ) -> "colcache.Entry":
        """The per-shard cache entry for a just-run ladder — ONE builder
        so the mesh and standard sharded paths can never cache divergent
        contents for the same shard."""
        return colcache.Entry(
            n=shard.n, n_pad=n_pad, ranges=shard.ranges, cols=cols,
            proj_data=shard.proj_data, proj_ok=shard.proj_ok,
            exploded=shard.exploded if plan.passthrough else None,
            parse_mode="structural" if structural else "staged",
        )

    def _shard_from_entry(
        self, shard: _HostShard, plan: ColumnarPlan, entry, n_pad: int
    ):
        """Fill a _HostShard from a cached per-shard entry (skips the
        whole host ladder) and return host predicate columns fitted to
        ``n_pad`` (entries cached under a different launch's row bucket
        pad/trim to this launch's — padding rows' bits are discarded, so
        the fit never changes output)."""
        shard.n = entry.n
        shard.ranges = list(entry.ranges)
        if plan.passthrough:
            shard.exploded = entry.exploded
            shard.proj_ok = np.ones(entry.n, dtype=bool)
        else:
            shard.proj_data = entry.proj_data
            shard.proj_ok = entry.proj_ok
        return _fit_cols(entry.cols, n_pad)

    def _shard_ladder(
        self, shard: _HostShard, plan: ColumnarPlan, batches, paths,
        structural: bool, n_pad: int | None = None,
        trace_id: int | None = None,
    ):
        """One shard's host parse/extract ladder (no predicate dispatch):
        explode + find (structural fused or staged), predicate column
        extraction, projection extraction. Fills ``shard`` and returns
        (cols, n_pad). ``n_pad`` pins the row bucket (the mesh path needs
        one COMMON bucket across every device shard so the stacked SPMD
        input has one shape); None buckets per shard. ``trace_id`` is the
        launch's, carried EXPLICITLY because shard ladders run on pool
        workers where no ambient trace is set."""

        def stage(key: str, t0: float) -> None:
            # shards run concurrently: summing their durations into the
            # launch-wall t_* keys would inflate those ~workers-fold, so
            # per-shard time lands under t_shard_* (CPU-seconds across
            # workers); the fan-out's wall time is t_sharded_dispatch.
            # _stat_stage mirrors the slice into the pandapulse timeline
            # under the same t_shard_* name (one clock read, shared dt).
            dt = self._stat_stage(
                "t_shard_" + key[2:], t0, trace_id=trace_id
            )
            shard.stages[key] = round(shard.stages.get(key, 0.0) + dt, 6)

        t0 = time.perf_counter()
        cache = None
        cols = None
        fused_proj = None  # (proj_data, proj_ok) from the fused lane
        sp = (
            batch_codec.explode_find_structural(
                batches, paths, need_joined=plan.byte_identity
            )
            if structural and paths
            else None
        )
        if sp is not None:
            stage("t_explode_find2", t0)
            shard.ranges = sp.ranges
            n = sp.n
            shard.n = n
            if n == 0:
                shard.proj_ok = np.zeros(0, dtype=bool)
                return None, n_pad or 0
            # passthrough framing gathers from the joined blob the fused
            # crossing built; projection shards never need raw bytes again
            shard.exploded = sp.exploded() if plan.byte_identity else None
            t0 = time.perf_counter()
            if n_pad is None:
                n_pad = _bucket_rows(n)
            cols, proj_data, proj_ok = plan.extract_fused(sp, n_pad)
            stage("t_fused_extract", t0)
            fused_proj = (proj_data, proj_ok)
        else:
            fused = (
                batch_codec.explode_and_find(batches, paths) if paths else None
            )
            if fused is not None:
                ex, types, vs, ve = fused
                cache = plan.make_cache_from_tables(ex, paths, types, vs, ve)
                stage("t_explode_find", t0)
            else:
                ex = batch_codec.explode_batches(batches)
                stage("t_explode", t0)
            shard.exploded = ex
            shard.ranges = ex.ranges
            n = len(ex.sizes)
            shard.n = n
            if n == 0:
                shard.proj_ok = np.zeros(0, dtype=bool)
                return None, n_pad or 0
            if cache is None:
                t0 = time.perf_counter()
                cache = plan.build_find_cache(ex.joined, ex.offsets, ex.sizes)
                stage("t_find", t0)
            if plan.dev_cols:
                t0 = time.perf_counter()
                if n_pad is None:
                    n_pad = _bucket_rows(n)
                cols = plan.extract_device_inputs(
                    ex.joined, ex.offsets, ex.sizes, n_pad, cache
                )
                stage("t_extract_pred", t0)
        if plan.passthrough:
            shard.proj_ok = np.ones(n, dtype=bool)
        elif fused_proj is not None:
            # projection rows came out of the fused extraction crossing
            shard.proj_data, shard.proj_ok = fused_proj
        else:
            t0 = time.perf_counter()
            data, ok = plan.extract_projection(
                ex.joined, ex.offsets, ex.sizes, cache
            )
            shard.proj_data = data
            shard.proj_ok = ok
            shard.exploded = None  # framing reads proj_data, not raw records
            stage("t_extract_proj", t0)
        return cols, (n_pad or 0)

    def _run_columnar_shard(
        self, idx: int, launch: _Launch, plan: ColumnarPlan, batches, paths,
        use_host, structural: bool = False,
    ) -> _HostShard:
        """One shard's dispatch-side host stages, on a pool worker:
        per-shard column-cache consult (a hit skips the whole ladder),
        explode + find, predicate column extraction, predicate dispatch
        (the shard's own device launch or numpy eval — issued as soon as
        THIS shard's columns land, overlapping later shards' extraction),
        projection extraction, cache populate. ``structural`` runs the
        shard through the fused structural ladder instead (same outputs;
        the engine-level decision is per launch). Touches only its own
        shard (SHD6xx)."""
        shard = _HostShard()
        t_shard0 = time.perf_counter()
        # shard-worker fault domain: a fault here (injected or real) fails
        # the fan-out, and _dispatch_sharded degrades the LAUNCH to the
        # inline path — stages re-execute launch-wide with exact output
        faults.inject(faults.SHARD_WORKER)
        key = self._shard_cache_key(launch.script_id, batches)
        entry = None
        dev_cols = None
        store_entry = None
        if key is not None:
            entry = self._colcache.lookup(key)
            self._count_colcache(entry is not None)
        if entry is not None:
            n_pad = _bucket_rows(entry.n) if entry.n else 0
            cols = self._shard_from_entry(shard, plan, entry, n_pad)
            if entry.cols_dev is not None and entry.n_pad == n_pad:
                dev_cols = entry.cols_dev
        else:
            cols, n_pad = self._shard_ladder(
                shard, plan, batches, paths, structural,
                trace_id=launch.trace_id,
            )
            if key is not None and shard.n and cols is not None:
                store_entry = self._shard_cache_entry(
                    shard, plan, cols, n_pad, structural
                )
        n = shard.n
        if n == 0:
            return shard
        if cols is not None:
            slot = _MaskSlot(n)
            slot.trace_id = launch.trace_id
            t0 = time.perf_counter()
            if use_host:
                slot._mask_np = plan.eval_host_mask(cols)
                dt = self._stat_stage(
                    "t_shard_dispatch", t0, trace_id=launch.trace_id
                )
                shard.stages["t_dispatch"] = round(
                    shard.stages.get("t_dispatch", 0.0) + dt, 6
                )
            else:
                def leg():
                    faults.inject(faults.DEVICE_DISPATCH)
                    fn = plan.compile_device(None)
                    args = dev_cols
                    if args is None:
                        if store_entry is not None:
                            # explicit device_put so the shard's cache
                            # entry owns committed device arrays — later
                            # hits launch with zero H2D (the PR-11 device
                            # residency, now per shard)
                            import jax

                            args = [jax.device_put(c) for c in cols]
                            store_entry.cols_dev = args
                        else:
                            args = cols
                    mask = fn(*args)
                    mask.copy_to_host_async()
                    return mask

                mask = self._try_device_leg(faults.DEVICE_DISPATCH, leg)
                dt = self._stat_stage(
                    "t_shard_dispatch", t0, trace_id=launch.trace_id
                )
                shard.stages["t_dispatch"] = round(
                    shard.stages.get("t_dispatch", 0.0) + dt, 6
                )
                if mask is None:
                    # this shard's exact host fallback; sibling shards keep
                    # their own device launches
                    slot._mask_np = plan.eval_host_mask(cols)
                    self._count_fallback(n)
                else:
                    self._breaker.record_success()  # dispatch-domain verdict
                    if dev_cols is None:
                        self._stat_add(
                            "bytes_h2d", sum(c.nbytes for c in cols)
                        )
                    self._stat_add("bytes_d2h", n_pad // 8)
                    slot._mask_dev = mask
                    slot._cols = cols
                    slot._mask_event = threading.Event()
                    slot._mask_state = "queued"
                    with launch._lock:
                        launch._pending_slots.append(slot)
                    self._ensure_harvester()
                    slot._enq_t = time.perf_counter()
                    self._harvest_q.put(slot)
            shard.mask = slot
        if store_entry is not None:
            # put AFTER the dispatch leg so a populated entry carries its
            # device-resident twins when the device path is live
            self._colcache.put(key, store_entry)
        tracer.record(
            "coproc.shard",
            (time.perf_counter() - t_shard0) * 1e6,
            launch.trace_id,
            start_perf=t_shard0,
            shard=idx,
            rows=n,
        )
        return shard

    # ------------------------------------------------------ mesh dispatch
    def _dispatch_mesh(self, launch: _Launch, plan, all_batches) -> bool:
        """The multi-chip lane (coproc/meshrunner.py): per-device
        sub-launches over the host-pool range shard, the predicate as ONE
        SPMD program over stacked [D, n_pad, ...] columns sharded on the
        mesh's partition axis, per-shard column-cache consult/populate.

        Returns False to send the launch down the standard single-device
        path: not mesh-eligible, sticky "single" decision, open
        mesh_dispatch breaker, or a launch too small to be worth an SPMD
        program. A mesh device-leg failure demotes THIS launch to the
        exact numpy predicate per shard — bit-identical output, and the
        breaker verdict routes later launches to the single-device path
        until the half-open probe re-admits the mesh."""
        runner = self._meshrunner
        if (
            runner is None
            or plan.mode != "columnar"
            or not plan.dev_cols
            or self._force_mode == "columnar_host"
            or self._mesh is not None
        ):
            return False
        decision = runner.decision
        if decision == "single":
            return False
        counts = [b.header.record_count for b in all_batches]
        n = sum(counts)
        if n == 0 or len(all_batches) < 2:
            return False
        if decision is None and n < meshrunner.PROBE_MIN_ROWS:
            return False  # too small to probe on; single, without pinning
        if decision is None and runner.probe_lock_busy:
            # a sibling launch is mid-calibration (seconds of jit): its
            # maybe_calibrate would route this launch single anyway, so
            # bail BEFORE paying the whole per-shard mesh ladder only to
            # re-run it launch-wide down the standard path
            return False
        if (
            decision == "mesh"
            and runner.probe_enabled
            and n < meshrunner.PROBE_MIN_ROWS
        ):
            # steady-state floor for the MEASURED pin: a trickle launch
            # (flush tail after a calibrated win) isn't worth the stack/
            # device_put/SPMD overhead — the single path is strictly
            # cheaper below the probe's own representativeness floor. A
            # config-forced pin (probe=False) stays unconditional: the
            # operator asked for the mesh lane, full stop.
            return False
        mesh_breaker = self.governor.breaker_for(faults.MESH_DISPATCH)
        if not mesh_breaker.allow_device():
            runner.note_demotion()
            self.governor.record_mode(
                governor.MESH,
                "single",
                "mesh_dispatch breaker open: mesh launches demoted to the "
                "bit-identical single-device path",
                {"devices": runner.n_devices},
                key="path",
            )
            return False
        parts = runner.shard_ranges(counts)
        # parse ladder decided ONCE per launch (may calibrate, inline) —
        # shard workers must not race the calibration or mix ladders
        structural = self._parse_path(plan, all_batches) == "structural"
        paths = plan.flat_paths()
        # one COMMON row bucket across every device shard: the stacked
        # SPMD input is one [D, n_pad, ...] array per column
        n_pad = _bucket_rows(max(sum(counts[s:e]) for s, e in parts))
        t0 = time.perf_counter()
        thunks = [
            (
                lambda d=d, s=s, e=e: self._run_mesh_shard(
                    d, launch, plan, all_batches[s:e], paths, structural,
                    n_pad,
                )
            )
            for d, (s, e) in enumerate(parts)
        ]
        pool = self._host_pool
        try:
            results = (
                pool.run(thunks)
                if pool is not None and len(thunks) >= 2
                else [t() for t in thunks]
            )
        except Exception as exc:
            # fail closed per-launch: a faulted shard worker degrades this
            # launch to the standard path, which re-executes every stage
            # launch-wide from the original batches (exact output)
            faults.note_failure(
                faults.SHARD_WORKER, exc, reraise_programming=True
            )
            return False
        self._stat_stage("t_mesh_ladder", t0)
        shards = [shard for shard, _ in results]
        shard_cols = [cols for _, cols in results]
        zeros = plan.zero_device_inputs(n_pad)
        n_arrays = len(zeros)
        stacked = []
        for i in range(n_arrays):
            blocks = [
                shard_cols[d][i]
                if d < len(shard_cols) and shard_cols[d] is not None
                else zeros[i]
                for d in range(runner.n_devices)
            ]
            stacked.append(np.stack(blocks))
        if decision is None:
            # the single-device baseline must see the rows the REAL single
            # path would launch — each shard trimmed to its true record
            # count, concatenated, padded to _bucket_rows(n) — not the
            # D * n_pad padded stack (which inflates t_single up to ~2x on
            # unbalanced shards and could pin "mesh" on a box where the
            # single path actually wins)
            n_flat = _bucket_rows(n)
            flat = []
            for i in range(n_arrays):
                parts_i = [
                    shard_cols[d][i][: shards[d].n]
                    for d in range(len(shards))
                    if shard_cols[d] is not None
                ]
                flat.append(
                    _fit_cols([np.concatenate(parts_i)], n_flat)[0]
                )
            decision = runner.maybe_calibrate(
                self.governor, plan, stacked, flat, n
            )
            if decision != "mesh":
                # the measured pin says single-device: this launch's ladder
                # re-runs down the standard path (a one-time cost per
                # engine — the sticky decision skips the mesh lane outright
                # from the next launch on)
                return False
        launch.r_out = plan.r_out
        t0 = time.perf_counter()

        def leg():
            faults.inject(faults.MESH_DISPATCH)
            fn = runner.predicate_fn(plan)
            args = runner.stack_and_put(stacked)
            mask = fn(*args)
            mask.copy_to_host_async()
            return mask

        mask = self._try_device_leg(faults.MESH_DISPATCH, leg)
        self._stat_stage("t_dispatch", t0)
        if mask is None:
            # exhausted mesh envelope: demote THIS launch to the exact
            # numpy predicate per shard (same columns, identical bits);
            # the breaker verdict (recorded by _try_device_leg) decides
            # whether the NEXT launch even tries the mesh
            runner.note_demotion()
            self._count_fallback(n)
            for shard, cols in zip(shards, shard_cols):
                if shard.n and cols is not None:
                    slot = _MaskSlot(shard.n)
                    slot.trace_id = launch.trace_id
                    slot._mask_np = plan.eval_host_mask(cols)
                    shard.mask = slot
        else:
            mesh_breaker.record_success()
            self._stat_add("bytes_h2d", sum(a.nbytes for a in stacked))
            self._stat_add("bytes_d2h", runner.n_devices * (n_pad // 8))
            for d, (shard, cols) in enumerate(zip(shards, shard_cols)):
                if shard.n == 0 or cols is None:
                    continue
                slot = _MaskSlot(shard.n)
                slot.trace_id = launch.trace_id
                # per-device block of the ONE sharded result; fetched
                # synchronously at harvest under the MASK_FETCH envelope
                # (no _mask_event -> _resolve_keep fetches directly), with
                # the exact numpy fallback over the retained columns
                slot._mask_dev = mask[d]
                slot._cols = cols
                shard.mask = slot
        launch._shards = shards
        ranges: list[tuple[int, int]] = []
        rec_base = 0
        for shard in shards:
            ranges.extend((a + rec_base, b + rec_base) for a, b in shard.ranges)
            rec_base += shard.n
        launch.ranges = ranges
        launch.n = rec_base
        if mask is not None:
            # mesh accounting only when the SPMD program actually ran:
            # a demoted launch (numpy per shard) must not journal a
            # healthy "mesh" posture or grow the mesh launch counters —
            # note_demotion above is its whole story
            runner.note_launch([shard.n for shard in shards])
            self.governor.record_mode(
                governor.MESH,
                "mesh",
                f"SPMD launch over the {runner.n_devices}-device mesh: "
                f"per-device sub-launches via the host-pool range shard, "
                f"one shard_map predicate program",
                {"devices": runner.n_devices, "rows": rec_base},
                key="path",
            )
            self._stat_add("n_mesh_launches", 1)
        self._stat_add("n_records", rec_base)
        self._stat_add("n_launches", 1)
        with self._stats_lock:
            probes.coproc_launch_rows_hist.record(rec_base)
            for shard in shards:
                probes.coproc_shard_rows_hist.record(shard.n)
            self.last_launch_shards = [
                {"rows": shard.n, **shard.stages} for shard in shards
            ]
        return True

    def _run_mesh_shard(
        self, d: int, launch: _Launch, plan: ColumnarPlan, batches, paths,
        structural: bool, n_pad: int,
    ) -> tuple[_HostShard, list | None]:
        """One mesh device's dispatch-side host ladder (pool worker or
        inline): per-shard column-cache consult, parse/extract with the
        LAUNCH-COMMON row bucket, projection extraction, cache populate.
        NO predicate dispatch — the predicate is one SPMD program over
        all shards, issued by _dispatch_mesh after the stack assembles.
        Touches only its own shard (SHD6xx)."""
        shard = _HostShard()
        t_shard0 = time.perf_counter()
        faults.inject(faults.SHARD_WORKER)
        key = self._shard_cache_key(launch.script_id, batches)
        entry = self._colcache.lookup(key) if key is not None else None
        if key is not None:
            self._count_colcache(entry is not None)
        if entry is not None:
            cols = self._shard_from_entry(shard, plan, entry, n_pad)
        else:
            cols, _ = self._shard_ladder(
                shard, plan, batches, paths, structural, n_pad=n_pad,
                trace_id=launch.trace_id,
            )
            if key is not None and shard.n and cols is not None:
                self._colcache.put(
                    key,
                    self._shard_cache_entry(
                        shard, plan, cols, n_pad, structural
                    ),
                )
        tracer.record(
            "coproc.mesh_shard",
            (time.perf_counter() - t_shard0) * 1e6,
            launch.trace_id,
            start_perf=t_shard0,
            shard=d,
            rows=shard.n,
        )
        return shard, cols

    def _dispatch_payload(self, launch: _Launch, exploded, n: int) -> None:
        fn, r_out = self._pipelines[launch.script_id]
        launch.r_out = r_out
        launch.fits = exploded.sizes <= self._row_stride
        if n == 0:
            return
        t0 = time.perf_counter()
        n_pad = _bucket_rows(n)
        staged = self._pack_staged(exploded, n_pad)
        self._stat_stage("t_pack", t0)
        self._launch_payload(launch, staged, n_pad, fn, r_out)

    def _dispatch_payload_ptrs(self, launch: _Launch, pe, n: int) -> None:
        """The pointer-table twin of _dispatch_payload: staging packs
        each batch's records straight from its retained decompressed
        payload buffer (batch_codec.PtrExploded) — byte-identical staged
        rows, one fewer full copy of the launch's record bytes."""
        fn, r_out = self._pipelines[launch.script_id]
        launch.r_out = r_out
        launch.fits = pe.sizes <= self._row_stride
        if n == 0:
            return
        t0 = time.perf_counter()
        n_pad = _bucket_rows(n)
        staged = self._pack_staged_ptrs(pe, n_pad)
        self._stat_stage("t_pack", t0)
        self._launch_payload(launch, staged, n_pad, fn, r_out)

    def _launch_payload(
        self, launch: _Launch, staged: np.ndarray, n_pad: int, fn, r_out: int
    ) -> None:
        """Issue one payload-plan device launch over a built staging
        matrix (breaker gate, fault envelope, exact host fallback) —
        shared by the classic joined-blob and pointer-table staging
        lanes."""
        import jax

        # retained until the packed result lands: the host fallback re-runs
        # the pipeline on the CPU backend over exactly these rows
        launch._staged_np = staged
        t0 = time.perf_counter()
        if not self._breaker.allow_device():
            launch._packed_dev = launch._payload_host_fallback()
            self._stat_stage("t_dispatch", t0)
            return

        def leg():
            faults.inject(faults.DEVICE_DISPATCH)
            dev = jax.device_put(staged)
            packed = fn(dev)
            packed.copy_to_host_async()
            return packed

        packed = self._try_device_leg(faults.DEVICE_DISPATCH, leg)
        if packed is None:
            launch._packed_dev = launch._payload_host_fallback()
            self._stat_stage("t_dispatch", t0)
            return
        # dispatch success IS the dispatch-domain verdict (the device
        # accepted the program); whether the RESULT comes back alive is
        # the harvest domain's verdict, recorded at fetch time
        self._breaker.record_success()
        self._stat_stage("t_dispatch", t0)
        self._stat_add("bytes_h2d", staged.nbytes)
        self._stat_add("bytes_d2h", n_pad * (r_out + 8))
        launch._packed_dev = packed

    def _dispatch_predicate(
        self, launch: _Launch, plan: ColumnarPlan, cols, n: int, n_pad: int,
        entry=None, dev_cols=None,
    ) -> None:
        """The columnar predicate leg over extracted columns — backend
        pick (measured probe), breaker gate, device dispatch or numpy
        eval, harvester enqueue. ONE copy shared by the staged, fused and
        cache-hit dispatch paths. ``entry``: a column-cache entry under
        construction — the device leg records its device-put arrays into
        it so later hits launch with zero H2D. ``dev_cols``: already
        device-resident arrays from a cache hit (no H2D accounting).
        ``cols`` are always the HOST arrays (probe + exact fallback)."""
        if not plan.dev_cols:
            return
        use_host = self._force_mode == "columnar_host"
        backend = TpuEngine.sticky_columnar_backend()
        if self._force_mode is None and self._mesh is None:
            if backend is None:
                if n_pad >= _PROBE_MIN_ROWS:
                    # double-checked under the probe RUN lock:
                    # concurrent first launches must not each pay the
                    # device probe (or tear the backend/probe-record
                    # pair) — the loser waits here and adopts the
                    # winner's pick. Readers never take this lock.
                    with TpuEngine._columnar_probe_run_lock:
                        if TpuEngine.sticky_columnar_backend() is None:
                            self._probe_columnar_backend(plan, cols)
                    backend = TpuEngine.sticky_columnar_backend()
                    use_host = backend == "host"
                else:
                    # too small to be representative of steady state:
                    # don't pin the process-wide choice on a trickle
                    # batch — numpy is the cheap safe pick at this size
                    use_host = True
            else:
                use_host = backend == "host"
        if backend is not None:
            # this engine runs the sticky process-wide pick (probed by
            # us just above, or inherited): posture only — the probe
            # that made the decision already journaled it
            self.governor.note_posture(
                governor.COLUMNAR_BACKEND, backend
            )
        breaker_demoted = False
        if not use_host and not self._breaker.allow_device():
            # open breaker: the whole launch stays on the exact numpy
            # predicate over the same columns — identical bits, no
            # device touch until the half-open probe re-admits it
            use_host = breaker_demoted = True
        t0 = time.perf_counter()
        if use_host:
            # measured-host predicate: SAME extracted columns, numpy —
            # what the probe (or the bench ablation) picked on this link
            launch._mask_np = plan.eval_host_mask(cols)
            self._stat_stage("t_dispatch", t0)
            if breaker_demoted:
                self._count_fallback(n)
        else:
            def leg():
                faults.inject(faults.DEVICE_DISPATCH)
                fn = plan.compile_device(self._mesh)
                args = dev_cols
                if args is None:
                    if entry is not None:
                        # explicit device_put so the cache entry owns
                        # committed device arrays: later hits pass them
                        # straight back to the jitted predicate and no
                        # byte re-crosses the link
                        import jax

                        args = [jax.device_put(c) for c in cols]
                        entry.cols_dev = args
                    else:
                        args = cols
                mask = fn(*args)
                mask.copy_to_host_async()
                return mask

            mask = self._try_device_leg(faults.DEVICE_DISPATCH, leg)
            if mask is None:
                launch._mask_np = plan.eval_host_mask(cols)
                self._stat_stage("t_dispatch", t0)
                self._count_fallback(n)
            else:
                self._breaker.record_success()  # dispatch-domain verdict
                self._stat_stage("t_dispatch", t0)
                if dev_cols is None:
                    self._stat_add("bytes_h2d", sum(c.nbytes for c in cols))
                self._stat_add("bytes_d2h", n_pad // 8)
                launch._mask_dev = mask
                launch._cols = cols
                launch._mask_event = threading.Event()
                launch._mask_state = "queued"
                self._ensure_harvester()
                launch._enq_t = time.perf_counter()
                self._harvest_q.put(launch)

    def _dispatch_columnar(
        self, launch: _Launch, plan: ColumnarPlan, exploded, n: int,
        cache=None, store_key=None,
    ) -> None:
        launch.r_out = plan.r_out
        if n == 0:
            launch._proj_ok = np.zeros(0, bool)
            return
        if cache is None:
            # split path (fused explode_find unavailable): ONE JSON walk
            # per record locates every referenced top-level field
            # (rp_find_multi); extraction gathers from the span tables
            t0 = time.perf_counter()
            cache = plan.build_find_cache(
                exploded.joined, exploded.offsets, exploded.sizes
            )
            self._stat_stage("t_find", t0)
        entry = None
        cols = None
        n_pad = _bucket_rows(n)
        if plan.dev_cols:
            t0 = time.perf_counter()
            cols = plan.extract_device_inputs(
                exploded.joined, exploded.offsets, exploded.sizes, n_pad, cache
            )
            self._stat_stage("t_extract_pred", t0)
            if store_key is not None and self._colcache is not None:
                entry = colcache.Entry(
                    n=n, n_pad=n_pad, ranges=launch.ranges, cols=cols,
                    exploded=exploded if plan.passthrough else None,
                    parse_mode="staged",
                )
            self._dispatch_predicate(launch, plan, cols, n, n_pad, entry=entry)
        # Projection extraction overlaps the device launch.
        t0 = time.perf_counter()
        if plan.passthrough:
            launch._proj_ok = np.ones(n, bool)
            launch._exploded = exploded
        else:
            data, ok = plan.extract_projection(
                exploded.joined, exploded.offsets, exploded.sizes, cache
            )
            launch._proj_data = data
            launch._proj_ok = ok
            if entry is not None:
                entry.proj_data = data
                entry.proj_ok = ok
                entry.nbytes = entry._measure()
        self._stat_stage("t_extract_proj", t0)
        if entry is not None:
            self._colcache.put(store_key, entry)

    def _dispatch_columnar_fused(
        self, launch: _Launch, plan: ColumnarPlan, sp, store_key=None
    ) -> None:
        """Structural fused lane: ONE record-major extraction crossing off
        the span tables the structural parse produced — predicate columns
        and packed projection rows together; the separate
        t_extract_pred/t_extract_proj passes don't exist on this path."""
        n = sp.n
        launch.r_out = plan.r_out
        if n == 0:
            launch._proj_ok = np.zeros(0, bool)
            return
        t0 = time.perf_counter()
        n_pad = _bucket_rows(n)
        cols, proj_data, proj_ok = plan.extract_fused(sp, n_pad)
        self._stat_stage("t_fused_extract", t0)
        ex = sp.exploded() if plan.passthrough else None
        if plan.passthrough:
            launch._proj_ok = np.ones(n, bool)
            launch._exploded = ex
        else:
            launch._proj_data = proj_data
            launch._proj_ok = proj_ok
        entry = None
        if store_key is not None and self._colcache is not None:
            entry = colcache.Entry(
                n=n, n_pad=n_pad, ranges=launch.ranges, cols=cols,
                proj_data=proj_data, proj_ok=launch._proj_ok, exploded=ex,
                parse_mode="structural",
            )
        self._dispatch_predicate(launch, plan, cols, n, n_pad, entry=entry)
        if entry is not None:
            self._colcache.put(store_key, entry)

    def _dispatch_columnar_cached(
        self, launch: _Launch, plan: ColumnarPlan, entry
    ) -> None:
        """Column-cache hit: every host dispatch stage (decompress, parse,
        find, extract) is skipped, and a device-backed predicate launches
        over the cached DEVICE-RESIDENT columns — zero H2D. Output is
        bit-identical to a cold run because the predicate and projection
        consume the exact arrays the cold launch produced (entries are
        read-only after put)."""
        n = entry.n
        launch.ranges = list(entry.ranges)
        launch.n = n
        launch.r_out = plan.r_out
        self._stat_add("n_records", n)
        self._stat_add("n_launches", 1)
        with self._stats_lock:
            probes.coproc_launch_rows_hist.record(n)
        if n == 0:
            launch._proj_ok = np.zeros(0, bool)
            return
        if plan.passthrough:
            launch._proj_ok = np.ones(n, bool)
            launch._exploded = entry.exploded
        else:
            launch._proj_data = entry.proj_data
            launch._proj_ok = entry.proj_ok
        self._dispatch_predicate(
            launch, plan, entry.cols, n, entry.n_pad,
            dev_cols=entry.cols_dev,
        )

    def _probe_columnar_backend(self, plan, cols) -> None:
        """One-time process-wide probe: run the SAME predicate over the SAME
        columns on the device (compile + fetch warmup, then a timed
        launch+fetch) and in numpy; keep the faster. The device leg runs on
        the shared abandonable fetch pool (coproc/faults.py) with a deadline
        because a wedged device link HANGS inside the fetch rather than
        raising — on timeout (or no device / compile error) the probe falls
        back to host. A wedged worker is abandoned; one that merely finishes
        LATE discards its stale timing and rejoins the pool, so repeated
        probes cannot grow threads."""
        import time as _t

        t0 = _t.perf_counter()
        plan.eval_host_mask(cols)
        t_host = _t.perf_counter() - t0

        def _device_leg() -> float:
            fn = plan.compile_device(None)
            np.asarray(fn(*cols))  # compile + first-launch warmup
            t1 = _t.perf_counter()
            np.asarray(fn(*cols))  # steady-state launch + fetch
            return _t.perf_counter() - t1

        try:
            t_dev = faults.fetch_with_deadline(
                _device_leg, _PROBE_DEVICE_TIMEOUT_S
            )
        except Exception as exc:
            # wedged (deadline) / no device / compile error: host wins the
            # probe, and the reason lands in coproc_failures_total
            faults.note_failure("columnar_probe", exc)
            t_dev = float("inf")
        chosen = "device" if t_dev * _PROBE_DEVICE_MARGIN < t_host else "host"
        # the two-field publish is the only region under the SHORT field
        # lock — readers (stats, dispatch snapshots) contend with a dict
        # assignment, never with the 120s probe envelope above
        with TpuEngine._columnar_probe_lock:
            TpuEngine._columnar_backend = chosen
            TpuEngine._columnar_probe = {
                "t_host_s": round(t_host, 6),
                "t_device_s": round(t_dev, 6) if t_dev != float("inf") else None,
                "margin": _PROBE_DEVICE_MARGIN,
                "chosen": chosen,
            }
        self.governor.record(
            governor.COLUMNAR_BACKEND,
            chosen,
            "measured predicate leg: host "
            f"{t_host * 1e3:.3f} ms vs device "
            + ("unavailable" if t_dev == float("inf")
               else f"{t_dev * 1e3:.3f} ms")
            + f" (device must win {_PROBE_DEVICE_MARGIN}x; process-sticky)",
            dict(TpuEngine._columnar_probe),
        )

    def _pack_staged(self, exploded, n_pad: int) -> np.ndarray:
        """[n_pad, row_stride + IN_META] uint8: record bytes then LE32 length.

        Records wider than the staging row cannot be transformed faithfully:
        their length is staged as 0 here and their keep bit is cleared after
        the launch via ``launch.fits`` (the reference bounds record size
        upstream via coproc_max_batch_size; truncating would corrupt data
        silently).
        """
        r = self._row_stride
        stride = r + IN_META
        n = len(exploded.sizes)
        offsets = exploded.offsets
        sizes = exploded.sizes
        if n_pad != n:
            offsets = np.concatenate([offsets, np.zeros(n_pad - n, np.int64)])
            sizes = np.concatenate([sizes, np.zeros(n_pad - n, np.int32)])
        fits = sizes <= r
        lens = np.where(fits, sizes, 0).astype("<i4")
        try:
            from redpanda_tpu.native import lib
        except Exception:
            lib = None
        if lib is not None:
            staged, _ = lib.pack_rows(exploded.joined, offsets, sizes, stride)
        else:
            from redpanda_tpu.ops.packing import pack_rows

            vals = [
                exploded.joined[o : o + s] for o, s in zip(offsets, np.minimum(sizes, r))
            ]
            staged, _ = pack_rows(vals, stride)
        staged[:, r : r + 4] = lens.view(np.uint8).reshape(n_pad, 4)
        staged[:, r + 4 :] = 0
        return staged

    def _pack_staged_ptrs(self, pe, n_pad: int) -> np.ndarray:
        """_pack_staged's pointer-table twin: the staging matrix fills
        straight from each batch's retained decompressed payload buffer
        (batch_codec.PtrExploded) — no joined blob is ever built or
        re-read. Byte-identical output to _pack_staged over the merged
        exploded table (the staging parity test pins it)."""
        from redpanda_tpu.native import lib

        r = self._row_stride
        stride = r + IN_META
        n = len(pe.sizes)
        staged = np.empty((n_pad, stride), dtype=np.uint8)
        row = 0
        for payload, off, ln in zip(pe.payloads, pe.rel_off, pe.rel_len):
            k = len(ln)
            if k:
                # rp_pack_rows clamps sizes to the stride and zero-fills
                # each row's tail, so per-batch packing into row slices is
                # byte-identical to one whole-launch pack
                lib.pack_rows_into(payload, off, ln, staged[row : row + k])
            row += k
        if n_pad > n:
            staged[n:] = 0
        fits = pe.sizes <= r
        lens = np.where(fits, pe.sizes, 0).astype("<i4")
        if n_pad > n:
            lens = np.concatenate([lens, np.zeros(n_pad - n, "<i4")])
        staged[:, r : r + 4] = lens.view(np.uint8).reshape(n_pad, 4)
        staged[:, r + 4 :] = 0
        return staged
