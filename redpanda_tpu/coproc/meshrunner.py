"""meshrunner: the multi-chip sharded coproc engine (BASELINE config 5).

The reference scales by spreading partitions over cores and nodes
(shard-per-core SMP + the cluster partition allocator — SURVEY §2.3); the
TPU-native analogue maps the ``[partition, batch, record]`` axis onto a
1-D device mesh (parallel/mesh.py) and runs ONE SPMD predicate program
per launch instead of one program per chip. MULTICHIP_r01–r05 dry-ran
that shape end to end; this module promotes it into the product path:

- a launch's batches partition into **per-device sub-launches** with the
  same contiguous range-shard machinery the host pool uses
  (``host_pool.partition_counts``), so the concatenated outputs are
  byte-identical to the single-device path by construction;
- the predicate pipeline is compiled ONCE under the mesh
  (``ColumnarPlan.compile_device_stacked``: shard_map over the 'p' axis,
  per-device blocks of stacked ``[D, n_pad, ...]`` columns);
- the config-5 stretch rides the same mesh: raft batched-CRC validation
  vmapped over the sharded record axis plus the vote-tally psum
  (``parallel.collectives.make_crc_vote_step``), consumed by
  ``raft/device_plane.py`` behind its own measured probe.

Mesh-vs-single-device is a MEASURED, journaled governor decision (domain
``mesh``, ``host_pool.PROBE_MARGIN`` posture: the mesh must show a real
win over the known single-device path before it pins). The
``mesh_dispatch`` fault domain gives the mesh its own circuit breaker —
a flaky mesh path demotes mesh launches to the bit-identical
single-device path while plain dispatch keeps its own breaker.
Observability: ``TpuEngine.stats()["mesh"]``, per-device
``coproc_mesh_device_rows_total`` counters, ``/v1/coproc/status`` and
``rpk debug coproc``.
"""

from __future__ import annotations

import logging
import threading
import time

import numpy as np

from redpanda_tpu.coproc import host_pool, lockwatch
from redpanda_tpu.coproc.governor import MESH
from redpanda_tpu.observability import probes

logger = logging.getLogger("rptpu.coproc.meshrunner")

# don't pin the engine-sticky mesh-vs-single decision on a launch too
# small to represent steady state (same floor as the columnar backend
# probe's _PROBE_MIN_ROWS posture)
PROBE_MIN_ROWS = 1024


def available_devices(backend: str | None = None) -> list:
    """Devices a mesh could span. ``backend='cpu'`` asks the CPU backend
    explicitly — under the axon plugin ``jax.devices()`` shows only the
    TPU even when a virtual CPU mesh was requested (see tests/conftest)."""
    import jax

    try:
        return jax.local_devices(backend=backend) if backend else jax.devices()
    except Exception as exc:
        # a missing backend means "no mesh possible", not a fault in the
        # engine — classified so the demotion shows on /metrics
        from redpanda_tpu.coproc import faults

        faults.note_failure("mesh_init", exc)
        return []


class MeshRunner:
    """Owns the partition-axis mesh and the mesh-vs-single decision.

    The engine keeps the launch machinery (ladders, column cache, host
    pool, fault envelopes); this class keeps everything mesh-shaped: the
    device list, the per-plan stacked predicate programs, the measured
    calibration, and the per-device accounting behind ``stats()``.
    """

    def __init__(
        self,
        n_devices: int | None = None,
        backend: str | None = None,
        devices=None,
        probe: bool = True,
    ):
        from redpanda_tpu.parallel.mesh import partition_mesh

        if devices is None:
            devices = available_devices(backend)
            if n_devices is not None:
                devices = devices[: int(n_devices)]
        if len(devices) < 2:
            raise ValueError(
                f"meshrunner needs >= 2 devices, have {len(devices)} "
                f"(backend={backend!r})"
            )
        self.mesh = partition_mesh(devices=devices)
        self.n_devices = len(devices)
        self._probe_enabled = bool(probe)
        # two-lock discipline (the columnar-backend / parse-path shape):
        # the RUN lock serializes calibration EXECUTION; the short
        # decision lock guards the fields so stats() readers never wait
        # behind a calibration's timed passes
        self._decision: str | None = None if probe else "mesh"
        self._probe: dict | None = None
        self._decision_lock = lockwatch.wrap(
            threading.Lock(), "MeshRunner._decision_lock"
        )
        self._probe_run_lock = lockwatch.wrap(
            threading.Lock(), "MeshRunner._probe_run_lock"
        )
        # accounting (guarded by the decision lock; per-launch cadence)
        self._n_launches = 0
        self._n_demotions = 0
        self._rows_per_device = [0] * self.n_devices

    # ------------------------------------------------------------ decision
    @property
    def decision(self) -> str | None:
        with self._decision_lock:
            return self._decision

    @property
    def probe_enabled(self) -> bool:
        return self._probe_enabled

    @property
    def probe_lock_busy(self) -> bool:
        """True while a calibration is executing — the engine checks
        this BEFORE paying the mesh per-shard ladder, since an undecided
        launch that loses the probe race runs single-device anyway."""
        return self._probe_run_lock.locked()

    def shard_ranges(self, counts: list[int]) -> list[tuple[int, int]]:
        """Per-device contiguous batch slices — the host pool's balanced
        range shard, one shard per mesh device (may return fewer when
        there are fewer batches than devices; the stack pads with empty
        shards)."""
        return host_pool.partition_counts(counts, self.n_devices)

    def predicate_fn(self, plan):
        return plan.compile_device_stacked(self.mesh)

    def stack_and_put(self, stacked: list[np.ndarray]):
        """device_put each [D, ...] stack with its partition sharding."""
        from redpanda_tpu.parallel.mesh import shard_to_mesh

        out = shard_to_mesh(self.mesh, *stacked)
        return out if isinstance(out, tuple) else (out,)

    # ------------------------------------------------------------ accounting
    def note_launch(self, shard_rows: list[int]) -> None:
        with self._decision_lock:
            self._n_launches += 1
            for d, n in enumerate(shard_rows):
                self._rows_per_device[d] += int(n)
        probes.coproc_mesh_launches.inc()
        for d, n in enumerate(shard_rows):
            if n:
                probes.coproc_mesh_device_rows(d).inc(n)

    def note_demotion(self) -> None:
        with self._decision_lock:
            self._n_demotions += 1
        probes.coproc_mesh_demotions.inc()

    # ------------------------------------------------------------ calibration
    def maybe_calibrate(self, governor, plan, stacked: list[np.ndarray],
                        flat: list[np.ndarray], n_rows: int) -> str:
        """The engine-sticky mesh-vs-single pin, measured on the FIRST
        representative launch's own columns: the SAME predicate over the
        SAME bytes, once as the stacked SPMD program over the mesh and
        once as the single-device program over the concatenated columns.
        The mesh must win by ``host_pool.PROBE_MARGIN`` — on co-located
        multi-chip ICI it does by construction, on a 1-core host-platform
        mesh it honestly self-demotes. Returns the decision."""
        with self._decision_lock:
            decision = self._decision
        if decision is not None:
            return decision
        if n_rows < PROBE_MIN_ROWS:
            # too small to be representative: run single WITHOUT pinning
            return "single"
        if not self._probe_run_lock.acquire(blocking=False):
            # a sibling launch is mid-calibration (seconds of jit): run
            # THIS launch single-device — bit-identical output — instead
            # of queueing behind the probe
            return "single"
        try:
            with self._decision_lock:
                decision = self._decision
            if decision is None:
                decision = self._calibrate(governor, plan, stacked, flat)
        finally:
            self._probe_run_lock.release()
        return decision

    def _calibrate(self, governor, plan, stacked, flat) -> str:
        from redpanda_tpu.coproc import faults

        try:
            t_mesh = t_single = float("inf")
            mesh_fn = self.predicate_fn(plan)
            args = self.stack_and_put(stacked)
            np.asarray(mesh_fn(*args))  # compile + warmup off the clock
            single_fn = plan.compile_device(None)
            np.asarray(single_fn(*flat))
            for _ in range(2):
                t0 = time.perf_counter()
                np.asarray(mesh_fn(*args))
                t_mesh = min(t_mesh, time.perf_counter() - t0)
                t0 = time.perf_counter()
                np.asarray(single_fn(*flat))
                t_single = min(t_single, time.perf_counter() - t0)
                # the single-device path's OTHER backend: on boxes where
                # the measured columnar pick is the numpy predicate, the
                # mesh must beat THAT, not a device leg nothing would run
                t0 = time.perf_counter()
                plan.eval_host_mask(flat)
                t_single = min(t_single, time.perf_counter() - t0)
        except Exception as exc:
            # a mesh whose probe blows up runs single-device forever —
            # classified so the demotion is visible on /metrics
            faults.note_failure("mesh_calibration", exc)
            logger.exception("mesh calibration failed; keeping single-device")
            with self._decision_lock:
                self._decision = "single"
            governor.record(
                MESH,
                "single",
                f"calibration FAILED ({faults.kind_of(exc)}); keeping the "
                "single-device path",
                {"error": faults.kind_of(exc), "devices": self.n_devices},
            )
            return "single"
        ratio = t_single / t_mesh if t_mesh > 0 else 0.0
        decision = "mesh" if ratio >= host_pool.PROBE_MARGIN else "single"
        probe = {
            "t_single_ms": round(t_single * 1e3, 3),
            "t_mesh_ms": round(t_mesh * 1e3, 3),
            "speedup": round(ratio, 3),
            "devices": self.n_devices,
            "chosen": decision,
        }
        with self._decision_lock:
            self._decision = decision
            self._probe = probe
        logger.info("mesh calibration: %s", probe)
        governor.record(
            MESH,
            decision,
            f"measured predicate leg: single-device {t_single * 1e3:.3f} ms"
            f" vs {self.n_devices}-device mesh {t_mesh * 1e3:.3f} ms (mesh "
            f"must win {host_pool.PROBE_MARGIN}x; engine-sticky)",
            dict(probe),
        )
        return decision

    # ------------------------------------------------------------ views
    def stats(self) -> dict:
        with self._decision_lock:
            out = {
                "devices": self.n_devices,
                "decision": self._decision,
                "launches": self._n_launches,
                "demotions": self._n_demotions,
                "rows_per_device": list(self._rows_per_device),
            }
            if self._probe is not None:
                out["probe"] = dict(self._probe)
        return out
