"""Device-resident column cache: exploded columns that survive launches.

A repeat script over the same partitions (bench re-runs, replayed reads,
any workload that re-submits an unchanged batch window) used to pay the
whole host ladder again — decompress, parse, find, extract — plus the H2D
replay of the very same predicate columns. The cache keys one launch's
columnar products by ``(script_id, content fingerprint of the batch
list)`` and hands them back whole: a hit skips every host dispatch stage,
and when the predicate ran on-device the stored ``cols_dev`` arrays are
already device-resident, so not a byte re-crosses the link.

Staleness is impossible by key construction, not by discipline: the
fingerprint covers each batch's payload CRC, base offset, record count,
payload length and compression, so an append, rewrite or reorder produces
a different key and a clean miss. The explicit invalidation hooks exist
for MEMORY, not correctness — the pacemaker drops a script's entries when
its input offsets advance (streaming never re-reads, so the bytes are
dead weight), and script unload drops them with the script.

Eviction is LRU under a byte budget (``coproc_device_column_cache_mb``;
0 disables the cache). ``stats()`` feeds ``TpuEngine.stats()["colcache"]``
→ ``/v1/coproc/status`` / ``rpk debug coproc`` / every BENCH json.
"""

from __future__ import annotations

import struct
import threading
from collections import OrderedDict

from redpanda_tpu.hashing.xx import xxhash64

def fingerprint(batches) -> int:
    """Content fingerprint of a batch list. The per-batch tuple (payload
    CRC, base offset, record count, payload length, attrs) pins both the
    bytes and their order; any append or rewrite changes it."""
    buf = bytearray()
    pack = struct.pack
    for b in batches:
        hdr = b.header
        buf += pack(
            "<qIiiI",
            hdr.base_offset,
            hdr.crc & 0xFFFFFFFF,
            hdr.record_count,
            len(b.payload),
            hdr.attrs & 0xFFFFFFFF,
        )
    return xxhash64(buf)


class Entry:
    """One launch's cached columnar products.

    ``cols`` are the HOST predicate column arrays (always present — the
    exact-fallback path and the backend probe need host arrays);
    ``cols_dev`` the device-put twins, recorded by the first device
    dispatch so later hits launch without an H2D. ``exploded`` is kept
    only for passthrough plans (their harvest gathers output bytes from
    the joined blob); projection plans store the packed rows + ok mask
    instead. Entries are immutable after ``put`` — every consumer is
    read-only, which is what makes a hit bit-identical to a cold run.
    """

    __slots__ = (
        "n", "n_pad", "ranges", "cols", "cols_dev", "proj_data", "proj_ok",
        "exploded", "parse_mode", "nbytes",
    )

    def __init__(self, *, n, n_pad, ranges, cols, proj_data=None,
                 proj_ok=None, exploded=None, parse_mode="staged"):
        self.n = n
        self.n_pad = n_pad
        self.ranges = list(ranges)
        self.cols = cols
        self.cols_dev = None
        self.proj_data = proj_data
        self.proj_ok = proj_ok
        self.exploded = exploded
        self.parse_mode = parse_mode
        self.nbytes = self._measure()

    def _measure(self) -> int:
        total = 0
        for c in self.cols or ():
            total += getattr(c, "nbytes", 0)
        if self.proj_ok is not None:
            total += self.proj_ok.nbytes
        for item in self.proj_data or ():
            for part in item[1:]:
                total += getattr(part, "nbytes", 0)
        if self.exploded is not None:
            j = self.exploded.joined
            total += getattr(j, "nbytes", len(j))
            total += self.exploded.offsets.nbytes + self.exploded.sizes.nbytes
        return total


class DeviceColumnCache:
    """Keyed LRU over Entry objects with a byte budget."""

    def __init__(self, budget_bytes: int):
        from redpanda_tpu.coproc import lockwatch

        self._lock = lockwatch.wrap(
            threading.Lock(), "DeviceColumnCache._lock"
        )
        self._budget = max(0, int(budget_bytes))
        self._entries: "OrderedDict[tuple, Entry]" = OrderedDict()
        self._bytes = 0
        self._hits = 0
        self._misses = 0
        self._evictions = 0
        self._invalidations = 0
        # memory pressure (resource_mgmt budget plane): while CRITICAL the
        # effective budget halves — LRU entries beyond it evict immediately
        # and stay out until the pressure clears
        self._pressure = False
        self._pressure_evictions = 0

    def _effective_budget(self) -> int:
        return self._budget // 2 if self._pressure else self._budget

    def set_pressure(self, critical: bool) -> int:
        """Enter/leave the reduced-budget posture. Entering evicts LRU
        entries beyond the halved budget and counts them as pressure
        evictions; leaving restores the configured budget (repopulation
        happens naturally on later misses). Idempotent per level."""
        evicted = 0
        with self._lock:
            self._pressure = bool(critical)
            budget = self._effective_budget()
            while self._bytes > budget and self._entries:
                _, entry = self._entries.popitem(last=False)
                self._bytes -= entry.nbytes
                self._evictions += 1
                self._pressure_evictions += 1
                evicted += 1
        return evicted

    def lookup(self, key: tuple) -> Entry | None:
        """The cached entry (refreshing LRU order) or None. Misses carry
        no side state: since the sharded path populates per shard, every
        miss — inline or sharded — populates on the SAME launch, so
        nothing needs to recognize a repeating workload anymore."""
        with self._lock:
            entry = self._entries.get(key)
            if entry is not None:
                self._entries.move_to_end(key)
                self._hits += 1
                return entry
            self._misses += 1
            return None

    def put(self, key: tuple, entry: Entry) -> bool:
        """Insert + evict LRU down to the budget. An entry bigger than
        the whole budget is refused outright (storing it would evict
        everything for a guaranteed-evicted tenant)."""
        with self._lock:
            budget = self._effective_budget()
            if entry.nbytes > budget:
                return False
            old = self._entries.pop(key, None)
            if old is not None:
                self._bytes -= old.nbytes
            self._entries[key] = entry
            self._bytes += entry.nbytes
            while self._bytes > budget and len(self._entries) > 1:
                _, evicted = self._entries.popitem(last=False)
                self._bytes -= evicted.nbytes
                self._evictions += 1
            if self._bytes > budget:
                # the just-inserted entry is the only one and still over
                # budget (budget shrank below it): drop it too
                self._entries.popitem(last=False)
                self._bytes -= entry.nbytes
                self._evictions += 1
                return False
        return True

    def invalidate(self, script_id: int | None = None) -> int:
        """Drop entries (all scripts when script_id is None). Returns the
        number dropped. Correctness never depends on this — the key is
        content-addressed — it reclaims memory for inputs that moved on."""
        with self._lock:
            if script_id is None:
                dropped = len(self._entries)
                self._entries.clear()
                self._bytes = 0
            else:
                keys = [k for k in self._entries if k[0] == script_id]
                for k in keys:
                    self._bytes -= self._entries.pop(k).nbytes
                dropped = len(keys)
            self._invalidations += dropped
        return dropped

    def reset(self) -> None:
        """Test hook: drop entries AND zero the counters."""
        with self._lock:
            self._entries.clear()
            self._bytes = 0
            self._hits = self._misses = 0
            self._evictions = self._invalidations = 0
            self._pressure = False
            self._pressure_evictions = 0

    def stats(self) -> dict:
        with self._lock:
            return {
                "hits": self._hits,
                "misses": self._misses,
                "entries": len(self._entries),
                "bytes": self._bytes,
                "budget_bytes": self._budget,
                "effective_budget_bytes": self._effective_budget(),
                "evictions": self._evictions,
                "invalidations": self._invalidations,
                "pressure": self._pressure,
                "pressure_evictions": self._pressure_evictions,
            }
