from redpanda_tpu.coproc.engine import (
    TpuEngine,
    ProcessBatchRequest,
    ProcessBatchReply,
    EnableResponseCode,
    DisableResponseCode,
    ErrorPolicy,
)

__all__ = [
    "TpuEngine",
    "ProcessBatchRequest",
    "ProcessBatchReply",
    "EnableResponseCode",
    "DisableResponseCode",
    "ErrorPolicy",
]
