"""The coproc governor: one decision plane for every adaptive choice.

The engine carries a family of measured probes — host-pool calibration (+
periodic recal), the columnar device-vs-host backend probe, the device_lz4
keep-or-kill probe, the circuit breakers, the harvest framing path and the
sharded-seal engagement — and before this module each made its call in its
own corner: a self-demoted pool or a tripped breaker could silently halve
the headline rb/s with no forensic trail beyond scattered stats keys. The
governor routes every such decision through ONE policy surface:

- **Decision journal** — a bounded in-memory ring of every adaptive
  decision made in this process: monotonic ``seq``, wall-clock ``ts``,
  ``domain``, the measured ``inputs`` that drove it, the ``verdict``, a
  human-readable ``reason`` and the active-config snapshot at decision
  time. ``GET /v1/governor`` / ``rpk debug governor`` render it; a bench
  run is reconstructible from the journal alone.
- **Metrics** — ``coproc_governor_decisions_total{domain,verdict}``
  counters, per-domain posture gauges (``coproc_governor_state{domain=}``)
  and per-domain breaker gauges (``coproc_breaker_state{domain=}`` — the
  labeled replacement for the old weakref-to-latest-engine hack).
- **Per-domain breakers** — the single per-engine breaker is split into
  one per device fault domain (dispatch / mask_fetch / harvest), so a
  flaky D2H mask-fetch path demotes fetches to the exact claim/fallback
  path while dispatch stays on-device.
- **Adaptive deadlines** — per-domain per-attempt deadlines derived from
  the observed ``coproc_stage_latency_us`` p99.9 of the domain's stage:
  ``deadline = clamp(margin * p99.9, floor, cap_x * floor)`` where the
  static ``coproc_device_deadline_ms`` is the FLOOR and the fallback below
  ``min_samples`` — the adaptive path may only ever RAISE a deadline (a
  link whose healthy tail outgrew the knob stops getting spurious
  abandon+retry cycles); it can never tighten below what the operator
  configured.

The journal and its counters are process-wide (like the metrics registry):
process-scoped decisions (the columnar backend, device_lz4) have no single
owning engine, and the operator's question — "what did this broker decide
and why" — is a process question. Governor instances are per-engine and
own the per-engine state: breakers, deadline derivation, posture.
"""

from __future__ import annotations

import collections
import dataclasses
import itertools
import logging
import threading
import time
import weakref

from redpanda_tpu.coproc import faults
from redpanda_tpu.metrics import Counter, registry
from redpanda_tpu.observability import probes

logger = logging.getLogger("rptpu.coproc.governor")

# ------------------------------------------------------------ decision domains
HOST_POOL = "host_pool"
COLUMNAR_BACKEND = "columnar_backend"
DEVICE_LZ4 = "device_lz4"
BREAKER = "breaker"
HARVEST_PATH = "harvest_path"
SHARDED_SEAL = "sharded_seal"
DEADLINE = "deadline"
# structural-index parse: the engine's measured fused-vs-staged probe
# (staged = scalar rp_explode_find ladder + per-column gathers; structural
# = rp_explode_find2 + one fused extraction crossing) journals its pick
# here — slower boxes self-demote honestly, same posture as host_pool
PARSE_PATH = "parse_path"
# device-resident column cache (coproc/colcache.py): budget/eviction
# pressure notes land here when the cache has to shed entries
COLUMN_CACHE = "column_cache"
# bench.py regression-diagnosis verdicts (A/A-bracketed config reruns)
DIAGNOSIS = "diagnosis"
# coproc_lockwatch: each newly observed runtime lock-order edge journals
# here (coproc/lockwatch.py) — the dynamic validation trail of the
# pandaraces static acquisition graph
LOCKWATCH = "lockwatch"
# coproc_leakwatch: first-seen acquire sites and any balance imbalance
# journal here (coproc/leakwatch.py) — the dynamic validation trail of
# the pandaleak static resource-lifecycle model
LEAKWATCH = "leakwatch"
# multi-chip sharded engine (coproc/meshrunner.py): the measured
# mesh-vs-single-device decision, the raft device-plane CRC/vote probe,
# and mesh breaker demotions all journal here (PROBE_MARGIN posture —
# the mesh must show a real win over the known single-device path)
MESH = "mesh"
# admission / backpressure (resource_mgmt budget plane): shed episodes,
# memory-pressure transitions acted on by the engine, and the dynamic
# group_ticks_per_launch / launch_depth autotune verdicts all journal
# here — the overload gate reconstructs every shed/resize from this domain
ADMISSION = "admission"
# pandatrend (observability/history.py): EWMA-band breaches over the
# metrics-history ring — tail latency, shed rate, occupancy, colcache hit
# rate leaving their measured band journal here, plus the sandbox
# watchdog's wall-clock kills (a runaway deployed transform is a trend
# incident: the containment model itself fired)
TREND = "trend"

DOMAINS = (
    HOST_POOL, COLUMNAR_BACKEND, DEVICE_LZ4, BREAKER, HARVEST_PATH,
    SHARDED_SEAL, DEADLINE, PARSE_PATH, COLUMN_CACHE, DIAGNOSIS, LOCKWATCH,
    LEAKWATCH, MESH, ADMISSION, TREND,
)

# fault domains that get their own breaker + adaptive deadline. Each
# deadline derives from the domain's SUCCESS-ONLY device-leg histogram
# (coproc_device_leg_latency_us{domain=}, fed by Governor.observe_leg at
# every successful leg completion) — NOT from the fetch-stage
# coproc_stage_latency_us histogram: the stage clock keeps running
# through abandoned attempts and envelope waits, so a burst of timeouts
# used to inflate the very tail the next deadline was derived from (the
# 8x cap bounded that feedback; the success-only source removes it).
BREAKER_DOMAINS = (
    faults.DEVICE_DISPATCH, faults.MASK_FETCH, faults.HARVEST,
    faults.MESH_DISPATCH,
)

# Adaptive-deadline shape: derived = clamp(margin * p99.9, floor, cap_x *
# floor). The cap bounds every waiter sized off envelope_s() (the tick
# backstop, _resolve_keep's harvester wait) — without it one wedged fetch
# recorded into the stage histogram could balloon the next deadline toward
# its own wedge duration.
DEADLINE_RECOMPUTE_SAMPLES = 64  # recompute p99.9 after this many new obs
_DEADLINE_JOURNAL_DELTA = 0.2    # journal a change only when >= 20%

# Launch-knob autotune (ADMISSION domain): how often a verdict may CHANGE
# the knobs (hysteresis hold window — a flapping input cannot flap the
# knobs faster than this), and where the success-only dispatch-leg p99.9
# sits relative to the static deadline floor before we grow (cheap legs:
# deepen batching toward the ~90%-utilization posture) or shrink (tail
# approaching the deadline: trade launch depth for latency).
AUTOTUNE_HOLD_S = 5.0
_AUTOTUNE_GROW_FRAC = 0.5
_AUTOTUNE_SHRINK_FRAC = 0.8

# posture verdict -> gauge value per domain (unknown/undecided = -1)
_STATE_ENCODING: dict[str, dict[str, float]] = {
    HOST_POOL: {"inline": 0.0, "sharded": 1.0},
    COLUMNAR_BACKEND: {"host": 0.0, "device": 1.0},
    DEVICE_LZ4: {"host": 0.0, "device": 1.0},
    HARVEST_PATH: {"padded": 0.0, "gather": 1.0},
    SHARDED_SEAL: {"inline": 0.0, "sharded": 1.0},
    PARSE_PATH: {"staged": 0.0, "structural": 1.0},
    MESH: {"single": 0.0, "mesh": 1.0},
}

_BREAKER_SEVERITY = {
    faults.STATE_CLOSED: 0,
    faults.STATE_HALF_OPEN: 1,
    faults.STATE_OPEN: 2,
}


# ------------------------------------------------------------ decision journal
class DecisionJournal:
    """Bounded ring of decision entries with a monotonic sequence.

    A standalone class (not bare module state) so the governor_overhead
    microbench can price appends on a throwaway instance without writing
    into the live process journal.
    """

    def __init__(self, capacity: int = 256) -> None:
        self._lock = threading.Lock()
        self._ring: collections.deque = collections.deque(
            maxlen=max(1, int(capacity))
        )
        self._seq = itertools.count(1)
        self._last_seq = 0

    @property
    def capacity(self) -> int:
        return self._ring.maxlen or 0

    def configure(self, capacity: int) -> None:
        capacity = max(1, int(capacity))
        with self._lock:
            if capacity != self._ring.maxlen:
                self._ring = collections.deque(self._ring, maxlen=capacity)

    def reset(self) -> None:
        with self._lock:
            self._ring.clear()
            self._seq = itertools.count(1)
            self._last_seq = 0

    def append(
        self,
        domain: str,
        verdict: str,
        reason: str,
        inputs: dict | None = None,
        config: dict | None = None,
        engine: str | None = None,
    ) -> dict:
        entry = {
            "seq": 0,  # assigned under the lock below
            "ts": time.time(),
            "domain": domain,
            "verdict": str(verdict),
            "reason": reason,
            "inputs": dict(inputs) if inputs else {},
            "config": dict(config) if config else {},
        }
        if engine is not None:
            entry["engine"] = engine
        with self._lock:
            entry["seq"] = self._last_seq = next(self._seq)
            self._ring.append(entry)
        return entry

    def entries(
        self, limit: int | None = None, domain: str | None = None
    ) -> list[dict]:
        """Newest-first entries, optionally filtered by domain."""
        with self._lock:
            items = list(self._ring)
        if domain is not None:
            items = [e for e in items if e["domain"] == domain]
        items.reverse()
        return items[:limit] if limit else items

    def summary(self) -> dict:
        with self._lock:
            items = list(self._ring)
            last_seq = self._last_seq
            cap = self._ring.maxlen or 0
        by: dict[str, dict[str, int]] = {}
        for e in items:
            d = by.setdefault(e["domain"], {})
            d[e["verdict"]] = d.get(e["verdict"], 0) + 1
        return {
            "entries": len(items),
            "seq": last_seq,          # decisions ever made this process
            "capacity": cap,
            "dropped": max(0, last_seq - len(items)),
            "by_domain": by,
        }


# The process journal (metrics-registry posture: one per process).
journal = DecisionJournal()

# Serializes device-leg histogram records PROCESS-wide: the default
# deadline source (probes.coproc_device_leg_hist) is one histogram per
# domain shared by every engine's governor, so a per-Governor lock would
# let two engines' legs interleave the same HdrHist read-modify-write —
# exactly the HST1001 contract. Leg completions are per-launch cadence;
# one module lock is plenty.
_leg_record_lock = threading.Lock()

# coproc_governor_decisions_total{domain,verdict}: lazy check-then-create
# under a lock, same reason as probes.coproc_failure_counter.
_decision_counters: dict[tuple[str, str], Counter] = {}
_decision_lock = threading.Lock()


def _decision_counter(domain: str, verdict: str) -> Counter:
    key = (domain, verdict)
    c = _decision_counters.get(key)
    if c is None:
        with _decision_lock:
            c = _decision_counters.get(key)
            if c is None:
                c = registry.counter(  # pandalint: disable=MET1701 -- memoized check-then-create: the lookup runs once per (domain,verdict) key under _decision_lock, hot calls hit the dict; the label set is open-ended so probes.py cannot pre-bind it
                    "coproc_governor_decisions_total",
                    "Adaptive decisions routed through the coproc governor",
                    domain=domain,
                    verdict=verdict,
                )
                _decision_counters[key] = c
    return c


def journal_record(
    domain: str,
    verdict: str,
    reason: str,
    inputs: dict | None = None,
    config: dict | None = None,
    engine: str | None = None,
) -> dict:
    """Append one decision to the process journal + its counter series.
    Process-scoped deciders with no engine (ops/lz4_device.measure_probe)
    call this directly; Governor.record wraps it with the engine's
    active-config snapshot."""
    entry = journal.append(domain, verdict, reason, inputs, config, engine)
    _decision_counter(domain, str(verdict)).inc()
    return entry


def reset_journal() -> None:
    """Test hook: clear the process journal (counters are registry-owned
    and keep their monotonic totals, like every other counter)."""
    journal.reset()


# ------------------------------------------------------------ governor
_engine_tags = itertools.count(1)


class Governor:
    """Per-engine decision plane: per-domain breakers, adaptive deadlines,
    posture, and the engine's view into the process decision journal."""

    def __init__(
        self,
        *,
        fault_policy: faults.FaultPolicy,
        breaker_threshold: int = 5,
        breaker_cooldown_s: float = 30.0,
        breaker_probe_timeout_s: float | None = None,
        clock=time.monotonic,
        adaptive_deadline: bool = True,
        deadline_margin: float = 4.0,
        deadline_cap_x: float = 8.0,
        deadline_min_samples: int = 64,
        stage_hist=None,
        engine_tag: str | None = None,
        register_gauges: bool = True,
        journal_override: DecisionJournal | None = None,
    ) -> None:
        self._policy = fault_policy
        self._clock = clock
        self._adaptive = bool(adaptive_deadline)
        self._margin = max(1.0, float(deadline_margin))
        self._cap_x = max(1.0, float(deadline_cap_x))
        self._min_samples = max(1, int(deadline_min_samples))
        # injectable histogram source: FAULT DOMAIN -> object with
        # .count/.percentile/.record (the process registry's success-only
        # device-leg HdrHist by default; tests inject their own so the
        # derivation is provable without polluting the live series).
        # observe_leg records into the same source, so injected tests see
        # a closed loop.
        self._stage_hist = stage_hist or (
            lambda domain: probes.coproc_device_leg_hist(domain).hist
        )
        self.engine_tag = engine_tag or f"engine-{next(_engine_tags)}"
        from redpanda_tpu.coproc import lockwatch

        self._lock = lockwatch.wrap(threading.Lock(), "Governor._lock")
        # benches/tests inject a private journal so scratch governors never
        # write the live process journal or its counters
        self._journal = journal_override if journal_override is not None else journal
        # active-config snapshot attached to every journal entry
        self._config: dict = {}
        # current per-domain posture (what the gauges and posture() show)
        self._posture_modes: dict[str, str] = {}
        # record_mode dedupe state, keyed (domain, caller key): the
        # harvest-path verdict is per SCRIPT (a mixed gather+padded
        # workload must journal once per script, not flip-flop the ring
        # on every alternating launch)
        self._mode_keys: dict[tuple, str] = {}
        # per-domain adaptive deadline state:
        # domain -> {"count": samples at last recompute, "deadline_s": ...}
        self._deadline_state: dict[str, dict] = {}
        # launch-knob autotune (configure_autotune arms it) + open shed
        # episodes (note_shed / note_admitted bracket them)
        self._auto: dict | None = None
        self._shed_open: set = set()
        self._policies: dict[str, faults.FaultPolicy] = {}
        # monotonic per-domain max of deadlines actually ISSUED (floor
        # when never raised): the basis of envelope_bound_s
        self._max_issued: dict[str, float] = {}
        self._breakers: dict[str, faults.CircuitBreaker] = {
            domain: faults.CircuitBreaker(
                threshold=breaker_threshold,
                cooldown_s=breaker_cooldown_s,
                clock=clock,
                probe_timeout_s=breaker_probe_timeout_s,
                name=domain,
                listener=self._on_breaker_transition,
            )
            for domain in BREAKER_DOMAINS
        }
        if register_gauges:
            self._register_gauges()

    # ------------------------------------------------------------ gauges
    def _register_gauges(self) -> None:
        """Labeled per-domain gauges bound to THIS governor via weakref.
        Registration overwrites the previous governor's gauges (the
        registry is process-wide and the broker owns exactly one engine);
        a collected governor reads -1 instead of a stale engine's state —
        the fix for the old weakref-to-latest-engine breaker gauge."""
        ref = weakref.ref(self)
        for domain in BREAKER_DOMAINS:
            registry.gauge(
                "coproc_breaker_state",
                self._breaker_gauge_fn(ref, domain),
                "Per-domain device circuit breaker state "
                "(0 closed, 1 open, 2 half_open, -1 none)",
                domain=domain,
            )
            registry.gauge(
                "coproc_governor_deadline_ms",
                self._deadline_gauge_fn(ref, domain),
                "Effective per-attempt device deadline per fault domain "
                "(adaptive over observed stage p99.9; floor = "
                "coproc_device_deadline_ms)",
                domain=domain,
            )
        for domain in _STATE_ENCODING:
            registry.gauge(
                "coproc_governor_state",
                self._posture_gauge_fn(ref, domain),
                "Governor posture per decision domain (see "
                "coproc/governor.py encoding; -1 undecided)",
                domain=domain,
            )
        for knob in ("group_ticks", "launch_depth"):
            # the autotune knobs as live gauges: the pandatrend history
            # ring samples these into `knob:*` counter tracks so a knob
            # resize is visible ON the launch timeline, not only as a
            # journal instant
            registry.gauge(
                "coproc_autotune_knob",
                self._knob_gauge_fn(ref, knob),
                "Current dynamic launch knob value (ADMISSION autotune; "
                "-1 when autotune is unarmed)",
                knob=knob,
            )

    @staticmethod
    def _breaker_gauge_fn(ref, domain):
        def fn() -> float:
            gov = ref()
            if gov is None:
                return -1.0
            return faults.STATE_NUM.get(gov._breakers[domain].state, -1.0)

        return fn

    @staticmethod
    def _deadline_gauge_fn(ref, domain):
        def fn() -> float:
            gov = ref()
            if gov is None:
                return -1.0
            return round(gov.deadline_s(domain) * 1000.0, 3)

        return fn

    @staticmethod
    def _posture_gauge_fn(ref, domain):
        def fn() -> float:
            gov = ref()
            if gov is None:
                return -1.0
            verdict = gov._posture_modes.get(domain)
            return _STATE_ENCODING[domain].get(verdict, -1.0)

        return fn

    @staticmethod
    def _knob_gauge_fn(ref, knob):
        def fn() -> float:
            gov = ref()
            if gov is None:
                return -1.0
            auto = gov._auto
            if auto is None:
                return -1.0
            with gov._lock:
                return float(auto[knob])

        return fn

    # ------------------------------------------------------------ config
    def set_config_snapshot(self, config: dict) -> None:
        """The knob values journal entries carry as their active-config
        snapshot (journal entries copy it at record time)."""
        self._config = dict(config)

    def update_config_snapshot(self, **kw) -> None:
        self._config.update(kw)

    # ------------------------------------------------------------ recording
    def _emit(
        self, domain: str, verdict: str, reason: str, inputs: dict | None
    ) -> dict:
        """Append to this governor's journal; the decision counters only
        move for the live process journal (a scratch governor with an
        injected journal must not write product metrics)."""
        entry = self._journal.append(
            domain, verdict, reason, inputs, self._config, self.engine_tag
        )
        if self._journal is journal:
            _decision_counter(domain, str(verdict)).inc()
        return entry

    def record(
        self, domain: str, verdict: str, reason: str, inputs: dict | None = None
    ) -> dict:
        """Journal one decision with this engine's config snapshot, and
        remember the verdict as the domain's current posture."""
        with self._lock:
            self._posture_modes[domain] = str(verdict)
        return self._emit(domain, verdict, reason, inputs)

    def note_posture(self, domain: str, verdict: str) -> None:
        """Update the domain's current posture WITHOUT a journal entry —
        for inherited process-wide picks (an engine adopting the sticky
        columnar backend made no new decision; the probe that did already
        journaled it)."""
        with self._lock:
            self._posture_modes[domain] = str(verdict)

    def record_mode(
        self,
        domain: str,
        verdict: str,
        reason: str,
        inputs: dict | None = None,
        key=None,
    ) -> bool:
        """Journal only when ``verdict`` differs from the last one recorded
        under ``(domain, key)`` — per-launch callers (harvest framing, seal
        engagement) would otherwise flood the bounded ring with identical
        entries. ``key`` scopes the dedupe (the harvest-path verdict is a
        property of the SCRIPT's plan: a mixed gather+padded workload
        journals once per script instead of flip-flopping every launch).
        The unchanged path is the hot path: one lock, two dict ops."""
        verdict = str(verdict)
        k = (domain, key)
        with self._lock:
            # posture always tracks the most recent launch's verdict
            self._posture_modes[domain] = verdict
            if self._mode_keys.get(k) == verdict:
                return False
            self._mode_keys[k] = verdict
        self._emit(domain, verdict, reason, inputs)
        return True

    def _on_breaker_transition(
        self, name: str, old: str, new: str, reason: str, info: dict
    ) -> None:
        self._emit(
            BREAKER,
            new,
            f"{name}: {old} -> {new} ({reason})",
            {"breaker": name, "from": old, **info},
        )

    # ------------------------------------------------------------ breakers
    def breaker_for(self, fault_domain: str) -> faults.CircuitBreaker:
        return self._breakers[fault_domain]

    def breakers_snapshot(self) -> dict:
        return {d: b.snapshot() for d, b in self._breakers.items()}

    def aggregate_breaker_snapshot(self) -> dict:
        """Engine-level rollup (the shape ``stats()["breaker"]`` always
        had): worst state across domains, the MAX per-domain consecutive
        count (a sum would contradict the per-domain threshold it sits
        next to — 3 domains at 4/5 must not read as 12/5), total trips —
        so "is any part of the device path demoted" stays a one-field
        answer."""
        snaps = [b.snapshot() for b in self._breakers.values()]
        worst = max(snaps, key=lambda s: _BREAKER_SEVERITY[s["state"]])
        return {
            "state": worst["state"],
            "consecutive_failures": max(
                s["consecutive_failures"] for s in snaps
            ),
            "trips": sum(s["trips"] for s in snaps),
            "threshold": snaps[0]["threshold"],
            "cooldown_ms": snaps[0]["cooldown_ms"],
        }

    # ------------------------------------------------------------ deadlines
    def observe_leg(self, fault_domain: str, dt_s: float) -> None:
        """Record one SUCCESSFUL device-leg wall time — the only samples
        the adaptive deadline derives from. Abandoned attempts never call
        this (the leg raised or never returned), so a burst of timeouts
        cannot inflate the tail that sizes the next deadline. Locked on
        the MODULE lock: the default histograms are process-wide per
        domain (shared across engines), and legs complete on fetch
        workers, the harvester and the tick executor concurrently."""
        hist = self._stage_hist(fault_domain)
        with _leg_record_lock:
            hist.record(int(dt_s * 1e6))

    def deadline_s(self, fault_domain: str) -> float:
        """Effective per-attempt deadline for one device fault domain.

        ``clamp(margin * observed_leg_p99.9, floor, cap_x * floor)``;
        the static floor is the fallback below ``min_samples`` and the
        derivation may only RAISE the deadline above it. Recomputed only
        after DEADLINE_RECOMPUTE_SAMPLES new observations (the common path
        is two dict lookups + an int compare)."""
        st = self._deadline_state.get(fault_domain)
        if st is not None:
            # hot path: one dict get + a histogram count compare. The
            # histogram OBJECT is cached per domain (registry histograms
            # are process-immortal; an injected test source is resolved
            # once per domain, up front).
            hist = st["hist"]
            if hist.count - st["count"] < DEADLINE_RECOMPUTE_SAMPLES:
                return st["deadline_s"]
            return self._recompute_deadline(
                fault_domain, st["stage"], hist, hist.count
            )
        floor = self._policy.deadline_s
        if not self._adaptive or fault_domain not in BREAKER_DOMAINS:
            return floor
        hist = self._stage_hist(fault_domain)
        return self._recompute_deadline(
            fault_domain, fault_domain, hist, hist.count
        )

    def _recompute_deadline(self, fault_domain, stage, hist, count) -> float:
        floor = self._policy.deadline_s
        cap = self._cap_x * floor
        p999_us = hist.percentile(99.9) if count else 0
        if count < self._min_samples:
            derived, verdict = floor, "floor"
        else:
            raw = self._margin * p999_us / 1e6
            derived = min(max(floor, raw), cap)
            if derived == floor:
                verdict = "floor"
            elif raw > cap:
                verdict = "capped"
            else:
                verdict = "raised"
        with self._lock:
            st = self._deadline_state.get(fault_domain)
            prev = st["deadline_s"] if st else floor
            self._deadline_state[fault_domain] = {
                "count": count, "deadline_s": derived,
                "stage": stage, "hist": hist,
            }
            # monotonic: envelope_bound_s waiters must cover every
            # deadline ever handed out, not just the current one
            self._max_issued[fault_domain] = max(
                self._max_issued.get(fault_domain, floor), derived
            )
            if derived != prev:
                self._policies.pop(fault_domain, None)
            changed = (
                abs(derived - prev) / max(prev, 1e-9) >= _DEADLINE_JOURNAL_DELTA
            )
        # a half-open probe in this domain runs under the (possibly just
        # raised) adaptive envelope: its stale-probe release must keep
        # outwaiting it, or a legitimately slow probe gets a second probe
        # stacked onto the same struggling device (the invariant
        # CircuitBreaker.probe_timeout_s documents). Plain float store —
        # _tick_locked reads it under the breaker's own lock.
        breaker = self._breakers.get(fault_domain)
        if breaker is not None:
            breaker.probe_timeout_s = max(
                breaker.probe_timeout_s,
                2.0 * self.envelope_bound_s(fault_domain),
            )
        if changed:
            self._emit(
                DEADLINE,
                verdict,
                f"{fault_domain}: success-only device-leg p99.9 = "
                f"{p999_us} us over {count} samples -> deadline "
                f"{derived * 1e3:.1f} ms "
                f"(floor {floor * 1e3:.1f} ms, margin {self._margin}x, "
                f"cap {cap * 1e3:.1f} ms)",
                {
                    "fault_domain": fault_domain,
                    "source": f"coproc_device_leg_latency_us[{stage}]",
                    "p999_us": int(p999_us),
                    "samples": int(count),
                    "floor_ms": round(floor * 1e3, 3),
                    "margin": self._margin,
                    "deadline_ms": round(derived * 1e3, 3),
                    "prev_deadline_ms": round(prev * 1e3, 3),
                },
            )
        return derived

    def policy_for(self, fault_domain: str) -> faults.FaultPolicy:
        """The fault envelope a device leg in this domain runs under: the
        engine's configured policy with the domain's effective (possibly
        adaptively raised) per-attempt deadline."""
        d = self.deadline_s(fault_domain)
        pol = self._policies.get(fault_domain)
        if pol is None or pol.deadline_s != d:
            pol = dataclasses.replace(self._policy, deadline_s=d)
            self._policies[fault_domain] = pol
        return pol

    def envelope_bound_s(self, fault_domain: str) -> float:
        """Envelope of the LARGEST deadline this governor has ever issued
        for the domain (monotonic; starts at the static floor, so with no
        adaptive raise this is exactly the pre-governor static envelope —
        not the 8x cap, which would inflate every wedge-abandonment wait
        ~an order of magnitude for deadlines that were never raised).

        A waiter that must outwait an envelope computed CONCURRENTLY by
        another thread (_resolve_keep waiting on the harvester's fetch)
        sizes off this bound rather than its own policy_for() snapshot,
        and RE-READS it before declaring the owner dead: the owner updates
        the issued maximum inside its own policy_for() before starting the
        fetch, so a recompute landing between the two reads cannot leave
        the re-reading waiter shorter than the fetch it waits on."""
        with self._lock:
            issued = self._max_issued.get(
                fault_domain, self._policy.deadline_s
            )
        if issued == self._policy.deadline_s:
            return self._policy.envelope_s()
        return dataclasses.replace(
            self._policy, deadline_s=issued
        ).envelope_s()

    def max_envelope_s(self) -> float:
        """Worst-case wall of one retried interaction across ALL domains
        at the deadlines actually issued so far — what outer backstops
        (the pacemaker tick deadline) must outwait. Grows monotonically
        with adaptive raises; equals the static envelope until one
        happens."""
        return max(
            self.envelope_bound_s(d) for d in BREAKER_DOMAINS
        )

    # ------------------------------------------------------------ admission
    def configure_autotune(
        self,
        *,
        enabled: bool = True,
        group_ticks: int = 1,
        group_ticks_cap: int = 8,
        launch_depth: int = 4,
        launch_depth_cap: int = 8,
        hold_s: float = AUTOTUNE_HOLD_S,
        pressure_fn=None,
    ) -> None:
        """Arm the dynamic ``group_ticks_per_launch`` / ``launch_depth``
        verdicts. ``pressure_fn() -> (level, occupancy)`` is the budget
        plane's signal (None = no plane: the latency guard still runs).
        The configured values are the STARTING point; verdicts move within
        [1, cap] and may only change once per ``hold_s`` (hysteresis) —
        the same floors/caps posture as the adaptive-deadline machinery."""
        with self._lock:
            self._auto = {
                "enabled": bool(enabled),
                "group_ticks": max(1, int(group_ticks)),
                "group_ticks_cap": max(1, int(group_ticks_cap)),
                "launch_depth": max(1, int(launch_depth)),
                "launch_depth_cap": max(1, int(launch_depth_cap)),
                "hold_s": max(0.0, float(hold_s)),
                "last_change": -float("inf"),
                "pressure_fn": pressure_fn,
            }

    def launch_knobs(self) -> dict:
        """Current {"group_ticks", "launch_depth"} — recomputed here (the
        pacemaker polls once per tick), journaled under the ADMISSION
        domain only when a knob actually moves, and held still inside the
        hysteresis window no matter what the inputs do."""
        auto = self._auto
        if auto is None:
            return {"group_ticks": 1, "launch_depth": 4}
        with self._lock:
            gt, ld = auto["group_ticks"], auto["launch_depth"]
            if not auto["enabled"]:
                return {"group_ticks": gt, "launch_depth": ld}
            now = self._clock()
            if now - auto["last_change"] < auto["hold_s"]:
                return {"group_ticks": gt, "launch_depth": ld}
        # inputs read OUTSIDE the lock (pressure_fn reaches the plane,
        # the histogram percentile walks buckets)
        level, occ = "ok", 0.0
        fn = auto["pressure_fn"]
        if fn is not None:
            try:
                level, occ = fn()
            except Exception as exc:
                # classified: a dead pressure source silently pins the
                # knobs at the latency-guard-only posture
                faults.note_failure("autotune_pressure", exc)
                logger.exception("autotune pressure source failed")
        hist = self._stage_hist(faults.DEVICE_DISPATCH)
        p999_us = hist.percentile(99.9) if hist.count >= self._min_samples else None
        floor_us = self._policy.deadline_s * 1e6
        new_gt, new_ld, verdict = gt, ld, None
        if level == "critical":
            # memory first: collapse to the floors so held staged bytes
            # drain; admission keeps shedding the excess meanwhile
            new_gt, new_ld, verdict = 1, 1, "floor"
        elif level == "warn":
            new_gt, new_ld = max(1, gt - 1), max(1, ld - 1)
            verdict = "shrink"
        elif p999_us is None:
            # no device-leg evidence yet (idle engine, host-pinned box):
            # HOLD the configured knobs — growing on zero samples would
            # ratchet to the caps exactly when nothing supports it
            pass
        elif p999_us > _AUTOTUNE_SHRINK_FRAC * floor_us:
            # device-leg tail approaching the deadline: trade depth for
            # latency before the deadline machinery starts abandoning
            new_gt, new_ld = max(1, gt - 1), max(1, ld - 1)
            verdict = "shrink"
        elif p999_us < _AUTOTUNE_GROW_FRAC * floor_us:
            new_gt = min(auto["group_ticks_cap"], gt + 1)
            new_ld = min(auto["launch_depth_cap"], ld + 1)
            verdict = "grow"
        if (new_gt, new_ld) == (gt, ld):
            return {"group_ticks": gt, "launch_depth": ld}
        with self._lock:
            # re-check under the lock: a concurrent caller may have moved
            # the knobs (and armed the hold window) while we read inputs
            if self._clock() - auto["last_change"] < auto["hold_s"]:
                return {
                    "group_ticks": auto["group_ticks"],
                    "launch_depth": auto["launch_depth"],
                }
            auto["group_ticks"], auto["launch_depth"] = new_gt, new_ld
            auto["last_change"] = self._clock()
        self._emit(
            ADMISSION,
            verdict,
            f"launch knobs {verdict}: group_ticks {gt} -> {new_gt}, "
            f"launch_depth {ld} -> {new_ld} (pressure {level}, occupancy "
            f"{occ:.2f}, dispatch-leg p99.9 "
            f"{'n/a' if p999_us is None else int(p999_us)} us vs floor "
            f"{int(floor_us)} us)",
            {
                "pressure": level,
                "occupancy": round(occ, 4),
                "p999_us": None if p999_us is None else int(p999_us),
                "floor_us": int(floor_us),
                "group_ticks": new_gt,
                "launch_depth": new_ld,
                "prev_group_ticks": gt,
                "prev_launch_depth": ld,
            },
        )
        return {"group_ticks": new_gt, "launch_depth": new_ld}

    def autotune_snapshot(self) -> dict | None:
        auto = self._auto
        if auto is None:
            return None
        with self._lock:
            return {
                k: auto[k]
                for k in (
                    "enabled", "group_ticks", "group_ticks_cap",
                    "launch_depth", "launch_depth_cap", "hold_s",
                )
            }

    def note_shed(
        self, subsystem: str, retry_after_ms: int, inputs: dict | None = None
    ) -> None:
        """Open a shed EPISODE in the journal: the first shed journals,
        repeats inside the same episode only count (the bounded ring must
        keep the episode boundary, not 10^6 identical entries)."""
        open_ = self._shed_open
        with self._lock:
            first = subsystem not in open_
            open_.add(subsystem)
        if first:
            self._emit(
                ADMISSION,
                "shed",
                f"{subsystem}: admission shedding (retry after "
                f"{retry_after_ms} ms)",
                {"subsystem": subsystem, "retry_after_ms": retry_after_ms,
                 **(inputs or {})},
            )

    def note_admitted(self, subsystem: str) -> None:
        """Close the shed episode (first successful admit after sheds)."""
        open_ = self._shed_open
        if not open_:
            return
        with self._lock:
            was_open = subsystem in open_
            open_.discard(subsystem)
        if was_open:
            self._emit(
                ADMISSION,
                "resumed",
                f"{subsystem}: admission resumed",
                {"subsystem": subsystem},
            )

    # ------------------------------------------------------------ views
    def posture(self) -> dict:
        """Current per-domain stance: the operator's one-glance answer to
        "where is every adaptive knob sitting right now"."""
        with self._lock:
            modes = dict(self._posture_modes)
        return {
            "engine": self.engine_tag,
            HOST_POOL: modes.get(HOST_POOL),
            COLUMNAR_BACKEND: modes.get(COLUMNAR_BACKEND),
            DEVICE_LZ4: modes.get(DEVICE_LZ4),
            HARVEST_PATH: modes.get(HARVEST_PATH),
            SHARDED_SEAL: modes.get(SHARDED_SEAL),
            PARSE_PATH: modes.get(PARSE_PATH),
            MESH: modes.get(MESH),
            ADMISSION: modes.get(ADMISSION),
            "autotune": self.autotune_snapshot(),
            "breakers": self.breakers_snapshot(),
            "deadlines_ms": {
                d: round(self.deadline_s(d) * 1e3, 3) for d in BREAKER_DOMAINS
            },
            "adaptive_deadline": self._adaptive,
        }

    def snapshot(self) -> dict:
        """The ``stats()["governor"]`` / BENCH block: posture + the
        journal's summary (NOT the full journal — stats() is polled)."""
        return {"posture": self.posture(), "journal": self._journal.summary()}
