"""Wire-deployable sandboxed Python transforms.

The declarative DSL (ops/exprs.py) covers predicates/projections; this is
the escape hatch for arbitrary per-record logic, deployable over the SAME
internal-topic event path as DSL specs — the tpu-native analogue of the
reference's JS blobs run under its supervisor (src/js/modules/supervisors/,
SimpleTransform.ts:18 `apply(record)`), with the isolation the reference
gets from a separate V8 process done here as a restricted-AST interpreter
boundary plus a hard per-record execution budget.

Containment model (validated at DEPLOY time on every broker, before the
script is registered):
- the source must define exactly `def transform(value): ...`
  (bytes in -> bytes | str | None out; None drops the record);
- AST whitelist: literals, arithmetic/bool/compare, locals, if/for/while,
  comprehensions, subscripts, calls to whitelisted builtins only;
- NO import, NO attribute access except a whitelisted set of safe
  str/bytes/dict/list methods (never underscore names — the
  `().__class__.__mro__` escape runs through dunder attributes);
- NO global/nonlocal, no lambda/def nesting, no decorators, no yield;
- executed with empty __builtins__ and a curated safe-globals table;
- bounded runtime: a wall-clock WATCHDOG enforces a per-record deadline
  (EXEC_WALL_DEADLINE_S) through three layers, because CPython cannot
  preempt a thread mid-opcode (a bigint ``10**10**8`` holds the GIL for
  minutes and no trace event, signal, or async exception lands until the
  opcode completes):
  1. the line tracer checks the deadline on every traced line — any LOOP
     is killed at the deadline, and a line-event budget
     (EXEC_LINE_BUDGET) additionally caps trace volume;
  2. operand guards injected at compile time around ``**``, ``<<``, ``*``
     and ``range(...)`` refuse, BEFORE entering the opcode, operations
     whose operands guarantee an uninterruptible overrun (result size
     bounds sized so every permitted op completes orders of magnitude
     inside the deadline) — the only sound kill for single-opcode burns;
  3. a post-completion elapsed check fails the record even when a
     residual single call (a large allocation) slipped past both.
  The line budget is therefore NOT the hard bound — the deadline is; the
  budget only bounds tracer work for hot tight loops.

Runtime failures surface through the engine's ErrorPolicy exactly like any
script failure: skip_on_failure drops the record, deregister unloads the
script (wasm_event.h policy semantics). Watchdog kills additionally
journal one entry into the governor TREND domain — a deployed transform
hitting its deadline is an operational trend event, visible in
`rpk debug governor` and on the Perfetto timeline next to the launch it
failed.
"""

from __future__ import annotations

import ast
import json
import sys
import time

EXEC_LINE_BUDGET = 100_000  # traced line events per record (tracer-work cap)
EXEC_WALL_DEADLINE_S = 1.0  # per-record wall-clock deadline (the hard bound)
MAX_SOURCE_BYTES = 64 * 1024
# operand-guard bounds: sized so any permitted single op completes orders
# of magnitude inside EXEC_WALL_DEADLINE_S on commodity hardware — a 2M-bit
# int multiply is ~ms; 10**10**8 (a 332M-bit result) is refused outright
MAX_INT_BITS = 1 << 21  # ~2M bits (~256 KiB integer)
MAX_SEQ_ELEMS = 1 << 24  # 16M elements for seq*int / range(...)


class SandboxViolation(Exception):
    """Source failed deploy-time validation."""


class SandboxBudgetExceeded(BaseException):
    """A record's execution exceeded the line budget.

    BaseException on purpose: user code may catch Exception (json error
    handling is legitimate), and the budget kill must NOT be swallowable —
    CPython also unsets the trace function when the tracer raises, so a
    caught budget exception would leave the rest of the transform running
    untraced and unbounded. Validation separately forbids bare except /
    except BaseException and `finally` (which would run untraced too).
    The run() wrapper converts it to SandboxRuntimeError (a plain
    Exception) once it has escaped every user frame, so the engine's
    ErrorPolicy machinery handles it like any script failure."""


class SandboxDeadlineExceeded(BaseException):
    """The wall-clock watchdog killed a record (deadline passed, or an
    operand guard refused an op that guarantees an uninterruptible
    overrun). BaseException for the same reason as SandboxBudgetExceeded:
    user code must not be able to catch the kill."""

    layer = "guard"  # overridden to "deadline" by the tracer's raise


class SandboxRuntimeError(Exception):
    """A record's execution was killed (line-budget overrun or watchdog
    deadline), reported at the sandbox boundary for the engine's
    ErrorPolicy to handle."""


_ALLOWED_NODES = (
    ast.Module, ast.FunctionDef, ast.arguments, ast.arg, ast.Return,
    ast.Expr, ast.Assign, ast.AugAssign, ast.AnnAssign,
    ast.Name, ast.Load, ast.Store, ast.Del, ast.Delete, ast.Constant,
    ast.Tuple, ast.List, ast.Dict, ast.Set,
    ast.BinOp, ast.UnaryOp, ast.BoolOp, ast.Compare, ast.IfExp,
    ast.Add, ast.Sub, ast.Mult, ast.Div, ast.FloorDiv, ast.Mod, ast.Pow,
    ast.LShift, ast.RShift, ast.BitOr, ast.BitXor, ast.BitAnd,
    ast.UAdd, ast.USub, ast.Not, ast.Invert,
    ast.And, ast.Or,
    ast.Eq, ast.NotEq, ast.Lt, ast.LtE, ast.Gt, ast.GtE,
    ast.Is, ast.IsNot, ast.In, ast.NotIn,
    ast.If, ast.For, ast.While, ast.Break, ast.Continue, ast.Pass,
    ast.Call, ast.keyword, ast.Starred,
    ast.Subscript, ast.Slice,
    ast.ListComp, ast.SetComp, ast.DictComp, ast.GeneratorExp,
    ast.comprehension,
    ast.Attribute,  # gated further by _SAFE_METHODS below
    ast.JoinedStr, ast.FormattedValue,  # f-strings (no .format with its
    # attribute-walking format-spec machinery — only plain interpolation)
    ast.Try, ast.ExceptHandler, ast.Raise,
)

# methods callable on values the sandbox can construct; NEVER underscore
# names, NEVER `format` (its format-spec minilanguage walks attributes)
_SAFE_METHODS = frozenset({
    # str/bytes
    "upper", "lower", "strip", "lstrip", "rstrip", "split", "rsplit",
    "splitlines", "join", "replace", "startswith", "endswith", "find",
    "rfind", "index", "count", "encode", "decode", "title", "capitalize",
    "casefold", "zfill", "ljust", "rjust", "isdigit", "isalpha",
    "isalnum", "isspace", "islower", "isupper", "hex", "removeprefix",
    "removesuffix", "partition", "rpartition",
    # dict
    "get", "keys", "values", "items", "setdefault", "pop", "update",
    # list/set
    "append", "extend", "insert", "remove", "clear", "sort", "reverse",
    "copy", "add", "discard", "union", "intersection", "difference",
})

# ---- watchdog operand guards -------------------------------------------
# CPython cannot preempt mid-opcode: once `10**10**8` starts, the GIL is
# held and NO trace event, async exception, or signal lands until the
# (minutes-long) opcode completes. The only sound kill for these burns is
# refusing the operation before it starts. compile_transform rewrites the
# (already validated) AST so `**`, `<<`, `*` route through these guards,
# and the `range` builtin is bounded at creation (a >16M-element range is
# only dangerous when materialized — sorted/list/sum of it is another
# uninterruptible C loop; a for-loop that large dies at the line budget
# long before, so legitimate transforms lose nothing).


def _guard_pow(a, b):
    if isinstance(a, int) and isinstance(b, int) and b > 0:
        if b * max(a.bit_length(), 1) > MAX_INT_BITS:
            raise SandboxDeadlineExceeded(
                f"watchdog: ** operands guarantee a deadline overrun "
                f"(result would exceed {MAX_INT_BITS} bits)"
            )
    return a ** b


def _guard_lshift(a, b):
    if isinstance(a, int) and isinstance(b, int) and b > 0:
        if b + a.bit_length() > MAX_INT_BITS:
            raise SandboxDeadlineExceeded(
                f"watchdog: << operands guarantee a deadline overrun "
                f"(result would exceed {MAX_INT_BITS} bits)"
            )
    return a << b


def _guard_mult(a, b):
    if isinstance(a, int) and isinstance(b, int):
        if a.bit_length() + b.bit_length() > MAX_INT_BITS:
            raise SandboxDeadlineExceeded(
                f"watchdog: * operands guarantee a deadline overrun "
                f"(result would exceed {MAX_INT_BITS} bits)"
            )
    else:
        seq, n = (a, b) if isinstance(b, int) else (b, a)
        if (
            isinstance(n, int)
            and isinstance(seq, (str, bytes, bytearray, list, tuple))
            and n > 0
            and len(seq) * n > MAX_SEQ_ELEMS
        ):
            raise SandboxDeadlineExceeded(
                f"watchdog: sequence * {n} would exceed "
                f"{MAX_SEQ_ELEMS} elements"
            )
    return a * b


def _guard_range(*args):
    r = range(*args)
    if len(r) > MAX_SEQ_ELEMS:
        raise SandboxDeadlineExceeded(
            f"watchdog: range of {len(r)} elements exceeds "
            f"{MAX_SEQ_ELEMS} (materializing it is an uninterruptible burn)"
        )
    return r


# injected under dunder-reserved names: validation rejects any dunder Name
# in USER source, so a transform can neither call nor rebind the guards —
# only compile_transform's post-validation rewrite references them
_GUARD_GLOBALS = {
    "__sbx_pow__": _guard_pow,
    "__sbx_lshift__": _guard_lshift,
    "__sbx_mult__": _guard_mult,
}


class _GuardInjector(ast.NodeTransformer):
    """Post-validation rewrite: `a ** b` -> `__sbx_pow__(a, b)` (likewise
    `<<`, `*`, and the augmented forms). Runs on the validated tree only —
    user source never names the guards (dunder names are rejected)."""

    _OPS = {
        ast.Pow: "__sbx_pow__",
        ast.LShift: "__sbx_lshift__",
        ast.Mult: "__sbx_mult__",
    }

    def _call(self, name: str, left, right, at):
        return ast.copy_location(
            ast.Call(
                func=ast.Name(id=name, ctx=ast.Load()),
                args=[left, right], keywords=[],
            ),
            at,
        )

    def visit_BinOp(self, node):
        self.generic_visit(node)
        name = self._OPS.get(type(node.op))
        if name is None:
            return node
        return self._call(name, node.left, node.right, node)

    def visit_AugAssign(self, node):
        self.generic_visit(node)
        name = self._OPS.get(type(node.op))
        if name is None:
            return node
        # `x **= y` -> `x = __sbx_pow__(x, y)`; a Subscript target's key
        # evaluates twice, acceptable in a side-effect-light sandbox
        import copy as _copy

        load_target = _copy.deepcopy(node.target)
        load_target.ctx = ast.Load()
        return ast.copy_location(
            ast.Assign(
                targets=[node.target],
                value=self._call(name, load_target, node.value, node),
            ),
            node,
        )


_SAFE_BUILTINS = {
    "len": len, "int": int, "float": float, "str": str, "bytes": bytes,
    "bool": bool, "dict": dict, "list": list, "tuple": tuple, "set": set,
    "min": min, "max": max, "sum": sum, "abs": abs, "round": round,
    "sorted": sorted, "reversed": reversed, "range": _guard_range,
    "enumerate": enumerate, "zip": zip, "map": map, "filter": filter,
    "any": any, "all": all, "ord": ord, "chr": chr, "repr": repr,
    "isinstance": isinstance, "divmod": divmod, "hash": hash,
    "ValueError": ValueError, "TypeError": TypeError, "KeyError": KeyError,
    "Exception": Exception, "StopIteration": StopIteration,
    # json travels as plain names (no attribute access on modules)
    "json_loads": json.loads,
    "json_dumps": lambda obj: json.dumps(obj, separators=(",", ":")),
}


# names refused at validation even though empty __builtins__ would already
# NameError them at runtime — deploy-time rejection with a clear reason is
# the contract (and defense in depth if the globals table ever grows)
_DENIED_NAMES = frozenset({
    "getattr", "setattr", "delattr", "hasattr", "eval", "exec", "compile",
    "open", "input", "breakpoint", "globals", "locals", "vars", "dir",
    "type", "object", "super", "memoryview", "classmethod", "staticmethod",
    "property", "callable", "id", "help", "exit", "quit", "license",
    "copyright", "credits", "import",
})


def validate_source(source: str) -> ast.Module:
    """Parse + whitelist-check; raises SandboxViolation with a reason."""
    if len(source.encode()) > MAX_SOURCE_BYTES:
        raise SandboxViolation(f"source exceeds {MAX_SOURCE_BYTES} bytes")
    try:
        tree = ast.parse(source)
    except (SyntaxError, ValueError, MemoryError, RecursionError) as e:
        # pathological sources under the byte cap can blow the parser
        # itself (MemoryError on long operator chains) — every parse
        # failure is a validation failure, never a broker fault
        raise SandboxViolation(f"unparseable source: {type(e).__name__}: {e}") from e
    if (
        len(tree.body) != 1
        or not isinstance(tree.body[0], ast.FunctionDef)
        or tree.body[0].name != "transform"
    ):
        raise SandboxViolation("source must define exactly one function: def transform(value)")
    fn = tree.body[0]
    if fn.decorator_list:
        raise SandboxViolation("decorators are not allowed")
    a = fn.args
    if (
        len(a.args) != 1 or a.vararg or a.kwarg or a.kwonlyargs
        or a.posonlyargs or a.defaults
    ):
        raise SandboxViolation("transform must take exactly one positional argument")
    for node in ast.walk(tree):
        if not isinstance(node, _ALLOWED_NODES):
            raise SandboxViolation(f"disallowed syntax: {type(node).__name__}")
        if isinstance(node, ast.FunctionDef) and node is not fn:
            raise SandboxViolation("nested function definitions are not allowed")
        if isinstance(node, ast.Attribute):
            if node.attr.startswith("_"):
                raise SandboxViolation(f"underscore attribute access: {node.attr}")
            if node.attr not in _SAFE_METHODS:
                raise SandboxViolation(f"attribute not in safe set: {node.attr}")
            if not isinstance(node.ctx, ast.Load):
                raise SandboxViolation("attribute assignment is not allowed")
        if isinstance(node, ast.Name):
            if node.id.startswith("__"):
                raise SandboxViolation(f"dunder name: {node.id}")
            if node.id in _DENIED_NAMES:
                raise SandboxViolation(f"denied name: {node.id}")
        if isinstance(node, ast.Try):
            if node.finalbody:
                # a finally block runs AFTER a budget kill with tracing
                # already unset — an unbounded escape hatch
                raise SandboxViolation("finally blocks are not allowed")
        if isinstance(node, ast.ExceptHandler):
            # the budget kill is a BaseException; handlers must not be able
            # to catch it
            names = []
            if node.type is None:
                raise SandboxViolation("bare except is not allowed")
            for t in ast.walk(node.type):
                if isinstance(t, ast.Name):
                    names.append(t.id)
            if "BaseException" in names:
                raise SandboxViolation("except BaseException is not allowed")
        if isinstance(node, ast.FormattedValue) and node.format_spec is not None:
            # format specs run the attribute-walking format machinery
            for sub in ast.walk(node.format_spec):
                if isinstance(sub, ast.FormattedValue):
                    raise SandboxViolation("nested format specs are not allowed")
    return tree


def _journal_watchdog_kill(script_id, layer: str, elapsed_s: float, reason: str):
    """One governor TREND entry per incident (the caller dedupes): a
    deployed transform hitting its wall-clock deadline is an operational
    trend event, not per-record noise."""
    try:
        from redpanda_tpu.coproc.governor import TREND, journal_record

        journal_record(
            TREND,
            "watchdog_kill",
            f"sandbox watchdog killed script "
            f"{script_id if script_id is not None else '?'} ({layer}): {reason}",
            inputs={
                "script_id": script_id,
                "layer": layer,
                "elapsed_s": round(elapsed_s, 6),
            },
            config={
                "deadline_s": EXEC_WALL_DEADLINE_S,
                "line_budget": EXEC_LINE_BUDGET,
                "max_int_bits": MAX_INT_BITS,
            },
        )
    except Exception:
        # journaling is best-effort; a kill must surface through
        # ErrorPolicy even if the governor import is unavailable
        # mid-shutdown (EXC901's import-probe exemption applies)
        pass


def compile_transform(source: str, script_id: int | None = None):
    """validate + guard-inject + compile -> callable(value) -> bytes | None.

    Each call runs under the three-layer watchdog (module docstring): a
    line tracer that enforces both EXEC_LINE_BUDGET and the wall-clock
    deadline, operand guards compiled around `**`/`<<`/`*`/`range`, and a
    post-completion elapsed check. Kills surface as SandboxRuntimeError
    for the engine's ErrorPolicy; watchdog kills additionally journal one
    governor TREND entry per incident."""
    from redpanda_tpu.coproc import faults

    # fault domain: a poisoned compile must refuse registration, not take
    # the broker down — the chaos suite drives this via the armed probe
    faults.inject(faults.SANDBOX_COMPILE)
    tree = validate_source(source)
    # post-validation rewrite: user source can neither name nor shadow the
    # dunder guard bindings (validation rejects dunder names)
    tree = _GuardInjector().visit(tree)
    ast.fix_missing_locations(tree)
    code = compile(tree, "<coproc-sandbox>", "exec")
    glb: dict = {"__builtins__": {}}
    glb.update(_SAFE_BUILTINS)
    glb.update(_GUARD_GLOBALS)
    exec(code, glb)  # defines transform in glb; body is whitelisted
    fn = glb["transform"]
    incident_journaled = False  # once per compiled transform, not per record

    def _kill(layer: str, elapsed_s: float, reason: str):
        nonlocal incident_journaled
        if not incident_journaled:
            incident_journaled = True
            _journal_watchdog_kill(script_id, layer, elapsed_s, reason)
        raise SandboxRuntimeError(reason) from None

    def run(value: bytes):
        budget = EXEC_LINE_BUDGET
        t0 = time.monotonic()
        deadline = t0 + EXEC_WALL_DEADLINE_S

        def tracer(frame, event, arg):
            nonlocal budget
            if event == "line":
                budget -= 1
                if budget <= 0:
                    raise SandboxBudgetExceeded(
                        f"transform exceeded {EXEC_LINE_BUDGET} lines"
                    )
                if time.monotonic() > deadline:
                    exc = SandboxDeadlineExceeded(
                        f"watchdog: record exceeded the "
                        f"{EXEC_WALL_DEADLINE_S}s wall-clock deadline"
                    )
                    exc.layer = "deadline"
                    raise exc
            return tracer

        old = sys.gettrace()
        sys.settrace(tracer)
        try:
            out = fn(value)
        except SandboxBudgetExceeded as e:
            # escaped every user frame (validation forbids catching it);
            # convert to a plain Exception for the ErrorPolicy machinery
            raise SandboxRuntimeError(str(e)) from None
        except SandboxDeadlineExceeded as e:
            _kill(e.layer, time.monotonic() - t0, str(e))  # pandalint: disable=PRF1501 -- the delta is the incident's elapsed_s journal payload (governor TREND entry), not a stage latency; launch timing is the engine's _stat_stage job
        finally:
            sys.settrace(old)
        elapsed = time.monotonic() - t0
        if elapsed > EXEC_WALL_DEADLINE_S:
            # layer 3: a residual single call (large allocation, big join)
            # slipped past tracer and guards — the record still fails
            _kill(
                "post_hoc", elapsed,
                f"watchdog: record took {elapsed:.3f}s "
                f"(> {EXEC_WALL_DEADLINE_S}s deadline)",
            )
        if out is None:
            return None
        if isinstance(out, str):
            return out.encode()
        if isinstance(out, (bytes, bytearray)):
            return bytes(out)
        raise TypeError(f"transform must return bytes|str|None, got {type(out).__name__}")

    run.__name__ = "sandboxed_transform"
    return run
