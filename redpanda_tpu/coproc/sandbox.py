"""Wire-deployable sandboxed Python transforms.

The declarative DSL (ops/exprs.py) covers predicates/projections; this is
the escape hatch for arbitrary per-record logic, deployable over the SAME
internal-topic event path as DSL specs — the tpu-native analogue of the
reference's JS blobs run under its supervisor (src/js/modules/supervisors/,
SimpleTransform.ts:18 `apply(record)`), with the isolation the reference
gets from a separate V8 process done here as a restricted-AST interpreter
boundary plus a hard per-record execution budget.

Containment model (validated at DEPLOY time on every broker, before the
script is registered):
- the source must define exactly `def transform(value): ...`
  (bytes in -> bytes | str | None out; None drops the record);
- AST whitelist: literals, arithmetic/bool/compare, locals, if/for/while,
  comprehensions, subscripts, calls to whitelisted builtins only;
- NO import, NO attribute access except a whitelisted set of safe
  str/bytes/dict/list methods (never underscore names — the
  `().__class__.__mro__` escape runs through dunder attributes);
- NO global/nonlocal, no lambda/def nesting, no decorators, no yield;
- executed with empty __builtins__ and a curated safe-globals table;
- bounded runtime: a line-trace budget aborts a record that executes more
  than EXEC_LINE_BUDGET traced lines (while-loop containment).

Runtime failures surface through the engine's ErrorPolicy exactly like any
script failure: skip_on_failure drops the record, deregister unloads the
script (wasm_event.h policy semantics).
"""

from __future__ import annotations

import ast
import json
import sys

EXEC_LINE_BUDGET = 100_000  # traced line events per record
MAX_SOURCE_BYTES = 64 * 1024


class SandboxViolation(Exception):
    """Source failed deploy-time validation."""


class SandboxBudgetExceeded(BaseException):
    """A record's execution exceeded the line budget.

    BaseException on purpose: user code may catch Exception (json error
    handling is legitimate), and the budget kill must NOT be swallowable —
    CPython also unsets the trace function when the tracer raises, so a
    caught budget exception would leave the rest of the transform running
    untraced and unbounded. Validation separately forbids bare except /
    except BaseException and `finally` (which would run untraced too).
    The run() wrapper converts it to SandboxRuntimeError (a plain
    Exception) once it has escaped every user frame, so the engine's
    ErrorPolicy machinery handles it like any script failure."""


class SandboxRuntimeError(Exception):
    """A record's execution was killed (budget overrun), reported at the
    sandbox boundary for the engine's ErrorPolicy to handle."""


_ALLOWED_NODES = (
    ast.Module, ast.FunctionDef, ast.arguments, ast.arg, ast.Return,
    ast.Expr, ast.Assign, ast.AugAssign, ast.AnnAssign,
    ast.Name, ast.Load, ast.Store, ast.Del, ast.Delete, ast.Constant,
    ast.Tuple, ast.List, ast.Dict, ast.Set,
    ast.BinOp, ast.UnaryOp, ast.BoolOp, ast.Compare, ast.IfExp,
    ast.Add, ast.Sub, ast.Mult, ast.Div, ast.FloorDiv, ast.Mod, ast.Pow,
    ast.LShift, ast.RShift, ast.BitOr, ast.BitXor, ast.BitAnd,
    ast.UAdd, ast.USub, ast.Not, ast.Invert,
    ast.And, ast.Or,
    ast.Eq, ast.NotEq, ast.Lt, ast.LtE, ast.Gt, ast.GtE,
    ast.Is, ast.IsNot, ast.In, ast.NotIn,
    ast.If, ast.For, ast.While, ast.Break, ast.Continue, ast.Pass,
    ast.Call, ast.keyword, ast.Starred,
    ast.Subscript, ast.Slice,
    ast.ListComp, ast.SetComp, ast.DictComp, ast.GeneratorExp,
    ast.comprehension,
    ast.Attribute,  # gated further by _SAFE_METHODS below
    ast.JoinedStr, ast.FormattedValue,  # f-strings (no .format with its
    # attribute-walking format-spec machinery — only plain interpolation)
    ast.Try, ast.ExceptHandler, ast.Raise,
)

# methods callable on values the sandbox can construct; NEVER underscore
# names, NEVER `format` (its format-spec minilanguage walks attributes)
_SAFE_METHODS = frozenset({
    # str/bytes
    "upper", "lower", "strip", "lstrip", "rstrip", "split", "rsplit",
    "splitlines", "join", "replace", "startswith", "endswith", "find",
    "rfind", "index", "count", "encode", "decode", "title", "capitalize",
    "casefold", "zfill", "ljust", "rjust", "isdigit", "isalpha",
    "isalnum", "isspace", "islower", "isupper", "hex", "removeprefix",
    "removesuffix", "partition", "rpartition",
    # dict
    "get", "keys", "values", "items", "setdefault", "pop", "update",
    # list/set
    "append", "extend", "insert", "remove", "clear", "sort", "reverse",
    "copy", "add", "discard", "union", "intersection", "difference",
})

_SAFE_BUILTINS = {
    "len": len, "int": int, "float": float, "str": str, "bytes": bytes,
    "bool": bool, "dict": dict, "list": list, "tuple": tuple, "set": set,
    "min": min, "max": max, "sum": sum, "abs": abs, "round": round,
    "sorted": sorted, "reversed": reversed, "range": range,
    "enumerate": enumerate, "zip": zip, "map": map, "filter": filter,
    "any": any, "all": all, "ord": ord, "chr": chr, "repr": repr,
    "isinstance": isinstance, "divmod": divmod, "hash": hash,
    "ValueError": ValueError, "TypeError": TypeError, "KeyError": KeyError,
    "Exception": Exception, "StopIteration": StopIteration,
    # json travels as plain names (no attribute access on modules)
    "json_loads": json.loads,
    "json_dumps": lambda obj: json.dumps(obj, separators=(",", ":")),
}


# names refused at validation even though empty __builtins__ would already
# NameError them at runtime — deploy-time rejection with a clear reason is
# the contract (and defense in depth if the globals table ever grows)
_DENIED_NAMES = frozenset({
    "getattr", "setattr", "delattr", "hasattr", "eval", "exec", "compile",
    "open", "input", "breakpoint", "globals", "locals", "vars", "dir",
    "type", "object", "super", "memoryview", "classmethod", "staticmethod",
    "property", "callable", "id", "help", "exit", "quit", "license",
    "copyright", "credits", "import",
})


def validate_source(source: str) -> ast.Module:
    """Parse + whitelist-check; raises SandboxViolation with a reason."""
    if len(source.encode()) > MAX_SOURCE_BYTES:
        raise SandboxViolation(f"source exceeds {MAX_SOURCE_BYTES} bytes")
    try:
        tree = ast.parse(source)
    except (SyntaxError, ValueError, MemoryError, RecursionError) as e:
        # pathological sources under the byte cap can blow the parser
        # itself (MemoryError on long operator chains) — every parse
        # failure is a validation failure, never a broker fault
        raise SandboxViolation(f"unparseable source: {type(e).__name__}: {e}") from e
    if (
        len(tree.body) != 1
        or not isinstance(tree.body[0], ast.FunctionDef)
        or tree.body[0].name != "transform"
    ):
        raise SandboxViolation("source must define exactly one function: def transform(value)")
    fn = tree.body[0]
    if fn.decorator_list:
        raise SandboxViolation("decorators are not allowed")
    a = fn.args
    if (
        len(a.args) != 1 or a.vararg or a.kwarg or a.kwonlyargs
        or a.posonlyargs or a.defaults
    ):
        raise SandboxViolation("transform must take exactly one positional argument")
    for node in ast.walk(tree):
        if not isinstance(node, _ALLOWED_NODES):
            raise SandboxViolation(f"disallowed syntax: {type(node).__name__}")
        if isinstance(node, ast.FunctionDef) and node is not fn:
            raise SandboxViolation("nested function definitions are not allowed")
        if isinstance(node, ast.Attribute):
            if node.attr.startswith("_"):
                raise SandboxViolation(f"underscore attribute access: {node.attr}")
            if node.attr not in _SAFE_METHODS:
                raise SandboxViolation(f"attribute not in safe set: {node.attr}")
            if not isinstance(node.ctx, ast.Load):
                raise SandboxViolation("attribute assignment is not allowed")
        if isinstance(node, ast.Name):
            if node.id.startswith("__"):
                raise SandboxViolation(f"dunder name: {node.id}")
            if node.id in _DENIED_NAMES:
                raise SandboxViolation(f"denied name: {node.id}")
        if isinstance(node, ast.Try):
            if node.finalbody:
                # a finally block runs AFTER a budget kill with tracing
                # already unset — an unbounded escape hatch
                raise SandboxViolation("finally blocks are not allowed")
        if isinstance(node, ast.ExceptHandler):
            # the budget kill is a BaseException; handlers must not be able
            # to catch it
            names = []
            if node.type is None:
                raise SandboxViolation("bare except is not allowed")
            for t in ast.walk(node.type):
                if isinstance(t, ast.Name):
                    names.append(t.id)
            if "BaseException" in names:
                raise SandboxViolation("except BaseException is not allowed")
        if isinstance(node, ast.FormattedValue) and node.format_spec is not None:
            # format specs run the attribute-walking format machinery
            for sub in ast.walk(node.format_spec):
                if isinstance(sub, ast.FormattedValue):
                    raise SandboxViolation("nested format specs are not allowed")
    return tree


def compile_transform(source: str):
    """validate + compile -> callable(value: bytes) -> bytes | None.

    Each call runs under a line-budget trace; the returned callable raises
    SandboxBudgetExceeded when a record overruns EXEC_LINE_BUDGET."""
    from redpanda_tpu.coproc import faults

    # fault domain: a poisoned compile must refuse registration, not take
    # the broker down — the chaos suite drives this via the armed probe
    faults.inject(faults.SANDBOX_COMPILE)
    tree = validate_source(source)
    code = compile(tree, "<coproc-sandbox>", "exec")
    glb: dict = {"__builtins__": {}}
    glb.update(_SAFE_BUILTINS)
    exec(code, glb)  # defines transform in glb; body is whitelisted
    fn = glb["transform"]

    def run(value: bytes):
        budget = EXEC_LINE_BUDGET

        def tracer(frame, event, arg):
            nonlocal budget
            if event == "line":
                budget -= 1
                if budget <= 0:
                    raise SandboxBudgetExceeded(
                        f"transform exceeded {EXEC_LINE_BUDGET} lines"
                    )
            return tracer

        old = sys.gettrace()
        sys.settrace(tracer)
        try:
            out = fn(value)
        except SandboxBudgetExceeded as e:
            # escaped every user frame (validation forbids catching it);
            # convert to a plain Exception for the ErrorPolicy machinery
            raise SandboxRuntimeError(str(e)) from None
        finally:
            sys.settrace(old)
        if out is None:
            return None
        if isinstance(out, str):
            return out.encode()
        if isinstance(out, (bytes, bytearray)):
            return bytes(out)
        raise TypeError(f"transform must return bytes|str|None, got {type(out).__name__}")

    run.__name__ = "sandboxed_transform"
    return run
