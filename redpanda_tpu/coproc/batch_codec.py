"""Host-side record-batch explode/rebuild for the engine data path.

The per-record work (varint framing) runs in native code
(native/redpanda_native.cc rp_parse_record_values / rp_frame_records) with a
Python fallback; Python only touches per-batch metadata. This is the
division of labour the whole engine is built around: Python per batch,
C per record, TPU per byte.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass

import numpy as np

from redpanda_tpu.compression import compress, registry, uncompress
from redpanda_tpu.models.record import Compression, Record, RecordBatch, RecordBatchHeader
from redpanda_tpu.utils.vint import decode_zigzag, encode_zigzag


def _native():
    try:
        from redpanda_tpu.native import lib

        return lib
    except Exception:
        return None


class Arena:
    """Reusable scratch buffers for the harvest path's framing crossings.

    Each launch used to allocate a fresh framing dst buffer (and offset
    arrays) only to throw it away after ``.tobytes()`` sliced the payloads
    out; at a steady tick cadence that is megabytes of allocator churn per
    launch for buffers whose size barely changes. The arena keeps a small
    free list instead: ``acquire`` hands back a previously released buffer
    when one is big enough, ``release`` returns it. Thread-safe — sharded
    harvests frame concurrently on pool workers.

    The engine owns one arena per instance (``TpuEngine.reset_arenas()``
    swaps in a fresh one for tests/bench so reuse accounting is
    deterministic)."""

    # bound the free list so a one-off giant launch cannot pin its buffers
    # forever once traffic returns to normal size
    MAX_FREE = 8

    def __init__(self) -> None:
        from redpanda_tpu.coproc import lockwatch

        self._lock = lockwatch.wrap(threading.Lock(), "Arena._lock")
        self._free: list[np.ndarray] = []
        self._allocs = 0
        self._reuses = 0
        self._alloc_bytes = 0
        self._trims = 0

    def acquire(self, nbytes: int) -> np.ndarray:
        """A uint8 1-D buffer of AT LEAST nbytes (callers track their own
        logical lengths; the buffer may be bigger)."""
        with self._lock:
            best = None
            for i, b in enumerate(self._free):
                if b.nbytes >= nbytes and (
                    best is None or b.nbytes < self._free[best].nbytes
                ):
                    best = i
            if best is not None:
                self._reuses += 1
                return self._free.pop(best)
            self._allocs += 1
            self._alloc_bytes += max(nbytes, 1)
        return np.empty(max(nbytes, 1), dtype=np.uint8)

    def release(self, buf: np.ndarray | None) -> None:
        if buf is None:
            return
        with self._lock:
            if len(self._free) < self.MAX_FREE:
                self._free.append(buf)
            # else: drop — the launch that needed it can re-allocate

    def trim(self) -> int:
        """Release every parked free-list buffer back to the allocator
        (memory-pressure hook: under a CRITICAL budget-plane signal the
        engine prefers reclaiming idle scratch over shedding work).
        Returns the number of buffers freed; in-flight buffers are
        untouched and later releases re-park as usual."""
        with self._lock:
            n = len(self._free)
            self._free.clear()
            self._trims += 1
        return n

    def stats(self) -> dict:
        with self._lock:
            return {
                "allocs": self._allocs,
                "reuses": self._reuses,
                "alloc_bytes": self._alloc_bytes,
                "free_buffers": len(self._free),
                "trims": self._trims,
            }


@dataclass
class ExplodedBatches:
    """All record values of a batch list, as offsets into one joined blob."""

    joined: bytes
    offsets: np.ndarray  # int64 [N]
    sizes: np.ndarray  # int32 [N] (null values -> 0)
    ranges: list[tuple[int, int]]  # per input batch: [start, end) in N


def _gather_payloads(batches: list[RecordBatch]):
    """Decompress + concatenate batch payloads; shared by the split and
    fused explode paths."""
    payloads: list[bytes] = []
    counts = np.empty(len(batches), np.int32)
    p_off = np.empty(len(batches), np.int64)
    p_len = np.empty(len(batches), np.int32)
    ranges: list[tuple[int, int]] = []
    base = 0
    n = 0
    for i, b in enumerate(batches):
        payload = b.payload
        if b.header.compression != Compression.none:
            payload = uncompress(payload, b.header.compression)
        count = b.header.record_count
        payloads.append(payload)
        counts[i] = count
        p_off[i] = base
        p_len[i] = len(payload)
        ranges.append((n, n + count))
        base += len(payload)
        n += count
    return payloads, counts, p_off, p_len, ranges, b"".join(payloads), n


def explode_and_find(batches: list[RecordBatch], paths: list[str]):
    """FUSED explode + find (rp_explode_find): framing parse and the
    k-path JSON walk in one native crossing and one cache-hot traversal.
    Returns (ExplodedBatches, types, vs, ve) or None when the native
    symbol is unavailable (caller runs the split stages)."""
    lib = _native()
    if lib is None or not getattr(lib, "has_explode_find", False) or not paths:
        return None
    _, counts, p_off, p_len, ranges, joined, n = _gather_payloads(batches)
    if n == 0:
        ex = ExplodedBatches(
            joined, np.zeros(0, np.int64), np.zeros(0, np.int32), ranges
        )
        k = len(paths)
        return ex, np.zeros((0, k), np.int8), np.zeros((0, k), np.int64), np.zeros((0, k), np.int64)
    off, ln, types, vs, ve = lib.explode_find(joined, p_off, p_len, counts, paths)
    ex = ExplodedBatches(joined, off, np.maximum(ln, 0), ranges)
    return ex, types, vs, ve


class StructuralParse:
    """One launch's structural-index parse: everything the fused
    extraction crossing (and the engine's bookkeeping) needs, with the
    decompressed per-batch payload buffers retained so record bytes stay
    reachable WITHOUT a joined blob. ``joined`` is populated (as a uint8
    ndarray view over the in-crossing copy) only when the caller asked
    for it — passthrough plans gather harvest output from it; projection
    plans never touch raw bytes again and skip the copy entirely."""

    __slots__ = (
        "payloads", "counts", "ranges", "joined", "val_off", "val_len",
        "types", "vs", "ve", "n",
    )

    def __init__(self, payloads, counts, ranges, joined, val_off, val_len,
                 types, vs, ve):
        self.payloads = payloads
        self.counts = counts
        self.ranges = ranges
        self.joined = joined
        self.val_off = val_off
        self.val_len = val_len
        self.types = types
        self.vs = vs
        self.ve = ve
        self.n = len(val_len)

    @property
    def sizes(self) -> np.ndarray:
        return np.maximum(self.val_len, 0)

    def exploded(self) -> ExplodedBatches:
        """The classic exploded table (requires ``joined``)."""
        return ExplodedBatches(
            self.joined, self.val_off, self.sizes, self.ranges
        )


def explode_find_structural(
    batches: list[RecordBatch], paths: list[str], need_joined: bool
) -> StructuralParse | None:
    """Structural-index fused parse (rp_explode_find2): decompressed
    payloads cross the native boundary ONCE as a pointer table — the
    Python-side b"".join copy of explode_and_find's path only happens
    in-crossing, and only when ``need_joined`` says the harvest will
    gather from the blob. Returns None when the native symbols are
    unavailable (caller runs the staged ladder)."""
    lib = _native()
    if lib is None or not getattr(lib, "has_structural", False) or not paths:
        return None
    payloads: list[bytes] = []
    counts = np.empty(len(batches), np.int32)
    ranges: list[tuple[int, int]] = []
    n = 0
    for i, b in enumerate(batches):
        payload = b.payload
        if b.header.compression != Compression.none:
            payload = uncompress(payload, b.header.compression)
        count = b.header.record_count
        payloads.append(payload)
        counts[i] = count
        ranges.append((n, n + count))
        n += count
    if n == 0:
        k = len(paths)
        return StructuralParse(
            payloads, counts, ranges,
            np.zeros(0, np.uint8) if need_joined else None,
            np.zeros(0, np.int64), np.zeros(0, np.int32),
            np.zeros((0, k), np.int8), np.zeros((0, k), np.int64),
            np.zeros((0, k), np.int64),
        )
    joined, off, ln, types, vs, ve = lib.explode_find_structural(
        payloads, counts, paths, need_joined
    )
    return StructuralParse(payloads, counts, ranges, joined, off, ln,
                           types, vs, ve)


@dataclass
class PtrExploded:
    """Pointer-table explode for the payload staging lane (ROADMAP item 1
    follow-on b): the decompressed per-batch payload buffers are retained
    and record (offset, len) stay RELATIVE to their own buffer, so
    staging packs straight from each buffer — the joined blob (and its
    b"".join copy, plus _pack_staged's second cache-cold read of it)
    never exists."""

    payloads: list[bytes]
    rel_off: list[np.ndarray]  # int64 per batch, relative to its payload
    rel_len: list[np.ndarray]  # int32 per batch (raw; -1 for null values)
    sizes: np.ndarray  # int32 [N] launch-wide, clamped >= 0
    ranges: list[tuple[int, int]]  # per input batch: [start, end) in N


def explode_ptrs(batches: list[RecordBatch]) -> PtrExploded | None:
    """Explode a batch list WITHOUT building the joined blob. Returns
    None when the native packer is unavailable — the classic joined-blob
    lane is the fallback and the parity oracle."""
    lib = _native()
    if lib is None:
        # rp_pack_rows is a mandatory symbol — a .so without it fails
        # _NativeLib binding entirely, so lib None IS the "packer
        # unavailable" case
        return None
    payloads: list[bytes] = []
    rel_off: list[np.ndarray] = []
    rel_len: list[np.ndarray] = []
    sizes_parts: list[np.ndarray] = []
    ranges: list[tuple[int, int]] = []
    n = 0
    for b in batches:
        payload = b.payload
        if b.header.compression != Compression.none:
            payload = uncompress(payload, b.header.compression)
        count = b.header.record_count
        if count:
            off, ln = lib.parse_record_values(payload, count)
        else:
            off = np.zeros(0, np.int64)
            ln = np.zeros(0, np.int32)
        payloads.append(payload)
        rel_off.append(off)
        rel_len.append(ln)
        sizes_parts.append(np.maximum(ln, 0))
        ranges.append((n, n + count))
        n += count
    sizes = (
        np.concatenate(sizes_parts).astype(np.int32)
        if sizes_parts
        else np.zeros(0, np.int32)
    )
    return PtrExploded(payloads, rel_off, rel_len, sizes, ranges)


def merge_exploded(parts: list[ExplodedBatches]) -> ExplodedBatches:
    """Concatenate per-shard explode results into one launch-wide table.

    Shards are contiguous batch slices in input order (host_pool
    .partition_counts), so the merge is pure concatenation with rebasing:
    value offsets shift by the preceding shards' joined length, per-batch
    record ranges by their record count. The result is byte- and
    index-identical to exploding the whole batch list inline — the
    downstream stages (_pack_staged, _mat_host, frame_ranges) cannot tell
    the difference, which is what the workers=0 parity tests assert.
    """
    if len(parts) == 1:
        return parts[0]
    if not parts:
        return ExplodedBatches(b"", np.zeros(0, np.int64), np.zeros(0, np.int32), [])
    joined = b"".join(p.joined for p in parts)
    offs, sizes, ranges = [], [], []
    byte_base = 0
    rec_base = 0
    for p in parts:
        offs.append(p.offsets + byte_base)
        sizes.append(p.sizes)
        ranges.extend((s + rec_base, e + rec_base) for s, e in p.ranges)
        byte_base += len(p.joined)
        rec_base += len(p.sizes)
    return ExplodedBatches(
        joined, np.concatenate(offs), np.concatenate(sizes), ranges
    )


def explode_batches(batches: list[RecordBatch]) -> ExplodedBatches:
    lib = _native()
    payloads, counts, p_off, p_len, ranges, joined, n = _gather_payloads(batches)
    if n == 0:
        return ExplodedBatches(
            joined, np.zeros(0, np.int64), np.zeros(0, np.int32), ranges
        )
    if lib is not None and getattr(lib, "has_parse_many", False):
        # ONE native crossing for the whole launch (not one per batch)
        off, ln = lib.parse_many(joined, p_off, p_len, counts)
    elif lib is not None:
        offs, lns = [], []
        for i, payload in enumerate(payloads):
            o, l = lib.parse_record_values(payload, int(counts[i]))
            offs.append(o + p_off[i])
            lns.append(l)
        off = np.concatenate(offs) if offs else np.zeros(0, np.int64)
        ln = np.concatenate(lns) if lns else np.zeros(0, np.int32)
    else:
        offs, lns = [], []
        for i, payload in enumerate(payloads):
            o, l = _parse_record_values_py(payload, int(counts[i]))
            offs.append(o + p_off[i])
            lns.append(l)
        off = np.concatenate(offs)
        ln = np.concatenate(lns)
    return ExplodedBatches(joined, off, np.maximum(ln, 0), ranges)


def _parse_record_values_py(payload: bytes, count: int):
    off = np.empty(count, dtype=np.int64)
    ln = np.empty(count, dtype=np.int32)
    pos = 0
    for i in range(count):
        body_len, k = decode_zigzag(payload, pos)
        pos += k
        body_end = pos + body_len
        p = pos + 1  # attributes
        _, k = decode_zigzag(payload, p)
        p += k
        _, k = decode_zigzag(payload, p)
        p += k
        klen, k = decode_zigzag(payload, p)
        p += k
        if klen > 0:
            p += klen
        vlen, k = decode_zigzag(payload, p)
        p += k
        off[i] = p
        ln[i] = vlen if vlen >= 0 else -1
        pos = body_end
    return off, ln


def frame_records(rows: np.ndarray, lens: np.ndarray, keep: np.ndarray) -> tuple[bytes, int]:
    lib = _native()
    if lib is not None:
        return lib.frame_records(rows, lens, keep)
    out = bytearray()
    seq = 0
    for i in range(len(keep)):
        if not keep[i]:
            continue
        vlen = max(int(lens[i]), 0)
        body = bytearray()
        body += b"\x00"
        body += encode_zigzag(0)
        body += encode_zigzag(seq)
        body += encode_zigzag(-1)
        body += encode_zigzag(vlen)
        body += rows[i, :vlen].tobytes()
        body += encode_zigzag(0)
        out += encode_zigzag(len(body))
        out += body
        seq += 1
    return bytes(out), seq


def frame_ranges(
    rows: np.ndarray,
    lens: np.ndarray,
    keep: np.ndarray,
    ranges: list[tuple[int, int]],
    arena: Arena | None = None,
) -> list[tuple[bytes, int]]:
    """Frame every [start, end) record range of a LAUNCH in one native
    crossing (rp_frame_many): [(payload, kept)] per range. The per-batch
    ctypes call overhead dominated rebuild at 32-record batches; this is
    the same loop, moved below the language boundary. ``arena`` (when
    given) supplies the reusable framing dst buffer."""
    if not ranges:
        # explicit on BOTH paths: the native branch previously fell through
        # to the Python list comprehension when ranges was empty, silently
        # taking the fallback path despite has_frame_many being true
        return []
    lib = _native()
    if lib is not None and getattr(lib, "has_frame_many", False):
        starts = np.fromiter((s for s, _ in ranges), np.int64, len(ranges))
        ends = np.fromiter((e for _, e in ranges), np.int64, len(ranges))
        n, stride = rows.shape
        scratch = arena.acquire(n * (stride + 16) + 16) if arena else None
        dst, off, ln, kept = lib.frame_many(
            rows, lens, keep, starts, ends, out=scratch
        )
        parts = [
            (dst[off[i] : off[i] + ln[i]].tobytes(), int(kept[i]))
            for i in range(len(ranges))
        ]
        if arena is not None:
            arena.release(dst)
            if dst is not scratch:
                # the binding replaced an undersized scratch; keep the old
                # buffer too — it can still serve a smaller launch
                arena.release(scratch)
        return parts
    return [frame_records(rows[s:e], lens[s:e], keep[s:e]) for s, e in ranges]


def _frame_gather_py(
    src, offsets, lens, keep, start: int, end: int
) -> tuple[bytes, int]:
    """Python gather framing for one range — bit-identical to
    rp_frame_gather (and to frame_records over packed rows, which the
    parity tests assert)."""
    out = bytearray()
    seq = 0
    for i in range(start, end):
        if not keep[i]:
            continue
        o = int(offsets[i])
        vlen = max(int(lens[i]), 0)
        body = bytearray()
        body += b"\x00"
        body += encode_zigzag(0)
        body += encode_zigzag(seq)
        body += encode_zigzag(-1)
        body += encode_zigzag(vlen)
        body += src[o : o + vlen]
        body += encode_zigzag(0)
        out += encode_zigzag(len(body))
        out += body
        seq += 1
    return bytes(out), seq


def frame_ranges_gather(
    src,
    offsets: np.ndarray,
    lens: np.ndarray,
    keep: np.ndarray,
    ranges: list[tuple[int, int]],
    arena: Arena | None = None,
) -> list[tuple[bytes, int]]:
    """ZERO-COPY launch framing (rp_frame_many_gather): kept records frame
    straight from ``src`` (the launch's joined blob) via per-record
    (offset, len) columns — the padded row matrix the padded path builds
    just to copy from never exists. Output is byte-identical to
    ``frame_ranges`` over rows packed from the same (offset, len) table;
    the engine picks this path only for byte-identity transforms
    (columnar passthrough, host identity)."""
    if not ranges:
        return []
    lib = _native()
    if lib is not None and getattr(lib, "has_frame_many_gather", False):
        starts = np.fromiter((s for s, _ in ranges), np.int64, len(ranges))
        ends = np.fromiter((e for _, e in ranges), np.int64, len(ranges))
        n = len(offsets)
        scratch = (
            arena.acquire(int(np.maximum(lens, 0).sum()) + 16 * n + 16)
            if arena
            else None
        )
        dst, off, ln, kept = lib.frame_many_gather(
            src, offsets, lens, keep, starts, ends, out=scratch
        )
        parts = [
            (dst[off[i] : off[i] + ln[i]].tobytes(), int(kept[i]))
            for i in range(len(ranges))
        ]
        if arena is not None:
            arena.release(dst)
            if dst is not scratch:
                arena.release(scratch)
        return parts
    return [
        _frame_gather_py(src, offsets, lens, keep, s, e) for s, e in ranges
    ]


def build_output_batch(
    source: RecordBatch,
    payload: bytes,
    kept: int,
    *,
    compress_threshold: int = 512,
    codec: Compression = Compression.zstd,
) -> RecordBatch | None:
    """Seal a framed payload into a materialized output batch.

    Mirrors the reference's write side (script_context_backend.cc:40-68):
    term reset, zstd recompression above a size threshold, fresh CRCs.
    Returns None when no record survives the transform.
    """
    if kept == 0:
        return None
    attrs = 0
    if len(payload) >= compress_threshold and codec != Compression.none:
        if not registry.is_available(codec):
            # degrade, don't drop: a missing optional codec library must
            # not silently discard every transformed batch (gzip is stdlib)
            codec = Compression.gzip
        payload = compress(payload, codec)
        attrs = int(codec)
    hdr = RecordBatchHeader(
        base_offset=0,  # assigned by the materialized log appender
        type=source.header.type,
        attrs=attrs,
        last_offset_delta=kept - 1,
        first_timestamp=source.header.first_timestamp,
        max_timestamp=source.header.max_timestamp,
        record_count=kept,
        term=0,
    )
    batch = RecordBatch(hdr, payload)
    batch.reseal()
    return batch


def rebuild_batch(
    source: RecordBatch,
    rows: np.ndarray,
    lens: np.ndarray,
    keep: np.ndarray,
    *,
    compress_threshold: int = 512,
    codec: Compression = Compression.zstd,
) -> RecordBatch | None:
    """Single-batch rebuild (frame + seal); the engine's launch path uses
    frame_ranges + build_output_batch to amortize the native crossing."""
    payload, kept = frame_records(rows, lens, keep)
    return build_output_batch(
        source, payload, kept,
        compress_threshold=compress_threshold, codec=codec,
    )
