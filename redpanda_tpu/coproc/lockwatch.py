"""coproc lockwatch: the runtime half of the pandaraces cross-check.

With ``coproc_lockwatch=true`` the engine's named locks are wrapped in a
recorder that journals every lock-ORDER edge it observes: acquiring lock
B while holding lock A (on the acquiring thread) is the edge ``A -> B``.
The edge set is what a dynamic deadlock detector would build; here its
job is to VALIDATE the static analyzer — a test runs the chaos parity
workload under lockwatch and asserts the observed edge set is a subgraph
of pandalint's static acquisition graph (tools/pandalint/lockgraph.py),
so the analyzer's call-resolution blind spots surface as test failures
instead of silent false-green gates.

Zero cost when off — the contract the ISSUE pins:

- ``wrap(lock, name)`` returns the RAW lock object untouched unless
  lockwatch was enabled before the owning object was constructed; the
  steady-state engine carries plain ``threading.Lock``s and pays one
  flag check per lock CONSTRUCTION, nothing per acquisition.
- ``enable()`` flips the flag and swaps the module-level locks
  (``engine._mask_claim_lock``, ``faults._pool_lock``/``_warned_lock``)
  for wrapped twins; ``disable()`` restores the originals. Per-object
  locks (engine, launches, pools, breakers) pick the wrapper up at
  construction, so enable() must run BEFORE the engine is built —
  CoprocApi does this off the config knob.

Canonical lock names deliberately match the static analyzer's identity
scheme (``Class.attr`` for instance/class locks, ``module.name`` for
module globals): the subgraph comparison is a set comparison on names.

Each NEWLY discovered edge journals a ``lockwatch`` governor decision
(GET /v1/governor, rpk debug governor) and bumps
``coproc_lockwatch_edges_total``; repeat observations are two set
lookups. The decision journal's own lock is intentionally NOT wrapped —
it is the recording channel, and wrapping it would recurse.
"""

from __future__ import annotations

import threading

_enabled = False
_state_lock = threading.Lock()
# (held_name, acquired_name) -> True, discovered this process
_edges: dict[tuple[str, str], bool] = {}
# locals of each thread: stack of lock names currently held (wrapped only)
_tls = threading.local()

# module-level locks swapped at enable(): (module, attr, canonical name)
_MODULE_LOCKS = (
    ("redpanda_tpu.coproc.engine", "_mask_claim_lock", "engine._mask_claim_lock"),
    ("redpanda_tpu.coproc.faults", "_pool_lock", "faults._pool_lock"),
    ("redpanda_tpu.coproc.faults", "_warned_lock", "faults._warned_lock"),
)
_swapped: list[tuple[object, str, object]] = []  # (module, attr, original)


def enabled() -> bool:
    return _enabled


def _held_stack() -> list[str]:
    stack = getattr(_tls, "stack", None)
    if stack is None:
        stack = _tls.stack = []
    return stack


class WatchedLock:
    """Context-manager/lock wrapper recording acquisition-order edges.

    Not reentrant-aware beyond what the wrapped lock is; `with` blocks
    release LIFO, raw acquire/release pairs are matched by name."""

    __slots__ = ("_lock", "name")

    def __init__(self, lock, name: str):
        self._lock = lock
        self.name = name

    def _note_acquired(self) -> None:
        stack = _held_stack()
        if _enabled:  # wrappers outlive disable(); they go quiet, not away
            for held in stack:
                if held != self.name:
                    _record_edge(held, self.name)
        stack.append(self.name)

    def acquire(self, *a, **kw):
        got = self._lock.acquire(*a, **kw)
        if got:
            self._note_acquired()
        return got

    def release(self) -> None:
        stack = _held_stack()
        # LIFO for with-blocks; tolerate out-of-order raw release
        for i in range(len(stack) - 1, -1, -1):
            if stack[i] == self.name:
                del stack[i]
                break
        self._lock.release()

    def __enter__(self):
        self._lock.acquire()
        self._note_acquired()
        return self

    def __exit__(self, *exc):
        self.release()
        return False

    def locked(self):
        return self._lock.locked()


def wrap(lock, name: str):
    """The ONE construction-time hook: returns `lock` untouched when
    lockwatch is off (zero steady-state overhead, no wrapper installed),
    a WatchedLock when on."""
    if not _enabled:
        return lock
    return WatchedLock(lock, name)


def _record_edge(src: str, dst: str) -> None:
    key = (src, dst)
    with _state_lock:
        if key in _edges:
            return
        _edges[key] = True
        n = len(_edges)
    # outside _state_lock: the journal and its counter take their own
    # (unwrapped) locks; _state_lock must stay a leaf
    from redpanda_tpu.coproc import governor
    from redpanda_tpu.observability import probes

    probes.coproc_lockwatch_edges.inc()
    governor.journal_record(
        governor.LOCKWATCH,
        "edge",
        f"observed lock-order edge {src} -> {dst} (#{n} this process); "
        f"the static acquisition graph must contain it",
        {"from": src, "to": dst, "edges_total": n},
    )


def edges() -> list[tuple[str, str]]:
    with _state_lock:
        return sorted(_edges)


def reset_edges() -> None:
    with _state_lock:
        _edges.clear()


def snapshot() -> dict:
    with _state_lock:
        return {"enabled": _enabled, "edges": len(_edges)}


def enable() -> None:
    """Flip lockwatch on and swap the module-level locks. Call BEFORE
    constructing engines: per-object locks bind at construction."""
    global _enabled
    import importlib

    with _state_lock:
        if _enabled:
            return
        _enabled = True
    for modname, attr, canonical in _MODULE_LOCKS:
        mod = importlib.import_module(modname)
        original = getattr(mod, attr)
        if isinstance(original, WatchedLock):  # pragma: no cover - defensive
            continue
        setattr(mod, attr, WatchedLock(original, canonical))
        _swapped.append((mod, attr, original))


def disable() -> None:
    """Restore the raw module locks and stop wrapping. Engines built
    while enabled keep their (now inert but harmless) wrappers."""
    global _enabled
    with _state_lock:
        _enabled = False
    while _swapped:
        mod, attr, original = _swapped.pop()
        setattr(mod, attr, original)
