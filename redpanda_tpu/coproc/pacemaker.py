"""Coproc pacemaker + script contexts: the steady-state transform loop.

Parity with coproc/pacemaker.h:41-145 and script_context.cc:47-135:
one ``ScriptContext`` fiber per deployed script runs
  read_from_inputs (per-ntp, from last_acked+1 up to the LSO, bounded by
  coproc_max_batch_size and the shared inflight-bytes semaphore,
  script_context_frontend.cc:80-117)
  → engine.process_batch (the TPU engine replaces the Node.js RPC hop)
  → write_materialized (CRC-checked, recompressed batches appended
  DIRECTLY to the materialized storage log, bypassing raft —
  script_context_backend.cc:40-68)
  → advance last_acked.
Offsets are snapshotted per flush interval into the kvstore's coproc
keyspace and recovered on startup (offset_storage_utils.cc:36-104).
"""

from __future__ import annotations

import asyncio
import json
import logging
import os
import time
from concurrent.futures import ThreadPoolExecutor

from redpanda_tpu.coproc import faults, leakwatch
from redpanda_tpu.coproc.engine import (
    ProcessBatchItem,
    ProcessBatchRequest,
    TpuEngine,
)
from redpanda_tpu.models.fundamental import NTP, MaterializedNTP
from redpanda_tpu.observability.trace import tracer
from redpanda_tpu.resource_mgmt.admission import ShedError
from redpanda_tpu.resource_mgmt.budgets import MemoryAccount
from redpanda_tpu.storage.kvstore import KeySpace

logger = logging.getLogger("rptpu.coproc.pacemaker")


class _StopScript(Exception):
    """Raised inside a script's own fiber to end it (deregistration from
    within tick — the fiber cannot await its own cancellation)."""


def _release_abandoned(engine):
    """Done-callback for a submit future whose tick gave up waiting: the
    orphan ticket will never be harvested, so its admission reservation
    releases here (a failed submit released its own in submit_group)."""

    def cb(fut):
        try:
            ticket = fut.result()
        except BaseException:  # pandalint: disable=EXC901 -- not a swallow: a raising submit released its own reservation and already classified the failure inside submit_group; this callback only exists for the SUCCESS-after-abandon path
            return
        engine._release_admission(ticket)

    return cb


class ScriptContext:
    def __init__(
        self,
        pacemaker: "Pacemaker",
        script_id: int,
        name: str,
        input_topics: tuple[str, ...],
    ) -> None:
        self.pacemaker = pacemaker
        self.script_id = script_id
        self.name = name
        self.input_topics = input_topics
        # per input ntp: offsets {last_read, last_acked}
        # (ntp_context.h:54-60 offset_tracker)
        self.offsets: dict[NTP, int] = {}
        self._task: asyncio.Task | None = None

    def start(self) -> None:
        self._task = asyncio.create_task(self._loop())

    async def stop(self) -> None:
        if self._task is not None:
            self._task.cancel()
            try:
                await self._task
            except asyncio.CancelledError:
                pass
            self._task = None

    async def _loop(self) -> None:
        """do_execute (script_context.cc:66): run ticks until cancelled;
        jittered idle sleep when no input advanced, exponential backoff on
        consecutive tick failures (a dead engine must not busy-spin reads).

        The retry posture IS the loop: a failed/timed-out tick advanced no
        offsets and wrote nothing, so the next tick re-reads the same
        records — bounded only by backoff, never by a give-up that would
        strand input."""
        pm = self.pacemaker
        failures = 0
        while True:
            try:
                moved = await self.tick()
                failures = 0
            except asyncio.CancelledError:
                raise
            except _StopScript:
                return
            except Exception as exc:
                failures += 1
                faults.note_failure("pacemaker_tick", exc)
                if failures == 1:
                    logger.exception("script %s tick failed", self.name)
                else:
                    logger.debug(
                        "script %s tick failed again (%d consecutive): %r",
                        self.name, failures, exc,
                    )
                moved = False
            if not moved:
                delay = pm.idle_sleep_s
                if failures:
                    delay = min(
                        pm.idle_sleep_s * (2 ** min(failures, 7)), 5.0
                    )
                await asyncio.sleep(delay)

    async def tick(self) -> bool:
        """One read → transform → write round; True if any offset moved.

        Offsets advance ONLY after the materialized write lands
        (script_context.cc's read → process → write → last_acked order) —
        advancing at read time would drop records on any write failure.
        """
        pm = self.pacemaker
        knobs = pm.launch_knobs()
        items = []
        read_high: dict[NTP, int] = {}
        t_read0 = time.perf_counter()
        # group_ticks_per_launch fuses N ticks' worth of input into one
        # launch (deeper batching amortizes the device round trip; the
        # governor shrinks it back to 1 under memory pressure)
        read_budget = pm.max_batch_size * knobs["group_ticks"]
        for ntp in self._input_ntps():
            batches = await self._read_ntp(ntp, read_budget)
            if batches:
                items.append(ProcessBatchItem(self.script_id, ntp, batches))
                read_high[ntp] = batches[-1].last_offset
        if not items:
            return False
        # One trace per productive tick (idle ticks would drown the ring);
        # the read phase is back-dated into it once we know work exists.
        with tracer.span(
            "coproc.tick", root=True,
            node=self.pacemaker.broker.config.node_id,
        ) as tick_span:
            tracer.record(
                "coproc.read",
                (time.perf_counter() - t_read0) * 1e6,
                tick_span.trace_id,
                start_perf=t_read0,
            )
            # Submit AND harvest run in worker threads: the first dispatch of
            # a spec jit-compiles for seconds, and anything that blocks the
            # broker's event loop that long stops raft heartbeats and forces
            # cluster-wide re-elections (measured: every group re-elected
            # ~10s after the first deploy when submit ran on-loop).
            loop = asyncio.get_running_loop()
            req = ProcessBatchRequest(items, trace_id=tick_span.trace_id)
            ex = pm.engine_executor
            # tick deadline: the engine's internal deadlines bound every
            # device leg, so these only fire when that machinery is itself
            # wedged. A timed-out executor call is ABANDONED, not retried
            # in place: its ticket is never harvested, so nothing is
            # written (no duplicates), and the un-advanced offsets make the
            # next tick re-read the same records (no loss). The governor
            # may have adaptively RAISED per-domain deadlines since the
            # static backstop was sized at startup, so re-derive per tick:
            # the backstop must always sit above the engine's own envelope
            # or it would abandon legitimately mid-envelope ticks.
            deadline_s = pm.tick_deadline_for(pm.engine)
            # launch_depth bounds concurrent submit+harvest regions across
            # every script fiber: the staged bytes of at most depth
            # launches are in flight, which is what keeps the coproc
            # account's occupancy (and so the pressure signal) meaningful
            async with pm._launch_cond:
                while pm._launch_inflight >= knobs["launch_depth"]:
                    await pm._launch_cond.wait()
                pm._launch_inflight += 1
            shed_retry_s = None
            try:
                sub_fut = loop.run_in_executor(ex, pm.engine.submit, req)
                try:
                    with tracer.span("coproc.submit.wait"):
                        ticket = await asyncio.wait_for(
                            asyncio.shield(sub_fut), timeout=deadline_s
                        )
                except (asyncio.TimeoutError, asyncio.CancelledError):
                    # timeout OR fiber cancellation (script removal): the
                    # executor thread cannot be cancelled, and the shielded
                    # submit's eventual ticket will never be harvested —
                    # hand its reservation back or the account ratchets
                    # shut one abandoned tick at a time
                    sub_fut.add_done_callback(_release_abandoned(pm.engine))
                    raise
                res_fut = loop.run_in_executor(ex, ticket.result)
                try:
                    with tracer.span("coproc.harvest.wait"):
                        reply = await asyncio.wait_for(
                            asyncio.shield(res_fut), timeout=deadline_s
                        )
                except (asyncio.TimeoutError, asyncio.CancelledError):
                    # shield the work item too: an un-started queued
                    # result() would otherwise be CANCELLED outright and
                    # its finally (the release) never run. Release here
                    # for promptness — _release_admission is atomic and
                    # idempotent, so the racing executor-side finally is
                    # harmless either way.
                    pm.engine._release_admission(ticket)
                    raise
            except ShedError as exc:
                # admission refused the staged bytes BEFORE any dispatch:
                # no offsets moved, nothing was written — back off the
                # throttle hint and re-read the same records (counted via
                # coproc_admission_shed_total, journaled as an ADMISSION
                # shed episode; not a fault, so no note_failure here)
                logger.debug(
                    "script %s submit shed: %s", self.name, exc
                )
                shed_retry_s = min(exc.retry_after_ms / 1000.0, 5.0)
            finally:
                async with pm._launch_cond:
                    pm._launch_inflight -= 1
                    pm._launch_cond.notify_all()
            if shed_retry_s is not None:
                # backoff OUTSIDE the depth gate: under a floored depth a
                # shed script sleeping inside the slot would head-of-line
                # block every other script's admissible launch
                await asyncio.sleep(shed_retry_s)
                return False
            if self.script_id in reply.deregistered:
                logger.warning("script %s deregistered by engine policy", self.name)
                pm.detach_script(self.name)
                self._task = None
                raise _StopScript()
            moved = False
            with tracer.span("coproc.write"):
                for item in reply.items:
                    if await self._write_materialized(item.source, item.batches):
                        self.offsets[item.source] = read_high[item.source]
                        moved = True
            if moved:
                # append-invalidation hook for the device column cache:
                # this script's input window just advanced, so its cached
                # columns can never be re-read (the cache key is
                # content-addressed — this reclaims memory, it is not
                # what keeps hits correct)
                pm.engine.invalidate_columns(self.script_id)
            return moved

    def _input_ntps(self) -> list[NTP]:
        out = []
        for topic in self.input_topics:
            md = self.pacemaker.broker.topic_table.get(topic)
            if md is None:
                continue
            out.extend(pa.ntp for pa in md.assignments.values())
        return out

    async def _read_ntp(self, ntp: NTP, max_bytes: int | None = None) -> list:
        """read_ntp (script_context_frontend.cc:80-98): from last_acked+1 up
        to the LSO, bounded by the read budget (max batch size scaled by
        the group_ticks launch knob) + the read semaphore."""
        pm = self.pacemaker
        p = pm.broker.partition_manager.get(ntp)
        if p is None or not p.is_leader():
            return []
        start = self.offsets.get(ntp, p.start_offset - 1) + 1
        lso = p.last_stable_offset  # exclusive
        if start >= lso:
            return []
        budget = max_bytes if max_bytes is not None else pm.max_batch_size
        reserved = await pm.read_budget.acquire(budget)
        try:
            # read what was RESERVED, not what was asked: an oversized
            # budget clamps to the whole account and must read that much,
            # or the bytes in flight exceed the bound they reserved against
            return await p.make_reader(start, reserved, max_offset=lso - 1)
        finally:
            pm.read_budget.release(reserved)

    async def _write_materialized(self, source: NTP, batches: list) -> bool:
        """do_write_materialized_partition (script_context_backend.cc:40-68):
        CRC check + append directly to the materialized log, no raft.
        Returns True when the source's offset may advance."""
        if not batches:
            return True  # everything filtered out: the read is still acked
        pm = self.pacemaker
        mntp = MaterializedNTP(source, self.name).ntp
        partition = await pm.ensure_materialized(source, mntp)
        if partition is None:
            return False  # create raced/failed: retry this read next tick
        good = []
        for b in batches:
            if b.verify_kafka_crc():
                good.append(b)
            else:
                logger.error("dropping corrupt transformed batch for %s", mntp)
        if good:
            await partition.replicate(good, 2)  # no_ack: direct log write
        return True


class Pacemaker:
    def __init__(
        self,
        broker,
        engine: TpuEngine,
        *,
        max_batch_size: int = 32 * 1024,
        max_inflight_reads: int = 8,
        offset_flush_interval_s: float = 5.0,
        idle_sleep_s: float = 0.05,
        tick_deadline_s: float = 120.0,
        group_ticks_per_launch: int = 1,
        launch_depth: int = 4,
    ) -> None:
        self.broker = broker
        self.engine = engine
        self.max_batch_size = max_batch_size
        self.tick_deadline_s = tick_deadline_s
        # The read bound is BYTE-denominated (a FIFO-waiting account of
        # max_inflight_reads * max_batch_size bytes), not a read-count
        # semaphore: the group_ticks launch knob scales each read's byte
        # budget, and a count-based gate sized for one-tick reads would
        # let concurrent buffers reach group_ticks_cap x the configured
        # coproc_max_inflight_bytes. An oversized single read clamps to
        # the whole account and proceeds alone (MemoryAccount semantics).
        self.read_budget = leakwatch.wrap(
            MemoryAccount(
                "coproc_read",
                max(1, int(max_inflight_reads)) * max(1, int(max_batch_size)),
            ),
            "pacemaker.read_budget",
        )
        # launch knobs (resource_mgmt / governor ADMISSION domain):
        # group_ticks_per_launch scales how many ticks' worth of input one
        # launch fuses (the read budget per ntp), launch_depth bounds
        # concurrent submit+harvest regions across ALL scripts. Static
        # here; when the engine's governor has autotune configured
        # (CoprocApi does), launch_knobs() returns ITS hysteresis-bounded
        # dynamic verdicts instead — the engine trades launch depth for
        # latency as memory pressure rises.
        self.group_ticks_per_launch = max(1, int(group_ticks_per_launch))
        self.launch_depth = max(1, int(launch_depth))
        self._launch_inflight = 0
        self._launch_cond = asyncio.Condition()
        self.offset_flush_interval_s = offset_flush_interval_s
        self.idle_sleep_s = idle_sleep_s
        self._scripts: dict[str, ScriptContext] = {}
        self._flush_task: asyncio.Task | None = None
        self._materialized_locks: dict[NTP, asyncio.Lock] = {}
        # Dedicated executor for engine submit/harvest: these block for a
        # whole launch (sharded host stages + a device round trip), and on
        # the loop's DEFAULT executor they would starve every
        # asyncio.to_thread user in the broker (storage/archival blocking
        # I/O shares that pool). Lazily created; sized like the default
        # executor it replaced — a harvest can block up to the 30s mask
        # timeout, so a small fixed cap would head-of-line block every
        # other script's tick behind a few wedged fetches.
        self._engine_executor: ThreadPoolExecutor | None = None

    def launch_knobs(self) -> dict:
        """Effective {"group_ticks", "launch_depth"} for the next tick:
        the governor's dynamic verdict when its autotune is configured
        (journaled, hysteresis-bounded), the static constructor knobs for
        bare engines/test doubles."""
        gov = getattr(self.engine, "governor", None)
        if gov is not None and gov.autotune_snapshot() is not None:
            return gov.launch_knobs()
        return {
            "group_ticks": self.group_ticks_per_launch,
            "launch_depth": self.launch_depth,
        }

    def tick_deadline_for(self, engine) -> float:
        """Effective tick backstop: the configured static deadline, never
        below 4x the engine's worst-case per-domain retry envelope (the
        governor can raise per-domain deadlines adaptively at runtime; a
        backstop sized once at startup would then fire on healthy-but-slow
        ticks). Engines without a governor (bare test doubles) keep the
        static value."""
        gov = getattr(engine, "governor", None)
        if gov is None:
            return self.tick_deadline_s
        return max(self.tick_deadline_s, 4.0 * gov.max_envelope_s())

    @property
    def engine_executor(self) -> ThreadPoolExecutor:
        if self._engine_executor is None:
            self._engine_executor = ThreadPoolExecutor(
                max_workers=min(32, (os.cpu_count() or 1) + 4),
                thread_name_prefix="rptpu-coproc-tick",
            )
        return self._engine_executor

    # ------------------------------------------------------------ lifecycle
    async def start(self) -> "Pacemaker":
        self._recover_offsets()
        self._flush_task = asyncio.create_task(self._flush_loop())
        return self

    async def stop(self) -> None:
        if self._flush_task is not None:
            self._flush_task.cancel()
            try:
                await self._flush_task
            except asyncio.CancelledError:
                pass
            self._flush_task = None
        for ctx in list(self._scripts.values()):
            await ctx.stop()
        self._save_offsets()
        self._scripts.clear()
        if self._engine_executor is not None:
            # fibers are stopped, nothing new can be submitted; don't block
            # broker shutdown on a straggling harvest
            self._engine_executor.shutdown(wait=False)
            self._engine_executor = None

    # ------------------------------------------------------------ scripts
    async def add_source(self, name: str, script_id: int, input_topics: tuple[str, ...]) -> None:
        """pacemaker.h:75 add_source: one fiber per script."""
        if name in self._scripts:
            return
        ctx = ScriptContext(self, script_id, name, input_topics)
        for key, off in self._saved_offsets().get(name, {}).items():
            ns, topic, part = key.rsplit("/", 2)
            ctx.offsets[NTP(ns, topic, int(part))] = off
        self._scripts[name] = ctx
        ctx.start()

    async def remove_script(self, name: str) -> None:
        ctx = self._scripts.pop(name, None)
        if ctx is not None:
            await ctx.stop()

    def detach_script(self, name: str) -> None:
        """Unregister without awaiting the fiber (used from INSIDE the
        fiber, which then exits via _StopScript)."""
        self._scripts.pop(name, None)

    def scripts(self) -> dict[str, ScriptContext]:
        return dict(self._scripts)

    # ------------------------------------------------------------ materialized logs
    async def ensure_materialized(self, source: NTP, mntp: NTP):
        """Create the materialized topic/partition on demand under a
        per-ntp mutex (script_context_backend.cc:70-78)."""
        lock = self._materialized_locks.setdefault(mntp, asyncio.Lock())
        async with lock:
            p = self.broker.partition_manager.get(mntp)
            if p is not None:
                return p
            if not self.broker.topic_table.contains(mntp.topic):
                from redpanda_tpu.cluster.topic_table import TopicConfig

                src_md = self.broker.topic_table.get(source.topic)
                n_parts = src_md.config.partition_count if src_md else 1
                try:
                    dispatcher = getattr(self.broker, "controller_dispatcher", None)
                    if dispatcher is not None:
                        # Clustered: replicate create_non_replicable_topic
                        # so every broker's metadata agrees; assignments
                        # mirror the source (group -1, coproc writes bypass
                        # raft — commands.h:112 non_replicable semantics)
                        from redpanda_tpu.cluster.service import (
                            OP_CREATE_NON_REPLICABLE,
                        )

                        await dispatcher.topic_op(  # pandalint: disable=LCK702 -- create-once-per-mntp mutex: a serialized tick beats duplicate create ops racing the controller
                            OP_CREATE_NON_REPLICABLE,
                            {"source": source.topic, "name": mntp.topic,
                             "ns": mntp.ns},
                        )
                        await self.broker._await_topic_table(
                            lambda: self.broker.topic_table.contains(mntp.topic),
                            f"materialize {mntp.topic}",
                        )
                    else:
                        # Standalone: the materialized log lives NEXT TO its
                        # source partition (script_context_backend.cc:70-78
                        # direct storage append, no raft)
                        await self.broker.create_topic(
                            TopicConfig(mntp.topic, n_parts, 1, ns=mntp.ns),
                            local_only=True,
                        )
                except ValueError:
                    pass
            # the local log: reconciled by the backend (clustered) or
            # created by the local path above
            p = self.broker.partition_manager.get(mntp)
            if p is None:
                for _ in range(100):
                    await asyncio.sleep(0.05)
                    p = self.broker.partition_manager.get(mntp)
                    if p is not None:
                        break
            return p

    # ------------------------------------------------------------ offsets
    def _kvs(self):
        return self.broker.storage.kvs

    def _saved_offsets(self) -> dict[str, dict[str, int]]:
        raw = self._kvs().get(KeySpace.coproc, b"offsets")
        return json.loads(raw.decode()) if raw else {}

    def _save_offsets(self) -> None:
        data = {
            name: {
                f"{ntp.ns}/{ntp.topic}/{ntp.partition}": off
                for ntp, off in ctx.offsets.items()
            }
            for name, ctx in self._scripts.items()
        }
        self._kvs().put(KeySpace.coproc, b"offsets", json.dumps(data).encode())

    def _recover_offsets(self) -> None:
        # contexts pick their saved offsets up in add_source
        pass

    async def _flush_loop(self) -> None:
        while True:
            await asyncio.sleep(self.offset_flush_interval_s)
            try:
                self._save_offsets()
            except Exception as exc:
                # classified: losing offset snapshots silently would turn a
                # later restart into a giant re-read with no warning
                faults.note_failure("offset_flush", exc)
                logger.exception("coproc offset flush failed")
