"""Execution planning for transform specs: columnar / payload / host.

The engine's measured link profile (tools/link_probe.py on the axon tunnel:
H2D ~15-70 MB/s, D2H ~3-14 MB/s, ~70 ms per synchronous round trip) makes
shipping record payloads to the device a guaranteed loss: a 64-partition
tick moves ~2.4 MB of padded rows each way while the transform itself needs
microseconds of compute. The reference hit the same wall in miniature — its
supervisor RPC ships batches to a sidecar process (coproc/script_context.cc
send_request) — and answered with batching; we answer with *pushdown*:

- **columnar** (v2 ``where`` expression specs): the native columnarizer
  (native/redpanda_native.cc rp_extract_*) turns each referenced field into
  a fixed-width column — a few bytes per record. The device evaluates the
  whole predicate tree over the columns and returns ONE BIT per record
  (bit-packed, so D2H is n/8 bytes). Projections are assembled host-side
  from columns the host already extracted; output framing/compression/CRC
  were always host work (ops/pipeline.py module docs).
- **payload** (v1 raw-byte specs: filter_contains, map_uppercase with
  filters): the original full-row staging pipeline. Correct everywhere,
  fast only when the device link is wide (co-located PCIe/ICI).
- **host** (identity, pure uppercase, py_transform escape hatch): no device
  stage exists or none is warranted; runs in the engine's host stage with
  the same interface and semantics.

`plan_spec` is the single decision point; `ColumnarPlan.compile_device`
builds the jitted predicate program (optionally SPMD over a mesh partition
axis), and `assemble_rows` materializes projection outputs.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass, field as dc_field

import numpy as np

from redpanda_tpu.ops import exprs as E
from redpanda_tpu.ops.transforms import (
    Concat,
    Float,
    Int,
    Str,
    Substr,
    TransformSpec,
    _MapProject,
    _MapUppercase,
    project_out_width,
)

_INT9 = 999_999_999  # v1 projection rule: ints limited to 9 digits


# ------------------------------------------------------------------ columns
@dataclass(frozen=True)
class DevCol:
    """One device input column; kind in {str, num, exists}."""

    kind: str
    path: str
    w: int = 0  # str byte width (merged across uses)


# input arrays contributed per DevCol kind: str -> (bytes, vlen),
# num -> (f32, i32, flags), exists -> (present,)
_COL_ARITY = {"str": 2, "num": 3, "exists": 1}

# DevCol kind -> rp_extract_cols2 desc kind code
_PRED_KIND = {"num": 0, "str": 1, "exists": 2}


class FindCache:
    """Span tables from ONE native JSON walk per record for every
    single-segment path a plan references (rp_find_multi) — the extractors
    gather from these tables instead of re-walking the record per field."""

    def __init__(self, lib, joined, offsets, sizes, paths: list[str]):
        self._lib = lib
        self._joined = joined
        self._offsets = np.ascontiguousarray(offsets, dtype=np.int64)
        self.col = {p: i for i, p in enumerate(paths)}
        self.types, self.vs, self.ve = lib.find_multi(joined, offsets, sizes, paths)

    @classmethod
    def from_tables(cls, lib, joined, offsets, paths, types, vs, ve) -> "FindCache":
        """Wrap span tables the fused explode_find pass already produced
        (same layout as find_multi's) without re-walking anything."""
        self = cls.__new__(cls)
        self._lib = lib
        self._joined = joined
        self._offsets = np.ascontiguousarray(offsets, dtype=np.int64)
        self.col = {p: i for i, p in enumerate(paths)}
        self.types, self.vs, self.ve = types, vs, ve
        return self

    def gather_str(self, path: str, w: int):
        i = self.col[path]
        return self._lib.gather_str(
            self._joined, self._offsets,
            self.types[:, i], self.vs[:, i], self.ve[:, i], w,
        )

    def gather_num(self, path: str):
        i = self.col[path]
        return self._lib.gather_num(
            self._joined, self._offsets,
            self.types[:, i], self.vs[:, i], self.ve[:, i],
        )

    def gather_exists(self, path: str):
        i = self.col[path]
        return (self.types[:, i] != 0).astype(np.uint8)


@dataclass
class ColumnarPlan:
    spec: TransformSpec
    dev_cols: list[DevCol]
    proj: tuple  # projection fields (may be empty -> passthrough)
    r_out: int
    passthrough: bool  # no projection: output = input value bytes
    _fn_cache: dict = dc_field(default_factory=dict)
    # compile_device may be reached from host-pool shard workers and
    # concurrent submitters; first-touch jit is seconds, so a racy
    # check-then-compile would trace the same predicate N times
    _fn_lock: threading.Lock = dc_field(default_factory=threading.Lock)

    mode = "columnar"

    @property
    def byte_identity(self) -> bool:
        """True when the transform's output bytes ARE the input value bytes
        (a pure filter: no projection mutates anything). The engine's
        zero-copy harvest gathers framed output straight from the launch's
        joined blob via (offset, len) — legal exactly when this holds; any
        projection assembles new bytes and must keep the padded path."""
        return self.passthrough

    def flat_paths(self) -> list[str]:
        """Distinct TOP-LEVEL (single-segment) paths the plan references;
        nested paths keep the per-path walker."""
        seen: dict[str, None] = {}
        for c in self.dev_cols:
            seen.setdefault(c.path)
        for f in self.proj:
            if isinstance(f, Concat):
                seen.setdefault(f.a)
                seen.setdefault(f.b)
            else:
                seen.setdefault(f.key)
        return [p for p in seen if "." not in p]

    def build_find_cache(self, joined, offsets, sizes) -> FindCache | None:
        lib = _native()
        if lib is None or not getattr(lib, "has_find_multi", False):
            return None
        paths = self.flat_paths()
        if not paths:
            return None
        return FindCache(lib, joined, offsets, sizes, paths)

    def make_cache_from_tables(self, exploded, paths, types, vs, ve) -> FindCache:
        """Adopt the span tables the fused explode_find pass produced.
        `paths` MUST be the exact list the fused call used — the table
        columns are ordered by it."""
        return FindCache.from_tables(
            _native(), exploded.joined, exploded.offsets, paths,
            types, vs, ve,
        )

    def _bind_slots(self, arrays) -> dict:
        """Ordered input arrays -> {(kind, path): arrays} slot map — the ONE
        place that knows the per-kind arity (str=2, num=3, exists=1); the
        device predicate, the host ablation, and extract_device_inputs all
        stay aligned through it."""
        slots = {}
        k = 0
        for c in self.dev_cols:
            arity = _COL_ARITY[c.kind]
            slots[(c.kind, c.path)] = (
                arrays[k] if arity == 1 else tuple(arrays[k : k + arity])
            )
            k += arity
        return slots

    # ------------------------------------------------------------ device
    def compile_device(self, mesh=None):
        """jit fn(*cols) -> packed keep bits (uint8 [n/8]).

        Each DevCol contributes inputs in order: str -> (bytes [n, w] u8,
        vlen [n] i32); num -> (f32 [n], i32 [n], flags [n] u8);
        exists -> (u8 [n]). Rows shard over `mesh`'s 'p' axis when given.
        """
        key = id(mesh) if mesh is not None else None
        fn = self._fn_cache.get(key)
        if fn is not None:
            return fn
        with self._fn_lock:
            fn = self._fn_cache.get(key)
            if fn is not None:
                return fn
            return self._compile_device_locked(key, mesh)

    def _compile_device_locked(self, key, mesh):
        import jax
        import jax.numpy as jnp

        expr = self.spec.where
        # comparison constants are converted HOST-side, once, before the
        # traced function exists: float()/int()/np.* inside the predicate is
        # exactly the hot-path impurity pandalint HPS201/HPN211 flags
        consts = _prepare_cmp_consts(expr)

        def predicate(*arrays):
            keep = _build_expr(jnp, expr, self._bind_slots(arrays), consts)
            return _packbits(jnp, keep)

        if mesh is None:
            fn = jax.jit(predicate)
        else:
            from jax.sharding import NamedSharding, PartitionSpec

            row_sharded = NamedSharding(mesh, PartitionSpec("p"))
            shardings = []
            for c in self.dev_cols:
                shardings += [row_sharded] * _COL_ARITY[c.kind]
            fn = jax.jit(
                predicate,
                in_shardings=tuple(shardings),
                out_shardings=NamedSharding(mesh, PartitionSpec()),
            )
        self._fn_cache[key] = fn
        return fn

    def compile_device_stacked(self, mesh):
        """shard_map'd twin of compile_device for the meshrunner: every
        input is a per-device STACK [D, n_pad, ...] sharded over the
        mesh's 'p' axis, output is packed keep bits [D, n_pad//8] with
        the same sharding. Each device evaluates its own [n_pad] block of
        the SAME predicate tree, so bit (d, i) is identical to what
        compile_device over device d's rows alone would produce — the
        mesh-vs-single parity contract."""
        key = ("stacked", id(mesh))
        fn = self._fn_cache.get(key)
        if fn is not None:
            return fn
        with self._fn_lock:
            fn = self._fn_cache.get(key)
            if fn is not None:
                return fn
            return self._compile_stacked_locked(key, mesh)

    def _compile_stacked_locked(self, key, mesh):
        import jax
        import jax.numpy as jnp

        try:  # jax >= 0.5 exports shard_map at top level
            from jax import shard_map
        except ImportError:
            from jax.experimental.shard_map import shard_map
        from jax.sharding import PartitionSpec

        from redpanda_tpu.parallel.mesh import PARTITION_AXIS

        expr = self.spec.where
        consts = _prepare_cmp_consts(expr)
        plan = self

        def _local(*arrays):
            # per-device block: [1, n_pad, ...] -> strip the device dim,
            # evaluate the shared predicate tree, re-add it for out_specs
            flat = [a[0] for a in arrays]
            keep = _build_expr(jnp, expr, plan._bind_slots(flat), consts)
            return _packbits(jnp, keep)[None, :]

        in_specs = []
        for c in self.dev_cols:
            in_specs += [PartitionSpec(PARTITION_AXIS)] * _COL_ARITY[c.kind]
        fn = jax.jit(
            shard_map(
                _local,
                mesh=mesh,
                in_specs=tuple(in_specs),
                out_specs=PartitionSpec(PARTITION_AXIS),
            )
        )
        self._fn_cache[key] = fn
        return fn

    def eval_host_mask(self, cols) -> np.ndarray:
        """ABLATION twin of compile_device: the SAME predicate tree over the
        SAME extracted columns, evaluated in numpy on the host — packed keep
        bits (uint8 [n/8]). _build_expr is namespace-generic and the slot
        binding is shared (_bind_slots), so device and host evaluation
        cannot drift; the bench runs both to measure what the device link
        actually buys."""
        keep = _build_expr(
            np,
            self.spec.where,
            self._bind_slots(cols),
            _prepare_cmp_consts(self.spec.where),
        )
        return _packbits(np, np.asarray(keep, dtype=bool))

    # ------------------------------------------------------------ host side
    def extract_device_inputs(self, joined, offsets, sizes, n_pad: int, cache=None):
        """Native pass over the records -> ordered device input arrays."""
        out = []
        for c in self.dev_cols:
            if c.kind == "str":
                b, v = _extract_str(joined, offsets, sizes, c.path, c.w, n_pad, cache)
                out += [b, v]
            elif c.kind == "num":
                f32, i32, fl = _extract_num(joined, offsets, sizes, c.path, n_pad, cache)
                out += [f32, i32, fl]
            else:
                out.append(_extract_exists(joined, offsets, sizes, c.path, n_pad, cache))
        return out

    def zero_device_inputs(self, n_pad: int) -> list:
        """All-padding device inputs — the dtypes/shapes/arity of
        extract_device_inputs with zero records (str validity -1 =
        absent). Keeps the per-kind array layout in ONE place: an empty
        mesh device shard stacks these so the SPMD input keeps one shape
        regardless of shard occupancy."""
        out = []
        for c in self.dev_cols:
            if c.kind == "str":
                out += [
                    np.zeros((n_pad, c.w), np.uint8),
                    np.full(n_pad, -1, np.int32),
                ]
            elif c.kind == "num":
                out += [
                    np.zeros(n_pad, np.float32),
                    np.zeros(n_pad, np.int32),
                    np.zeros(n_pad, np.uint8),
                ]
            else:
                out.append(np.zeros(n_pad, np.uint8))
        return out

    def extract_projection(self, joined, offsets, sizes, cache=None):
        """Host-side projection columns -> (per-field data, ok mask [n]).

        Fast path: when every projection field is Int/Float/Str over a
        cached span column, ONE native pass (rp_project_rows) gathers all
        fields straight into the packed output rows — no per-field
        [n, w] temporaries, no numpy masking; assemble_rows then just
        unwraps them. Substr/Concat/nested paths keep the general path."""
        n = len(sizes)
        fused = self._project_descs(cache)
        if fused is not None and n:
            descs, lib = fused
            rows, ok = lib.project_rows(
                joined, offsets, cache.types, cache.vs, cache.ve,
                descs, self.r_out,
            )
            return [("rows", rows)], ok
        ok = np.ones(n, dtype=bool)
        data = []
        for f in self.proj:
            if isinstance(f, Int):
                _, i32, fl = _extract_num(joined, offsets, sizes, f.key, n, cache)
                fok = (
                    (fl & (E.F_PRESENT | E.F_NUMBER | E.F_INT_EXACT))
                    == (E.F_PRESENT | E.F_NUMBER | E.F_INT_EXACT)
                ) & (np.abs(i32.astype(np.int64)) <= _INT9)
                ok &= fok
                data.append(("int", i32))
            elif isinstance(f, Float):
                f32, _, fl = _extract_num(joined, offsets, sizes, f.key, n, cache)
                ok &= (fl & (E.F_PRESENT | E.F_NUMBER)) == (
                    E.F_PRESENT | E.F_NUMBER
                )
                data.append(("float", f32))
            elif isinstance(f, Substr):
                b, v = _extract_str(
                    joined, offsets, sizes, f.key, f.start + f.length, n, cache
                )
                ok &= v >= 0
                body = b[:, f.start : f.start + f.length]
                slen = np.clip(v - f.start, 0, f.length).astype(np.int32)
                data.append(("str", body, slen, f.length))
            elif isinstance(f, Concat):
                ba, va = _extract_str(joined, offsets, sizes, f.a, f.max_len, n, cache)
                bb, vb = _extract_str(joined, offsets, sizes, f.b, f.max_len, n, cache)
                ok &= (va >= 0) & (vb >= 0)
                data.append(("concat", ba, va, bb, vb, f.max_len))
            else:  # Str
                b, v = _extract_str(joined, offsets, sizes, f.key, f.max_len, n, cache)
                ok &= (v >= 0) & (v <= f.max_len)
                data.append(("str", b, np.clip(v, 0, f.max_len), f.max_len))
        return data, ok

    def _proj_desc_rows(self, col_of: dict) -> list | None:
        """[{kind, span col, w, out off}] rows for the fused projector, or
        None when any field needs the general path (Substr/Concat/nested).
        Field order and widths MUST mirror assemble_rows' layout walk —
        shared by the staged (rp_project_rows) and structural
        (rp_extract_cols2) fused projectors."""
        descs = []
        off = 0
        for f in self.proj:
            if isinstance(f, Int) and f.key in col_of:
                descs.append((0, col_of[f.key], 0, off))
                off += 4
            elif isinstance(f, Float) and f.key in col_of:
                descs.append((1, col_of[f.key], 0, off))
                off += 4
            elif type(f) is Str and f.key in col_of:
                descs.append((2, col_of[f.key], f.max_len, off))
                off += 2 + f.max_len
            else:  # Substr/Concat/nested: general path
                return None
        return descs

    def _project_descs(self, cache):
        """[n_fields, 4] int32 {kind, span col, w, out off} when the fused
        projector applies to this plan, else None."""
        if cache is None:
            return None
        lib = _native()
        if lib is None or not getattr(lib, "has_project_rows", False):
            return None
        descs = self._proj_desc_rows(cache.col)
        if descs is None:
            return None
        return np.asarray(descs, dtype=np.int32), lib

    # ------------------------------------------------------ structural fused
    def structural_eligible(self) -> bool:
        """Whether the structural-index fused ladder can serve this plan:
        the native structural symbols exist, every DevCol path is a
        top-level single segment, and the projection (when any) is
        expressible as fused Int/Float/Str descs. Anything else keeps the
        staged ladder — the parity contract is 'same outputs, different
        machinery', never 'almost'."""
        lib = _native()
        if lib is None or not getattr(lib, "has_structural", False):
            return False
        col_of = {p: i for i, p in enumerate(self.flat_paths())}
        if not col_of or any(c.path not in col_of for c in self.dev_cols):
            return False
        if self.passthrough:
            return True
        return self._proj_desc_rows(col_of) is not None

    def extract_fused(self, sp, n_pad: int):
        """ONE record-major native crossing off the structural parse's
        span tables: every predicate column and (for projection plans) the
        packed output rows — replaces extract_device_inputs' per-column
        gathers + pads AND extract_projection's separate crossing.
        Returns (cols, proj_data | None, proj_ok | None): cols in
        _bind_slots order, proj_data in assemble_rows' fused shape."""
        lib = _native()
        col_of = {p: i for i, p in enumerate(self.flat_paths())}
        pred = np.asarray(
            [(_PRED_KIND[c.kind], col_of[c.path], c.w, 0)
             for c in self.dev_cols],
            dtype=np.int32,
        ).reshape(-1, 4)
        proj_descs = None
        if not self.passthrough:
            proj_descs = np.asarray(
                self._proj_desc_rows(col_of), dtype=np.int32
            )
        cols, rows, ok = lib.extract_cols2(
            sp.payloads, sp.counts, sp.val_off, sp.val_len,
            sp.types, sp.vs, sp.ve, pred, n_pad, proj_descs, self.r_out,
        )
        if self.passthrough:
            return cols, None, None
        return cols, [("rows", rows)], ok

    def assemble_rows(self, data, n: int) -> tuple[np.ndarray, np.ndarray]:
        """Projection columns -> ([n, r_out] u8 rows, [n] i32 lens)."""
        if len(data) == 1 and data[0][0] == "rows":
            # fused projector already packed the rows at extract time
            return data[0][1], np.full(n, self.r_out, dtype=np.int32)
        rows = np.zeros((n, self.r_out), dtype=np.uint8)
        off = 0
        for item in data:
            kind = item[0]
            if kind in ("int", "float"):
                arr = item[1]
                rows[:, off : off + 4] = (
                    np.ascontiguousarray(arr).view(np.uint8).reshape(n, 4)
                )
                off += 4
            elif kind == "str":
                _, body, slen, w = item
                rows[:, off] = slen & 0xFF
                rows[:, off + 1] = (slen >> 8) & 0xFF
                mask = np.arange(w, dtype=np.int32)[None, :] < slen[:, None]
                rows[:, off + 2 : off + 2 + w] = np.where(mask, body, 0)
                off += 2 + w
            else:  # concat
                _, ba, va, bb, vb, w = item
                alen = np.clip(va, 0, w).astype(np.int32)
                blen = np.clip(vb, 0, np.maximum(w - alen, 0)).astype(np.int32)
                total = alen + blen
                rows[:, off] = total & 0xFF
                rows[:, off + 1] = (total >> 8) & 0xFF
                idx = np.arange(w, dtype=np.int32)[None, :]
                in_a = idx < alen[:, None]
                from_b = idx - alen[:, None]
                in_b = ~in_a & (from_b < blen[:, None])
                a_part = np.where(in_a, ba[:, :w], 0)
                b_idx = np.clip(from_b, 0, w - 1)
                b_part = np.where(in_b, np.take_along_axis(bb[:, :w], b_idx, axis=1), 0)
                rows[:, off + 2 : off + 2 + w] = a_part | b_part
                off += 2 + w
        lens = np.full(n, self.r_out, dtype=np.int32)
        return rows, lens


@dataclass
class PayloadPlan:
    spec: TransformSpec
    mode = "payload"
    # device-transformed rows: never a view into the input blob
    byte_identity = False


@dataclass
class HostPlan:
    """No device stage: identity / pure uppercase / py_transform."""

    spec: TransformSpec
    kind: str  # identity | uppercase | python
    fn: object = None  # python escape hatch: callable(bytes) -> bytes | None
    mode = "host"

    @property
    def byte_identity(self) -> bool:
        # identity emits the input value bytes untouched (its keep rule —
        # drop empty values — needs only the sizes column); uppercase and
        # python transforms mutate bytes
        return self.kind == "identity"


def plan_spec(spec: TransformSpec, py_fn=None):
    """Pick the execution mode for a spec (see module docs)."""
    if py_fn is not None:
        return HostPlan(spec, "python", py_fn)
    if spec.where is not None:
        if spec.filters:
            raise ValueError("where-exprs cannot combine with raw filters")
        if isinstance(spec.mapper, _MapUppercase):
            raise ValueError("uppercase is a raw-byte map; use payload specs")
        proj = spec.mapper.fields if isinstance(spec.mapper, _MapProject) else ()
        cols = _collect_dev_cols(spec.where)
        r_out = project_out_width(proj) if proj else 0
        return ColumnarPlan(
            spec, cols, tuple(proj), r_out, passthrough=not proj
        )
    if spec.filters:
        return PayloadPlan(spec)
    if isinstance(spec.mapper, _MapUppercase):
        return HostPlan(spec, "uppercase")
    if isinstance(spec.mapper, _MapProject):
        # A v1 projection-only spec keeps the v1 payload pipeline: its Int
        # semantics differ from columnar (v1 _parse_int_at truncates "3.5"
        # to 3; columnar requires an exact integer) and deployed spec JSON
        # must not change outputs across an upgrade. v2 columnar projection
        # is opted into by writing a where() stage.
        return PayloadPlan(spec)
    return HostPlan(spec, "identity")


# ------------------------------------------------------------------ internals
def _collect_dev_cols(expr) -> list[DevCol]:
    cols: dict[tuple, DevCol] = {}

    def need(kind: str, path: str, w: int = 0):
        k = (kind, path)
        if k in cols:
            if kind == "str" and w > cols[k].w:
                cols[k] = DevCol(kind, path, w)
        else:
            cols[k] = DevCol(kind, path, w)

    def walk(e):
        if isinstance(e, (E.And, E.Or)):
            walk(e.a)
            walk(e.b)
        elif isinstance(e, E.Not):
            walk(e.a)
        elif isinstance(e, E.Exists):
            need("exists", e.path)
        elif isinstance(e, E.StrContains):
            need("str", e.path, e.window)
        elif isinstance(e, E.Cmp):
            v = e.value
            if isinstance(v, (str, bytes)):
                raw = v.encode() if isinstance(v, str) else bytes(v)
                need("str", e.path, len(raw))
            elif isinstance(v, (bool, int, float, np.integer, np.floating)) or v is None:
                need("num", e.path)
            else:
                raise TypeError(
                    f"unsupported comparison constant {v!r} for {e.path!r}"
                )
        else:
            raise TypeError(f"not an expr: {e!r}")

    if expr is not None:
        walk(expr)
    return list(cols.values())


def _packbits(jnp, keep):
    """bool [n] -> uint8 [n/8], big-endian bit order (numpy unpackbits)."""
    n = keep.shape[0]
    assert n % 8 == 0, "row buckets are multiples of 8"
    b = keep.astype(jnp.uint8).reshape(n // 8, 8)
    weights = jnp.array([128, 64, 32, 16, 8, 4, 2, 1], dtype=jnp.uint8)
    return (b * weights[None, :]).sum(axis=1).astype(jnp.uint8)


def _prepare_cmp_consts(expr) -> dict[int, tuple]:
    """id(Cmp node) -> (f32 const, i32 const | None), prepared host-side.

    Every numeric comparison constant in the tree is classified and
    converted ONCE, before tracing: conversions inside the traced predicate
    would run per trace on host (pandalint HPS201/HPN211 hot-path purity).
    The i32 constant exists only when the spec value is int32-exact, which
    is what gates the exact-integer comparison path on device.
    """
    out: dict[int, tuple] = {}

    def walk(e):
        if isinstance(e, (E.And, E.Or)):
            walk(e.a)
            walk(e.b)
        elif isinstance(e, E.Not):
            walk(e.a)
        elif isinstance(e, E.Cmp):
            v = e.value
            if v is None or isinstance(v, (bool, str, bytes)):
                return
            const_int = (
                isinstance(v, (int, np.integer))
                and not isinstance(v, bool)
                and -(2**31) <= int(v) <= 2**31 - 1
            ) or (
                isinstance(v, (float, np.floating))
                and float(v) == int(v)
                and -(2**31) <= int(v) <= 2**31 - 1
            )
            out[id(e)] = (
                np.float32(float(v)),
                np.int32(int(v)) if const_int else None,
            )

    if expr is not None:
        walk(expr)
    return out


def _build_expr(jnp, expr, slots, consts):
    if isinstance(expr, E.And):
        return _build_expr(jnp, expr.a, slots, consts) & _build_expr(
            jnp, expr.b, slots, consts
        )
    if isinstance(expr, E.Or):
        return _build_expr(jnp, expr.a, slots, consts) | _build_expr(
            jnp, expr.b, slots, consts
        )
    if isinstance(expr, E.Not):
        return ~_build_expr(jnp, expr.a, slots, consts)
    if isinstance(expr, E.Exists):
        col = slots[("exists", expr.path)]
        return col != 0
    if isinstance(expr, E.StrContains):
        bytes_col, vlen = slots[("str", expr.path)]
        return _contains(jnp, bytes_col, vlen, expr.needle, expr.window)
    assert isinstance(expr, E.Cmp)
    v = expr.value
    if isinstance(v, (str, bytes)):
        raw = v.encode() if isinstance(v, str) else bytes(v)
        bytes_col, vlen = slots[("str", expr.path)]
        present = vlen >= 0
        eq = present & (vlen == len(raw))
        for i, ch in enumerate(raw):
            eq = eq & (bytes_col[:, i] == jnp.uint8(ch))
        return eq if expr.op == "eq" else present & ~eq
    f32, i32, flags = slots[("num", expr.path)]
    present = (flags & E.F_PRESENT) != 0
    if isinstance(v, bool):
        isbool = (flags & E.F_BOOL) != 0
        eq = isbool & (i32 == (1 if v else 0))
        return eq if expr.op == "eq" else isbool & ~eq
    if v is None:
        isnull = (flags & E.F_NULL) != 0
        return isnull if expr.op == "eq" else present & ~isnull
    # numeric constant: prepared host-side by _prepare_cmp_consts — no
    # conversions may run inside the traced predicate.
    # E._cmp_num is dtype-generic; sharing it keeps host-oracle and device
    # comparison semantics in one place.
    isnum = (flags & E.F_NUMBER) != 0
    f32c, i32c = consts[id(expr)]
    fcmp = E._cmp_num(expr.op, f32, f32c)
    if i32c is not None:
        int_exact = (flags & E.F_INT_EXACT) != 0
        icmp = E._cmp_num(expr.op, i32, i32c)
        return isnum & jnp.where(int_exact, icmp, fcmp)
    return isnum & fcmp


def _contains(jnp, bytes_col, vlen, needle: bytes, window: int):
    """needle in raw[:window]; scan limited to min(vlen, window).

    The column may be wider than this predicate's window when another
    expression on the same path merged a larger width — the scan must still
    honor THIS predicate's window (host_eval parity)."""
    n, w = bytes_col.shape
    weff = min(window, w)
    l = len(needle)
    present = vlen >= 0
    if l == 0:
        return present
    if l > weff:
        return present & False
    span = jnp.minimum(vlen, weff)  # valid scan length per row
    nwin = weff - l + 1
    match = jnp.ones((n, nwin), dtype=bool)
    for i, ch in enumerate(needle):
        match = match & (bytes_col[:, i : i + nwin] == jnp.uint8(ch))
    starts = jnp.arange(nwin, dtype=jnp.int32)
    match = match & (starts[None, :] <= (span - l)[:, None])
    return present & match.any(axis=1)


# ---------------------------------------------------------------- extractors
def _native():
    try:
        from redpanda_tpu.native import lib

        return lib
    except Exception:
        return None


def _extract_str(joined, offsets, sizes, path, w, n_pad, cache=None):
    lib = _native()
    n = len(sizes)
    if cache is not None and path in cache.col:
        b, v = cache.gather_str(path, w)
    elif lib is not None:
        b, v = lib.extract_str(joined, offsets, sizes, path, w)
    else:
        b = np.zeros((n, w), dtype=np.uint8)
        v = np.full(n, -1, dtype=np.int32)
        for i in range(n):
            rec = joined[offsets[i] : offsets[i] + sizes[i]]
            t, vs, ve = E.json_find(rec, path)
            if t == 1:
                # ve < vs when the record is truncated inside an
                # unterminated string: empty-but-present (native clamp)
                v[i] = max(ve - vs, 0)
                cp = min(v[i], w)
                b[i, :cp] = np.frombuffer(rec[vs : vs + cp], np.uint8)
    if n_pad > n:
        b = np.concatenate([b, np.zeros((n_pad - n, w), np.uint8)])
        v = np.concatenate([v, np.full(n_pad - n, -1, np.int32)])
    return b, v


def _extract_num(joined, offsets, sizes, path, n_pad, cache=None):
    lib = _native()
    n = len(sizes)
    if cache is not None and path in cache.col:
        f32, i32, fl = cache.gather_num(path)
    elif lib is not None:
        f32, i32, fl = lib.extract_num(joined, offsets, sizes, path)
    else:
        f32 = np.zeros(n, np.float32)
        i32 = np.zeros(n, np.int32)
        fl = np.zeros(n, np.uint8)
        for i in range(n):
            rec = joined[offsets[i] : offsets[i] + sizes[i]]
            f = E.host_field(rec, path)
            f32[i], i32[i], fl[i] = f["f32"], f["i32"], f["flags"]
    if n_pad > n:
        f32 = np.concatenate([f32, np.zeros(n_pad - n, np.float32)])
        i32 = np.concatenate([i32, np.zeros(n_pad - n, np.int32)])
        fl = np.concatenate([fl, np.zeros(n_pad - n, np.uint8)])
    return f32, i32, fl


def _extract_exists(joined, offsets, sizes, path, n_pad, cache=None):
    lib = _native()
    n = len(sizes)
    if cache is not None and path in cache.col:
        ex = cache.gather_exists(path)
    elif lib is not None:
        ex = lib.extract_exists(joined, offsets, sizes, path)
    else:
        ex = np.zeros(n, np.uint8)
        for i in range(n):
            rec = joined[offsets[i] : offsets[i] + sizes[i]]
            ex[i] = 1 if E.json_find(rec, path)[0] else 0
    if n_pad > n:
        ex = np.concatenate([ex, np.zeros(n_pad - n, np.uint8)])
    return ex
