"""Coproc deploy events.

Parity with coproc/wasm_event.h:28-41 + wasm_event.cc validation: scripts
are (un)deployed by producing records to ``coprocessor_internal_topic``.
Record layout: key = script name, value = the script body (here: a
TransformSpec JSON + input topics instead of a JS blob), headers:
  action: "deploy" | "remove"
  checksum: xxhash64 of the value (integrity, wasm_event.cc checks it)
  type: "transform-spec" (the reference uses "wasm")
Reconciliation keeps only the LAST event per script (wasm_event.cc
reconcile), so redeploys and removes compose naturally with log replay.
"""

from __future__ import annotations

import json
import struct
from dataclasses import dataclass

from redpanda_tpu.hashing.xx import xxhash64
from redpanda_tpu.models.record import Record, RecordBatch, RecordHeader

DEPLOY = b"deploy"
REMOVE = b"remove"
EVENT_TYPE = b"transform-spec"
EVENT_TYPE_PY = b"py-sandbox"  # sandboxed python transform (coproc/sandbox.py)


@dataclass
class WasmEvent:
    name: str
    action: bytes
    spec_json: str = ""
    input_topics: tuple[str, ...] = ()
    checksum: int = 0
    py_source: str = ""  # non-empty for EVENT_TYPE_PY deploys
    policy: str = "skip"  # "skip" | "deregister" (wasm_event.h policy)

    @property
    def script_id(self) -> int:
        """Stable id from the script name (the reference keys scripts by the
        event's sharded id; a name hash keeps ids stable across redeploys)."""
        return xxhash64(self.name.encode()) & 0x7FFFFFFF


def make_deploy_record(name: str, spec_json: str, input_topics: list[str]) -> Record:
    value = json.dumps(
        {"spec": json.loads(spec_json), "input_topics": list(input_topics)},
        separators=(",", ":"),
    ).encode()
    return Record(
        key=name.encode(),
        value=value,
        headers=(
            RecordHeader(b"action", DEPLOY),
            RecordHeader(b"checksum", struct.pack("<Q", xxhash64(value))),
            RecordHeader(b"type", EVENT_TYPE),
        ),
    )


def make_py_deploy_record(
    name: str,
    py_source: str,
    input_topics: list[str],
    policy: str = "skip",
) -> Record:
    """Deploy a sandboxed python transform over the SAME event path as DSL
    specs (the reference ships JS blobs the same way, wasm_event.h:28-41).
    Validation happens again on every consuming broker at enable time; this
    client-side check fails fast at the deploy call site."""
    from redpanda_tpu.coproc.sandbox import validate_source

    validate_source(py_source)
    if policy not in ("skip", "deregister"):
        raise ValueError(f"unknown policy {policy!r}")
    value = json.dumps(
        {
            "py_source": py_source,
            "input_topics": list(input_topics),
            "policy": policy,
        },
        separators=(",", ":"),
    ).encode()
    return Record(
        key=name.encode(),
        value=value,
        headers=(
            RecordHeader(b"action", DEPLOY),
            RecordHeader(b"checksum", struct.pack("<Q", xxhash64(value))),
            RecordHeader(b"type", EVENT_TYPE_PY),
        ),
    )


def make_remove_record(name: str) -> Record:
    return Record(
        key=name.encode(),
        value=None,
        headers=(RecordHeader(b"action", REMOVE),),
    )


def parse_event(rec: Record) -> WasmEvent | None:
    """Validated decode; None for malformed events (wasm_event.cc rules:
    missing action/key → reject; deploy needs value + matching checksum)."""
    if rec.key is None:
        return None
    headers = {h.key: h.value for h in rec.headers}
    action = headers.get(b"action")
    name = rec.key.decode("utf-8", "replace")
    if action == REMOVE:
        return WasmEvent(name, REMOVE)
    if action != DEPLOY:
        return None
    if rec.value is None:
        return None
    csum_raw = headers.get(b"checksum")
    if csum_raw is None or len(csum_raw) != 8:
        return None
    (csum,) = struct.unpack("<Q", csum_raw)
    if xxhash64(rec.value) != csum:
        return None
    try:
        body = json.loads(rec.value.decode())
        topics = tuple(body["input_topics"])
        if headers.get(b"type") == EVENT_TYPE_PY:
            py_source = body["py_source"]
            policy = body.get("policy", "skip")
            if policy not in ("skip", "deregister") or not isinstance(py_source, str):
                return None
            ev = WasmEvent(
                name, DEPLOY, "", topics, csum,
                py_source=py_source, policy=policy,
            )
        else:
            ev = WasmEvent(name, DEPLOY, json.dumps(body["spec"]), topics, csum)
    except (ValueError, KeyError):
        return None
    if not topics:
        return None
    return ev


def reconcile(events: list[WasmEvent]) -> dict[str, WasmEvent]:
    """Last event per script wins."""
    out: dict[str, WasmEvent] = {}
    for ev in events:
        out[ev.name] = ev
    return out


def deploy_batch(records: list[Record]) -> RecordBatch:
    return RecordBatch.build(
        [
            Record(
                attributes=r.attributes, timestamp_delta=r.timestamp_delta,
                offset_delta=i, key=r.key, value=r.value, headers=r.headers,
            )
            for i, r in enumerate(records)
        ]
    )
