"""Application assembly: wire and start every service.

Parity with redpanda/application.cc (wire_up_services :492-882, start
:884-1060): construct the service graph in dependency order, start it, and
stop in reverse on shutdown. Two modes, like the reference's single-broker
vs clustered deployments:

- single-node: storage → broker (direct-consensus partitions) → kafka
  server → admin server.
- clustered: + internal rpc server, raft group manager, controller (raft0),
  controller backend, metadata dissemination; the broker routes mutations
  through the controller dispatcher.
"""

from __future__ import annotations

import asyncio
import logging
import os

from redpanda_tpu import rpc
from redpanda_tpu.admin import AdminServer
from redpanda_tpu.config import Configuration
from redpanda_tpu.kafka.server.broker import Broker, BrokerConfig
from redpanda_tpu.kafka.server.protocol import KafkaServer
from redpanda_tpu.metrics import registry
from redpanda_tpu.storage.log_manager import StorageApi

logger = logging.getLogger("rptpu.app")


class Application:
    def __init__(self, config: Configuration) -> None:
        self.config = config
        self.storage: StorageApi | None = None
        self.broker: Broker | None = None
        self.kafka_server: KafkaServer | None = None
        self.admin: AdminServer | None = None
        # clustered-mode services
        self.rpc_server = None
        self.group_manager = None
        self.controller = None
        self.backend = None
        self.md_dissemination = None
        self.connections = None
        self.coproc = None
        self._stop_order: list = []

    # ------------------------------------------------------------ wiring
    def _broker_config(self) -> BrokerConfig:
        c = self.config
        return BrokerConfig(
            node_id=c.node_id,
            cluster_id=c.cluster_id,
            advertised_host=c.advertised_kafka_api_host,
            advertised_port=c.advertised_kafka_api_port,
            data_dir=c.data_directory,
            auto_create_topics=c.auto_create_topics_enabled,
            default_partitions=c.default_topic_partitions,
            default_replication=c.default_topic_replication,
            fetch_poll_interval_s=c.fetch_poll_interval_ms / 1000.0,
            sasl_enabled=c.enable_sasl,
            superusers=[u for u in c.superusers.split(",") if u],
            unsafe_relaxed_acks=c.unsafe_relaxed_acks,
            target_quota_byte_rate=c.target_quota_byte_rate or None,
            kafka_qdc_enable=c.kafka_qdc_enable,
            kafka_qdc_max_latency_ms=float(c.kafka_qdc_max_latency_ms),
        )

    def _tls_for(self, prefix: str):
        """Build the hot-reloadable TLS context for a listener group, or
        None when that listener is plaintext."""
        from redpanda_tpu.security.tls import ReloadableTlsContext, TlsConfig

        c = self.config
        if not getattr(c, f"{prefix}_tls_enabled"):
            return None
        return ReloadableTlsContext(
            TlsConfig(
                enabled=True,
                cert_file=getattr(c, f"{prefix}_tls_cert_file"),
                key_file=getattr(c, f"{prefix}_tls_key_file"),
                truststore_file=getattr(c, f"{prefix}_tls_truststore_file", ""),
                require_client_auth=getattr(
                    c, f"{prefix}_tls_require_client_auth", False
                ),
            )
        )

    async def start(self) -> "Application":
        c = self.config
        # refuse unsuitable environments up front with actionable messages
        # (application.cc:364-373 check_environment -> syschecks)
        from redpanda_tpu.syschecks import check_environment

        check_environment(c)
        # operator-pinned CPU backend also drops the axon factory, so an
        # unhealthy device tunnel cannot hang this broker's engine
        from redpanda_tpu.utils.platform import pin_cpu_if_requested

        pin_cpu_if_requested()
        # pandaprobe: the probe histograms are always on; the span tracer
        # only spends clock reads + ring slots when the operator asks
        from redpanda_tpu.observability import tracer

        tracer.configure(
            enabled=c.trace_enabled,
            capacity=c.trace_ring_capacity,
            slow_threshold_ms=float(c.trace_slow_threshold_ms),
            # namespace trace/span ids by node: cluster-assembled traces
            # merge by trace id across brokers, so ids must never collide
            node_id=c.node_id,
        )
        # pandapulse: the flight recorder rides the tracer's commit path
        # (span sink — one bounded-deque append per committed span), so it
        # installs whenever enabled and simply sees nothing until
        # trace_enabled flips the plane on (the pandascope rollout-gate
        # posture). The wall profiler is its own low-frequency thread,
        # profile_hz=0 keeps it entirely absent.
        from redpanda_tpu.observability.pulse import pulse

        pulse.configure(
            enabled=c.pulse_enabled,
            ring_capacity=c.pulse_ring_capacity,
            profile_hz=float(c.profile_hz),
        )
        # pandatrend: the bounded metrics-history ring (delta windows over
        # the whole registry, EWMA breach journaling into the governor's
        # trend domain, Perfetto counter tracks). interval 0 = off and NO
        # recorder thread — the profile_hz=0 contract.
        from redpanda_tpu.observability.history import history

        history.configure(
            interval_s=float(c.history_interval_s),
            windows=c.history_windows,
            max_bytes=c.history_max_bytes,
        )
        # SLO engine: operator objectives (or the lenient broker defaults)
        # judged at GET /v1/slo; loading arms per-metric breach thresholds
        # so over-threshold observations record trace exemplars
        from redpanda_tpu.observability.slo import slo

        if c.slo_objectives_file:
            slo.configure_from_file(c.slo_objectives_file)
        else:
            slo.arm_exemplars()
        # rpk iotune's characterization, when present (io-config.json in the
        # data dir): published below as metrics for operators/dashboards
        from redpanda_tpu.config.io_config import load_io_config

        self.io_config = load_io_config(c.data_directory)
        self.rpc_tls = self._tls_for("rpc_server")
        log_config = None
        if c.debug_sanitize_files:
            from redpanda_tpu.storage import file_sanitizer
            from redpanda_tpu.storage.log import LogConfig

            # arm BEFORE any storage handle opens (the kvstore WAL opens
            # during StorageApi.start, ahead of the first DiskLog.open)
            file_sanitizer.enable()
            log_config = LogConfig(
                base_dir=os.path.join(c.data_directory, "data"),
                sanitize_files=True,
            )
        # Budget plane (resource_mgmt): installed BEFORE storage so the
        # first kvstore/log appends already charge the storage account;
        # the split + thresholds come from config (memory_groups posture).
        from redpanda_tpu.resource_mgmt import admission as rm_admission
        from redpanda_tpu.resource_mgmt import budgets as rm_budgets

        if getattr(c, "coproc_leakwatch", False):
            # must flip BEFORE the plane is built: accounts bind their
            # balance recorder (or lack of one) at construction
            from redpanda_tpu.coproc import leakwatch

            leakwatch.enable()
        self.budget_plane = rm_budgets.BudgetPlane(
            total_bytes=c.resource_memory_total_mb << 20,
            warn_pct=c.resource_pressure_warn_pct,
            critical_pct=c.resource_pressure_critical_pct,
            register_gauges=True,
        )
        rm_budgets.install(self.budget_plane)
        self.storage = await StorageApi(c.data_directory, log_config).start()
        self._stop_order.append(self.storage)
        self.broker = Broker(self._broker_config(), self.storage)
        self.broker.budget_plane = self.budget_plane
        self.broker.produce_admission = rm_admission.AdmissionController(
            self.budget_plane.account("kafka_produce"), "kafka_produce",
            warn_pct=self.budget_plane.warn_pct,
            on_episode=self._journal_admission_episode,
        )

        is_clustered = bool(c.seed_servers)
        if is_clustered:
            await self._start_cluster_services()

        self.kafka_tls = self._tls_for("kafka_api")
        self.kafka_server = await KafkaServer(
            self.broker, c.kafka_api_host, c.kafka_api_port, tls=self.kafka_tls
        ).start()
        # ephemeral bind (port 0, tests) must advertise the real port or
        # metadata sends clients to a dead address
        adv = c.advertised_kafka_api_port
        if c.kafka_api_port == 0 or adv == 0:
            adv = self.kafka_server.port
        self.broker.config.advertised_port = adv
        self._stop_order.append(self.kafka_server)

        self.admin_tls = self._tls_for("admin_api")
        self.admin = await AdminServer(
            self.broker,
            config=c,
            group_manager=self.group_manager,
            controller=self.controller,
            host=c.admin_api_host,
            port=c.admin_api_port,
            require_auth=c.admin_api_require_auth,
            auth_token=c.admin_api_auth_token or None,
            tls=self.admin_tls,
        ).start()
        self.admin.tls_contexts = {
            "kafka": self.kafka_tls,
            "rpc": self.rpc_tls,
            "admin": self.admin_tls,
        }
        self._stop_order.append(self.admin)

        if is_clustered:
            # announce ourselves AFTER the admin server is up so the
            # register_node command can advertise the real (possibly
            # ephemeral) admin port — the cluster observability plane
            # (trace fan-out, /metrics federation) dials peers by it.
            # In a real multi-process cluster the first election only
            # completes after a MAJORITY of seed brokers finish interpreter
            # startup (~10s each), so registration must outwait peers, not
            # give up in the default few retries (tests/chaos drives this
            # path with SIGKILLed real processes).
            from redpanda_tpu.cluster import commands as ccmds

            await self._dispatcher.replicate(
                ccmds.register_node_cmd(
                    c.node_id, c.rpc_server_host, self.rpc_server.port,
                    c.advertised_kafka_api_host, c.advertised_kafka_api_port,
                    admin_port=self.admin.port,
                ),
                retries=300,
            )

        if c.coproc_enable:
            await self._start_coproc()

        if c.cloud_storage_enabled:
            await self._start_archival()
            # admin surface (POST /v1/archival/run_once, GET .../status):
            # the admin server started earlier, so hand it the scheduler
            self.admin.archival = self.archival

        self._register_metrics()
        await self.storage.log_mgr.start_housekeeping(
            c.log_compaction_interval_ms / 1000.0
        )
        logger.info("application started (node %d)", c.node_id)
        return self

    async def _start_cluster_services(self) -> None:
        """Internal RPC + raft + controller (application.cc :521-610)."""
        from redpanda_tpu.cluster import (
            Controller,
            ControllerBackend,
            ControllerDispatcher,
            ClusterService,
            MetadataCache,
            MetadataDisseminationService,
            PartitionLeadersTable,
            ShardTable,
        )
        from redpanda_tpu.cluster import commands as ccmds
        from redpanda_tpu.cluster.metadata_dissemination import md_dissemination_service
        from redpanda_tpu.raft.consensus import RaftTimings
        from redpanda_tpu.raft.group_manager import GroupManager
        from redpanda_tpu.raft.types import VNode

        c = self.config
        rpc_client_ssl = (
            self.rpc_tls.client_context() if self.rpc_tls is not None else None
        )
        self.connections = rpc.ConnectionCache(ssl_context=rpc_client_ssl)
        self_vnode = VNode(c.node_id, 0)
        self.group_manager = GroupManager(
            self_vnode, self.storage, self.connections,
            timings=RaftTimings(
                election_timeout_ms=c.raft_election_timeout_ms,
                heartbeat_interval_ms=c.raft_heartbeat_interval_ms,
            ),
            recovery_concurrency=c.raft_recovery_concurrency,
        )
        # raft device plane (BASELINE config 5): batched follower CRC
        # validation + per-tick cross-group ack tally, both behind their
        # own measured host-vs-device probe (raft/device_plane.py)
        from redpanda_tpu.raft import device_plane as raft_device_plane

        raft_device_plane.configure(
            crc_validate=getattr(c, "raft_device_crc_validate", False),
            vote_tally=getattr(c, "raft_device_vote_tally", False),
            # the plane shares the coproc engine's multi-chip topology:
            # >= 2 devices gives the sharded crc+vote step the psum lane
            mesh_devices=getattr(c, "coproc_mesh_devices", 0),
            mesh_backend=getattr(c, "coproc_mesh_backend", "") or None,
        )
        self.controller = Controller(self_vnode, self.group_manager, self.connections)
        # One topic table per node: the controller STM's replicated view IS
        # the broker's view (topic_table.h — metadata_cache aggregates the
        # same table). The broker's standalone-mode private table is only
        # for controller-less single-node runs.
        self.broker.topic_table = self.controller.topic_table
        dispatcher = ControllerDispatcher(self.controller, self.connections)
        leaders = PartitionLeadersTable()
        self.md_dissemination = MetadataDisseminationService(
            c.node_id, leaders, self.controller.members, self.connections
        )
        self.backend = ControllerBackend(
            self_vnode, self.controller.topic_table, self.group_manager,
            self.broker.partition_manager, leaders_table=leaders,
            shard_table=ShardTable(),
            finish_move=lambda ntp, reps: dispatcher.replicate(
                ccmds.finish_moving_cmd(ntp, reps)
            ),
        )
        def _on_leadership(cons):
            self.md_dissemination.notify_leadership(
                cons.ntp, cons.leader_id, cons.term
            )
            # Coordinator failover: gaining a group-topic partition means
            # replaying its log into group state (group_manager.cc
            # handle_leader_change), or committed offsets vanish for every
            # group hashed onto the partition.
            if (
                cons.ntp.topic == "__consumer_offsets"
                and cons.leader_id == c.node_id
            ):
                self.broker.group_coordinator.on_leadership_gained(
                    cons.ntp.partition
                )

        self.group_manager.register_leadership_notification(_on_leadership)
        from redpanda_tpu.resource_mgmt import admission as rm_admission

        proto = rpc.SimpleProtocol(
            node_id=c.node_id,
            # dispatch-time shed (STATUS_BACKPRESSURE) once inflight
            # requests or their body bytes exceed the rpc account — peers
            # resend; nothing ran, nothing is lost
            inflight_gate=rm_admission.InflightGate(
                self.budget_plane.account("rpc"),
                max_requests=c.rpc_server_max_inflight_requests,
                on_episode=self._journal_admission_episode,
            ),
        )
        self.group_manager.register_service(proto)
        ClusterService(self.controller, dispatcher).register(proto)
        # tx gateway: cross-node marker fan-out + staged-offset routing
        from redpanda_tpu.cluster.tx_gateway import TxGatewayService

        TxGatewayService(self.broker).register(proto)
        proto.register_service(
            rpc.ServiceHandler(md_dissemination_service, self.md_dissemination)
        )
        self.rpc_server = rpc.Server(
            c.rpc_server_host, c.rpc_server_port, tls=self.rpc_tls
        )
        self.rpc_server.set_protocol(proto)
        await self.rpc_server.start()
        await self.group_manager.start()
        self._stop_order += [self.rpc_server, self.group_manager]

        seeds = []
        for hp in c.seed_servers.split(","):
            if not hp:
                continue
            node_str, _, addr = hp.partition("@")
            host, _, port = addr.partition(":")
            seeds.append((int(node_str), host, int(port)))
        for node_id, host, port in seeds:
            if node_id != c.node_id:
                self.connections.register(node_id, host, port)
        seed_vnodes = [VNode(nid, 0) for nid, _, _ in seeds]
        await self.controller.start(seed_vnodes)
        await self.backend.start()
        await self.md_dissemination.start()
        # A (re)starting broker only hears about FUTURE elections from the
        # gossip loop; leaders elected while it was down must be pulled from
        # a peer (metadata_dissemination get_leadership_request semantics).
        for node_id, _h, _p in seeds:
            if node_id == c.node_id:
                continue
            try:
                await self.md_dissemination.pull_initial(node_id)
                break
            except Exception:
                continue  # peer down/fresh cluster: gossip will catch us up
        self._stop_order += [self.md_dissemination, self.backend, self.controller]

        self.broker.controller_dispatcher = dispatcher
        self.broker.controller_leader_fn = lambda: self.controller.leader_id
        self.broker.security.attach(self.controller)
        self.broker.data_policies.attach(self.controller)
        self.broker.metadata_cache = MetadataCache(
            self.controller.topic_table, self.controller.members, leaders
        )
        from redpanda_tpu.cluster.tx_gateway import TxRouter

        self.broker.tx_coordinator.router = TxRouter(
            self.broker, self.broker.metadata_cache, self.connections
        )
        # node registration happens in start() once the admin server is up
        # (its port rides the register_node command for pandascope fan-out)
        self._dispatcher = dispatcher

    @staticmethod
    def _journal_admission_episode(kind: str, info: dict) -> None:
        """Shed episodes land in the process decision journal (ADMISSION
        domain) so /v1/governor reconstructs every shed — one entry per
        episode boundary, never per request (the ring is bounded)."""
        from redpanda_tpu.coproc import governor as _governor

        _governor.journal_record(
            _governor.ADMISSION, kind,
            f"{info.get('subsystem', '?')} admission {kind}", info,
        )

    async def _start_coproc(self) -> None:
        from redpanda_tpu.coproc.api import CoprocApi

        self.coproc = await CoprocApi(self.broker, self.config).start()
        self.broker.coproc_api = self.coproc
        self._stop_order.append(self.coproc)

    async def _start_archival(self) -> None:
        """Tiered storage, wired only when enabled (application.cc:630-649)."""
        from redpanda_tpu.archival import ArchivalScheduler
        from redpanda_tpu.cloud_storage import Remote
        from redpanda_tpu.s3 import S3Client

        c = self.config
        client = S3Client(
            c.cloud_storage_bucket,
            region=c.cloud_storage_region,
            endpoint=c.cloud_storage_api_endpoint or None,
            access_key=c.cloud_storage_access_key,
            secret_key=c.cloud_storage_secret_key,
        )
        import os

        from redpanda_tpu.cloud_storage.cache import CacheService

        cache = CacheService(
            os.path.join(c.data_directory, "cloud_storage_cache"),
            max_bytes=c.cloud_storage_cache_size,
        )
        self.archival = await ArchivalScheduler(
            self.broker, Remote(client),
            interval_s=c.cloud_storage_segment_max_upload_interval_sec,
            cache=cache,
        ).start()
        self._stop_order.append(self.archival)
        self._s3_client = client

    def _register_metrics(self) -> None:
        b = self.broker
        registry.gauge(
            "partitions_total", lambda: len(b.partition_manager.partitions()),
            "Local partition replicas",
        )
        registry.gauge(
            "topics_total", lambda: len(b.topic_table.topics()), "Known topics"
        )
        bc = self.storage.log_mgr.batch_cache
        registry.gauge("batch_cache_hits", lambda: bc.hits, "Batch cache hits")
        registry.gauge(
            "batch_cache_misses", lambda: bc.misses, "Batch cache misses"
        )
        registry.gauge(
            "batch_cache_bytes", lambda: bc.bytes_used, "Batch cache bytes"
        )
        lm = self.storage.log_mgr
        registry.gauge(
            "compaction_backlog_bytes",
            lambda: lm.compaction_backlog(),
            "Closed un-compacted bytes (backlog controller input)",
        )
        registry.gauge(
            "compaction_interval_s",
            lambda: lm.backlog_controller.last_interval,
            "Backlog-controlled compaction pass interval",
        )
        rc = self.storage.log_mgr.readers_cache
        registry.gauge("readers_cache_hits", lambda: rc.hits, "Read cursor hits")
        registry.gauge(
            "readers_cache_misses", lambda: rc.misses, "Read cursor misses"
        )
        if self.coproc is not None:
            eng = self.coproc.engine
            # pool size is static per process; the busy-worker gauge
            # (coproc_host_pool_busy_workers) lives in observability.probes
            registry.gauge(
                "coproc_host_workers",
                lambda: float(eng._host_workers),
                "Configured host-stage worker pool size (0 = inline)",
            )
        from redpanda_tpu.observability import tracer

        registry.gauge(
            "trace_enabled", lambda: 1.0 if tracer.enabled else 0.0,
            "pandaprobe span tracer armed",
        )
        registry.gauge(
            "trace_spans_recorded", lambda: tracer.spans_recorded,
            "Spans committed to the trace ring since start",
        )
        from redpanda_tpu.observability.pulse import pulse as _pulse

        registry.gauge(
            "pulse_spans_recorded",
            lambda: float(_pulse.recorder.spans_recorded),
            "Spans the pandapulse flight recorder has retained-or-rotated",
        )
        registry.gauge(
            "pulse_profile_samples",
            lambda: float(_pulse.profiler.samples),
            "Wall-profile sampling ticks taken (profile_hz > 0)",
        )
        from redpanda_tpu.observability.history import history as _history

        registry.gauge(
            "history_windows_retained",
            lambda: float(len(_history.windows())),
            "Delta windows currently held in the pandatrend history ring",
        )
        registry.gauge(
            "history_breaches_total",
            lambda: float(_history.breaches_total),
            "EWMA-band breaches the trend judge has journaled since start",
        )
        from redpanda_tpu.observability.slo import slo as _slo

        registry.gauge(
            "slo_objectives_total",
            lambda: float(len(_slo.spec.objectives)),
            "Objectives in the active SLO spec (GET /v1/slo)",
        )
        if self.io_config:
            io = self.io_config
            registry.gauge(
                "iotune_seq_write_mb_s",
                lambda: io["seq_write_mb_s"],
                "iotune: sequential write MB/s",
            )
            registry.gauge(
                "iotune_fsync_p99_ms",
                lambda: io["fsync_4k"]["p99_ms"],
                "iotune: 4k fsync p99 latency",
            )

    # ------------------------------------------------------------ shutdown
    async def stop(self) -> None:
        """Reverse-order stop (application.cc:179-185)."""
        for svc in reversed(self._stop_order):
            try:
                await svc.stop()
            except Exception:
                logger.exception("stopping %s failed", type(svc).__name__)
        self._stop_order.clear()
        # uninstall OUR plane (if still current): a stopped app's module-
        # level plane would otherwise keep gating later brokers/tests in
        # this interpreter and pin its gauges' weakref alive forever
        from redpanda_tpu.resource_mgmt import budgets as rm_budgets

        if (
            getattr(self, "budget_plane", None) is not None
            and rm_budgets.current() is self.budget_plane
        ):
            rm_budgets.install(None)
        if getattr(self, "_s3_client", None) is not None:
            await self._s3_client.close()
            self._s3_client = None
        if self.connections is not None:
            await self.connections.close()

    async def run_forever(self) -> None:
        stop_event = asyncio.Event()
        try:
            await stop_event.wait()
        finally:
            await self.stop()
