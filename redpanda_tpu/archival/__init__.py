"""Tiered-storage upload side (src/v/archival parity)."""

from redpanda_tpu.archival.archiver import NtpArchiver
from redpanda_tpu.archival.scheduler import ArchivalScheduler

__all__ = ["ArchivalScheduler", "NtpArchiver"]
