"""Per-ntp segment archiver.

Parity with archival/ntp_archiver_service.h:72 + archival_policy: on each
pass, pick upload candidates — CLOSED segments (everything but the active
head) whose offsets are not yet in the remote manifest — upload them, then
upload the refreshed partition manifest. Restart-safe: the remote manifest
is the source of truth for what's already uploaded (the reference
re-downloads it on startup).
"""

from __future__ import annotations

import asyncio
import logging
import os

from redpanda_tpu.cloud_storage.manifest import PartitionManifest, SegmentMeta
from redpanda_tpu.cloud_storage.remote import Remote
from redpanda_tpu.models.fundamental import NTP

logger = logging.getLogger("rptpu.archival")


class NtpArchiver:
    def __init__(self, ntp: NTP, log, remote: Remote, revision: int = 0) -> None:
        self.ntp = ntp
        self.log = log  # storage.DiskLog
        self.remote = remote
        self.manifest = PartitionManifest(ntp, revision)
        self._synced = False
        # set when segment uploads landed but the manifest upload failed:
        # the next pass must retry the manifest even with nothing new
        self._manifest_dirty = False

    async def sync_manifest(self) -> None:
        """Seed local state from the remote manifest (startup/recovery)."""
        remote_manifest = await self.remote.download_partition_manifest(self.manifest)
        if remote_manifest is not None:
            self.manifest = remote_manifest
        self._synced = True

    def upload_candidates(self) -> list:
        """archival_policy: closed segments not yet uploaded."""
        segs = self.log.segments
        if not segs:
            return []
        closed = [s for s in segs if not s.writable]
        return [
            s for s in closed
            if not self.manifest.contains(os.path.basename(s.data_path))
            and s.dirty_offset >= s.base_offset  # non-empty
        ]

    async def upload_next_candidates(self) -> int:
        """One reconciliation pass; returns the number of uploads."""
        if not self._synced:
            await self.sync_manifest()
        uploaded = 0
        for seg in self.upload_candidates():
            name = os.path.basename(seg.data_path)
            # a closed segment can be hundreds of MB: reading it inline
            # would stall every partition on this shard for the disk read
            data = await asyncio.to_thread(_read_file, seg.data_path)
            key = self.manifest.segment_key(name)
            await self.remote.upload_segment(key, data)
            self.manifest.add(
                SegmentMeta(name, seg.base_offset, seg.dirty_offset, len(data), seg.term)
            )
            uploaded += 1
            logger.info("uploaded %s (%d bytes) for %s", name, len(data), self.ntp)
        if uploaded or self._manifest_dirty:
            self._manifest_dirty = True
            await self.remote.upload_manifest(self.manifest)
            self._manifest_dirty = False
        return uploaded


def _read_file(path: str) -> bytes:
    with open(path, "rb") as f:
        return f.read()
