"""Archival scheduler: reconcile archivers with the partition set.

Parity with archival/service.h:96-186 scheduler_service: a periodic fiber
(re)builds the ntp → archiver map from the partitions this node leads,
runs each archiver's upload pass, and uploads topic manifests for new
topics. Only wired when cloud_storage_enabled (application.cc:630-649).
"""

from __future__ import annotations

import asyncio
import logging

from redpanda_tpu.archival.archiver import NtpArchiver
from redpanda_tpu.cloud_storage.manifest import TopicManifest
from redpanda_tpu.cloud_storage.remote import Remote
from redpanda_tpu.models.fundamental import NTP

logger = logging.getLogger("rptpu.archival")


class ArchivalScheduler:
    def __init__(
        self, broker, remote: Remote, *, interval_s: float = 30.0, cache=None
    ) -> None:
        self.broker = broker
        self.remote = remote
        self.cache = cache  # cloud_storage.CacheService for the read side
        self.interval_s = interval_s
        self.archivers: dict[NTP, NtpArchiver] = {}
        self._uploaded_topic_manifests: set[str] = set()
        self._task: asyncio.Task | None = None
        self._bg_tasks: set[asyncio.Task] = set()

    async def start(self) -> "ArchivalScheduler":
        self._task = asyncio.create_task(self._loop())
        return self

    async def stop(self) -> None:
        for t in list(self._bg_tasks) + ([self._task] if self._task else []):
            t.cancel()
        tasks = list(self._bg_tasks) + ([self._task] if self._task else [])
        if tasks:
            await asyncio.gather(*tasks, return_exceptions=True)
        self._bg_tasks.clear()
        self._task = None

    async def _loop(self) -> None:
        while True:
            try:
                await self.run_once()
            except asyncio.CancelledError:
                raise
            except Exception:
                logger.exception("archival pass failed")
            await asyncio.sleep(self.interval_s)

    async def run_once(self) -> int:
        """One reconcile + upload pass; returns total segment uploads.
        Failures are isolated per ntp so one poisoned partition cannot
        starve the rest (the reference's per-archiver fibers)."""
        self._reconcile()
        total = 0
        for ntp, archiver in list(self.archivers.items()):
            try:
                total += await archiver.upload_next_candidates()
            except asyncio.CancelledError:
                raise
            except Exception:
                logger.exception("archival pass failed for %s", ntp)
        return total

    def _reconcile(self) -> None:
        """Archive partitions this node leads, skip internal topics."""
        current: set[NTP] = set()
        for ntp, p in self.broker.partition_manager.partitions().items():
            if self.broker.is_internal_topic(ntp.topic) or "$" in ntp.topic:
                continue
            if not p.is_leader():
                continue
            current.add(ntp)
            if ntp not in self.archivers:
                md = self.broker.topic_table.get(ntp.topic)
                revision = md.config.revision if md else 0
                archiver = NtpArchiver(ntp, p.log, self.remote, revision)
                self.archivers[ntp] = archiver
                # read side: fetches below the local start fall through to
                # the bucket; the leader shares the archiver's manifest
                from redpanda_tpu.cloud_storage.remote_partition import RemotePartition

                p.attach_remote(
                    RemotePartition(
                        ntp, self.remote, self.cache, revision,
                        manifest_source=lambda a=archiver: a.manifest,
                    )
                )
            if ntp.topic not in self._uploaded_topic_manifests:
                self._uploaded_topic_manifests.add(ntp.topic)
                t = asyncio.get_running_loop().create_task(
                    self._upload_topic_manifest(ntp.topic)
                )
                self._bg_tasks.add(t)
                t.add_done_callback(self._bg_tasks.discard)
        for gone in set(self.archivers) - current:
            del self.archivers[gone]

    async def _upload_topic_manifest(self, topic: str) -> None:
        md = self.broker.topic_table.get(topic)
        if md is None:
            return
        cfg_map = {k: v for k, v in md.config.config_map().items() if v is not None}
        # recovery needs the incarnation id to locate partition manifests
        cfg_map["x-rp-revision"] = str(md.config.revision)
        tm = TopicManifest(
            md.config.ns, topic, md.config.partition_count,
            md.config.replication_factor, cfg_map,
        )
        try:
            await self.remote.upload_manifest(tm)
        except Exception:
            logger.exception("topic manifest upload failed for %s", topic)
            self._uploaded_topic_manifests.discard(topic)
