"""Owned async HTTP/1.1 server.

Server twin of `http/client.py` — the reference owns both sides of its
HTTP stack (seastar httpd under pandaproxy/server.h:40 `server`
ctx/routes, admin_server.cc swagger routes); this is the tpu-native
equivalent: an asyncio server that owns request-line/header parsing,
Content-Length and chunked request bodies, 100-continue, keep-alive with
an idle deadline, TLS, routing with `{param}` path templates, a
middleware chain, and graceful shutdown. Admin API, REST proxy, and
schema registry all serve on this (no third-party HTTP library).

Handlers are `async def h(request) -> Response`. The `web` facade in
`http/web.py` exposes the familiar route-table surface
(`web.get(path, h)`, `web.json_response`, ...) on top of this module.
"""

from __future__ import annotations

import asyncio
import json
import logging
import re
import ssl as ssl_mod
import urllib.parse
from dataclasses import dataclass
from http import HTTPStatus

from redpanda_tpu.http.framing import (
    FramingError,
    Headers,
    read_chunked,
    read_header_block,
)

MAX_BODY_BYTES = 256 * 1024 * 1024  # REST proxy produce payloads can be large
IDLE_KEEPALIVE_S = 75.0
# headers+body must arrive within this once the request line lands —
# bounds slowloris-style dribble on admin/proxy ports
REQUEST_READ_TIMEOUT_S = 120.0


class BadRequest(Exception):
    """Malformed wire input; connection answers 400 and closes."""


# ----------------------------------------------------------------- request
class Query:
    """Read-only view of the query string (parse once, first value wins —
    matches how the admin/proxy handlers consume repeated keys)."""

    def __init__(self, raw: str) -> None:
        self._raw = raw
        self._d = {k: v[0] for k, v in urllib.parse.parse_qs(raw, keep_blank_values=True).items()}

    def get(self, key: str, default: str | None = None) -> str | None:
        return self._d.get(key, default)

    def __contains__(self, key: str) -> bool:
        return key in self._d

    def __getitem__(self, key: str) -> str:
        return self._d[key]

    def items(self):
        return self._d.items()


class Request:
    def __init__(
        self,
        method: str,
        target: str,
        version: str,
        headers: "Headers",
        body: bytes,
        peername: tuple | None = None,
    ) -> None:
        self.method = method
        self.version = version
        self.raw_path = target  # path?query exactly as sent
        path, _, qs = target.partition("?")
        # routing matches on the RAW path (an encoded %2F must not split a
        # {param} segment); params and .path are percent-decoded after
        self.path_raw = path
        self.path = urllib.parse.unquote(path)
        self.query_string = qs
        self.query = Query(qs)
        self.headers = headers  # keys lower-cased, duplicates comma-joined
        self.match_info: dict[str, str] = {}
        self.peername = peername
        self._body = body

    # -- body accessors (async for handler-code symmetry with the client
    # and so a future streaming-body server keeps the same handler API)
    async def read(self) -> bytes:
        return self._body

    async def text(self) -> str:
        return self._body.decode("utf-8")

    async def json(self):
        if not self._body:
            return None
        try:
            return json.loads(self._body)
        except ValueError as e:
            raise BadRequest(f"invalid json body: {e}") from e

    @property
    def content_type(self) -> str:
        return self.headers.get("content-type", "").partition(";")[0].strip()

    @property
    def can_read_body(self) -> bool:
        return bool(self._body)


# ---------------------------------------------------------------- response
class Response:
    def __init__(
        self,
        *,
        status: int = 200,
        body: bytes | None = None,
        text: str | None = None,
        headers: dict[str, str] | None = None,
        content_type: str | None = None,
        charset: str | None = None,
    ) -> None:
        self.status = status
        if text is not None:
            self.body = text.encode(charset or "utf-8")
            if content_type is None:
                content_type = "text/plain"
        else:
            self.body = body or b""
        self.headers = dict(headers or {})
        self.content_type = content_type
        self.charset = charset


def json_response(
    data,
    *,
    status: int = 200,
    headers: dict[str, str] | None = None,
    content_type: str | None = None,  # e.g. application/vnd.kafka.v2+json
) -> Response:
    return Response(
        status=status,
        body=json.dumps(data).encode(),
        headers=headers,
        content_type=content_type or "application/json",
        charset="utf-8",
    )


# ----------------------------------------------------------------- routing
_PARAM_RE = re.compile(r"\{([a-zA-Z_][a-zA-Z0-9_]*)\}")


@dataclass(frozen=True)
class Route:
    method: str
    pattern: re.Pattern
    handler: object
    raw_path: str


def compile_route(method: str, path: str, handler) -> Route:
    """`/v1/partitions/kafka/{topic}/{partition}/x` -> anchored regex with
    named groups; a param matches one path segment (no '/')."""
    rx = "^" + _PARAM_RE.sub(lambda m: f"(?P<{m.group(1)}>[^/]+)", re.escape(path).replace(r"\{", "{").replace(r"\}", "}")) + "$"
    return Route(method.upper(), re.compile(rx), handler, path)


class Router:
    def __init__(self) -> None:
        self._routes: list[Route] = []

    def add(self, route: Route) -> None:
        self._routes.append(route)

    def resolve(self, method: str, path: str) -> tuple[object | None, dict[str, str], bool]:
        """-> (handler, params, path_known). path_known distinguishes
        404 (no route at all) from 405 (path exists, method doesn't)."""
        path_known = False
        for r in self._routes:
            m = r.pattern.match(path)
            if m is None:
                continue
            path_known = True
            if r.method == method or (method == "HEAD" and r.method == "GET"):
                return r.handler, {k: urllib.parse.unquote(v) for k, v in m.groupdict().items()}, True
        return None, {}, path_known


# ------------------------------------------------------------------ server
class HttpServer:
    """One listener + routing + middleware chain + connection loop."""

    def __init__(
        self,
        host: str = "127.0.0.1",
        port: int = 0,
        *,
        middlewares: list | None = None,
        logger: logging.Logger | None = None,
        idle_timeout: float = IDLE_KEEPALIVE_S,
    ) -> None:
        self.host = host
        self.port = port
        self.router = Router()
        self.middlewares = list(middlewares or [])
        self.log = logger or logging.getLogger("rptpu.http.server")
        self.idle_timeout = idle_timeout
        self._server: asyncio.AbstractServer | None = None
        self._conn_tasks: set[asyncio.Task] = set()

    # -- route registration
    def add_route(self, method: str, path: str, handler) -> None:
        self.router.add(compile_route(method, path, handler))

    # -- lifecycle
    async def start(self, ssl_context: ssl_mod.SSLContext | None = None) -> "HttpServer":
        self._server = await asyncio.start_server(
            self._on_connection, self.host, self.port, ssl=ssl_context
        )
        if self.port == 0:
            self.port = self._server.sockets[0].getsockname()[1]
        return self

    async def stop(self) -> None:
        if self._server is not None:
            self._server.close()
        # in-flight handlers get cancelled BEFORE wait_closed: on 3.12+
        # Server.wait_closed blocks until every connection handler returns,
        # and idle keep-alive loops would hold it for idle_timeout (the
        # reference's httpd likewise aborts sockets on shutdown rather
        # than draining indefinitely)
        for t in list(self._conn_tasks):
            t.cancel()
        if self._conn_tasks:
            await asyncio.gather(*self._conn_tasks, return_exceptions=True)
        self._conn_tasks.clear()
        if self._server is not None:
            await self._server.wait_closed()
            self._server = None

    # -- connection loop
    async def _on_connection(self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter) -> None:
        task = asyncio.current_task()
        if task is not None:
            self._conn_tasks.add(task)
            task.add_done_callback(self._conn_tasks.discard)
        try:
            await self._serve_connection(reader, writer)
        except (
            asyncio.IncompleteReadError,
            asyncio.TimeoutError,
            ConnectionError,
            ssl_mod.SSLError,
        ):
            pass  # peer went away / idle close: normal
        except asyncio.CancelledError:
            pass  # server stopping
        except Exception:
            self.log.exception("connection loop failed")
        finally:
            try:
                writer.close()
                await writer.wait_closed()
            except (OSError, asyncio.CancelledError):
                pass

    async def _serve_connection(self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter) -> None:
        peer = writer.get_extra_info("peername")
        while True:
            try:
                req, keep_alive = await self._read_request(reader, writer, peer)
            except ValueError as e:
                # StreamReader line-limit overrun: a header/chunk line longer
                # than the read buffer — same answer as an oversized section
                await self._write_response(
                    writer, False,
                    json_response({"error": "header line too long"}, status=400),
                    head_only=False,
                )
                return
            except BadRequest as e:
                await self._write_response(
                    writer, False,
                    json_response({"error": str(e)}, status=400), head_only=False,
                )
                return
            if req is None:
                return  # clean EOF between requests
            resp = await self._dispatch(req)
            try:
                await self._write_response(
                    writer, keep_alive, resp, head_only=req.method == "HEAD"
                )
            except (ConnectionError, OSError):
                return
            if not keep_alive:
                return

    async def _read_request(
        self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter, peer
    ) -> tuple[Request | None, bool]:
        # idle deadline applies to waiting for the NEXT request line
        try:
            request_line = await asyncio.wait_for(reader.readline(), timeout=self.idle_timeout)
        except asyncio.TimeoutError:
            return None, False
        if not request_line:
            return None, False
        # once the request line lands, the rest of the message must arrive
        # within the read deadline — a peer dribbling headers or never
        # finishing its body (slowloris) must not pin the task forever
        try:
            return await asyncio.wait_for(
                self._read_rest(reader, writer, peer, request_line),
                timeout=REQUEST_READ_TIMEOUT_S,
            )
        except asyncio.TimeoutError as e:
            raise BadRequest("request read timed out") from e
        except FramingError as e:
            raise BadRequest(str(e)) from e

    async def _read_rest(
        self, reader, writer, peer, request_line: bytes
    ) -> tuple[Request, bool]:
        try:
            method, target, version = request_line.decode("latin-1").rstrip("\r\n").split(" ", 2)
        except ValueError as e:
            raise BadRequest("malformed request line") from e
        method = method.upper()
        if not version.startswith("HTTP/1."):
            raise BadRequest(f"unsupported version {version!r}")

        headers, _ = await read_header_block(reader, len(request_line), eof_ends=False)

        # RFC 9110 §10.1.1: reply 100 Continue before the client commits
        # the body (our own client doesn't send Expect; curl does on big PUTs)
        if headers.get("expect", "").lower() == "100-continue":
            writer.write(b"HTTP/1.1 100 Continue\r\n\r\n")
            await writer.drain()

        body = b""
        te = headers.get("transfer-encoding", "").lower()
        if "chunked" in te:
            body = await read_chunked(reader, MAX_BODY_BYTES)
        elif te and te != "identity":
            raise BadRequest(f"unsupported transfer-encoding {te!r}")
        elif "content-length" in headers:
            try:
                n = int(headers["content-length"])
            except ValueError as e:
                raise BadRequest("bad content-length") from e
            if n < 0 or n > MAX_BODY_BYTES:
                raise BadRequest(f"content-length out of range: {n}")
            if n:
                body = await reader.readexactly(n)

        keep_alive = (
            headers.get("connection", "").lower() != "close"
            if version == "HTTP/1.1"
            else headers.get("connection", "").lower() == "keep-alive"
        )
        return Request(method, target, version, headers, body, peer), keep_alive

    # -- dispatch
    async def _dispatch(self, req: Request) -> Response:
        handler, params, path_known = self.router.resolve(req.method, req.path_raw)
        if handler is None:
            if path_known:
                return json_response({"error": "method not allowed"}, status=405)
            return json_response({"error": f"unknown path {req.path}"}, status=404)
        req.match_info = params

        call = handler
        # middleware chain, outermost first (signature:
        # mw(request, handler) -> response)
        for mw in reversed(self.middlewares):
            call = _bind_middleware(mw, call)
        try:
            return await call(req)
        except BadRequest as e:
            return json_response({"error": str(e)}, status=400)
        except asyncio.CancelledError:
            raise
        except Exception:
            self.log.exception("%s %s handler failed", req.method, req.path)
            return json_response({"error": "internal server error"}, status=500)

    async def _write_response(
        self,
        writer: asyncio.StreamWriter,
        keep_alive: bool,
        resp: Response,
        *,
        head_only: bool,
    ) -> None:
        reason = HTTPStatus(resp.status).phrase if resp.status in HTTPStatus._value2member_map_ else ""
        hdrs = {k.lower(): v for k, v in resp.headers.items()}
        if resp.content_type is not None and "content-type" not in hdrs:
            ct = resp.content_type
            if resp.charset:
                ct += f"; charset={resp.charset}"
            hdrs["content-type"] = ct
        hdrs["content-length"] = str(len(resp.body))
        hdrs["connection"] = "keep-alive" if keep_alive else "close"
        head = f"HTTP/1.1 {resp.status} {reason}\r\n"
        head += "".join(f"{k}: {v}\r\n" for k, v in hdrs.items())
        head += "\r\n"
        writer.write(head.encode("latin-1") + (b"" if head_only else resp.body))
        await writer.drain()


def _bind_middleware(mw, nxt):
    async def bound(request: Request) -> Response:
        return await mw(request, nxt)

    return bound
