"""Owned async HTTP/1.1 client.

Parity with the reference's Beast-based http layer (http/client.h:71-99
`client : rpc::base_transport` with get_connected/max_idle_time,
http/chunk_encoding.h chunked framing, http/probe.h counters): an
asyncio-streams client that owns its wire framing rather than delegating to
a third-party HTTP library — request serialization, status-line/header
parsing, Content-Length and chunked transfer decoding, keep-alive
connection reuse with an idle deadline, TLS, and per-request timeouts.

One `HttpClient` holds at most one live connection per origin (the
reference's client is likewise one transport; `s3::client_pool` layers
pooling above it, as our S3 layer does with retries above this class).
"""

from __future__ import annotations

import asyncio
import ssl as ssl_mod
import time
import urllib.parse
from dataclasses import dataclass, field

from redpanda_tpu.http.framing import (
    MAX_HEADER_BYTES,
    FramingError,
    read_chunked,
    read_header_block,
)

DEFAULT_CONNECT_TIMEOUT = 5.0  # http/client.h:63 default_connect_timeout = 5s
MAX_BODY_BYTES = 1 << 30


class HttpError(Exception):
    """Transport- or framing-level failure (not a non-2xx status)."""


@dataclass
class HttpProbe:
    """Client counters (http/probe.h): requests, bytes, errors."""

    requests: int = 0
    responses: int = 0
    transport_errors: int = 0
    bytes_sent: int = 0
    bytes_received: int = 0

    def as_dict(self) -> dict[str, int]:
        return dict(self.__dict__)


@dataclass
class HttpResponse:
    status: int
    reason: str
    headers: dict[str, str]  # keys lower-cased; duplicates comma-joined
    body: bytes

    def header(self, name: str, default: str = "") -> str:
        return self.headers.get(name.lower(), default)


@dataclass
class _Conn:
    reader: asyncio.StreamReader
    writer: asyncio.StreamWriter
    last_used: float = field(default_factory=time.monotonic)

    def stale(self, max_idle: float) -> bool:
        return (time.monotonic() - self.last_used) > max_idle

    async def close(self) -> None:
        try:
            self.writer.close()
            await self.writer.wait_closed()
        except (OSError, asyncio.CancelledError):
            pass


def _parse_origin(base_url: str) -> tuple[str, str, int, bool, str]:
    u = urllib.parse.urlsplit(base_url)
    if u.scheme not in ("http", "https"):
        raise HttpError(f"unsupported scheme: {base_url!r}")
    tls = u.scheme == "https"
    if not u.hostname:
        raise HttpError(f"no host in {base_url!r}")
    prefix = u.path.rstrip("/")  # base path (e.g. reverse-proxy mount point)
    return u.hostname, u.netloc, u.port or (443 if tls else 80), tls, prefix


# methods safe to transparently resend after a connection-level failure
_IDEMPOTENT = frozenset({"GET", "HEAD", "PUT", "DELETE", "OPTIONS", "TRACE"})


class HttpClient:
    """HTTP/1.1 client for one origin with a keep-alive connection pool.

    `base_url` fixes scheme/host/port (plus an optional base path prefix,
    e.g. a reverse-proxy mount point); `request()` takes the raw
    path-and-query string and sends it verbatim (no re-encoding — the S3
    SigV4 path depends on byte-identical URIs, s3/signature parity).

    Up to `max_connections` requests run concurrently, each on its own
    connection (the reference layers `s3::client_pool` above its
    one-connection client; here the pool is built in, client.h:217-227).
    """

    def __init__(
        self,
        base_url: str,
        *,
        connect_timeout: float = DEFAULT_CONNECT_TIMEOUT,
        request_timeout: float = 60.0,
        max_idle_s: float = 30.0,
        max_connections: int = 8,
        ssl_context: ssl_mod.SSLContext | None = None,
        verify_tls: bool = True,
    ) -> None:
        (
            self.host,
            self.netloc,
            self.port,
            self.tls,
            self.path_prefix,
        ) = _parse_origin(base_url)
        self.connect_timeout = connect_timeout
        self.request_timeout = request_timeout
        self.max_idle_s = max_idle_s
        self.probe = HttpProbe()
        self._idle: list[_Conn] = []
        self._closed = False
        self._sem = asyncio.Semaphore(max_connections)
        if ssl_context is not None:
            self._ssl: ssl_mod.SSLContext | None = ssl_context
        elif self.tls:
            ctx = ssl_mod.create_default_context()
            if not verify_tls:
                ctx.check_hostname = False
                ctx.verify_mode = ssl_mod.CERT_NONE
            self._ssl = ctx
        else:
            self._ssl = None

    # ------------------------------------------------------------ lifecycle
    async def _checkout(self) -> _Conn:
        """Adopt an idle keep-alive connection or dial (client.h:97-99)."""
        if self._closed:
            raise HttpError("client closed")
        while self._idle:
            conn = self._idle.pop()
            # at_eof catches a peer half-close (server idle timeout shorter
            # than ours) that writer.is_closing() cannot see
            if (
                conn.stale(self.max_idle_s)
                or conn.writer.is_closing()
                or conn.reader.at_eof()
            ):
                await conn.close()
                continue
            return conn
        return await self._dial()

    async def _dial(self, timeout: float | None = None) -> _Conn:
        try:
            reader, writer = await asyncio.wait_for(
                asyncio.open_connection(self.host, self.port, ssl=self._ssl),
                timeout=self.connect_timeout if timeout is None else timeout,
            )
        except (OSError, asyncio.TimeoutError) as e:
            self.probe.transport_errors += 1
            raise HttpError(f"connect {self.host}:{self.port}: {e}") from e
        return _Conn(reader, writer)

    def _checkin(self, conn: _Conn) -> None:
        if self._closed:
            # a request that was in flight when close() ran must not park
            # its socket in a pool nobody will drain again
            conn.writer.close()
            return
        conn.last_used = time.monotonic()
        self._idle.append(conn)

    async def close(self) -> None:
        self._closed = True
        while self._idle:
            await self._idle.pop().close()

    async def __aenter__(self) -> "HttpClient":
        return self

    async def __aexit__(self, *exc) -> None:
        await self.close()

    # -------------------------------------------------------------- request
    async def request(
        self,
        method: str,
        path_qs: str,
        *,
        headers: dict[str, str] | None = None,
        body: bytes = b"",
        chunked: bool = False,
    ) -> HttpResponse:
        """Send one request; `chunked=True` frames the body with chunked
        transfer-encoding (http/chunk_encoding.h) instead of Content-Length."""
        async with self._sem:
            # A connection-level failure (peer dropped a keep-alive socket,
            # reset before the response) is retried ONCE on a fresh dial —
            # but only for idempotent methods: a POST may have executed
            # server-side even though the response never arrived.
            # request_timeout is one budget for the whole logical request:
            # the retry attempt gets only what the first attempt left.
            deadline = time.monotonic() + self.request_timeout
            for attempt in (0, 1):
                if attempt == 0:
                    conn = await self._checkout()
                else:
                    budget = deadline - time.monotonic()
                    if budget <= 0:
                        self.probe.transport_errors += 1
                        raise HttpError(
                            f"request timeout ({self.request_timeout}s)"
                        )
                    # dial fresh for the retry — the pool may hold more
                    # half-closed sockets from the same server idle-timeout
                    # sweep (checkout's at_eof guard drops those lazily)
                    conn = await self._dial(timeout=min(self.connect_timeout, budget))
                try:
                    resp = await asyncio.wait_for(
                        self._round_trip(conn, method, path_qs, headers, body, chunked),
                        timeout=max(0.001, deadline - time.monotonic()),
                    )
                except (
                    HttpError,
                    OSError,
                    ValueError,  # int parses + StreamReader limit overruns
                    asyncio.IncompleteReadError,
                    asyncio.TimeoutError,
                ) as e:
                    # never reuse a connection in an unknown framing state
                    await conn.close()
                    # TimeoutError subclasses OSError (3.11+): never retried
                    retriable = (
                        isinstance(e, (OSError, asyncio.IncompleteReadError))
                        and not isinstance(e, asyncio.TimeoutError)
                        and method in _IDEMPOTENT
                        and attempt == 0
                    )
                    if not retriable:
                        self.probe.transport_errors += 1
                        if isinstance(e, asyncio.TimeoutError):
                            raise HttpError(f"request timeout ({self.request_timeout}s)") from e
                        raise e if isinstance(e, HttpError) else HttpError(str(e)) from e
                else:
                    return resp
            raise AssertionError("unreachable")

    async def _round_trip(
        self,
        conn: _Conn,
        method: str,
        path_qs: str,
        headers: dict[str, str] | None,
        body: bytes,
        chunked: bool,
    ) -> HttpResponse:
        hdrs = {"host": self.netloc, "connection": "keep-alive"}
        if headers:
            hdrs.update({k.lower(): v for k, v in headers.items()})
        if chunked:
            hdrs["transfer-encoding"] = "chunked"
            hdrs.pop("content-length", None)
        elif body or method in ("PUT", "POST", "PATCH"):
            hdrs["content-length"] = str(len(body))

        if not path_qs.startswith("/"):
            path_qs = "/" + path_qs
        if self.path_prefix:
            path_qs = self.path_prefix + path_qs
        head = f"{method} {path_qs} HTTP/1.1\r\n"
        head += "".join(f"{k}: {v}\r\n" for k, v in hdrs.items())
        head += "\r\n"
        wire = head.encode("latin-1")
        if chunked:
            # single data chunk + terminal chunk is a valid chunked stream
            if body:
                wire += f"{len(body):x}\r\n".encode() + body + b"\r\n"
            wire += b"0\r\n\r\n"
        else:
            wire += body
        conn.writer.write(wire)
        await conn.writer.drain()
        self.probe.requests += 1
        self.probe.bytes_sent += len(wire)

        resp = await self._read_response(conn.reader, method)
        self.probe.responses += 1
        if resp.header("connection").lower() == "close":
            await conn.close()
        else:
            self._checkin(conn)
        return resp

    # ------------------------------------------------------------- response
    async def _read_response(
        self, reader: asyncio.StreamReader, method: str
    ) -> HttpResponse:
        # RFC 9110 §15.2: interim 1xx responses may precede the final one;
        # each is a bare status line + headers with no body. Loop until a
        # final (>=200) status arrives — returning a 1xx would leave the
        # real response unread and desync the keep-alive framing. `total`
        # accumulates across interim messages so MAX_HEADER_BYTES bounds
        # the whole exchange (a server streaming 100s forever fails fast).
        total = 0
        while True:
            status_line = await reader.readline()
            if not status_line:
                raise asyncio.IncompleteReadError(b"", None)
            parts = status_line.decode("latin-1").rstrip("\r\n").split(" ", 2)
            if len(parts) < 2 or not parts[0].startswith("HTTP/1."):
                raise HttpError(f"bad status line: {status_line!r}")
            try:
                status = int(parts[1])
            except ValueError as e:
                raise HttpError(f"bad status line: {status_line!r}") from e
            reason = parts[2] if len(parts) > 2 else ""

            total += len(status_line)
            try:
                headers, total = await read_header_block(reader, total, eof_ends=True)
            except FramingError as e:
                raise HttpError(str(e)) from e
            if status >= 200:
                break

        body = b""
        if method != "HEAD" and status not in (204, 304):
            if "chunked" in headers.get("transfer-encoding", "").lower():
                try:
                    body = await read_chunked(reader, MAX_BODY_BYTES)
                except FramingError as e:
                    raise HttpError(str(e)) from e
            elif "content-length" in headers:
                try:
                    n = int(headers["content-length"])
                except ValueError as e:
                    raise HttpError(
                        f"bad content-length: {headers['content-length']!r}"
                    ) from e
                if n > MAX_BODY_BYTES:
                    raise HttpError(f"body too large: {n}")
                body = await reader.readexactly(n) if n else b""
            else:
                # no framing info: body runs to connection close (HTTP/1.0
                # style). StreamReader.read(n) returns what's buffered after
                # one wait, so loop until true EOF.
                parts = []
                got = 0
                while got <= MAX_BODY_BYTES:
                    part = await reader.read(64 * 1024)
                    if not part:
                        break
                    parts.append(part)
                    got += len(part)
                else:
                    raise HttpError("unframed body too large")
                body = b"".join(parts)
                headers["connection"] = "close"
        self.probe.bytes_received += len(body)
        return HttpResponse(status, reason, headers, body)
