"""Shared HTTP/1.1 wire framing used by BOTH the owned client and the
owned server (http/client.py, http/server.py).

One implementation of header-block parsing and chunked transfer decoding
so a framing fix can never land on one side only. The reference keeps the
same split: http/chunk_encoding.h is shared by its client and the seastar
httpd server path.
"""

from __future__ import annotations

import asyncio

MAX_HEADER_BYTES = 64 * 1024


class FramingError(Exception):
    """Wire-level framing violation. The client surfaces it as HttpError,
    the server as a 400 response."""


class Headers(dict):
    """Case-insensitive header mapping (stored lower-cased; callers may
    look up 'Authorization' or 'authorization' interchangeably).

    The MUTATORS normalize too: a mixed-case write must land on the same
    key the readers resolve, or `h['Content-Length'] = n` next to a parsed
    'content-length' creates an unreachable duplicate that serializes as
    two conflicting wire headers."""

    def __getitem__(self, key: str) -> str:
        return super().__getitem__(key.lower())

    def get(self, key: str, default=None):
        return super().get(key.lower(), default)

    def __contains__(self, key) -> bool:
        return super().__contains__(str(key).lower())

    def __setitem__(self, key: str, value) -> None:
        super().__setitem__(key.lower(), value)

    def __delitem__(self, key: str) -> None:
        super().__delitem__(key.lower())

    def setdefault(self, key: str, default=None):
        return super().setdefault(key.lower(), default)

    _POP_MISSING = object()

    def pop(self, key: str, default=_POP_MISSING):
        if default is self._POP_MISSING:
            return super().pop(key.lower())
        return super().pop(key.lower(), default)

    def update(self, other=(), **kw):
        # route every entry through __setitem__ (dict.update bypasses it)
        items = other.items() if hasattr(other, "items") else other
        for k, v in items:
            self[k] = v
        for k, v in kw.items():
            self[k] = v


async def read_header_block(
    reader: asyncio.StreamReader,
    total: int,
    *,
    eof_ends: bool,
) -> tuple[Headers, int]:
    """Parse `k: v` lines up to the blank line. `total` counts bytes already
    consumed of this message's head (request/status line) — and, on the
    client, of preceding interim 1xx messages, so MAX_HEADER_BYTES bounds
    the whole exchange; returns the updated count. `eof_ends=True` treats
    EOF as end-of-headers (client posture for torn responses); False raises
    (server posture — a request without its blank line is malformed)."""
    headers = Headers()
    while True:
        line = await reader.readline()
        total += len(line)
        if total > MAX_HEADER_BYTES:
            raise FramingError("header section too large")
        if line in (b"\r\n", b"\n"):
            break
        if line == b"":
            if eof_ends:
                break
            raise FramingError("eof in headers")
        k, sep, v = line.decode("latin-1").partition(":")
        if not sep:
            raise FramingError("malformed header line")
        k = k.strip().lower()
        v = v.strip()
        headers[k] = f"{headers[k]}, {v}" if k in headers else v
    return headers, total


async def read_chunked(reader: asyncio.StreamReader, max_bytes: int) -> bytes:
    """Chunked transfer decoding (http/chunk_encoding.h inverse), strict:
    a blank line where a chunk-size line belongs is a framing error, not a
    terminal chunk — treating it as '0' would silently accept a truncated
    body and desync keep-alive framing."""
    out = bytearray()
    while True:
        size_line = await reader.readline()
        if not size_line:
            raise asyncio.IncompleteReadError(b"", None)
        stripped = size_line.split(b";", 1)[0].strip()
        if not stripped:
            raise FramingError("blank chunk size line")
        try:
            size = int(stripped, 16)
        except ValueError as e:
            raise FramingError(f"bad chunk size: {size_line!r}") from e
        if size == 0:
            # trailers until blank line (EOF also terminates: the message
            # is complete at the 0-chunk; trailers are optional metadata)
            while True:
                t = await reader.readline()
                if t in (b"\r\n", b"\n", b""):
                    return bytes(out)
        if len(out) + size > max_bytes:
            raise FramingError("chunked body too large")
        out += await reader.readexactly(size)
        if await reader.readexactly(2) != b"\r\n":
            raise FramingError("bad chunk terminator")
