"""Route-table facade over the owned HTTP server (`http/server.py`).

The admin API, REST proxy, and schema registry declare their surfaces as
route tables (`web.get(path, handler)`, handlers returning
`web.json_response(...)`) — the same shape the reference declares in its
api-doc JSON + seastar httpd route registrations (pandaproxy/server.h:40,
admin_server.cc). This module maps that declaration style onto the owned
`HttpServer`; no third-party HTTP library is involved.
"""

from __future__ import annotations

import ssl as ssl_mod
from dataclasses import dataclass

from redpanda_tpu.http.server import (  # noqa: F401  (re-exported surface)
    BadRequest,
    HttpServer,
    Request,
    Response,
    json_response,
)


@dataclass(frozen=True)
class RouteDef:
    method: str
    path: str
    handler: object


def get(path: str, handler) -> RouteDef:
    return RouteDef("GET", path, handler)


def post(path: str, handler) -> RouteDef:
    return RouteDef("POST", path, handler)


def put(path: str, handler) -> RouteDef:
    return RouteDef("PUT", path, handler)


def delete(path: str, handler) -> RouteDef:
    return RouteDef("DELETE", path, handler)


def middleware(fn):
    """Marker for middleware callables `mw(request, handler) -> response`
    (kept for declaration-site readability; the chain binds by position)."""
    return fn


class Application:
    """A route table + middleware list, served by `AppRunner`."""

    def __init__(self, middlewares: list | None = None) -> None:
        self.middlewares = list(middlewares or [])
        self.routes: list[RouteDef] = []

    def add_routes(self, routes: list[RouteDef]) -> None:
        self.routes.extend(routes)


class AppRunner:
    """Owns the listening `HttpServer` for one Application."""

    def __init__(self, app: Application, access_log=None) -> None:
        self.app = app
        self._server: HttpServer | None = None

    async def setup(self) -> None:  # split start kept for lifecycle parity
        pass

    async def listen(
        self,
        host: str,
        port: int,
        ssl_context: ssl_mod.SSLContext | None = None,
        logger=None,
    ) -> int:
        srv = HttpServer(host, port, middlewares=self.app.middlewares, logger=logger)
        for r in self.app.routes:
            srv.add_route(r.method, r.path, r.handler)
        await srv.start(ssl_context=ssl_context)
        self._server = srv
        return srv.port

    @property
    def addresses(self) -> list[tuple[str, int]]:
        if self._server is None:
            return []
        return [(self._server.host, self._server.port)]

    async def cleanup(self) -> None:
        if self._server is not None:
            await self._server.stop()
            self._server = None
