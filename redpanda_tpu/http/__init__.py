"""Owned async HTTP/1.1 client layer (reference src/v/http)."""

from redpanda_tpu.http.client import (
    HttpClient,
    HttpError,
    HttpProbe,
    HttpResponse,
)

__all__ = ["HttpClient", "HttpError", "HttpProbe", "HttpResponse"]
