"""Owned async HTTP/1.1 layer — client AND server (reference src/v/http
for the client, pandaproxy/server.h + seastar httpd for the server)."""

from redpanda_tpu.http import web
from redpanda_tpu.http.client import (
    HttpClient,
    HttpError,
    HttpProbe,
    HttpResponse,
)
from redpanda_tpu.http.server import HttpServer

__all__ = [
    "HttpClient",
    "HttpError",
    "HttpProbe",
    "HttpResponse",
    "HttpServer",
    "web",
]
