"""Raft consensus layer (parity with src/v/raft).

One ``Consensus`` per partition replica over the storage log; batched
cross-group heartbeats; prevote elections; recovery with a shared throttle;
snapshot install; joint-consensus membership changes; state-machine apply
loops. The ``GroupManager`` wires it all to the internal RPC mesh.
"""

from redpanda_tpu.raft.configuration import ConfigurationManager, GroupConfiguration
from redpanda_tpu.raft.consensus import Consensus, OffsetMonitor, RaftTimings
from redpanda_tpu.raft.group_manager import GroupManager
from redpanda_tpu.raft.heartbeat_manager import HeartbeatManager
from redpanda_tpu.raft.service import RaftService, raftgen_service
from redpanda_tpu.raft.state_machine import MuxStateMachine, StateMachine
from redpanda_tpu.raft.types import (
    ConsistencyLevel,
    Errc,
    FollowerIndex,
    RaftError,
    ReplicateResult,
    VNode,
)

__all__ = [
    "ConfigurationManager", "GroupConfiguration", "Consensus", "OffsetMonitor",
    "RaftTimings", "GroupManager", "HeartbeatManager", "RaftService",
    "raftgen_service", "MuxStateMachine", "StateMachine", "ConsistencyLevel",
    "Errc", "FollowerIndex", "RaftError", "ReplicateResult", "VNode",
]
