"""Raft RPC service schema + server-side dispatch.

Parity with the service generated from raftgen.json (vote, append_entries,
heartbeat, install_snapshot, timeout_now, transfer_leadership) — the
reference renders these with tools/rpcgen.py; here they are declared with
the rpc serde tables. The heartbeat method is **batched**: one request per
destination node carries metadata for every raft group hosted there
(heartbeat_manager.cc:155-204).
"""

from __future__ import annotations

from redpanda_tpu import rpc
from redpanda_tpu.rpc import serde

_VNODE = serde.S(("id", serde.I32), ("revision", serde.I64))

VOTE_REQUEST = serde.S(
    ("group", serde.I64),
    ("node", _VNODE),
    ("target", _VNODE),
    ("term", serde.I64),
    ("prev_log_index", serde.I64),
    ("prev_log_term", serde.I64),
    ("leadership_transfer", serde.BOOL),
    ("prevote", serde.BOOL),
)
VOTE_REPLY = serde.S(
    ("term", serde.I64),
    ("granted", serde.BOOL),
    ("log_ok", serde.BOOL),
)

APPEND_ENTRIES_REQUEST = serde.S(
    ("group", serde.I64),
    ("node", _VNODE),
    ("target", _VNODE),
    ("term", serde.I64),
    ("prev_log_index", serde.I64),
    ("prev_log_term", serde.I64),
    ("commit_index", serde.I64),
    # encoded internal-format record batches, possibly empty (heartbeat-like)
    ("batches", serde.BYTES),
    ("flush", serde.BOOL),
)
APPEND_ENTRIES_REPLY = serde.S(
    ("group", serde.I64),
    ("node", _VNODE),
    ("target", _VNODE),
    ("term", serde.I64),
    ("last_dirty_log_index", serde.I64),
    ("last_flushed_log_index", serde.I64),
    # 0=success 1=failure 2=group_unavailable (raft/types.h append_entries_reply)
    ("result", serde.I8),
)

_HEARTBEAT_META = serde.S(
    ("group", serde.I64),
    ("node", _VNODE),
    ("target", _VNODE),
    ("term", serde.I64),
    ("prev_log_index", serde.I64),
    ("prev_log_term", serde.I64),
    ("commit_index", serde.I64),
)
HEARTBEAT_REQUEST = serde.S(("heartbeats", serde.Vector(_HEARTBEAT_META)))
HEARTBEAT_REPLY = serde.S(("meta", serde.Vector(APPEND_ENTRIES_REPLY)))

INSTALL_SNAPSHOT_REQUEST = serde.S(
    ("group", serde.I64),
    ("node", _VNODE),
    ("target", _VNODE),
    ("term", serde.I64),
    ("last_included_index", serde.I64),
    ("last_included_term", serde.I64),
    ("file_offset", serde.I64),
    ("chunk", serde.BYTES),
    ("done", serde.BOOL),
)
INSTALL_SNAPSHOT_REPLY = serde.S(
    ("term", serde.I64),
    ("bytes_stored", serde.I64),
    ("success", serde.BOOL),
)

TIMEOUT_NOW_REQUEST = serde.S(
    ("group", serde.I64),
    ("node", _VNODE),
    ("target", _VNODE),
    ("term", serde.I64),
)
TIMEOUT_NOW_REPLY = serde.S(("term", serde.I64), ("result", serde.I8))

TRANSFER_LEADERSHIP_REQUEST = serde.S(
    ("group", serde.I64),
    ("target_id", serde.I32),  # -1: leader picks the best candidate
)
TRANSFER_LEADERSHIP_REPLY = serde.S(("success", serde.BOOL), ("result", serde.I8))

raftgen_service = rpc.ServiceDef(
    "raft",
    "raftgen",
    [
        rpc.MethodDef("vote", VOTE_REQUEST, VOTE_REPLY),
        rpc.MethodDef("append_entries", APPEND_ENTRIES_REQUEST, APPEND_ENTRIES_REPLY),
        rpc.MethodDef("heartbeat", HEARTBEAT_REQUEST, HEARTBEAT_REPLY),
        rpc.MethodDef("install_snapshot", INSTALL_SNAPSHOT_REQUEST, INSTALL_SNAPSHOT_REPLY),
        rpc.MethodDef("timeout_now", TIMEOUT_NOW_REQUEST, TIMEOUT_NOW_REPLY),
        rpc.MethodDef(
            "transfer_leadership", TRANSFER_LEADERSHIP_REQUEST, TRANSFER_LEADERSHIP_REPLY
        ),
    ],
)


class RaftService:
    """Routes raft RPCs to the consensus instance owning each group
    (raft/service.h — the sharded service looks groups up in the shard
    table; here the group manager holds them all)."""

    def __init__(self, group_manager) -> None:
        self._gm = group_manager

    def _group(self, group_id: int):
        return self._gm.consensus_for(group_id)

    async def vote(self, req: dict) -> dict:
        c = self._group(req["group"])
        if c is None:
            return {"term": -1, "granted": False, "log_ok": False}
        return await c.handle_vote(req)

    async def append_entries(self, req: dict) -> dict:
        c = self._group(req["group"])
        if c is None:
            return _unavailable_reply(req)
        return await c.handle_append_entries(req)

    async def heartbeat(self, req: dict) -> dict:
        replies = []
        for meta in req["heartbeats"]:
            c = self._group(meta["group"])
            if c is None:
                replies.append(_unavailable_reply(meta))
                continue
            replies.append(await c.handle_heartbeat(meta))
        return {"meta": replies}

    async def install_snapshot(self, req: dict) -> dict:
        c = self._group(req["group"])
        if c is None:
            return {"term": -1, "bytes_stored": 0, "success": False}
        return await c.handle_install_snapshot(req)

    async def timeout_now(self, req: dict) -> dict:
        c = self._group(req["group"])
        if c is None:
            return {"term": -1, "result": 2}
        return await c.handle_timeout_now(req)

    async def transfer_leadership(self, req: dict) -> dict:
        c = self._group(req["group"])
        if c is None:
            return {"success": False, "result": 2}
        ok = await c.do_transfer_leadership(req.get("target_id", -1))
        return {"success": ok, "result": 0 if ok else 1}


def _unavailable_reply(req: dict) -> dict:
    return {
        "group": req["group"],
        "node": req.get("target", {"id": -1, "revision": 0}),
        "target": req.get("node", {"id": -1, "revision": 0}),
        "term": -1,
        "last_dirty_log_index": -1,
        "last_flushed_log_index": -1,
        "result": 2,
    }
