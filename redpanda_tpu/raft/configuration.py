"""Raft group configuration and its offset-indexed history.

Parity with raft/group_configuration.h (voters/learners, joint-consensus
transitions) and raft/configuration_manager.h (configurations tracked by the
offset of the batch that introduced them, so truncation can roll them back).

Configurations travel in the log as ``raft_configuration`` batches; the
offset translator later subtracts them from Kafka offsets
(kafka/server/offset_translator.h:11-26).
"""

from __future__ import annotations

import json
from dataclasses import dataclass, field

from redpanda_tpu.raft.types import VNode


@dataclass
class GroupConfiguration:
    voters: list[VNode] = field(default_factory=list)
    learners: list[VNode] = field(default_factory=list)
    # During a joint-consensus membership change both old and new voter sets
    # must independently reach majority (group_configuration.h old/current).
    old_voters: list[VNode] | None = None
    revision: int = 0

    def all_nodes(self) -> list[VNode]:
        seen: dict[int, VNode] = {}
        for n in self.voters + self.learners + (self.old_voters or []):
            seen.setdefault(n.id, n)
        return list(seen.values())

    def all_voters(self) -> list[VNode]:
        seen: dict[int, VNode] = {}
        for n in self.voters + (self.old_voters or []):
            seen.setdefault(n.id, n)
        return list(seen.values())

    def contains(self, node: VNode) -> bool:
        return any(n.id == node.id for n in self.all_nodes())

    def is_voter(self, node: VNode) -> bool:
        return any(n.id == node.id for n in self.all_voters())

    def majority(self, acked: set[int]) -> bool:
        """True when `acked` (node ids) is a majority of voters — and of the
        old voter set too while a joint configuration is active."""

        def maj(nodes: list[VNode]) -> bool:
            if not nodes:
                return True
            return len([n for n in nodes if n.id in acked]) * 2 > len(nodes)

        if not maj(self.voters):
            return False
        if self.old_voters is not None and not maj(self.old_voters):
            return False
        return True

    def enter_joint(self, new_voters: list[VNode]) -> "GroupConfiguration":
        return GroupConfiguration(
            voters=list(new_voters),
            learners=list(self.learners),
            old_voters=list(self.voters),
            revision=self.revision + 1,
        )

    def leave_joint(self) -> "GroupConfiguration":
        return GroupConfiguration(
            voters=list(self.voters),
            learners=list(self.learners),
            old_voters=None,
            revision=self.revision + 1,
        )

    # ------------------------------------------------------------ codec
    def encode(self) -> bytes:
        return json.dumps(
            {
                "voters": [[n.id, n.revision] for n in self.voters],
                "learners": [[n.id, n.revision] for n in self.learners],
                "old_voters": None
                if self.old_voters is None
                else [[n.id, n.revision] for n in self.old_voters],
                "revision": self.revision,
            }
        ).encode()

    @staticmethod
    def decode(buf: bytes) -> "GroupConfiguration":
        d = json.loads(bytes(buf).decode())
        mk = lambda pairs: [VNode(i, r) for i, r in pairs]
        return GroupConfiguration(
            voters=mk(d["voters"]),
            learners=mk(d["learners"]),
            old_voters=None if d["old_voters"] is None else mk(d["old_voters"]),
            revision=d["revision"],
        )


class ConfigurationManager:
    """Offset-ordered configuration history (configuration_manager.h)."""

    def __init__(self, initial: GroupConfiguration) -> None:
        self._history: list[tuple[int, GroupConfiguration]] = [(-1, initial)]

    def add(self, offset: int, cfg: GroupConfiguration) -> None:
        assert offset > self._history[-1][0], "configs must arrive in offset order"
        self._history.append((offset, cfg))

    def latest(self) -> GroupConfiguration:
        return self._history[-1][1]

    def latest_offset(self) -> int:
        return self._history[-1][0]

    def get(self, offset: int) -> GroupConfiguration:
        """Config active at `offset`."""
        ans = self._history[0][1]
        for off, cfg in self._history:
            if off <= offset:
                ans = cfg
            else:
                break
        return ans

    def truncate(self, offset: int) -> None:
        """Drop configs introduced at or after `offset` (log suffix truncate)."""
        self._history = [(o, c) for o, c in self._history if o < offset] or [
            (-1, GroupConfiguration())
        ]

    def prefix_truncate(self, offset: int) -> None:
        """Keep the newest config at or below `offset` as the base entry."""
        base = self.get(offset)
        self._history = [(-1, base)] + [(o, c) for o, c in self._history if o > offset]

    def configs_up_to(self, offset: int) -> int:
        """Number of configuration batches at offsets <= `offset` (for the
        kafka offset delta)."""
        return sum(1 for o, _ in self._history if 0 <= o <= offset)
