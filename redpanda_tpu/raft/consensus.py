"""Raft consensus — one instance per partition replica.

Parity with raft/consensus.h:51 / consensus.cc: ``replicate()`` with three
consistency levels (consensus.cc:600-650), concurrent quorum writes coalesced
by a batcher (replicate_batcher.cc:40), prevote+vote elections
(vote_stm/prevote_stm), follower catch-up (recovery_stm.cc) throttled by a
shared recovery throttle, snapshot install, joint-consensus membership
change, and leadership transfer via timeout_now.

Durable state: term + voted_for live in the per-shard kvstore
(KeySpace.consensus, mirroring kvstore.h:61-73); entries live in the
storage log with the term stamped in each batch header; configurations are
``raft_configuration`` batches in the log, tracked by ConfigurationManager.
"""

from __future__ import annotations

import asyncio
import bisect
import logging
import random
import struct
import time

from redpanda_tpu.finjector import honey_badger
from redpanda_tpu.metrics import registry
from redpanda_tpu.models.fundamental import NTP
from redpanda_tpu.observability import probes
from redpanda_tpu.observability.trace import tracer
from redpanda_tpu.models.record import Record, RecordBatch, RecordBatchType
from redpanda_tpu.raft import device_plane
from redpanda_tpu.raft.configuration import ConfigurationManager, GroupConfiguration
from redpanda_tpu.raft.types import (
    ConsistencyLevel,
    Errc,
    FollowerIndex,
    RaftError,
    ReplicateResult,
    VNode,
)
from redpanda_tpu.rpc.transport import RpcError, TransportClosed
from redpanda_tpu.storage.kvstore import KeySpace
from redpanda_tpu.storage.snapshot import SnapshotManager

logger = logging.getLogger("rptpu.raft")

# chaos probe: one byte of a received append blob flips before validation
# (finjector CORRUPT effect — loadgen crc_chaos drives it)
honey_badger.register_probe("raft", "append_blob")

# follower-side batched-CRC rejections (raft/device_plane.py, config
# raft_device_crc_validate): the federated scrape must SEE torn appends
# being refused, not just a leader-side retry
raft_crc_rejected = registry.counter(
    "raft_crc_rejected_batches_total",
    "Append-entries batches rejected by the follower CRC validation",
)

FOLLOWER = "follower"
CANDIDATE = "candidate"
LEADER = "leader"


class RaftTimings:
    """Tunable timings (config/configuration.cc raft_* properties)."""

    def __init__(
        self,
        election_timeout_ms: float = 600.0,
        heartbeat_interval_ms: float = 60.0,
        recovery_chunk_bytes: int = 512 * 1024,
        rpc_timeout_s: float = 2.0,
    ) -> None:
        self.election_timeout_ms = election_timeout_ms
        self.heartbeat_interval_ms = heartbeat_interval_ms
        self.recovery_chunk_bytes = recovery_chunk_bytes
        self.rpc_timeout_s = rpc_timeout_s

    def jittered_timeout(self) -> float:
        base = self.election_timeout_ms / 1000.0
        return base + random.random() * base


class OffsetMonitor:
    """Waiters on a monotonically advancing offset (raft/offset_monitor.h)."""

    def __init__(self) -> None:
        self._waiters: list[tuple[int, asyncio.Future]] = []

    def notify(self, offset: int) -> None:
        fire = [w for w in self._waiters if w[0] <= offset]
        self._waiters = [w for w in self._waiters if w[0] > offset]
        for _, fut in fire:
            if not fut.done():
                fut.set_result(offset)

    def fail_all(self, exc: Exception) -> None:
        for _, fut in self._waiters:
            if not fut.done():
                fut.set_exception(exc)
        self._waiters = []

    async def wait_for(self, offset: int, current: int, timeout: float | None = None) -> int:
        if current >= offset:
            return current
        fut: asyncio.Future = asyncio.get_event_loop().create_future()
        self._waiters.append((offset, fut))
        if timeout is None:
            return await fut
        try:
            return await asyncio.wait_for(fut, timeout)
        except asyncio.TimeoutError:
            raise RaftError(Errc.timeout, f"offset {offset} not committed in time")


class Consensus:
    def __init__(
        self,
        group: int,
        ntp: NTP,
        self_node: VNode,
        initial_config: GroupConfiguration,
        log,
        kvstore,
        client_for,  # callable(node_id) -> raftgen rpc.Client
        timings: RaftTimings | None = None,
        leadership_cb=None,  # callable(consensus) on leadership change
        recovery_throttle: asyncio.Semaphore | None = None,
    ) -> None:
        self.group = group
        self.ntp = ntp
        self.self_node = self_node
        self.log = log
        self._kvstore = kvstore
        self._client_for = client_for
        self.timings = timings or RaftTimings()
        self._leadership_cb = leadership_cb
        self._recovery_throttle = recovery_throttle or asyncio.Semaphore(4)

        self.term = 0
        self.voted_for: VNode | None = None
        self.role = FOLLOWER
        self.leader_id: int | None = None
        self._commit_index = -1
        self.config_mgr = ConfigurationManager(initial_config)

        self._followers: dict[int, FollowerIndex] = {}
        self._op_lock = asyncio.Lock()
        self._commit_monitor = OffsetMonitor()
        self._term_starts: list[tuple[int, int]] = []  # (first_offset, term) spans
        self._last_leader_contact = 0.0
        self._election_task: asyncio.Task | None = None
        self._recovery_tasks: dict[int, asyncio.Task] = {}
        # fire-and-forget work (step-down, transfer elections, quorum acks):
        # handles are retained so the tasks can't be GC'd mid-flight and are
        # cancelled on stop() (pandalint TSK301)
        self._bg_tasks: set[asyncio.Task] = set()
        self._batcher: _ReplicateBatcher | None = None
        # sampled "owner trace" for the replicate path's detached rpc sends:
        # the batcher's flush task and follower recovery run under
        # tracer.detached() by span-hygiene design, so their rpc.send spans
        # (and SLO breach exemplars) carried no trace id at all. One
        # submitter's ambient trace id per coalesced flush round is sampled
        # here and consumed ONCE PER FOLLOWER (seq-tracked below) by the
        # next append_entries send to that follower — so the sampled
        # produce's cluster trace gains a JOINed leg on EVERY replica
        # (pandascope wire propagation rides those sends) while the
        # long-lived tasks are never re-attributed wholesale: a follower
        # that already consumed this round's owner sends untraced again.
        self._replicate_owner: int | None = None
        self._replicate_owner_seq = 0
        self._owner_consumed: dict[int, int] = {}  # follower id -> seq
        self._snapshots = SnapshotManager(log.dir, name="raft_snapshot")
        self._snapshot_rx: dict | None = None  # in-progress chunked install
        self._transferring = False
        self._stopped = False

    # ---------------------------------------------------------------- state
    @property
    def commit_index(self) -> int:
        return self._commit_index

    @property
    def dirty_offset(self) -> int:
        return self.log.offsets().dirty_offset

    @property
    def flushed_offset(self) -> int:
        return self.log.offsets().committed_offset

    # Partition-facade accessors (cluster::partition delegates here; the
    # same names DirectConsensus exposes — raw log offsets, pre-translation)
    @property
    def committed_offset(self) -> int:
        return self._commit_index

    @property
    def last_stable_offset(self) -> int:
        """Exclusive; tx-aware LSO clamping happens in rm_stm above."""
        return self._commit_index + 1

    @property
    def start_offset(self) -> int:
        return self.log.offsets().start_offset

    def is_leader(self) -> bool:
        return self.role == LEADER

    def leadership_settled(self) -> bool:
        """Raft §8 read barrier: a NEW leader may only serve linearizable
        reads once an entry of ITS OWN term has committed (the election
        configuration batch, _become_leader) — prior-term quorum entries
        are only then covered by the commit rule, so the high watermark
        cannot show a reader less than what an earlier leader acked."""
        return (
            self.role == LEADER
            and self._commit_index >= 0
            and self.term_at(self._commit_index) == self.term
        )

    def config(self) -> GroupConfiguration:
        return self.config_mgr.latest()

    def term_at(self, offset: int) -> int:
        """Term of the batch covering `offset` (-1 when unknown/compacted)."""
        if offset < 0:
            return -1
        idx = bisect.bisect_right(self._term_starts, (offset, 1 << 62)) - 1
        if idx < 0:
            return -1
        return self._term_starts[idx][1]

    def _note_term_span(self, first_offset: int, term: int) -> None:
        if not self._term_starts or self._term_starts[-1][1] != term:
            self._term_starts.append((first_offset, term))

    # ---------------------------------------------------------------- lifecycle
    async def start(self) -> "Consensus":
        raw = self._kvstore.get(KeySpace.consensus, self._kv_key(b"voted_for"))
        if raw is not None:
            term, vid, vrev, has_vote = struct.unpack("<qiqB", raw)
            self.term = term
            self.voted_for = VNode(vid, vrev) if has_vote else None
        snap = self._snapshots.read()
        if snap is not None:
            meta, _payload = snap
            last_idx, last_term = struct.unpack("<qq", meta[:16])
            self._term_starts = [(last_idx, last_term)]
            self._commit_index = max(self._commit_index, last_idx)
        await self._rebuild_from_log()
        self._election_task = asyncio.create_task(self._election_loop())
        self._batcher = _ReplicateBatcher(self)
        return self

    async def _rebuild_from_log(self) -> None:
        """Scan the log once to rebuild term spans + config history
        (the reference persists both and CRC-scans the tail; our storage
        recovery already validated CRCs)."""
        offsets = self.log.offsets()
        at = offsets.start_offset
        while at <= offsets.dirty_offset:
            batches = self.log.read(at, 4 << 20)
            if asyncio.iscoroutine(batches):
                batches = await batches
            if not batches:
                break
            for b in batches:
                self._note_term_span(b.base_offset, b.header.term)
                self.term = max(self.term, b.header.term)
                if b.header.type == RecordBatchType.raft_configuration:
                    cfg = GroupConfiguration.decode(b.record_values()[0])
                    if b.base_offset > self.config_mgr.latest_offset():
                        self.config_mgr.add(b.base_offset, cfg)
            at = batches[-1].last_offset + 1

    def _spawn_bg(self, coro) -> asyncio.Task:
        """create_task with a retained handle: fire-and-forget raft work
        (step-down, transfer elections, quorum acks) must not be GC'd
        mid-flight and must die with the group (pandalint TSK301).
        Detached from any ambient trace: these outlive the request that
        triggered them, and create_task's context copy would otherwise pin
        its trace id onto everything they ever record."""
        with tracer.detached():
            t = asyncio.create_task(coro)
        self._bg_tasks.add(t)
        t.add_done_callback(self._bg_tasks.discard)
        return t

    async def stop(self) -> None:
        self._stopped = True
        tasks = [
            t
            for t in [
                self._election_task,
                *self._recovery_tasks.values(),
                *self._bg_tasks,
            ]
            if t
        ]
        if self._batcher is not None:
            tasks.extend(self._batcher.tasks())
        for t in tasks:
            t.cancel()
        if tasks:
            await asyncio.gather(*tasks, return_exceptions=True)
        self._commit_monitor.fail_all(RaftError(Errc.shutting_down))

    def _kv_key(self, suffix: bytes) -> bytes:
        return b"raft/%d/" % self.group + suffix

    def _persist_vote(self) -> None:
        v = self.voted_for
        self._kvstore.put(
            KeySpace.consensus,
            self._kv_key(b"voted_for"),
            struct.pack(
                "<qiqB",
                self.term,
                v.id if v else -1,
                v.revision if v else 0,
                1 if v else 0,
            ),
        )

    # ---------------------------------------------------------------- election
    async def _election_loop(self) -> None:
        loop = asyncio.get_event_loop()
        while not self._stopped:
            timeout = self.timings.jittered_timeout()
            await asyncio.sleep(timeout)
            if self._stopped or self.is_leader():
                continue
            if not self.config().is_voter(self.self_node):
                continue  # learners never start elections
            if loop.time() - self._last_leader_contact < timeout:
                continue  # heard from a live leader recently
            try:
                await self.dispatch_election()
            except asyncio.CancelledError:
                raise
            except Exception:
                logger.exception("group %d election failed", self.group)

    async def dispatch_election(self, *, leadership_transfer: bool = False) -> bool:
        """Prevote round then a real vote round (vote_stm/prevote_stm)."""
        if not leadership_transfer:
            ok = await self._request_votes(self.term + 1, prevote=True)
            if not ok:
                return False
        async with self._op_lock:
            self.role = CANDIDATE
            self.term += 1
            self.leader_id = None
            self.voted_for = self.self_node
            self._persist_vote()
            term = self.term
        granted = await self._request_votes(term, prevote=False, leadership_transfer=leadership_transfer)
        if granted and self.role == CANDIDATE and self.term == term:
            await self._become_leader()
            return True
        return False

    async def _request_votes(self, term: int, *, prevote: bool, leadership_transfer: bool = False) -> bool:
        cfg = self.config()
        last_idx = self.dirty_offset
        last_term = self.term_at(last_idx)
        req = {
            "group": self.group,
            "node": {"id": self.self_node.id, "revision": self.self_node.revision},
            "term": term,
            "prev_log_index": last_idx,
            "prev_log_term": last_term,
            "leadership_transfer": leadership_transfer,
            "prevote": prevote,
        }
        acked = {self.self_node.id}

        async def ask(node: VNode) -> None:
            client = self._client_for(node.id)
            try:
                reply = await client.vote(
                    {**req, "target": {"id": node.id, "revision": node.revision}},
                    timeout=self.timings.rpc_timeout_s,
                )
            except (RpcError, TransportClosed, OSError):
                return
            if reply["granted"]:
                acked.add(node.id)
            elif not prevote and reply["term"] > self.term:
                await self._step_down(reply["term"])

        await asyncio.gather(*(ask(n) for n in cfg.all_voters() if n.id != self.self_node.id))
        return cfg.majority(acked)

    async def _become_leader(self) -> None:
        async with self._op_lock:
            self.role = LEADER
            self.leader_id = self.self_node.id
            dirty = self.dirty_offset
            self._followers = {
                n.id: FollowerIndex(n, next_index=dirty + 1)
                for n in self.config().all_nodes()
                if n.id != self.self_node.id
            }
            # Commit a configuration batch in the new term: commits all prior-
            # term entries once it replicates (the raft "no-op on election"
            # rule; the reference replicates the active configuration).
            await self._append_config_locked(self.config())
        logger.info("group %d: node %d elected leader term %d", self.group, self.self_node.id, self.term)
        self._fanout_append()
        if self._leadership_cb:
            self._leadership_cb(self)

    async def _step_down(self, term: int, leader: int | None = None) -> None:
        async with self._op_lock:
            self._step_down_locked(term, leader)

    def _step_down_locked(self, term: int, leader: int | None = None) -> None:
        was_leader = self.role == LEADER
        if term > self.term:
            self.term = term
            self.voted_for = None
            self._persist_vote()
        self.role = FOLLOWER
        self.leader_id = leader
        for t in self._recovery_tasks.values():
            t.cancel()
        self._recovery_tasks.clear()
        if was_leader:
            self._commit_monitor.fail_all(RaftError(Errc.not_leader))
            if self._leadership_cb:
                self._leadership_cb(self)

    # ---------------------------------------------------------------- vote RPC
    async def handle_vote(self, req: dict) -> dict:
        async with self._op_lock:
            req_term = req["term"]
            candidate = VNode(req["node"]["id"], req["node"]["revision"])
            last_idx = self.dirty_offset
            log_ok = req["prev_log_term"] > self.term_at(last_idx) or (
                req["prev_log_term"] == self.term_at(last_idx)
                and req["prev_log_index"] >= last_idx
            )
            if req["prevote"]:
                # Prevote grants without disturbing state: would we vote?
                granted = log_ok and req_term > self.term
                if not granted and req.get("leadership_transfer"):
                    granted = log_ok
                return {"term": self.term, "granted": granted, "log_ok": log_ok}
            if req_term < self.term:
                return {"term": self.term, "granted": False, "log_ok": log_ok}
            if req_term > self.term:
                self._step_down_locked(req_term)
            granted = log_ok and (self.voted_for is None or self.voted_for.id == candidate.id)
            if granted:
                self.voted_for = candidate
                self._persist_vote()
                self._last_leader_contact = asyncio.get_event_loop().time()
            return {"term": self.term, "granted": granted, "log_ok": log_ok}

    # ---------------------------------------------------------------- replicate
    async def replicate(
        self,
        batches: list[RecordBatch],
        consistency: ConsistencyLevel = ConsistencyLevel.quorum_ack,
        timeout: float | None = 10.0,
    ) -> ReplicateResult:
        t0 = time.perf_counter()
        try:
            with tracer.span("raft.replicate"):
                enqueued, replicated = await self.replicate_in_stages(
                    batches, consistency, timeout
                )
                await enqueued
                return await replicated
        finally:
            probes.observe_us(probes.raft_replicate_hist, t0)

    async def replicate_in_stages(
        self,
        batches: list[RecordBatch],
        consistency: ConsistencyLevel = ConsistencyLevel.quorum_ack,
        timeout: float | None = 10.0,
    ):
        """Two-stage replicate (consensus.cc:576-650): the first future
        resolves when the entry is enqueued/appended (order fixed), the
        second when the requested consistency level is reached."""
        if not self.is_leader():
            raise RaftError(Errc.not_leader, f"group {self.group}: not leader")
        if consistency == ConsistencyLevel.quorum_ack:
            return await self._batcher.submit(batches, timeout)
        loop = asyncio.get_event_loop()
        enqueued: asyncio.Future = loop.create_future()
        replicated: asyncio.Future = loop.create_future()
        async with self._op_lock:
            if not self.is_leader():
                raise RaftError(Errc.not_leader)
            res = await self._append_locked(batches)
            enqueued.set_result(res.last_offset)
        self._fanout_append()
        replicated.set_result(ReplicateResult(res.last_offset, self.term))
        return enqueued, replicated

    async def _append_locked(self, batches: list[RecordBatch]):
        res = self.log.append(batches, term=self.term)
        if asyncio.iscoroutine(res):
            res = await res
        self._note_term_span(res.base_offset, self.term)
        return res

    async def _append_config_locked(self, cfg: GroupConfiguration) -> int:
        batch = RecordBatch.build(
            [Record(offset_delta=0, value=cfg.encode())],
            type=RecordBatchType.raft_configuration,
        )
        res = await self._append_locked([batch])
        self.config_mgr.add(res.base_offset, cfg)
        # Flush so the leader's own ack counts toward the quorum: config
        # appends happen outside the batcher's flush path, and in a 2-voter
        # group one follower ack alone can never reach majority.
        r = self.log.flush()
        if asyncio.iscoroutine(r):
            await r
        self._maybe_advance_commit_index()
        return res.last_offset

    def _fanout_append(self) -> None:
        """Kick per-follower dispatch; recovery handles lagging peers."""
        for f in self._followers.values():
            if not f.is_recovering:
                self._start_recovery(f)

    def _start_recovery(self, f: FollowerIndex) -> None:
        if f.is_recovering or self._stopped or not self.is_leader():
            return
        f.is_recovering = True
        # detached: recovery outlives the replicate that kicked it and
        # serves every later append too — no single trace owns it
        with tracer.detached():
            t = asyncio.create_task(self._recover_follower(f))
        self._recovery_tasks[f.node.id] = t
        t.add_done_callback(lambda _t: self._recovery_tasks.pop(f.node.id, None))

    async def _recover_follower(self, f: FollowerIndex) -> None:
        """recovery_stm: stream chunks until the follower's dirty offset
        matches ours; falls back to install_snapshot when the follower needs
        offsets we no longer have."""
        try:
            while self.is_leader() and not self._stopped and f.next_index <= self.dirty_offset:
                async with self._recovery_throttle:
                    if f.next_index < self.start_offset:
                        ok = await self._install_snapshot_on(f)
                        if not ok:
                            return
                        continue
                    prev = f.next_index - 1
                    batches = self.log.read(f.next_index, self.timings.recovery_chunk_bytes)
                    if asyncio.iscoroutine(batches):
                        batches = await batches
                    blob = _encode_entries(batches)
                    req = {
                        "group": self.group,
                        "node": {"id": self.self_node.id, "revision": self.self_node.revision},
                        "target": {"id": f.node.id, "revision": f.node.revision},
                        "term": self.term,
                        "prev_log_index": prev,
                        "prev_log_term": self.term_at(prev),
                        "commit_index": self._commit_index,
                        "batches": blob,
                        "flush": True,
                    }
                    # consume-once-per-follower owner trace: the span JOINS
                    # the sampled submitter's trace for exactly one send to
                    # THIS follower per sampled round (trace_id=None = the
                    # usual untraced no-op), so the rpc.send histogram
                    # record inside — and any exemplar a breach captures —
                    # resolves to a real trace, and the propagated context
                    # lands a JOINed leg on every replica of the round.
                    # Once every CURRENT follower consumed the round the
                    # owner is cleared — without that, a follower added
                    # (or rejoining) hours later would join an arbitrarily
                    # stale trace and propagate it over the wire into an
                    # unrelated, possibly recycled cluster view.
                    owner = None
                    seq = self._replicate_owner_seq
                    if (
                        self._replicate_owner is not None
                        and self._owner_consumed.get(f.node.id, 0) < seq
                    ):
                        owner = self._replicate_owner
                        self._owner_consumed[f.node.id] = seq
                        if all(
                            self._owner_consumed.get(fid, 0) >= seq
                            for fid in self._followers
                        ):
                            self._replicate_owner = None
                    try:
                        with tracer.span(
                            "raft.append_entries.send", trace_id=owner,
                            node=self.self_node.id,
                        ):
                            reply = await self._client_for(f.node.id).append_entries(
                                req, timeout=self.timings.rpc_timeout_s
                            )
                    except (RpcError, TransportClosed, OSError):
                        return  # next heartbeat/append retries
                    if reply["term"] > self.term:
                        await self._step_down(reply["term"])
                        return
                    if reply["result"] == 0:
                        f.last_dirty_offset = reply["last_dirty_log_index"]
                        f.last_flushed_offset = reply["last_flushed_log_index"]
                        f.next_index = f.last_dirty_offset + 1
                        self._maybe_advance_commit_index()
                    elif reply["result"] == 1:
                        # Divergence: back up to the follower's tail.
                        f.next_index = min(f.next_index - 1, reply["last_dirty_log_index"] + 1)
                        f.next_index = max(f.next_index, 0)
                    else:
                        return
        except asyncio.CancelledError:
            pass
        finally:
            f.is_recovering = False

    async def _install_snapshot_on(self, f: FollowerIndex) -> bool:
        snap = self._snapshots.read()
        if snap is None:
            meta = struct.pack("<qq", self.start_offset - 1, self.term_at(self.start_offset - 1))
            payload = b""
        else:
            meta, payload = snap
        last_idx, last_term = struct.unpack("<qq", meta[:16])
        chunk_size = self.timings.recovery_chunk_bytes
        at = 0
        while True:
            chunk = payload[at : at + chunk_size]
            done = at + len(chunk) >= len(payload)
            req = {
                "group": self.group,
                "node": {"id": self.self_node.id, "revision": self.self_node.revision},
                "target": {"id": f.node.id, "revision": f.node.revision},
                "term": self.term,
                "last_included_index": last_idx,
                "last_included_term": last_term,
                "file_offset": at,
                "chunk": chunk,
                "done": done,
            }
            try:
                reply = await self._client_for(f.node.id).install_snapshot(
                    req, timeout=self.timings.rpc_timeout_s
                )
            except (RpcError, TransportClosed, OSError):
                return False
            if reply["term"] > self.term:
                await self._step_down(reply["term"])
                return False
            if not reply["success"]:
                return False
            at += len(chunk)
            if done:
                f.next_index = last_idx + 1
                f.last_dirty_offset = last_idx
                return True

    # ---------------------------------------------------------------- commit
    def _maybe_advance_commit_index(self) -> None:
        if not self.is_leader():
            return
        cfg = self.config()
        self_flushed = self.flushed_offset
        candidates = sorted(
            {self_flushed}
            | {f.last_flushed_offset for f in self._followers.values() if cfg.is_voter(f.node)},
            reverse=True,
        )
        for offset in candidates:
            if offset <= self._commit_index:
                break
            acked = {self.self_node.id} if self_flushed >= offset else set()
            acked |= {
                fid for fid, f in self._followers.items() if f.last_flushed_offset >= offset
            }
            # Only entries from the current term commit by counting (§5.4.2).
            if cfg.majority(acked) and self.term_at(offset) == self.term:
                self._set_commit_index(offset)
                break

    def _set_commit_index(self, offset: int) -> None:
        if offset > self._commit_index:
            self._commit_index = offset
            self._commit_monitor.notify(offset)

    async def wait_for_commit(self, offset: int, timeout: float | None = None) -> int:
        return await self._commit_monitor.wait_for(offset, self._commit_index, timeout)

    # ---------------------------------------------------------------- append RPC
    async def handle_append_entries(self, req: dict) -> dict:
        blob = req["batches"]
        # chaos probe (finjector CORRUPT): flip one byte of the received
        # blob BEFORE validation, as a torn wire/disk read would — the
        # device-plane CRC check below must reject it, the leader's
        # recovery resend repairs it, and quorum acks ride the healthy
        # replicas meanwhile (loadgen crc_chaos scenario)
        if blob and honey_badger.enabled and honey_badger.corrupt_claim(
            "raft", "append_blob"
        ):
            blob = blob[:-1] + bytes([blob[-1] ^ 0xFF])
        crc_failures = 0
        batches = None
        if blob and device_plane.crc_validate_enabled():
            # BASELINE config 5 (follower half): batched CRC validation of
            # the whole append in ONE kernel call instead of one host CRC
            # per batch — the measured probe inside the plane decides
            # host-vs-device, both bit-exact. Runs BEFORE _op_lock: the
            # validation is a pure function of the wire bytes, and the
            # first representative call jit-compiles for seconds — held
            # under the lock that would queue this group's heartbeats
            # while the unlocked election-loop staleness check fires a
            # spurious election against a healthy leader.
            batches = _decode_batches(blob)
            if batches:
                ok = await asyncio.to_thread(
                    device_plane.default_plane().validate,
                    [b.crc_region() for b in batches],
                    [b.header.crc for b in batches],
                )
                crc_failures = int((~ok).sum())
                if crc_failures:
                    raft_crc_rejected.inc(crc_failures)
                    logger.warning(
                        "group %d: rejecting append, %d/%d batch CRC "
                        "failures", self.group, crc_failures, len(ok),
                    )
        async with self._op_lock:
            return await self._do_handle_append(
                req, blob, req["flush"],
                crc_failures=crc_failures, batches=batches,
            )

    async def handle_heartbeat(self, meta: dict) -> dict:
        async with self._op_lock:
            return await self._do_handle_append(meta, b"", False)

    async def _do_handle_append(
        self, req: dict, blob: bytes, flush: bool,
        crc_failures: int = 0, batches: list[RecordBatch] | None = None,
    ) -> dict:
        def reply(result: int) -> dict:
            return {
                "group": self.group,
                "node": {"id": self.self_node.id, "revision": self.self_node.revision},
                "target": req["node"],
                "term": self.term,
                "last_dirty_log_index": self.dirty_offset,
                "last_flushed_log_index": self.flushed_offset,
                "result": result,
            }

        if req["term"] < self.term:
            return reply(1)
        if req["term"] > self.term or self.role != FOLLOWER or self.leader_id != req["node"]["id"]:
            self._step_down_locked(req["term"], leader=req["node"]["id"])
        self._last_leader_contact = asyncio.get_event_loop().time()

        prev_idx = req["prev_log_index"]
        dirty = self.dirty_offset
        if prev_idx > dirty:
            return reply(1)  # gap: leader must back up / recover
        if prev_idx >= self.start_offset and prev_idx >= 0:
            local_term = self.term_at(prev_idx)
            if local_term != -1 and local_term != req["prev_log_term"]:
                # Divergent history: drop our conflicting suffix.
                await self._truncate_locked(prev_idx)
                return reply(1)
        if blob:
            if crc_failures:
                # a corrupted wire batch (caught by the pre-lock batched
                # CRC validation in handle_append_entries) rejects the
                # append — the leader retries/recovers — instead of
                # poisoning the follower log
                return reply(1)
            if batches is None:
                batches = _decode_batches(blob)
            if batches:
                first = batches[0].base_offset
                if first <= dirty:
                    # Overlap: if already-present suffix matches terms, skip
                    # duplicates; otherwise truncate the divergent tail.
                    if self.term_at(dirty) == batches[-1].header.term and batches[-1].last_offset <= dirty:
                        return reply(0)
                    await self._truncate_locked(first)
                res = self.log.append(batches, assign_offsets=False)
                if asyncio.iscoroutine(res):
                    res = await res
                for b in batches:
                    self._note_term_span(b.base_offset, b.header.term)
                    if b.header.type == RecordBatchType.raft_configuration:
                        if b.base_offset > self.config_mgr.latest_offset():
                            self.config_mgr.add(
                                b.base_offset, GroupConfiguration.decode(b.record_values()[0])
                            )
        if flush:
            r = self.log.flush()
            if asyncio.iscoroutine(r):
                await r
        self._set_commit_index(min(req["commit_index"], self.dirty_offset))
        return reply(0)

    async def _truncate_locked(self, offset: int) -> None:
        r = self.log.truncate(offset)
        if asyncio.iscoroutine(r):
            await r
        self._term_starts = [(o, t) for o, t in self._term_starts if o < offset]
        self.config_mgr.truncate(offset)
        self._commit_index = min(self._commit_index, self.dirty_offset)

    # ---------------------------------------------------------------- snapshot RPC
    async def handle_install_snapshot(self, req: dict) -> dict:
        async with self._op_lock:
            if req["term"] < self.term:
                return {"term": self.term, "bytes_stored": 0, "success": False}
            if req["term"] > self.term:
                self._step_down_locked(req["term"], leader=req["node"]["id"])
            self._last_leader_contact = asyncio.get_event_loop().time()
            if req["file_offset"] == 0:
                self._snapshot_rx = {"data": bytearray(), "meta": (req["last_included_index"], req["last_included_term"])}
            rx = self._snapshot_rx
            if rx is None or req["file_offset"] != len(rx["data"]):
                return {"term": self.term, "bytes_stored": 0, "success": False}
            rx["data"] += req["chunk"]
            if req["done"]:
                last_idx, last_term = rx["meta"]
                self._snapshots.write(struct.pack("<qq", last_idx, last_term), bytes(rx["data"]))
                self._snapshot_rx = None
                r = self.log.prefix_truncate(last_idx + 1)
                if asyncio.iscoroutine(r):
                    await r
                # Preserve the term of retained entries above last_idx: the
                # span covering them may START at an offset <= last_idx, and
                # dropping it would make term_at() return -1 for offsets we
                # still hold, breaking divergence detection on later appends.
                retained_term = (
                    self.term_at(last_idx + 1) if self.dirty_offset > last_idx else -1
                )
                kept = [(o, t) for o, t in self._term_starts if o > last_idx]
                spans = [(last_idx, last_term)]
                if retained_term != -1 and not any(o == last_idx + 1 for o, _ in kept):
                    spans.append((last_idx + 1, retained_term))
                self._term_starts = spans + kept
                self.config_mgr.prefix_truncate(last_idx)
                self._set_commit_index(max(self._commit_index, last_idx))
            return {"term": self.term, "bytes_stored": len(rx["data"]), "success": True}

    def write_snapshot(self, last_included: int, payload: bytes) -> None:
        """Local snapshot at a committed offset + log prefix eviction
        (log_eviction_stm / install-snapshot source)."""
        assert last_included <= self._commit_index
        self._snapshots.write(
            struct.pack("<qq", last_included, self.term_at(last_included)), payload
        )

    def read_snapshot(self) -> tuple[int, bytes] | None:
        snap = self._snapshots.read()
        if snap is None:
            return None
        meta, payload = snap
        (last_idx,) = struct.unpack("<q", meta[:8])
        return last_idx, payload

    # ---------------------------------------------------------------- transfer
    async def handle_timeout_now(self, req: dict) -> dict:
        if req["term"] < self.term:
            return {"term": self.term, "result": 1}
        self._spawn_bg(self.dispatch_election(leadership_transfer=True))
        return {"term": self.term, "result": 0}

    async def do_transfer_leadership(self, target_id: int = -1) -> bool:
        """Suppress new writes, wait for the target to catch up, then ask it
        to start an immediate election (consensus transfer_leadership)."""
        if not self.is_leader():
            return False
        if self._transferring:
            raise RaftError(Errc.leadership_transfer_in_progress)
        voters = [f for f in self._followers.values() if self.config().is_voter(f.node)]
        if not voters:
            return False
        if target_id == -1:
            target = max(voters, key=lambda f: f.last_dirty_offset)
        else:
            match = [f for f in voters if f.node.id == target_id]
            if not match:
                raise RaftError(Errc.node_does_not_exist)
            target = match[0]
        self._transferring = True
        try:
            deadline = asyncio.get_event_loop().time() + 5.0
            self._start_recovery(target)
            while target.last_dirty_offset < self.dirty_offset:
                if asyncio.get_event_loop().time() > deadline:
                    return False
                await asyncio.sleep(0.01)
                self._start_recovery(target)
            # Ask the target to start an immediate election; retry until we
            # observe ourselves deposed (its election can lose a timing race
            # under load — a single shot would leave leadership stuck here).
            while asyncio.get_event_loop().time() < deadline:
                try:
                    reply = await self._client_for(target.node.id).timeout_now(
                        {
                            "group": self.group,
                            "node": {"id": self.self_node.id, "revision": self.self_node.revision},
                            "target": {"id": target.node.id, "revision": target.node.revision},
                            "term": self.term,
                        },
                        timeout=self.timings.rpc_timeout_s,
                    )
                except (RpcError, TransportClosed, OSError):
                    return False
                if reply["result"] != 0:
                    return False
                step_deadline = asyncio.get_event_loop().time() + 1.0
                while asyncio.get_event_loop().time() < step_deadline:
                    if not self.is_leader():
                        return True
                    await asyncio.sleep(0.02)
            return not self.is_leader()
        finally:
            self._transferring = False

    # ---------------------------------------------------------------- membership
    async def change_configuration(self, new_voters: list[VNode], timeout: float = 10.0) -> None:
        """Joint-consensus membership change: replicate Cold+Cnew, wait for
        it to commit under both majorities, then replicate Cnew."""
        if not self.is_leader():
            raise RaftError(Errc.not_leader)
        if self.config().old_voters is not None:
            # An earlier change attempt left a joint config in the log (e.g.
            # its commit timed out while a new voter bootstrapped). Resume it
            # if the target matches; a different target must wait.
            if sorted(v.id for v in self.config().voters) != sorted(
                v.id for v in new_voters
            ):
                raise RaftError(Errc.configuration_change_in_progress)
            off = self.config_mgr.latest_offset()
        else:
            async with self._op_lock:
                joint = self.config().enter_joint(new_voters)
                off = await self._append_config_locked(joint)
                self._sync_followers_with_config(joint)
        self._fanout_append()
        await self.wait_for_commit(off, timeout)
        async with self._op_lock:
            final = self.config_mgr.latest().leave_joint()
            off = await self._append_config_locked(final)
            self._sync_followers_with_config(final)
        self._fanout_append()
        await self.wait_for_commit(off, timeout)

    def _sync_followers_with_config(self, cfg: GroupConfiguration) -> None:
        dirty = self.dirty_offset
        for n in cfg.all_nodes():
            if n.id != self.self_node.id and n.id not in self._followers:
                self._followers[n.id] = FollowerIndex(n, next_index=0)
        for fid in list(self._followers):
            if not any(n.id == fid for n in cfg.all_nodes()):
                t = self._recovery_tasks.get(fid)
                if t:
                    t.cancel()
                del self._followers[fid]

    # ---------------------------------------------------------------- reads
    async def make_reader(
        self,
        start_offset: int,
        max_bytes: int = 1 << 20,
        max_offset: int | None = None,
        type_filter=None,
    ):
        """Committed reads only (partition::make_reader clamps to
        committed/LSO — partition.h:65). max_offset is a raw log offset,
        further clamped to the commit index."""
        if self._commit_index < start_offset:
            return []
        limit = self._commit_index
        if max_offset is not None:
            limit = min(limit, max_offset)
        r = self.log.read(
            start_offset, max_bytes, max_offset=limit, type_filter=type_filter
        )
        if asyncio.iscoroutine(r):
            r = await r
        return r

    # ------------------------------------------------------------ heartbeats
    def heartbeat_metadata(self) -> list[dict]:
        """Per-follower heartbeat metadata for the shard-level batched
        heartbeat (heartbeat_manager.cc:155-204)."""
        if not self.is_leader():
            return []
        out = []
        for f in self._followers.values():
            if f.is_recovering:
                continue  # recovery traffic already acts as heartbeats
            prev = f.last_dirty_offset if f.last_dirty_offset >= 0 else self.dirty_offset
            out.append(
                {
                    "group": self.group,
                    "node": {"id": self.self_node.id, "revision": self.self_node.revision},
                    "target": {"id": f.node.id, "revision": f.node.revision},
                    "term": self.term,
                    "prev_log_index": prev,
                    "prev_log_term": self.term_at(prev),
                    "commit_index": self._commit_index,
                }
            )
        return out

    def process_heartbeat_reply(self, reply: dict) -> None:
        if not self.is_leader():
            return
        if reply["term"] > self.term:
            self._spawn_bg(self._step_down(reply["term"]))
            return
        f = self._followers.get(reply["node"]["id"])
        if f is None:
            return
        if reply["result"] == 0:
            f.last_dirty_offset = reply["last_dirty_log_index"]
            f.last_flushed_offset = reply["last_flushed_log_index"]
            f.last_hbeat_ok = True
            if f.next_index <= self.dirty_offset and not f.is_recovering:
                f.next_index = max(f.next_index, f.last_dirty_offset + 1)
                if f.next_index <= self.dirty_offset:
                    self._start_recovery(f)
            self._maybe_advance_commit_index()
        elif reply["result"] == 1:
            f.last_hbeat_ok = False
            f.next_index = max(0, min(f.next_index - 1, reply["last_dirty_log_index"] + 1))
            self._start_recovery(f)


class _ReplicateBatcher:
    """Coalesces concurrent quorum-ack replicates into one append + fanout +
    flush (replicate_batcher.cc:40-62)."""

    def __init__(self, consensus: Consensus) -> None:
        self._c = consensus
        self._pending: list[tuple[list[RecordBatch], asyncio.Future, asyncio.Future, float | None]] = []
        self._flush_task: asyncio.Task | None = None

    def tasks(self) -> list[asyncio.Task]:
        return [self._flush_task] if self._flush_task else []

    async def submit(self, batches: list[RecordBatch], timeout: float | None):
        loop = asyncio.get_event_loop()
        enqueued: asyncio.Future = loop.create_future()
        replicated: asyncio.Future = loop.create_future()
        # raft account (resource_mgmt budget plane): batcher entries are
        # bounded bytes, held from submit until the append phase resolves
        # either way. Waiting is bounded backpressure (submitters sit
        # behind the kafka produce admission gate); plane-less processes
        # skip it entirely.
        from redpanda_tpu.resource_mgmt import budgets as _budgets

        acct = _budgets.account_or_none("raft")
        if acct is not None:
            n = sum(b.size_bytes for b in batches)
            reserved = await acct.acquire(n)
            enqueued.add_done_callback(
                lambda _f, a=acct, r=reserved: a.release(r)
            )
        # sample the submitter's ambient trace as the round's owner trace
        # (the flush task itself is deliberately detached); latest non-None
        # submitter wins — ONE resolvable exemplar per flush round is the
        # contract, not per-submission attribution
        tid = tracer.current_trace()
        if tid is not None:
            self._c._replicate_owner = tid
            self._c._replicate_owner_seq += 1
        self._pending.append((batches, enqueued, replicated, timeout))
        if self._flush_task is None or self._flush_task.done():
            # detached: under sustained load this task loops across MANY
            # coalesced replicates — inheriting the first caller's trace id
            # would mis-attribute every later append's spans to it
            with tracer.detached():
                self._flush_task = asyncio.create_task(self._flush())
        return enqueued, replicated

    async def _flush(self) -> None:
        c = self._c
        while self._pending:
            pending, self._pending = self._pending, []
            async with c._op_lock:
                if not c.is_leader():
                    for _, enq, rep, _t in pending:
                        err = RaftError(Errc.not_leader)
                        enq.set_exception(err)
                        rep.set_exception(err)
                        rep.exception()  # consumed
                    continue
                term = c.term
                lasts: list[int] = []
                for batches, enq, _rep, _t in pending:
                    try:
                        res = await c._append_locked(batches)
                        lasts.append(res.last_offset)
                        enq.set_result(res.last_offset)
                    except Exception as e:  # storage failure
                        lasts.append(-1)
                        enq.set_exception(e)
                r = c.log.flush()
                if asyncio.iscoroutine(r):
                    await r
            c._maybe_advance_commit_index()  # single-replica groups commit here
            c._fanout_append()

            async def wait_one(last: int, rep: asyncio.Future, timeout: float | None) -> None:
                if last < 0:
                    if not rep.done():
                        rep.set_exception(RaftError(Errc.timeout, "append failed"))
                    return
                try:
                    await c.wait_for_commit(last, timeout)
                    if not rep.done():
                        rep.set_result(ReplicateResult(last, term))
                except RaftError as e:
                    if not rep.done():
                        rep.set_exception(e)
                except asyncio.CancelledError:
                    # stop() cancels retained bg tasks: submitters must not
                    # hang on a future nobody will resolve
                    if not rep.done():
                        rep.set_exception(RaftError(Errc.shutting_down))
                    raise

            # Don't block the batcher loop on quorum: new submissions keep
            # coalescing while acks stream in. Handles live in the consensus
            # bg set so stop() cancels pending quorum waits.
            for (batches, enq, rep, t), last in zip(pending, lasts):
                c._spawn_bg(wait_one(last, rep, t))


def _encode_entries(batches: list[RecordBatch]) -> bytes:
    """Wire format for append_entries payloads: [term i64][internal batch]…

    The on-disk 61-byte header carries no term (term context comes from the
    segment), but the RPC payload must — the reference's async_adl for
    record_batch_header serializes ctx.term the same way."""
    parts = []
    for b in batches:
        parts.append(struct.pack("<q", b.header.term))
        parts.append(b.encode_internal())
    return b"".join(parts)


def _decode_batches(blob: bytes) -> list[RecordBatch]:
    from redpanda_tpu.models.record import INTERNAL_HEADER_SIZE

    out = []
    at = 0
    while at + 8 + INTERNAL_HEADER_SIZE <= len(blob):
        (term,) = struct.unpack_from("<q", blob, at)
        batch, consumed = RecordBatch.decode_internal(blob, at + 8)
        batch.header.term = term
        out.append(batch)
        at += 8 + consumed
    return out
