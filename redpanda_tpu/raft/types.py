"""Raft domain types.

Parity with raft/types.h: vnode (id + revision), consistency levels
(raft/types.h replicate_options — quorum_ack / leader_ack / no_ack),
replicate results, and error codes (raft/errc.h).
"""

from __future__ import annotations

import enum
from dataclasses import dataclass


class ConsistencyLevel(enum.IntEnum):
    quorum_ack = 0  # acks=-1: majority has fsynced
    leader_ack = 1  # acks=1: leader appended (no flush wait)
    no_ack = 2      # acks=0: fire and forget


class Errc(enum.IntEnum):
    success = 0
    not_leader = 1
    timeout = 2
    shutting_down = 3
    append_entries_rejection = 4
    leadership_transfer_in_progress = 5
    node_does_not_exist = 6
    configuration_change_in_progress = 7
    group_not_exists = 8


class RaftError(Exception):
    def __init__(self, errc: Errc, msg: str = "") -> None:
        super().__init__(msg or errc.name)
        self.errc = errc


@dataclass(frozen=True, order=True)
class VNode:
    """Node id + revision: a re-added node gets a new revision so stale
    votes/acks from its previous incarnation are ignored (raft/types.h vnode)."""

    id: int
    revision: int = 0


@dataclass
class ReplicateResult:
    last_offset: int
    term: int


@dataclass
class FollowerIndex:
    """Leader-side view of one follower (raft/follower_index.h semantics)."""

    node: VNode
    last_dirty_offset: int = -1
    last_flushed_offset: int = -1
    next_index: int = 0
    is_recovering: bool = False
    last_hbeat_ok: bool = True
    suppress_heartbeats: bool = False
