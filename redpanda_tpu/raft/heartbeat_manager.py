"""Batched cross-group heartbeats.

Parity with raft/heartbeat_manager.cc:155-204: one heartbeat manager per
shard coalesces the heartbeats of ALL raft groups into a single RPC per
destination node per tick — the reason a node with thousands of partitions
doesn't send thousands of heartbeat RPCs.
"""

from __future__ import annotations

import asyncio
import logging
from collections import defaultdict

from redpanda_tpu.rpc.transport import RpcError, TransportClosed

logger = logging.getLogger("rptpu.raft.heartbeat")


class HeartbeatManager:
    def __init__(self, client_for, interval_ms: float = 60.0) -> None:
        self._client_for = client_for  # callable(node_id) -> raftgen Client
        self.interval_ms = interval_ms
        self._groups: dict[int, object] = {}  # group id -> Consensus
        self._task: asyncio.Task | None = None
        # last tick's per-group ack counts from the batched device-plane
        # tally (raft/device_plane.py; empty until raft_device_vote_tally
        # is on) — a debug/observability view, not an acking input
        self.last_tick_acks: dict[int, int] = {}

    def register(self, consensus) -> None:
        self._groups[consensus.group] = consensus

    def deregister(self, group: int) -> None:
        self._groups.pop(group, None)

    async def start(self) -> None:
        self._task = asyncio.create_task(self._loop())

    async def stop(self) -> None:
        if self._task is not None:
            self._task.cancel()
            try:
                await self._task
            except asyncio.CancelledError:
                pass
            self._task = None

    async def _loop(self) -> None:
        while True:
            await asyncio.sleep(self.interval_ms / 1000.0)
            try:
                await self.send_heartbeats()
            except asyncio.CancelledError:
                raise
            except Exception:
                logger.exception("heartbeat tick failed")

    async def send_heartbeats(self) -> None:
        # Gather per-destination batches across every leader group on this
        # node (heartbeat_manager.cc requests_for_range).
        by_node: dict[int, list[dict]] = defaultdict(list)
        for c in list(self._groups.values()):
            for meta in c.heartbeat_metadata():
                by_node[meta["target"]["id"]].append(meta)
        if not by_node:
            return
        acks = await asyncio.gather(
            *(self._send_one(nid, metas) for nid, metas in by_node.items())
        )
        self._tally_acks(acks)

    def _tally_acks(self, acks: list[dict]) -> None:
        """BASELINE config 5 (vote half): the per-tick cross-group ack
        tally as ONE batched reduction over a [replier, group] bit matrix
        instead of counting one reply message at a time. The plane's
        measured probe decides host-vs-device; counts are identical
        either way. Feeds the per-group quorum view (last_tick_acks) the
        admin/debug surfaces read — replication acking itself stays on
        the per-reply path (process_heartbeat_reply)."""
        from redpanda_tpu.raft import device_plane

        if not device_plane.vote_tally_enabled():
            return
        groups = sorted(self._groups)
        if not groups or not acks:
            return
        import numpy as np

        col = {g: i for i, g in enumerate(groups)}
        bits = np.zeros((len(acks), len(groups)), dtype=np.uint8)
        for row, per_node in enumerate(acks):
            for g, ok in (per_node or {}).items():
                if ok and g in col:
                    bits[row, col[g]] = 1
        tally = device_plane.default_plane().tally_votes(bits)
        self.last_tick_acks = {g: int(tally[col[g]]) for g in groups}

    async def _send_one(self, node_id: int, metas: list[dict]) -> dict:
        """Returns {group: replied_ok} for the ack tally."""
        try:
            reply = await self._client_for(node_id).heartbeat(
                {"heartbeats": metas}, timeout=self.interval_ms / 1000.0 * 4
            )
        except (RpcError, TransportClosed, OSError):
            # follower timeout detection is the election timer's job
            return {m["group"]: False for m in metas}
        acks: dict[int, bool] = {}
        for m in reply["meta"]:
            c = self._groups.get(m["group"])
            if c is not None:
                c.process_heartbeat_reply(m)
            acks[m["group"]] = m.get("result", 1) == 0
        return acks
