"""Device-side raft batched CRC + vote aggregation (BASELINE config 5).

The reference validates batch CRCs one at a time in host code
(kafka_batch_adapter.cc:93, record_utils.cc:82) and counts votes and
heartbeat acks one message at a time (heartbeat_manager.cc:155-204).
The batched analogues run as ONE device program over the
``[partition, batch, record]`` axis (parallel/collectives.py
``make_crc_vote_step``): every batch of every partition CRC-validated by
the vmapped table-driven CRC kernel (ops/crc32c_device.py), ack/vote
bits tallied per group by a single mesh psum.

Where that program runs is a MEASURED decision, exactly like the coproc
engine's probes: the first representative validation times the device
step against the host ``crc32c_many`` oracle on the same rows and the
process keeps the winner (``host_pool.PROBE_MARGIN`` posture, journaled
in the governor's ``mesh`` domain). On a tunneled link the host wins and
the plane honestly self-demotes; on co-located chips the mesh step wins.
Either backend is bit-exact — ``validate`` and ``tally_votes`` return
identical arrays, only the executor changes.

Consumers: ``Consensus._do_handle_append`` (follower-side batched CRC
reject, config ``raft_device_crc_validate``) and
``HeartbeatManager.send_heartbeats`` (per-tick cross-group ack tally,
config ``raft_device_vote_tally``).
"""

from __future__ import annotations

import logging
import threading
import time

import numpy as np

from redpanda_tpu.coproc import host_pool
from redpanda_tpu.hashing.crc32c import crc32c, crc32c_many

logger = logging.getLogger("rptpu.raft.device_plane")

# probe floor: fewer rows than this stay on the host oracle without
# pinning the process-wide decision (a 3-batch heartbeat tick is not
# representative of a recovery-scan burst)
PROBE_MIN_ROWS = 64

# ceiling on the padded [n, bucket(max_len)] device matrix: the pack
# amplifies a width-skewed blob (512 x 1KB + one 8MB region = a ~4GB
# matrix) — past this, validate unpadded on the host instead
_PACK_BUDGET_BYTES = 64 << 20


def _bucket(r: int) -> int:
    b = 64
    while b < r:
        b *= 2
    return b


def _host_validate(regions: list[bytes], claimed: np.ndarray) -> np.ndarray:
    """The unpadded host oracle: crc each region where it lies."""
    n = len(regions)
    got = np.fromiter((crc32c(x) for x in regions), np.uint32, n)
    lens = np.fromiter((len(x) for x in regions), np.int64, n)
    return (got == claimed) & (lens > 0)


class RaftDevicePlane:
    """Process-scoped batched CRC/vote executor with a measured backend.

    ``mesh`` (optional): a ``jax.sharding.Mesh`` over the partition axis
    — when given (>= 2 devices) the device leg runs the sharded
    ``make_crc_vote_step`` with the vote psum; without one it runs the
    single-device vmapped kernel. The host leg is ``crc32c_many`` +
    ``np.sum`` — the oracle the device legs are tested against.
    """

    def __init__(self, mesh=None, probe: bool = True):
        self.mesh = mesh
        self.n_devices = int(mesh.devices.size) if mesh is not None else 1
        self._probe_enabled = bool(probe)
        self._decision: str | None = None if probe else "device"
        self._probe: dict | None = None
        self._lock = threading.Lock()
        # serializes the one multi-second calibration; siblings that
        # lose the race serve their call on the host oracle instead of
        # queueing a duplicate jit compile (MeshRunner._probe_run_lock
        # posture)
        self._probe_run_lock = threading.Lock()
        self._steps: dict[object, object] = {}
        self._n_validations = 0
        self._n_tallies = 0
        self._rows_validated = 0

    # ------------------------------------------------------------ decision
    @property
    def decision(self) -> str | None:
        with self._lock:
            return self._decision

    def _device_step(self, r: int):
        with self._lock:
            fn = self._steps.get(r)
        if fn is None:
            if self.mesh is not None:
                from redpanda_tpu.parallel.collectives import make_crc_vote_step

                fn = make_crc_vote_step(self.mesh, r)
            else:
                from redpanda_tpu.ops.crc32c_device import make_crc_fn

                fn = make_crc_fn(r)
            with self._lock:
                fn = self._steps.setdefault(r, fn)
        return fn

    def _run_device(self, rows, lens, claimed, votes):
        """(ok, tally) on the device backend; rows is [N, r] host-packed."""
        n, r = rows.shape
        if self.mesh is not None:
            d = self.n_devices
            n_pad = -(-n // d) * d  # round N up to a multiple of D
            if n_pad != n:
                rows = np.concatenate(
                    [rows, np.zeros((n_pad - n, r), np.uint8)]
                )
                lens = np.concatenate([lens, np.zeros(n_pad - n, np.int32)])
                claimed = np.concatenate(
                    [claimed, np.zeros(n_pad - n, np.uint32)]
                )
            g = votes.shape[1] if votes is not None and votes.ndim == 2 else 1
            v = (
                votes
                if votes is not None
                else np.zeros((d, g), np.uint8)
            )
            step = self._device_step(r)
            ok, _bad, tally = step(
                rows.reshape(d, n_pad // d, r),
                lens.reshape(d, n_pad // d),
                claimed.reshape(d, n_pad // d),
                v,
            )
            return np.asarray(ok).reshape(n_pad)[:n], np.asarray(tally)
        crc = self._device_step(r)
        got = np.asarray(crc(rows, lens))
        ok = (got == claimed) & (lens > 0)
        tally = (
            votes.astype(np.int32).sum(axis=0)
            if votes is not None
            else np.zeros(0, np.int32)
        )
        return ok, tally

    def _calibrate(self, regions, rows, lens, claimed) -> str:
        """Host-vs-device pin on representative rows; journaled (mesh
        domain) so ``rpk debug governor`` reconstructs the choice."""
        from redpanda_tpu.coproc import governor as gov_mod

        try:
            # time the host leg that actually SERVES a "host" pin
            # (_host_validate, unpadded per-region crcs) — measuring
            # crc32c_many over the padded matrix would journal a verdict
            # about a code path the pin never runs
            t0 = time.perf_counter()
            host_ok = _host_validate(regions, claimed)
            t_host = time.perf_counter() - t0
            self._run_device(rows, lens, claimed, None)  # compile + warm
            t0 = time.perf_counter()
            dev_ok, _ = self._run_device(rows, lens, claimed, None)
            t_dev = time.perf_counter() - t0
            if not np.array_equal(host_ok, dev_ok):
                raise RuntimeError("device CRC mismatch vs host oracle")
            if self.mesh is not None:
                # warm the vote aggregator HERE, off the event loop
                # (calibration runs under asyncio.to_thread): the
                # heartbeat tick calls tally_votes on the loop and must
                # never pay a first-use compile there
                from redpanda_tpu.parallel.collectives import (
                    make_vote_aggregator,
                )

                fn = make_vote_aggregator(self.mesh)
                np.asarray(
                    fn(np.zeros((self.n_devices, 1), np.uint8))
                )
                with self._lock:
                    self._steps.setdefault("vote", fn)
        except Exception as exc:
            logger.exception("raft device-plane probe failed; keeping host")
            with self._lock:
                self._decision = "host"
            gov_mod.journal_record(
                gov_mod.MESH,
                "host",
                f"raft CRC/vote probe FAILED ({type(exc).__name__}); "
                "keeping the host oracle",
                {"devices": self.n_devices},
            )
            return "host"
        ratio = t_host / t_dev if t_dev > 0 else 0.0
        decision = "device" if ratio >= host_pool.PROBE_MARGIN else "host"
        probe = {
            "t_host_ms": round(t_host * 1e3, 3),
            "t_device_ms": round(t_dev * 1e3, 3),
            "speedup": round(ratio, 3),
            "devices": self.n_devices,
            "rows": int(len(lens)),
            "chosen": decision,
        }
        with self._lock:
            self._decision = decision
            self._probe = probe
        gov_mod.journal_record(
            gov_mod.MESH,
            decision,
            f"raft batched CRC/vote probe: host {t_host * 1e3:.3f} ms vs "
            f"device ({self.n_devices} dev) {t_dev * 1e3:.3f} ms (device "
            f"must win {host_pool.PROBE_MARGIN}x; process-sticky)",
            dict(probe),
        )
        return decision

    # ------------------------------------------------------------ API
    def validate(self, regions: list[bytes], claimed) -> np.ndarray:
        """ok[i] = crc32c(regions[i]) == claimed[i] & non-empty — batched
        over all regions, on the measured backend (bit-exact on both)."""
        n = len(regions)
        claimed = np.asarray(claimed, dtype=np.uint32)
        if n == 0:
            return np.zeros(0, dtype=bool)
        with self._lock:
            decision = self._decision
            self._n_validations += 1
            self._rows_validated += n
        if decision == "host" or (decision is None and n < PROBE_MIN_ROWS):
            # host-pinned (or too small to probe on): crc each region in
            # place — no reason to pay the O(n * max_len) padded-matrix
            # pack the device leg needs
            return _host_validate(regions, claimed)
        r = _bucket(max(len(x) for x in regions))
        if n * r > _PACK_BUDGET_BYTES:
            # pathological width skew (one outsized region buckets EVERY
            # row to its width): the padded device matrix would amplify
            # the blob by orders of magnitude — validate unpadded on the
            # host, without pinning anything
            return _host_validate(regions, claimed)
        from redpanda_tpu.ops.packing import pack_rows

        rows = lens = None
        if decision is None:
            if not self._probe_run_lock.acquire(blocking=False):
                # a sibling thread is mid-calibration: answer on the
                # host oracle (bit-exact) rather than stacking another
                # seconds-long jit compile behind it — checked BEFORE
                # the pack so the lock-busy path never builds the
                # padded matrix it would throw away
                return _host_validate(regions, claimed)
            try:
                with self._lock:
                    decision = self._decision
                if decision is None:
                    rows, lens = pack_rows(regions, r)
                    lens = np.asarray(lens, dtype=np.int32)
                    decision = self._calibrate(regions, rows, lens, claimed)
            finally:
                self._probe_run_lock.release()
        if decision == "device":
            try:
                if rows is None:
                    rows, lens = pack_rows(regions, r)
                    lens = np.asarray(lens, dtype=np.int32)
                ok, _ = self._run_device(rows, lens, claimed, None)
                return ok
            except Exception:
                # a dying device leg degrades to the oracle, exactly
                logger.exception("device CRC leg failed; host fallback")
        return _host_validate(regions, claimed)

    def tally_votes(self, votes: np.ndarray) -> np.ndarray:
        """Per-group vote/ack tally over a [voters, groups] bit matrix —
        the batched analogue of counting one reply at a time. The mesh
        backend lays voters over the 'p' axis and psums; the host oracle
        is ``np.sum(axis=0)``. Identical int32 counts either way."""
        votes = np.ascontiguousarray(votes, dtype=np.uint8)
        with self._lock:
            self._n_tallies += 1
            decision = self._decision
        if (
            decision == "device"
            and self.mesh is not None
            and votes.shape[0] == self.n_devices
        ):
            try:
                from redpanda_tpu.parallel.collectives import (
                    make_vote_aggregator,
                )

                with self._lock:
                    fn = self._steps.get("vote")
                if fn is None:
                    fn = make_vote_aggregator(self.mesh)
                    with self._lock:
                        fn = self._steps.setdefault("vote", fn)
                return np.asarray(fn(votes))
            except Exception:
                logger.exception("device vote tally failed; host fallback")
        return votes.astype(np.int32).sum(axis=0)

    def stats(self) -> dict:
        with self._lock:
            out = {
                "decision": self._decision,
                "devices": self.n_devices,
                "validations": self._n_validations,
                "rows_validated": self._rows_validated,
                "tallies": self._n_tallies,
            }
            if self._probe is not None:
                out["probe"] = dict(self._probe)
        return out


# broker wiring (app.py _start_cluster_services reads the config knobs):
# both consumers are off by default — the measured probe decides WHERE a
# validation runs, these flags decide WHETHER the call sites run at all.
# The mesh knobs mirror the coproc engine's multi-chip topology: with
# >= 2 devices the default plane's device leg is the sharded
# make_crc_vote_step (vote psum), built lazily on first use.
_crc_validate = False
_vote_tally = False
_mesh_devices = 0
_mesh_backend: str | None = None


def configure(
    crc_validate: bool | None = None,
    vote_tally: bool | None = None,
    mesh_devices: int | None = None,
    mesh_backend: str | None = None,
) -> None:
    global _crc_validate, _vote_tally, _mesh_devices, _mesh_backend
    if crc_validate is not None:
        _crc_validate = bool(crc_validate)
    if vote_tally is not None:
        _vote_tally = bool(vote_tally)
    if mesh_devices is not None:
        _mesh_devices = int(mesh_devices)
    if mesh_backend is not None:
        _mesh_backend = mesh_backend or None


def crc_validate_enabled() -> bool:
    return _crc_validate


def vote_tally_enabled() -> bool:
    return _vote_tally


_default: RaftDevicePlane | None = None
_default_lock = threading.Lock()


def default_plane() -> RaftDevicePlane:
    """Process-wide plane, built lazily on first use. With configured
    mesh knobs (>= 2 devices available) the device leg is the sharded
    crc+vote step; otherwise the single-device vmapped kernel. A mesh
    that fails to build degrades to single-device, never to a crash."""
    global _default
    if _default is None:
        with _default_lock:
            if _default is None:
                mesh = None
                if _mesh_devices >= 2:
                    try:
                        from redpanda_tpu.parallel.mesh import partition_mesh

                        mesh = partition_mesh(
                            n_devices=_mesh_devices, backend=_mesh_backend
                        )
                        if mesh.devices.size < 2:
                            mesh = None
                    except Exception:
                        logger.exception(
                            "raft device-plane mesh init failed; "
                            "single-device leg"
                        )
                        mesh = None
                _default = RaftDevicePlane(mesh=mesh)
    return _default


def reset_default_plane() -> None:
    """Test hook: forget the process plane (and its sticky decision)."""
    global _default
    with _default_lock:
        _default = None
