"""Raft group manager — creates/removes consensus groups on a node.

Parity with raft/group_manager.h:33: owns the shard's heartbeat manager and
the shared recovery throttle (application.cc:556-584), creates a
``Consensus`` per partition replica, routes the raftgen RPC service, and
dispatches leadership notifications to registered callbacks (the partition
leaders table, metadata dissemination).
"""

from __future__ import annotations

import asyncio
import logging

from redpanda_tpu import rpc
from redpanda_tpu.models.fundamental import NTP
from redpanda_tpu.raft.configuration import GroupConfiguration
from redpanda_tpu.raft.consensus import Consensus, RaftTimings
from redpanda_tpu.raft.heartbeat_manager import HeartbeatManager
from redpanda_tpu.raft.service import RaftService, raftgen_service
from redpanda_tpu.raft.types import VNode

logger = logging.getLogger("rptpu.raft.group_manager")


class GroupManager:
    def __init__(
        self,
        self_node: VNode,
        storage,  # StorageApi
        connection_cache: rpc.ConnectionCache,
        timings: RaftTimings | None = None,
        recovery_concurrency: int = 4,
    ) -> None:
        self.self_node = self_node
        self.storage = storage
        self.connections = connection_cache
        self.timings = timings or RaftTimings()
        self._groups: dict[int, Consensus] = {}
        self._leadership_callbacks: list = []
        self._recovery_throttle = asyncio.Semaphore(recovery_concurrency)
        self.heartbeats = HeartbeatManager(
            self.client_for, interval_ms=self.timings.heartbeat_interval_ms
        )
        self.service = RaftService(self)

    # ------------------------------------------------------------ wiring
    def client_for(self, node_id: int) -> rpc.Client:
        # Resolve the transport through the cache EVERY call: when a node
        # rejoins on a new address, register() swaps the transport and a
        # cached client would keep dialing the dead one.
        return rpc.Client(raftgen_service, self.connections.get(node_id))

    def register_service(self, protocol: rpc.SimpleProtocol) -> None:
        protocol.register_service(rpc.ServiceHandler(raftgen_service, self.service))

    def register_leadership_notification(self, cb) -> None:
        """cb(consensus) fires on every leadership change on this node."""
        self._leadership_callbacks.append(cb)

    def _on_leadership(self, consensus: Consensus) -> None:
        for cb in self._leadership_callbacks:
            try:
                cb(consensus)
            except Exception:
                logger.exception("leadership callback failed")

    # ------------------------------------------------------------ lifecycle
    async def start(self) -> "GroupManager":
        await self.heartbeats.start()
        return self

    async def stop(self) -> None:
        await self.heartbeats.stop()
        for c in list(self._groups.values()):
            await c.stop()
        self._groups.clear()

    # ------------------------------------------------------------ groups
    def consensus_for(self, group: int) -> Consensus | None:
        return self._groups.get(group)

    def groups(self) -> list[Consensus]:
        return list(self._groups.values())

    async def create_group(
        self,
        group: int,
        ntp: NTP,
        nodes: list[VNode],
        *,
        timings: RaftTimings | None = None,
        log_overrides=None,
    ) -> Consensus:
        assert group not in self._groups, f"group {group} already exists"
        log = await self.storage.log_mgr.manage(ntp, overrides=log_overrides)
        cfg = GroupConfiguration(voters=list(nodes))
        c = Consensus(
            group,
            ntp,
            self.self_node,
            cfg,
            log,
            self.storage.kvs,
            self.client_for,
            timings=timings or self.timings,
            leadership_cb=self._on_leadership,
            recovery_throttle=self._recovery_throttle,
        )
        await c.start()
        self._groups[group] = c
        self.heartbeats.register(c)
        return c

    async def remove_group(self, group: int, *, delete_log: bool = False) -> None:
        c = self._groups.pop(group, None)
        if c is None:
            return
        self.heartbeats.deregister(group)
        await c.stop()
        if delete_log:
            await self.storage.log_mgr.remove(c.ntp)
        else:
            await self.storage.log_mgr.shutdown(c.ntp)
