"""State machine apply loops over a raft log.

Parity with raft/state_machine.h:57 (a fiber that reads committed batches
and calls ``apply``) and raft/mux_state_machine.h (several STMs demultiplexed
from one log by batch type — the controller pattern).
"""

from __future__ import annotations

import asyncio
import logging

from redpanda_tpu.raft.types import Errc, RaftError

logger = logging.getLogger("rptpu.raft.stm")


class StateMachine:
    """Applies committed batches in order; tracks last_applied."""

    def __init__(self, consensus) -> None:
        self.consensus = consensus
        self.last_applied = -1
        self._task: asyncio.Task | None = None
        self._applied_waiters: list[tuple[int, asyncio.Future]] = []

    async def apply(self, batch) -> None:  # override
        raise NotImplementedError

    async def start(self) -> "StateMachine":
        self._task = asyncio.create_task(self._apply_loop())
        return self

    async def stop(self) -> None:
        if self._task is not None:
            self._task.cancel()
            try:
                await self._task
            except asyncio.CancelledError:
                pass
            self._task = None

    async def wait_applied(self, offset: int, timeout: float | None = None) -> None:
        if self.last_applied >= offset:
            return
        fut: asyncio.Future = asyncio.get_event_loop().create_future()
        self._applied_waiters.append((offset, fut))
        if timeout is None:
            await fut
        else:
            try:
                await asyncio.wait_for(fut, timeout)
            except asyncio.TimeoutError:
                raise RaftError(Errc.timeout, f"offset {offset} not applied in time")

    def _notify_applied(self) -> None:
        fire = [w for w in self._applied_waiters if w[0] <= self.last_applied]
        self._applied_waiters = [w for w in self._applied_waiters if w[0] > self.last_applied]
        for _, fut in fire:
            if not fut.done():
                fut.set_result(None)

    async def _apply_loop(self) -> None:
        c = self.consensus
        while True:
            try:
                if c.commit_index <= self.last_applied:
                    try:
                        await c.wait_for_commit(self.last_applied + 1, timeout=0.5)
                    except RaftError as e:
                        if e.errc == Errc.shutting_down:
                            return
                        continue
                    except Exception:
                        continue
                start = max(self.last_applied + 1, c.start_offset)
                batches = await c.make_reader(start, 4 << 20)
                if not batches:
                    # Prefix-truncated past our cursor (snapshot install).
                    if c.start_offset > self.last_applied + 1:
                        self.last_applied = c.start_offset - 1
                        self._notify_applied()
                    else:
                        await asyncio.sleep(0.01)
                    continue
                for b in batches:
                    await self.apply(b)
                    self.last_applied = b.last_offset
                self._notify_applied()
            except asyncio.CancelledError:
                raise
            except Exception:
                logger.exception("stm apply loop error (group %d)", c.group)
                await asyncio.sleep(0.05)


class MuxStateMachine(StateMachine):
    """Routes batches to sub-STMs by batch type (mux_state_machine.h)."""

    def __init__(self, consensus, handlers: dict) -> None:
        """handlers: RecordBatchType -> async callable(batch)."""
        super().__init__(consensus)
        self._handlers = dict(handlers)

    async def apply(self, batch) -> None:
        handler = self._handlers.get(batch.header.type)
        if handler is not None:
            await handler(batch)
