"""slodiff: judge one SLO/BENCH artifact against another, inside noise bands.

ROADMAP item 6's release-flow cap: "the driver runs `loadgen --scenario
mixed_64p --backend proc` per PR and diffs SLO_r0N.json like BENCH, with
aa_skew_pct-style noise bands wired into the comparison — observability
PRs stop being unjudged by definition." This module is that diff.

Usage::

    python -m tools.slodiff SLO_r10.json SLO_r14.json [--noise-band-pct 20]
    python -m tools.slodiff BENCH_r05.json BENCH_r06.json --json

Verdict vocabulary (the BENCH_r06 ``config3_diagnosis`` vocabulary,
promoted to the release flow):

- **PASS**    — no worse than the baseline (or better) on this item.
- **WEATHER** — worse, but inside the noise band: the same-code A/A skew
  measured on the box (``aa_skew_pct`` when the artifacts carry it, the
  ``--noise-band-pct`` knob otherwise) is larger than the move, so the
  delta is indistinguishable from weather — exactly the judgment the
  r04→r05 payload-bridge "drop" needed before anyone bisected it.
- **REGRESS** — worse beyond the band, or a hard status flip
  (an objective that PASSed the baseline now FAILs).

The overall verdict is the worst item verdict; ``NO_DATA`` items (an
objective idle in either window) judge nothing. Exit code: 0 for
PASS/WEATHER, 1 for REGRESS — WEATHER is reported loudly but does not
fail a release, because failing on weather just teaches people to rerun
until green.

Artifact kinds are sniffed: an SLO report carries ``objectives`` (+
``throughput``); a BENCH artifact carries ``metric``/``value`` (+
config sub-rates), possibly wrapped under ``parsed`` by the driver.
"""

from __future__ import annotations

import argparse
import json
import sys

DEFAULT_BAND_PCT = 20.0

PASS, WEATHER, REGRESS, NO_DATA = "PASS", "WEATHER", "REGRESS", "NO_DATA"
_RANK = {NO_DATA: -1, PASS: 0, WEATHER: 1, REGRESS: 2}

# BENCH config blocks judged by their rate (higher = better); the headline
# "value" is judged the same way.
_BENCH_RATE_KEY = "record_batches_per_sec"


def _load(path: str) -> dict:
    with open(path) as f:
        doc = json.load(f)
    # driver wrapping: {"n":…, "cmd":…, "parsed": {…}}
    if "parsed" in doc and isinstance(doc["parsed"], dict):
        doc = doc["parsed"]
    return doc


def _verdict_lower_better(old, new, band_pct: float) -> tuple[str, float]:
    """Latency-style item: a higher new value is worse. Returns
    (verdict, delta_pct); delta > 0 means worse."""
    if not old or old <= 0 or new is None:
        return NO_DATA, 0.0
    delta_pct = (new - old) / old * 100.0
    if delta_pct <= 0:
        return PASS, delta_pct
    return (WEATHER if delta_pct <= band_pct else REGRESS), delta_pct


def _verdict_higher_better(old, new, band_pct: float) -> tuple[str, float]:
    """Throughput-style item: a lower new value is worse."""
    if not old or old <= 0 or new is None:
        return NO_DATA, 0.0
    delta_pct = (new - old) / old * 100.0
    if delta_pct >= 0:
        return PASS, delta_pct
    return (WEATHER if -delta_pct <= band_pct else REGRESS), delta_pct


def _worst(verdicts) -> str:
    worst = NO_DATA
    any_v = False
    for v in verdicts:
        any_v = True
        if _RANK[v] > _RANK[worst]:
            worst = v
    # a diff that judged NOTHING must not read as a clean pass — an
    # all-NO_DATA comparison (wrong artifact pair, every objective idle)
    # says so instead
    return worst if any_v else NO_DATA


# ================================================================ SLO diff
def diff_slo(old: dict, new: dict, band_pct: float) -> dict:
    """Objective-by-objective diff of two SLO_r0N.json reports."""
    old_by = {o["name"]: o for o in old.get("objectives", [])}
    items = []
    for o in new.get("objectives", []):
        name = o["name"]
        base = old_by.get(name)
        entry = {
            "name": name,
            "metric": o.get("metric"),
            **({"labels": o["labels"]} if o.get("labels") else {}),
            "quantile": o.get("quantile"),
            "threshold_ms": o.get("threshold_ms"),
            "old_status": (base or {}).get("status"),
            "new_status": o.get("status"),
            "old_observed_ms": (base or {}).get("observed_ms"),
            "new_observed_ms": o.get("observed_ms"),
        }
        if base is not None and (
            base.get("metric") != o.get("metric")
            or (base.get("labels") or {}) != (o.get("labels") or {})
        ):
            # the NAME matches but the series does not (a relabeled
            # stage, a repointed metric): comparing the observed values
            # would be apples-to-oranges — say so instead of judging
            entry["verdict"] = NO_DATA
            entry["detail"] = (
                "objective series changed: "
                f"{base.get('metric')}{base.get('labels') or {}} -> "
                f"{o.get('metric')}{o.get('labels') or {}}"
            )
        elif base is None or "NO_DATA" in (o.get("status"), base.get("status")):
            entry["verdict"] = NO_DATA
            entry["detail"] = (
                "no baseline objective" if base is None
                else "objective idle in one window"
            )
        elif base.get("status") == "PASS" and o.get("status") == "FAIL":
            # a hard flip is a regression regardless of the band: the SLO
            # threshold is the contract, not a point estimate
            entry["verdict"] = REGRESS
            entry["detail"] = "status flipped PASS -> FAIL"
            entry["delta_pct"] = round(
                _verdict_lower_better(
                    base.get("observed_ms"), o.get("observed_ms"), band_pct
                )[1], 2,
            )
        else:
            v, delta = _verdict_lower_better(
                base.get("observed_ms"), o.get("observed_ms"), band_pct
            )
            if base.get("status") == "FAIL" and o.get("status") == "PASS":
                v = PASS  # recovered: latency delta is secondary
                entry["detail"] = "status recovered FAIL -> PASS"
            entry["verdict"] = v
            entry["delta_pct"] = round(delta, 2)
        items.append(entry)
    # throughput: the scenario's offered/served rates (higher = better)
    thr_items = []
    for key in ("produced_records_per_s", "produce_ops_per_s"):
        old_v = (old.get("throughput") or {}).get(key)
        new_v = (new.get("throughput") or {}).get(key)
        v, delta = _verdict_higher_better(old_v, new_v, band_pct)
        thr_items.append({
            "name": key, "verdict": v, "delta_pct": round(delta, 2),
            "old": old_v, "new": new_v,
        })
    verdict = _worst(
        [i["verdict"] for i in items] + [i["verdict"] for i in thr_items]
    )
    out = {
        "kind": "slo",
        "objectives": items,
        "throughput": thr_items,
        "verdict": verdict,
    }
    # load-confounding caveat: closed-loop latency scales with offered
    # load, so "p99 worse while throughput ROSE beyond the band" is an
    # ambiguous reading, not clean evidence of a code regression — say so
    # on the diff's face (the judge should re-run at matched load or
    # bracket with a same-code A/A, exactly what bench.py's aa_skew does)
    prod = next(
        (t for t in thr_items if t["name"] == "produced_records_per_s"),
        None,
    )
    if (
        prod is not None
        and prod["verdict"] == PASS
        and (prod.get("delta_pct") or 0) > band_pct
        and any(i["verdict"] == REGRESS for i in items)
    ):
        out["caveats"] = [
            f"candidate served {prod['delta_pct']:+.1f}% more offered "
            f"load than the baseline (closed-loop clients): latency "
            f"REGRESS verdicts above are load-confounded — judge at "
            f"matched load or against a same-code A/A control"
        ]
    return out


# ================================================================ BENCH diff
def _bench_rates(doc: dict) -> dict[str, float]:
    rates = {}
    if isinstance(doc.get("value"), (int, float)):
        rates["headline"] = float(doc["value"])
    for key, sub in doc.items():
        if isinstance(sub, dict) and isinstance(
            sub.get(_BENCH_RATE_KEY), (int, float)
        ):
            rates[key] = float(sub[_BENCH_RATE_KEY])
    return rates


def diff_bench(old: dict, new: dict, band_pct: float | None) -> dict:
    """Config-by-config diff of two BENCH_r0N.json artifacts. The band
    defaults to the LARGER of the two runs' measured same-code A/A skew
    (each artifact judges with the noise of its own box/day)."""
    aa = [
        float(d["aa_skew_pct"])
        for d in (old, new)
        if isinstance(d.get("aa_skew_pct"), (int, float))
    ]
    band = band_pct if band_pct is not None else (
        max(aa) if aa else DEFAULT_BAND_PCT
    )
    old_rates, new_rates = _bench_rates(old), _bench_rates(new)
    items = []
    for key in sorted(set(old_rates) | set(new_rates)):
        v, delta = _verdict_higher_better(
            old_rates.get(key), new_rates.get(key), band
        )
        items.append({
            "name": key, "verdict": v, "delta_pct": round(delta, 2),
            "old": old_rates.get(key), "new": new_rates.get(key),
        })
    return {
        "kind": "bench",
        "band_pct": round(band, 2),
        "aa_skew_pcts": aa,
        "configs": items,
        "verdict": _worst(i["verdict"] for i in items),
    }


# ================================================================ entry
def diff_artifacts(
    old: dict, new: dict, band_pct: float | None = None
) -> dict:
    """Sniff the artifact kind and diff. ``band_pct=None`` lets BENCH
    artifacts use their own measured A/A skew; SLO reports carry no A/A
    control, so they take the default band."""
    if "objectives" in new or "objectives" in old:
        out = diff_slo(
            old, new, band_pct if band_pct is not None else DEFAULT_BAND_PCT
        )
        out["band_pct"] = (
            band_pct if band_pct is not None else DEFAULT_BAND_PCT
        )
    elif "value" in new or "value" in old or "metric" in new:
        out = diff_bench(old, new, band_pct)
    else:
        raise ValueError(
            "unrecognized artifact shape: neither an SLO report "
            "(objectives) nor a BENCH artifact (metric/value)"
        )
    out["old_scenario"] = old.get("scenario") or old.get("metric")
    out["new_scenario"] = new.get("scenario") or new.get("metric")
    return out


def render(diff: dict, old_path: str, new_path: str) -> str:
    lines = [
        f"slodiff {old_path} -> {new_path}  "
        f"[band {diff.get('band_pct', '?')}%]",
    ]
    rows = diff.get("objectives") or []
    for r in rows:
        if r["verdict"] == NO_DATA:
            lines.append(
                f"  {r['verdict']:<8}{r['name']:<28}{r.get('detail', '')}"
            )
            continue
        lines.append(
            f"  {r['verdict']:<8}{r['name']:<28}"
            f"{r.get('old_observed_ms')}ms -> {r.get('new_observed_ms')}ms "
            f"({r.get('delta_pct', 0):+.1f}%)"
            + (f"  [{r['detail']}]" if r.get("detail") else "")
        )
    for r in diff.get("throughput") or []:
        lines.append(
            f"  {r['verdict']:<8}{r['name']:<28}"
            f"{r.get('old')} -> {r.get('new')} "
            f"({r.get('delta_pct', 0):+.1f}%)"
        )
    for r in diff.get("configs") or []:
        lines.append(
            f"  {r['verdict']:<8}{r['name']:<28}"
            f"{r.get('old')} -> {r.get('new')} rb/s "
            f"({r.get('delta_pct', 0):+.1f}%)"
        )
    for c in diff.get("caveats") or []:
        lines.append(f"  CAVEAT: {c}")
    lines.append(f"verdict: {diff['verdict']}")
    return "\n".join(lines)


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("old", help="baseline artifact (SLO_r0N.json / BENCH)")
    p.add_argument("new", help="candidate artifact")
    p.add_argument(
        "--noise-band-pct", type=float, default=None, metavar="PCT",
        help=f"worse-but-within-this-band reads WEATHER, beyond it "
             f"REGRESS (default: the artifacts' own aa_skew_pct for "
             f"BENCH, {DEFAULT_BAND_PCT}%% for SLO reports)",
    )
    p.add_argument("--json", action="store_true", help="raw JSON diff")
    args = p.parse_args(argv)
    diff = diff_artifacts(
        _load(args.old), _load(args.new), args.noise_band_pct
    )
    if args.json:
        print(json.dumps(diff, indent=2, sort_keys=True))
    else:
        print(render(diff, args.old, args.new))
    return 1 if diff["verdict"] == REGRESS else 0


if __name__ == "__main__":
    sys.exit(main())
