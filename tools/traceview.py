"""Render pandaprobe trace dumps as per-stage tables + text flamegraphs.

Input is the JSON shape ``GET /v1/trace/recent`` returns (or the bare list
of traces inside it): each trace is ``{"trace_id": n, "wall_us": n,
"spans": [{"name", "start_us", "dur_us", "thread", ...extras}]}``.

Usage:
    python tools/traceview.py dump.json          # from a saved dump
    rpk debug trace | ...                        # rpk renders via this module
    curl -s :9644/v1/trace/recent | python tools/traceview.py -

Two views per run:
  * a per-stage breakdown across all traces (count / total / mean / max /
    share of traced wall time) — the "where does the time go" table the
    BASELINE perf work needs;
  * a flamegraph-style tree per trace, spans indented by containment, with
    proportional bars — the "what happened to THIS batch" view.
"""

from __future__ import annotations

import argparse
import json
import sys

_BAR_WIDTH = 24
# node renders as its own column (cluster-assembled traces); span_id is
# plumbing for cross-node dedup/anchoring, not operator signal
_EXTRA_KEYS_SKIP = {
    "trace_id", "name", "start_us", "dur_us", "thread", "node", "span_id",
}


def _fmt_us(us: float) -> str:
    if us >= 1_000_000:
        return f"{us / 1e6:.2f}s"
    if us >= 1_000:
        return f"{us / 1e3:.2f}ms"
    return f"{int(us)}us"


def _extras(span: dict) -> str:
    kv = {k: v for k, v in span.items() if k not in _EXTRA_KEYS_SKIP}
    return " ".join(f"{k}={v}" for k, v in sorted(kv.items()))


def stage_breakdown(traces: list[dict]) -> str:
    """Aggregate per-stage table over every span of every trace."""
    agg: dict[str, list[int]] = {}  # name -> [count, total_us, max_us]
    wall = 0
    for t in traces:
        wall += t.get("wall_us", 0)
        for s in t.get("spans", []):
            row = agg.setdefault(s["name"], [0, 0, 0])
            row[0] += 1
            row[1] += s["dur_us"]
            row[2] = max(row[2], s["dur_us"])
    if not agg:
        return "(no spans)"
    name_w = max(len(n) for n in agg) + 2
    lines = [
        f"{'stage':<{name_w}}{'count':>7}{'total':>11}{'mean':>11}"
        f"{'max':>11}{'share':>8}"
    ]
    total_all = sum(r[1] for r in agg.values())
    for name, (count, total, mx) in sorted(
        agg.items(), key=lambda kv: -kv[1][1]
    ):
        share = 100.0 * total / total_all if total_all else 0.0
        lines.append(
            f"{name:<{name_w}}{count:>7}{_fmt_us(total):>11}"
            f"{_fmt_us(total / count):>11}{_fmt_us(mx):>11}{share:>7.1f}%"
        )
    lines.append(
        f"{len(traces)} trace(s), {sum(r[0] for r in agg.values())} span(s), "
        f"{_fmt_us(wall)} traced wall time"
    )
    return "\n".join(lines)


def render_trace(trace: dict) -> str:
    """One trace as an indentation flamegraph: a span nests under the
    nearest earlier span whose [start, end) interval contains it. Cluster-
    assembled traces (GET /v1/trace/cluster/<id>) carry a ``node`` per
    span — rendered as a leading ``n<id>`` column so the hop from leader
    dispatch to follower append reads straight down the containment tree."""
    spans = sorted(
        trace.get("spans", []), key=lambda s: (s["start_us"], -s["dur_us"])
    )
    if not spans:
        return f"trace {trace.get('trace_id', '?')}: (empty)"
    t0 = min(s["start_us"] for s in spans)
    wall = max(1, trace.get("wall_us") or 1)
    nodes = trace.get("nodes") or sorted(
        {s["node"] for s in spans if s.get("node") is not None}
    )
    head = f"trace {trace.get('trace_id', '?')}  wall={_fmt_us(wall)}"
    if nodes:
        head += f"  nodes={','.join(str(n) for n in nodes)}"
    lines = [head]
    with_nodes = bool(nodes)
    node_w = max((len(f"n{n}") for n in nodes), default=0) + 1
    stack: list[tuple[int, int]] = []  # (end_us, depth)
    name_w = max(len(s["name"]) for s in spans) + 2
    for s in spans:
        start, end = s["start_us"], s["start_us"] + s["dur_us"]
        while stack and start >= stack[-1][0]:
            stack.pop()
        depth = stack[-1][1] + 1 if stack else 0
        stack.append((end, depth))
        bar_n = max(1, round(_BAR_WIDTH * s["dur_us"] / wall))
        pad = "  " * depth
        extras = _extras(s)
        node_col = ""
        if with_nodes:
            tag = f"n{s['node']}" if s.get("node") is not None else "?"
            node_col = f"{tag:<{node_w}}"
        lines.append(
            f"  {node_col}{pad}{s['name']:<{max(1, name_w - len(pad))}}"
            f"{_fmt_us(s['dur_us']):>10}  +{_fmt_us(start - t0):<9}"
            f"{'#' * bar_n:<{_BAR_WIDTH}} {s['thread']}"
            + (f"  [{extras}]" if extras else "")
        )
    return "\n".join(lines)


def _coerce_traces(doc) -> list[dict]:
    if isinstance(doc, dict):
        doc = doc.get("traces", [])
    if not isinstance(doc, list):
        raise ValueError("expected a trace list or a /v1/trace/recent object")
    return doc


def render_report(doc, max_traces: int = 10) -> str:
    """Breakdown table + per-trace flamegraphs for a dump document."""
    traces = _coerce_traces(doc)
    parts = [stage_breakdown(traces)]
    for t in traces[:max_traces]:
        parts.append("")
        parts.append(render_trace(t))
    if len(traces) > max_traces:
        parts.append(f"... {len(traces) - max_traces} more trace(s) not shown")
    return "\n".join(parts)


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    p.add_argument("path", help="trace dump JSON file, or - for stdin")
    p.add_argument(
        "--max-traces", type=int, default=10, help="flamegraphs to render"
    )
    args = p.parse_args(argv)
    try:
        raw = sys.stdin.read() if args.path == "-" else open(args.path).read()
        doc = json.loads(raw)
        print(render_report(doc, max_traces=args.max_traces))
    except (OSError, ValueError) as e:
        print(f"traceview: {e}", file=sys.stderr)
        return 2
    return 0


if __name__ == "__main__":
    sys.exit(main())
