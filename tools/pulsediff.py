"""pulsediff: slodiff for timelines — stage-by-stage, inside noise bands.

ROADMAP 7d: SLO_r14's three judgment blocks proved that artifact-level
diffs (headline rb/s, objective p99s) can move for reasons a stage-level
view immediately disambiguates — a 10% headline drop that is ALL in
queue-wait is backpressure weather, the same drop concentrated in one
device leg is a code regression with a name. This module diffs two
pandapulse timeline artifacts (``rpk debug profile --perfetto`` output /
``timeline.json`` from a debug bundle) stage by stage:

- **per-stage wall split**: total duration per span name, normalized per
  launch, judged lower-is-better inside the noise band;
- **queue-wait**: the gap between a launch group's first span start and
  its dispatch leg — backpressure shows up HERE, not in compute stages;
- **counter-track envelopes**: min/mean/max per ``ph:"C"`` trend track
  (occupancy, shed rate, pressure...), reported for drill-down and judged
  only for hard posture flips (shed rate appearing where there was none).

Verdict vocabulary is slodiff's, verbatim: PASS / WEATHER / REGRESS with
the band from ``--noise-band-pct`` or the artifacts' own embedded
``aa_band_pct`` (what ``loadgen --ab-rounds`` measures same-session —
the only honest band, per SLO_r14). Percentage bands alone misjudge
tiny stages: a 40us extract leg doubling is +100% but +40us/launch — it
cannot explain any headline move and sits below a shared box's scheduler
jitter, so an A/A pair would read REGRESS on a different micro-stage
every rerun. Stages (and queue-wait) whose ABSOLUTE per-launch delta is
under ``--min-delta-us`` (default 100us) therefore clamp REGRESS ->
WEATHER with the floor named on the row's face — loud, never fatal.
Non-timeline artifacts (SLO reports, BENCH files) delegate to
tools/slodiff.py unchanged, so one entry point judges whatever pair the
release flow hands it::

    python -m tools.pulsediff old_timeline.json new_timeline.json
    python -m tools.pulsediff SLO_r14.json SLO_r17.json   # -> slodiff

Exit code 0 for PASS/WEATHER, 1 for REGRESS — WEATHER is loud but does
not fail a release (failing on weather teaches people to rerun until
green).
"""

from __future__ import annotations

import argparse
import json
import sys

from tools.slodiff import (
    DEFAULT_BAND_PCT, NO_DATA, PASS, REGRESS, WEATHER,
    _verdict_lower_better, _worst, diff_artifacts as _slodiff_artifacts,
)


def _load(path: str) -> dict:
    with open(path) as f:
        doc = json.load(f)
    if "parsed" in doc and isinstance(doc["parsed"], dict):
        doc = doc["parsed"]
    return doc


def is_timeline(doc: dict) -> bool:
    return isinstance(doc.get("traceEvents"), list)


# ================================================================ extraction
def stage_profile(doc: dict) -> dict:
    """Per-stage wall totals + queue-wait + counter envelopes from one
    timeline document. Durations are normalized per launch when the
    artifact says how many launches it covers — two rings of different
    depth must still compare."""
    events = doc.get("traceEvents") or []
    launches = max(1, int(doc.get("launches") or 1))
    stages: dict[str, dict] = {}
    group_start: dict = {}
    dispatch_start: dict = {}
    for ev in events:
        if ev.get("ph") != "X":
            continue
        name = ev.get("name", "?")
        dur = float(ev.get("dur") or 0.0)
        st = stages.setdefault(name, {"total_us": 0.0, "count": 0})
        st["total_us"] += dur
        st["count"] += 1
        # queue-wait: first span of the trace -> the dispatch-family leg.
        # trace_id groups a launch lifecycle; derived spans excluded (they
        # re-cover the same wall).
        args = ev.get("args") or {}
        tid = args.get("trace_id")
        if tid is None or ev.get("cat") == "derived":
            continue
        ts = float(ev.get("ts") or 0.0)
        if tid not in group_start or ts < group_start[tid]:
            group_start[tid] = ts
        if "dispatch" in name and (
            tid not in dispatch_start or ts < dispatch_start[tid]
        ):
            dispatch_start[tid] = ts
    waits = [
        max(0.0, dispatch_start[t] - group_start[t])
        for t in dispatch_start
        if t in group_start
    ]
    counters: dict[str, dict] = {}
    for ev in events:
        if ev.get("ph") != "C":
            continue
        v = (ev.get("args") or {}).get("value")
        if not isinstance(v, (int, float)):
            continue
        c = counters.setdefault(
            ev.get("name", "?"),
            {"min": v, "max": v, "sum": 0.0, "n": 0},
        )
        c["min"] = min(c["min"], v)
        c["max"] = max(c["max"], v)
        c["sum"] += v
        c["n"] += 1
    return {
        "launches": launches,
        "stages": {
            name: {
                "per_launch_us": round(st["total_us"] / launches, 2),
                "total_us": round(st["total_us"], 1),
                "count": st["count"],
            }
            for name, st in stages.items()
        },
        "queue_wait_us": {
            "mean": round(sum(waits) / len(waits), 2) if waits else None,
            "max": round(max(waits), 2) if waits else None,
            "n": len(waits),
        },
        "counters": {
            name: {
                "min": round(c["min"], 4),
                "mean": round(c["sum"] / c["n"], 4),
                "max": round(c["max"], 4),
                "n": c["n"],
            }
            for name, c in counters.items()
        },
    }


# ================================================================ diff
#: REGRESS requires the stage to have moved by at least this much wall
#: per launch, not just by a percentage — micro-stages under the floor
#: are below the judge's resolution and clamp to WEATHER.
MIN_DELTA_US = 100.0


def diff_timelines(
    old: dict, new: dict, band_pct: float | None,
    min_delta_us: float = MIN_DELTA_US,
) -> dict:
    """Stage-by-stage diff of two timeline artifacts. The band defaults
    to the LARGER of the two artifacts' embedded same-session A/A bands
    (``aa_band_pct``, what loadgen --ab-rounds stamps), else slodiff's
    default — cross-session timelines with no measured band get the
    honest wide one."""
    aa = [
        float(d["aa_band_pct"])
        for d in (old, new)
        if isinstance(d.get("aa_band_pct"), (int, float))
    ]
    band = band_pct if band_pct is not None else (
        max(aa) if aa else DEFAULT_BAND_PCT
    )
    po, pn = stage_profile(old), stage_profile(new)
    items = []
    for name in sorted(set(po["stages"]) | set(pn["stages"])):
        o = po["stages"].get(name)
        n = pn["stages"].get(name)
        entry = {
            "name": name,
            "old_per_launch_us": (o or {}).get("per_launch_us"),
            "new_per_launch_us": (n or {}).get("per_launch_us"),
        }
        if o is None or n is None:
            entry["verdict"] = NO_DATA
            entry["detail"] = (
                "stage absent in baseline" if o is None
                else "stage no longer runs"
            )
        else:
            v, delta = _verdict_lower_better(
                o["per_launch_us"], n["per_launch_us"], band
            )
            abs_delta = n["per_launch_us"] - o["per_launch_us"]
            if v == REGRESS and abs_delta < min_delta_us:
                v = WEATHER
                entry["detail"] = (
                    f"below resolution floor (+{abs_delta:.1f}us/launch "
                    f"< {min_delta_us:g}us)"
                )
            entry["verdict"] = v
            entry["delta_pct"] = round(delta, 2)
        items.append(entry)
    # queue-wait judged like a stage (lower is better): the backpressure
    # component separated from compute so a REGRESS names the right culprit
    qo, qn = po["queue_wait_us"]["mean"], pn["queue_wait_us"]["mean"]
    qv, qdelta = _verdict_lower_better(qo, qn, band)
    if qv == REGRESS and qo is not None and qn is not None \
            and (qn - qo) < min_delta_us:
        qv = WEATHER
    queue_item = {
        "name": "queue_wait",
        "verdict": qv,
        "delta_pct": round(qdelta, 2),
        "old_mean_us": qo,
        "new_mean_us": qn,
    }
    # counter envelopes: drill-down rows; judged only on hard flips (a
    # shed/pressure track going 0 -> nonzero is an incident, not weather)
    counter_items = []
    for name in sorted(set(po["counters"]) | set(pn["counters"])):
        co = po["counters"].get(name)
        cn = pn["counters"].get(name)
        entry = {"name": name, "old": co, "new": cn, "verdict": NO_DATA}
        if (
            co is not None and cn is not None
            and name.startswith(("trend:shed_rate", "trend:pressure"))
        ):
            if co["max"] <= 0 and cn["max"] > 0:
                entry["verdict"] = REGRESS
                entry["detail"] = "track flipped idle -> active"
            else:
                entry["verdict"] = PASS
        counter_items.append(entry)
    verdict = _worst(
        [i["verdict"] for i in items]
        + [queue_item["verdict"]]
        + [i["verdict"] for i in counter_items]
    )
    return {
        "kind": "timeline",
        "band_pct": round(band, 2),
        "min_delta_us": min_delta_us,
        "aa_band_pcts": aa,
        "stages": items,
        "queue_wait": queue_item,
        "counters": counter_items,
        "old_launches": po["launches"],
        "new_launches": pn["launches"],
        "verdict": verdict,
    }


def diff_artifacts(
    old: dict, new: dict, band_pct: float | None = None,
    min_delta_us: float = MIN_DELTA_US,
) -> dict:
    """Sniff the pair: two timelines diff here, anything else delegates
    to slodiff (one judge entry point for the whole release flow). A
    mixed pair is an error — apples to oranges, never a verdict."""
    ot, nt = is_timeline(old), is_timeline(new)
    if ot and nt:
        return diff_timelines(old, new, band_pct, min_delta_us)
    if ot or nt:
        raise ValueError(
            "artifact kinds differ: one is a timeline, the other is not"
        )
    return _slodiff_artifacts(old, new, band_pct)


def render(diff: dict, old_path: str, new_path: str) -> str:
    if diff.get("kind") != "timeline":
        from tools.slodiff import render as slodiff_render

        return slodiff_render(diff, old_path, new_path)
    lines = [
        f"pulsediff {old_path} -> {new_path}  "
        f"[band {diff['band_pct']}%; "
        f"{diff['old_launches']} -> {diff['new_launches']} launches]",
    ]
    for r in diff["stages"]:
        if r["verdict"] == NO_DATA:
            lines.append(
                f"  {r['verdict']:<8}{r['name']:<40}{r.get('detail', '')}"
            )
            continue
        lines.append(
            f"  {r['verdict']:<8}{r['name']:<40}"
            f"{r['old_per_launch_us']}us -> {r['new_per_launch_us']}us "
            f"/launch ({r.get('delta_pct', 0):+.1f}%)"
            + (f"  [{r['detail']}]" if r.get("detail") else "")
        )
    q = diff["queue_wait"]
    lines.append(
        f"  {q['verdict']:<8}{'queue_wait':<40}"
        f"{q['old_mean_us']}us -> {q['new_mean_us']}us mean "
        f"({q.get('delta_pct', 0):+.1f}%)"
    )
    for r in diff["counters"]:
        o, n = r.get("old") or {}, r.get("new") or {}
        lines.append(
            f"  {r['verdict']:<8}{r['name']:<40}"
            f"env [{o.get('min')}..{o.get('max')}] -> "
            f"[{n.get('min')}..{n.get('max')}]"
            + (f"  [{r['detail']}]" if r.get("detail") else "")
        )
    lines.append(f"verdict: {diff['verdict']}")
    return "\n".join(lines)


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("old", help="baseline artifact (timeline/SLO/BENCH)")
    p.add_argument("new", help="candidate artifact")
    p.add_argument(
        "--noise-band-pct", type=float, default=None, metavar="PCT",
        help="worse-but-within-this-band reads WEATHER, beyond it REGRESS "
             "(default: the artifacts' own embedded same-session band, "
             f"else {DEFAULT_BAND_PCT}%%)",
    )
    p.add_argument(
        "--min-delta-us", type=float, default=MIN_DELTA_US, metavar="US",
        help="timeline stages must move at least this much wall per "
             "launch to REGRESS — smaller absolute deltas are below the "
             "judge's resolution and clamp to WEATHER "
             f"(default {MIN_DELTA_US:g}us; 0 disables the floor)",
    )
    p.add_argument("--json", action="store_true", help="raw JSON diff")
    args = p.parse_args(argv)
    diff = diff_artifacts(
        _load(args.old), _load(args.new), args.noise_band_pct,
        args.min_delta_us,
    )
    if args.json:
        print(json.dumps(diff, indent=2, sort_keys=True))
    else:
        print(render(diff, args.old, args.new))
    return 1 if diff["verdict"] == REGRESS else 0


if __name__ == "__main__":
    sys.exit(main())
