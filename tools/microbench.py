"""Component microbenchmarks.

Parity with the reference's seastar perf tests (SURVEY §4.1: hashing
hash_bench, compression zstd_stream_bench, storage compaction_idx_bench,
rpc rpc_bench, cluster allocation_bench): each bench exercises one hot
component in isolation and reports ops/s or MB/s as one JSON object on
stdout. Run-it-yourself, like the reference's: `python tools/microbench.py
[--secs 0.5] [--only crc32c,rpc_echo,...]`.
"""

from __future__ import annotations

import argparse
import asyncio
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np  # noqa: E402


def _rate(fn, secs: float, unit_per_call: float) -> float:
    """Calls fn in a timed loop; returns units/sec."""
    # warmup
    fn()
    n = 0
    t0 = time.perf_counter()
    while time.perf_counter() - t0 < secs:
        fn()
        n += 1
    dt = time.perf_counter() - t0
    return n * unit_per_call / dt


def bench_crc32c(secs: float) -> dict:
    from redpanda_tpu.hashing.crc32c import crc32c

    blob = os.urandom(1 << 20)
    mb_s = _rate(lambda: crc32c(blob), secs, 1.0)  # 1 MB per call
    return {"crc32c_mb_s": round(mb_s, 1)}


def bench_xxhash(secs: float) -> dict:
    from redpanda_tpu.hashing.xx import xxhash64

    blob = os.urandom(1 << 20)
    return {"xxhash64_mb_s": round(_rate(lambda: xxhash64(blob), secs, 1.0), 1)}


def bench_zstd_stream(secs: float) -> dict:
    from redpanda_tpu.compression import compress, is_available, uncompress
    from redpanda_tpu.models.record import Compression

    if not is_available(Compression.zstd):
        return {"zstd_skipped": "zstandard not installed"}
    rng = np.random.default_rng(7)
    # compressible-ish payload (zstd_stream_bench uses realistic frames)
    blob = bytes(rng.integers(0, 16, 1 << 20, dtype=np.uint8))
    packed = compress(blob, Compression.zstd)
    c = _rate(lambda: compress(blob, Compression.zstd), secs, 1.0)
    d = _rate(lambda: uncompress(packed, Compression.zstd), secs, 1.0)
    return {"zstd_compress_mb_s": round(c, 1), "zstd_uncompress_mb_s": round(d, 1)}


def bench_batch_codec(secs: float) -> dict:
    from redpanda_tpu.models.record import Record, RecordBatch

    recs = [Record(offset_delta=i, value=b"x" * 256) for i in range(32)]
    batch = RecordBatch.build(recs, base_offset=0)
    wire = batch.encode_internal()
    enc = _rate(lambda: RecordBatch.build(recs, base_offset=0).encode_internal(), secs, 1.0)
    dec = _rate(lambda: RecordBatch.decode_internal(wire), secs, 1.0)
    return {
        "batch_encode_per_s": round(enc, 1),
        "batch_decode_per_s": round(dec, 1),
    }


def bench_explode_find(secs: float) -> dict:
    """Staged-vs-structural parse+extract ladders (min-of-blocks) over
    three record shapes, plus the old per-component rates.

    staged = the scalar rp_explode_find ladder exactly as the engine runs
    it (Python payload join, scalar fused parse, per-column span gathers +
    pads, project_rows crossing); structural = the fused ladder
    (rp_explode_find2 pointer-table parse — no join for projection plans —
    + ONE rp_extract_cols2 extraction crossing). The parse-only split is
    also reported so the kernel and the fusion are attributable
    separately.

    Shapes: ``flat`` is the bench.py 64p headline shape (~1KB records, one
    long string value — the scalar walker's memchr best case, where the
    two ladders are closest); ``nested`` buries an unselected nested
    container the scalar walker must skip byte-at-a-time; ``stringified``
    carries a stringified-JSON msg (escaped quotes everywhere — the
    memchr-restart pathology, and THE log-analytics shape the structural
    escape mask exists for). --assert-explode-speedup gates
    ``explode_find_speedup`` = staged/structural on the stringified shape;
    the engine's own parse-path probe decides per box which ladder
    production launches take (BENCH json records its verdict)."""
    from redpanda_tpu.coproc import batch_codec
    from redpanda_tpu.coproc.column_plan import plan_spec
    from redpanda_tpu.models.record import Record, RecordBatch
    from redpanda_tpu.ops.exprs import field
    from redpanda_tpu.ops.transforms import Int, Str, map_project, where

    rng = np.random.default_rng(0)

    def flat(p, i):
        return json.dumps({
            "level": ["error", "info", "warn"][(p + i) % 3], "code": i,
            "msg": "x" * (900 + int(rng.integers(0, 100))),
        }).encode()

    def nested(p, i):
        inner = {
            "user": {"id": i, "tags": ["a", "b", "c"],
                     "attrs": {f"k{j}": j for j in range(20)}},
            "ctx": [{"s": "x", "n": j} for j in range(10)],
        }
        return json.dumps({
            "level": ["error", "info"][i % 2], "payload": inner,
            "code": i, "msg": "x" * 120,
        }).encode()

    def stringified(p, i):
        inner = json.dumps({
            "trace": "abc", "fields": {f"f{j}": "v" * 8 for j in range(24)},
        })
        return json.dumps({
            "level": ["error", "info"][i % 2], "code": i, "msg": inner,
        }).encode()

    spec = where(field("level") == "error") | map_project(Int("code"), Str("msg", 64))
    plan = plan_spec(spec)
    paths = plan.flat_paths()
    lib = batch_codec._native()
    out = {}

    def min_of_blocks(fn) -> float:
        fn()  # warmup
        best = float("inf")
        t_end = time.perf_counter() + secs
        while time.perf_counter() < t_end:
            t0 = time.perf_counter()
            fn()
            best = min(best, time.perf_counter() - t0)
        return best

    def bucket(n: int) -> int:
        b = 128
        while b < n:
            b *= 2
        return b

    for shape, value_fn in (("flat", flat), ("nested", nested),
                            ("stringified", stringified)):
        batches = [
            RecordBatch.build(
                [Record(offset_delta=i, value=value_fn(p, i)) for i in range(32)],
                base_offset=0,
            )
            for p in range(64)
        ]
        n = 64 * 32
        n_pad = bucket(n)

        def staged():
            got = batch_codec.explode_and_find(batches, paths)
            if got is None:
                raise RuntimeError("staged native ladder unavailable")
            ex, types, vs, ve = got
            cache = plan.make_cache_from_tables(ex, paths, types, vs, ve)
            plan.extract_device_inputs(ex.joined, ex.offsets, ex.sizes, n_pad, cache)
            plan.extract_projection(ex.joined, ex.offsets, ex.sizes, cache)

        def structural():
            sp = batch_codec.explode_find_structural(batches, paths, False)
            if sp is None:
                raise RuntimeError("structural native ladder unavailable")
            plan.extract_fused(sp, n_pad)

        try:
            s = min_of_blocks(staged)
        except RuntimeError:
            out["explode_find_skipped"] = "native lib unavailable"
            return out
        out[f"explode_find_{shape}_staged_ms"] = round(s * 1e3, 3)
        out[f"explode_find_{shape}_staged_recs_per_s"] = round(n / s, 1)
        if lib is not None and getattr(lib, "has_structural", False):
            f = min_of_blocks(structural)
            out[f"explode_find_{shape}_structural_ms"] = round(f * 1e3, 3)
            out[f"explode_find_{shape}_structural_recs_per_s"] = round(n / f, 1)
            out[f"explode_find_{shape}_speedup"] = round(s / f, 3)
            # parse-only split: the kernels alone, identical inputs
            payloads, counts, p_off, p_len, _r, joined, _n = (
                batch_codec._gather_payloads(batches)
            )
            ps = min_of_blocks(
                lambda: lib.explode_find(joined, p_off, p_len, counts, paths)
            )
            pf = min_of_blocks(
                lambda: lib.explode_find_structural(payloads, counts, paths, False)
            )
            out[f"explode_find_{shape}_parse_scalar_ms"] = round(ps * 1e3, 3)
            out[f"explode_find_{shape}_parse_structural_ms"] = round(pf * 1e3, 3)
    if "explode_find_stringified_speedup" in out:
        # the gated number: the structural-index target shape
        out["explode_find_speedup"] = out["explode_find_stringified_speedup"]
    return out


def bench_host_pool_scaling(secs: float) -> dict:
    """Host-stage pool scaling: the same columnar launch at workers 1/2/4.

    force_mode='columnar_host' keeps the whole run on host stages (explode
    +find, extraction, numpy predicate, framing) — exactly the work the
    pool shards — so the w4/w1 ratio is the pool's speedup, not device
    noise. workers=1 is the inline path (the pool only exists at >= 2).
    Rates are best-of-rounds (min-of-blocks posture: shared-machine load
    spikes can only slow a round down)."""
    from redpanda_tpu.coproc import TpuEngine, ProcessBatchRequest
    from redpanda_tpu.coproc.engine import ProcessBatchItem
    from redpanda_tpu.models import NTP
    from redpanda_tpu.models.record import Record, RecordBatch
    from redpanda_tpu.ops.exprs import field
    from redpanda_tpu.ops.transforms import Int, Str, map_project, where

    rng = np.random.default_rng(3)
    spec = where(field("level") == "error") | map_project(Int("code"), Str("msg", 64))
    batches = []
    for _ in range(64):
        recs = [
            Record(
                offset_delta=i,
                value=json.dumps({
                    "level": ["error", "info"][i % 2], "code": i,
                    "msg": "x" * int(rng.integers(40, 90)),
                }).encode(),
            )
            for i in range(64)
        ]
        batches.append(RecordBatch.build(recs, base_offset=0))
    req = ProcessBatchRequest(
        [ProcessBatchItem(1, NTP.kafka("bench", 0), batches)]
    )
    n_recs = 64 * 64
    out = {}
    for workers in (1, 2, 4):
        engine = TpuEngine(
            row_stride=256,
            compress_threshold=10**9,
            force_mode="columnar_host",
            host_workers=workers,
            host_pool_probe=False,  # this bench IS the capacity measurement
        )
        codes = engine.enable_coprocessors([(1, spec.to_json(), ("bench",))])
        assert codes == [0]
        engine.process_batch(req)  # warmup
        best = 0.0
        t_end = time.perf_counter() + secs
        while time.perf_counter() < t_end:
            t0 = time.perf_counter()
            engine.process_batch(req)
            best = max(best, n_recs / (time.perf_counter() - t0))
        out[f"host_pool_w{workers}_recs_per_s"] = round(best, 1)
    w1 = out["host_pool_w1_recs_per_s"]
    out["host_pool_speedup_best"] = round(
        max(out["host_pool_w2_recs_per_s"], out["host_pool_w4_recs_per_s"]) / w1, 3
    )
    # context for sub-1x results: synthetic thread-scaling on this box
    # (quota-limited hosts advertise CPUs they don't have; the product
    # engine calibrates on its real explode stage and self-demotes there)
    from redpanda_tpu.coproc import host_pool

    probe = host_pool.measure_parallel_capacity()
    out["host_pool_synthetic_thread_speedup"] = probe["speedup"]
    return out


def bench_mesh_scaling(secs: float) -> dict:
    """Multi-chip mesh scaling: the config-5 sharded CRC+vote step
    (parallel.collectives.make_crc_vote_step — the device half of the
    meshrunner's launch) over the SAME total work at 1/2/4/8 devices on
    the host-platform mesh. Pure device compute, no host ladder: the
    ratio is what the mesh buys the kernel, not parse noise. Rates are
    best-of-rounds (min-of-blocks posture). Reports rows/s per device
    count plus ``mesh_speedup_best`` = best multi-device rate over the
    1-device mesh — the ``--assert-mesh-speedup`` gate's input.

    Requires the virtual host-platform mesh
    (XLA_FLAGS=--xla_force_host_platform_device_count=8, set by
    force_cpu_platform before jax initializes); device counts beyond
    what the backend offers are skipped and reported as absent.

    Threshold guidance for the gate: virtual host-platform devices share
    the box's real cores, so the achievable ratio is bounded by the
    MEASURED parallel capacity reported alongside
    (``mesh_parallel_capacity``, same diagnostic the host-pool bench
    carries) — on a quota-limited 1-core box the honest floor is ~1.0
    (the sharded program must cost nothing over the 1-device mesh: a
    no-regression gate), while co-located multi-chip ICI justifies 1.5+.
    The engine itself never trusts this bench: the meshrunner's own
    PROBE_MARGIN calibration decides mesh-vs-single per process."""
    from redpanda_tpu.utils.platform import force_cpu_platform

    force_cpu_platform(8)
    import jax

    from redpanda_tpu.hashing.crc32c import crc32c
    from redpanda_tpu.parallel import make_crc_vote_step, partition_mesh, shard_to_mesh

    devs = jax.local_devices(backend="cpu")
    rng = np.random.default_rng(7)
    n_batches, r, groups = 512, 1024, 64
    payloads = [rng.bytes(r - (i % 129)) for i in range(n_batches)]
    rows = np.zeros((n_batches, r), np.uint8)
    lens = np.empty(n_batches, np.int32)
    claimed = np.empty(n_batches, np.uint32)
    for i, p in enumerate(payloads):
        rows[i, : len(p)] = np.frombuffer(p, np.uint8)
        lens[i] = len(p)
        claimed[i] = crc32c(p)
    out: dict = {"mesh_available_devices": len(devs)}
    rates: dict[int, float] = {}
    for d in (1, 2, 4, 8):
        if d > len(devs) or n_batches % d:
            continue
        mesh = partition_mesh(devices=devs[:d])
        step = make_crc_vote_step(mesh, r)
        votes = rng.integers(0, 2, (d, groups)).astype(np.uint8)
        args = shard_to_mesh(
            mesh,
            rows.reshape(d, n_batches // d, r),
            lens.reshape(d, n_batches // d),
            claimed.reshape(d, n_batches // d),
            votes,
        )
        ok, _bad, tally = step(*args)  # compile + warm off the clock
        assert bool(np.asarray(ok).all()), "CRC kernel mismatch on probe rows"
        assert np.array_equal(
            np.asarray(tally), votes.astype(np.int32).sum(axis=0)
        ), "vote psum mismatch vs host oracle"
        best = 0.0
        t_end = time.perf_counter() + secs
        while time.perf_counter() < t_end:
            t0 = time.perf_counter()
            jax.block_until_ready(step(*args))
            best = max(best, n_batches / (time.perf_counter() - t0))
        rates[d] = best
        out[f"mesh_d{d}_batches_per_s"] = round(best, 1)
    if 1 in rates and len(rates) > 1:
        out["mesh_speedup_best"] = round(
            max(v for d, v in rates.items() if d > 1) / rates[1], 3
        )
    # context for ~1.0x results: what thread-level parallelism this box
    # actually has (virtual devices share the real cores)
    from redpanda_tpu.coproc import host_pool

    out["mesh_parallel_capacity"] = host_pool.measure_parallel_capacity()[
        "speedup"
    ]
    return out


def bench_harvest_path(secs: float) -> dict:
    """Zero-copy harvest: gather vs padded framing on the 64-partition
    JSON-filter workload (a pure where-filter -> passthrough plan, ~1KB
    records — the shape where the padded path's [N, maxlen] row matrix
    is pure overhead).

    STAGE-TIME criterion, min-of-blocks: wall-clock A/B on a shared box
    has ±30% A/A skew, so each block runs the same tick count and sums the
    engine's own harvest-side stage seconds (extract_proj + assemble +
    frame + seal); the per-mode result is the BEST block. The output
    recompression cost is mode-independent (identical bytes compress on
    both paths), so compress_threshold is maxed to keep the codec's
    throughput — measured by zstd_stream on its own — from diluting the
    copy physics the gather path removes."""
    from redpanda_tpu.coproc import TpuEngine, ProcessBatchRequest
    from redpanda_tpu.coproc.engine import ProcessBatchItem
    from redpanda_tpu.models import NTP
    from redpanda_tpu.models.record import Record, RecordBatch
    from redpanda_tpu.ops.exprs import field
    from redpanda_tpu.ops.transforms import where

    rng = np.random.default_rng(11)
    spec = where(field("level") == "error")
    items = []
    for p in range(64):
        recs = [
            Record(
                offset_delta=i,
                value=json.dumps({
                    "level": ["error", "info", "warn"][(p + i) % 3],
                    "code": i,
                    "msg": "x" * (900 + int(rng.integers(0, 100))),
                }).encode(),
            )
            for i in range(32)
        ]
        items.append(
            ProcessBatchItem(1, NTP.kafka("bench", p), [RecordBatch.build(recs, base_offset=0)])
        )
    req = ProcessBatchRequest(items)
    n_recs = 64 * 32
    stage_keys = (
        "t_extract_proj", "t_assemble", "t_rebuild",
        "t_frame_gather", "t_seal", "t_sharded_seal",
    )
    ticks_per_block = 4
    out = {}
    for mode, gather in (("gather", True), ("padded", False)):
        engine = TpuEngine(
            row_stride=1152,
            compress_threshold=10**9,
            force_mode="columnar_host",
            host_workers=0,
            gather_frame=gather,
        )
        codes = engine.enable_coprocessors([(1, spec.to_json(), ("bench",))])
        assert codes == [0]
        engine.process_batch(req)  # warmup
        best_stage = float("inf")
        best_rate = 0.0
        t_end = time.perf_counter() + secs
        while time.perf_counter() < t_end:
            engine.reset_stats()
            t0 = time.perf_counter()
            for _ in range(ticks_per_block):
                engine.process_batch(req)
            dt = time.perf_counter() - t0
            stats = engine.stats()
            block = sum(stats.get(k, 0.0) for k in stage_keys)
            best_stage = min(best_stage, block)
            best_rate = max(best_rate, ticks_per_block * n_recs / dt)
        out[f"harvest_{mode}_stage_s"] = round(best_stage, 6)
        out[f"harvest_{mode}_recs_per_s"] = round(best_rate, 1)
        engine.shutdown()
    gather_s = out["harvest_gather_stage_s"]
    padded_s = out["harvest_padded_stage_s"]
    out["harvest_speedup"] = round(padded_s / gather_s, 3) if gather_s > 0 else 0.0
    out["harvest_stage_cut_pct"] = (
        round((1.0 - gather_s / padded_s) * 100.0, 1) if padded_s > 0 else 0.0
    )
    return out


def bench_compaction_index(secs: float) -> dict:
    """Key-index build rate (compaction_idx_bench shape)."""
    from redpanda_tpu.storage.compaction import KeyLatestIndex

    keys = [b"key-%06d" % (i % 4096) for i in range(10_000)]

    def build():
        idx = KeyLatestIndex(max_keys_in_memory=1 << 20)
        for off, k in enumerate(keys):
            idx.put(k, off)

    return {"compaction_keyindex_keys_per_s": round(_rate(build, secs, len(keys)), 1)}


def bench_allocation(secs: float) -> dict:
    """Partition allocator throughput (allocation_bench shape)."""
    from redpanda_tpu.cluster.allocator import PartitionAllocator

    def alloc():
        pa = PartitionAllocator()
        for nid in range(5):
            pa.register_node(nid)
        for _ in range(16):
            pa.allocate(6, 3)

    return {"allocator_assignments_per_s": round(_rate(alloc, secs, 16 * 6), 1)}


def bench_tracer_overhead(secs: float) -> dict:
    """Disabled-tracer cost on a produce-hot-path-shaped op.

    Baseline = batch build+encode (the codec work every produce pays);
    traced = the same op under a DISABLED ``tracer.span(...)`` — the
    exact no-op the instrumented produce path executes when tracing is
    off. The always-on probe layer (a perf_counter pair + histogram
    record, the reference's probe.h cost) is measured and reported
    SEPARATELY (``probe_cost_ns``): it is a deliberate steady cost, not
    part of the disabled-tracer budget.

    The headline ``tracer_disabled_overhead_pct`` is DERIVED: (min-based
    per-call cost of the disabled span alone) / (min-based per-op cost of
    the payload). The span is strictly additive straight-line code, so
    the quotient IS its share of the hot path — and both measurements use
    timeit's min-of-many-blocks posture, which resolves nanoseconds
    reliably. The direct A/B wall-clock ratio is reported too
    (``tracer_ab_overhead_pct``) but is informational only: its
    shared-machine noise floor (~5-10%) sits far above the sub-1% signal,
    as an A/A control run demonstrates. The acceptance bar (<2%) is
    asserted by --assert-tracer-overhead, not here."""
    from redpanda_tpu.observability import tracer

    was_enabled = tracer.enabled
    tracer.configure(enabled=False)
    try:
        return _bench_tracer_overhead_disabled(secs)
    finally:
        # the process-wide tracer must come back even if the bench raises
        tracer.configure(enabled=was_enabled)


def _bench_tracer_overhead_disabled(secs: float) -> dict:
    from redpanda_tpu.models.record import Record, RecordBatch
    from redpanda_tpu.observability import probes, tracer

    recs = [Record(offset_delta=i, value=b"x" * 256) for i in range(32)]

    def op():
        RecordBatch.build(recs, base_offset=0).encode_internal()

    # scratch histogram, NOT a registered series: the probe-cost loop below
    # records thousands of synthetic samples, which must never leak into
    # the live registry a --metrics-snapshot run is diffing
    from redpanda_tpu.metrics import Histogram

    hist = Histogram("bench_scratch_us", "unregistered bench scratch")

    def traced_op():
        with tracer.span("bench.produce"):
            op()

    def timed_block(fn, k: int) -> float:
        t0 = time.perf_counter()
        for _ in range(k):
            fn()
        return time.perf_counter() - t0

    # warmup + block sizing: many short rounds inside the time budget
    op()
    traced_op()
    per_op = min(timed_block(op, 4) / 4 for _ in range(3))
    # ~3 ms blocks: short enough that plenty of rounds dodge load spikes
    # entirely, long enough to amortize the timer reads
    k = max(4, int(0.003 / per_op))
    rounds = max(24, int(secs * 2 / (2 * k * per_op)))
    best_base = float("inf")
    best_traced = float("inf")
    n_done = 0
    for r in range(rounds):
        if r % 2 == 0:
            tb, tt = timed_block(op, k), timed_block(traced_op, k)
        else:
            tt, tb = timed_block(traced_op, k), timed_block(op, k)
        best_base = min(best_base, tb / k)
        best_traced = min(best_traced, tt / k)
        n_done += 2 * k
    # per-call cost of the disabled span alone, then of one probe
    # histogram observation — same min-of-blocks discipline
    span_ns = float("inf")
    probe_ns = float("inf")
    for _ in range(10):
        n_raw = 2000
        t0 = time.perf_counter()
        for _ in range(n_raw):
            with tracer.span("bench.noop"):
                pass
        span_ns = min(span_ns, (time.perf_counter() - t0) / n_raw * 1e9)
        t0 = time.perf_counter()
        for _ in range(n_raw):
            probes.observe_us(hist, t0)
        probe_ns = min(probe_ns, (time.perf_counter() - t0) / n_raw * 1e9)
    ab_pct = (best_traced / best_base - 1.0) * 100.0 if best_base else 0.0
    overhead_pct = span_ns / (best_base * 1e9) * 100.0 if best_base else 0.0
    return {
        "tracer_block_ops": n_done,
        "tracer_span_cost_ns": round(span_ns, 1),
        "probe_cost_ns": round(probe_ns, 1),
        "tracer_op_cost_ns": round(best_base * 1e9, 1),
        "tracer_ab_overhead_pct": round(max(ab_pct, 0.0), 2),
        "tracer_disabled_overhead_pct": round(overhead_pct, 2),
    }


def bench_slo_eval_overhead(secs: float) -> dict:
    """Cost of the always-on SLO layer on a produce-shaped op.

    What a produce pays for the SLO harness is ONE exemplar-aware
    histogram record (probes.record_us: the raw bucket record plus a
    threshold lookup + compare — the breach slow path never runs in
    steady state). Same derived min-of-blocks discipline as the tracer
    and breaker benches: wall-clock A/B cannot resolve sub-1% on a
    shared box, but the hook is strictly additive straight-line code, so
    (per-call hook delta) / (per-op cost) IS its share of the hot path.
    ``slo_evaluate_ms`` — one full spec evaluation, the operator-triggered
    GET /v1/slo cost — is reported informationally; it is never on a
    request path."""
    from redpanda_tpu.metrics import Histogram
    from redpanda_tpu.models.record import Record, RecordBatch
    from redpanda_tpu.observability import probes, tracer
    from redpanda_tpu.observability.slo import DEFAULT_SPEC, SloEngine

    was_enabled = tracer.enabled
    tracer.configure(enabled=False)
    recs = [Record(offset_delta=i, value=b"x" * 256) for i in range(32)]

    def op():
        RecordBatch.build(recs, base_offset=0).encode_internal()

    # scratch histograms, NOT registered series: thousands of synthetic
    # samples must never leak into the live registry
    raw = Histogram("bench_slo_raw_us", "unregistered bench scratch")
    hooked = Histogram("bench_slo_hooked_us", "unregistered bench scratch")
    # armed with a threshold the samples never cross: the steady-state
    # shape (the breach path is per-incident, not per-op)
    probes.arm_exemplar_threshold(hooked, 1e12)
    try:

        def timed_block(fn, k: int) -> float:
            t0 = time.perf_counter()
            for _ in range(k):
                fn()
            return time.perf_counter() - t0

        op()
        per_op = min(timed_block(op, 4) / 4 for _ in range(3))
        k = max(4, int(0.003 / per_op))
        rounds = max(16, int(secs / (k * per_op)))
        best_op = min(timed_block(op, k) / k for _ in range(rounds))

        record_ns = float("inf")
        hooked_ns = float("inf")
        n_raw = 2000
        for _ in range(10):
            t0 = time.perf_counter()
            for _ in range(n_raw):
                raw.record(500)
            record_ns = min(record_ns, (time.perf_counter() - t0) / n_raw * 1e9)
            t0 = time.perf_counter()
            for _ in range(n_raw):
                probes.record_us(hooked, 500)
            hooked_ns = min(hooked_ns, (time.perf_counter() - t0) / n_raw * 1e9)
        hook_ns = max(0.0, hooked_ns - record_ns)
        pct = hook_ns / (best_op * 1e9) * 100.0 if best_op else 0.0

        # informational: one operator-triggered evaluation of the default
        # spec over the live registry
        # arm=False: a read-only judgment — the bench must not overwrite
        # exemplar thresholds an in-process caller armed on the LIVE
        # registry with DEFAULT_SPEC's lenient ones
        eng = SloEngine()
        eng.evaluate(DEFAULT_SPEC, arm=False)  # warm lazy imports
        t0 = time.perf_counter()
        eng.evaluate(DEFAULT_SPEC, arm=False)
        eval_ms = (time.perf_counter() - t0) * 1e3
        return {
            "slo_record_raw_ns": round(record_ns, 1),
            "slo_record_hooked_ns": round(hooked_ns, 1),
            "slo_hook_cost_ns": round(hook_ns, 1),
            "slo_op_cost_ns": round(best_op * 1e9, 1),
            "slo_evaluate_ms": round(eval_ms, 3),
            "slo_eval_overhead_pct": round(pct, 3),
        }
    finally:
        # surgical: an in-process caller's armed objectives must survive
        probes.disarm_exemplar_threshold(hooked)
        tracer.configure(enabled=was_enabled)


def bench_breaker_overhead(secs: float) -> dict:
    """Cost of the fault machinery on the UNFAULTED coproc launch path.

    A healthy launch pays, per device leg: one closed-breaker
    ``allow_device()`` (a lock + two compares), one disabled honey-badger
    ``inject()`` (an attribute check), one ``record_success()``, and the
    ``retry_call`` envelope around the leg. The headline
    ``breaker_overhead_pct`` is DERIVED the same way as the tracer bench
    (wall-clock A/B cannot resolve sub-1% on a shared box): min-of-blocks
    per-call cost of the checks alone, times a deliberately conservative
    per-launch check count, over the min-of-blocks cost of a real
    columnar launch. The checks are strictly additive straight-line code,
    so the quotient IS their share of the hot path.

    The abandonable-fetch envelope (``fetch_envelope_us``) is reported
    separately and informationally: it prices the thread handoff a
    DEADLINE-BEARING device leg pays, which is per-launch, bounded, and a
    deliberate trade for wedge immunity — not part of the closed-breaker
    + disabled-badger budget the <1% gate covers."""
    import json as _json

    from redpanda_tpu.coproc import TpuEngine, ProcessBatchRequest, faults
    from redpanda_tpu.coproc.engine import ProcessBatchItem
    from redpanda_tpu.finjector import honey_badger
    from redpanda_tpu.models import NTP, Record, RecordBatch
    from redpanda_tpu.ops.exprs import field
    from redpanda_tpu.ops.transforms import Int, Str, map_project, where

    # disable() also CLEARS every armed probe, so snapshot the armed map
    # and re-arm on the way out — an in-process caller mid-fault-campaign
    # must get its badger back exactly as it was
    was_enabled = honey_badger.enabled
    was_armed = honey_badger.armed()
    honey_badger.disable()
    try:
        # a real launch as the denominator: columnar host predicate over
        # 512 records — device-free, so the op is deterministic on any box
        engine = TpuEngine(
            row_stride=256, compress_threshold=10**9,
            force_mode="columnar_host", host_workers=0,
        )
        spec = where(field("level") == "error") | map_project(
            Int("code"), Str("msg", 16)
        )
        engine.enable_coprocessors([(1, spec.to_json(), ("orders",))])
        recs = [
            Record(
                offset_delta=i, timestamp_delta=i,
                value=_json.dumps(
                    {"level": ["error", "info"][i % 2], "code": i,
                     "msg": f"m{i}"},
                    separators=(",", ":"),
                ).encode(),
            )
            for i in range(512)
        ]
        batch = RecordBatch.build(recs, base_offset=0, first_timestamp=1000)
        req = ProcessBatchRequest(
            [ProcessBatchItem(1, NTP.kafka("orders", 0), [batch])]
        )

        def op():
            engine.process_batch(req)

        def timed_block(fn, k: int) -> float:
            t0 = time.perf_counter()
            for _ in range(k):
                fn()
            return time.perf_counter() - t0

        op()  # warmup (plan compile, caches)
        per_op = min(timed_block(op, 2) / 2 for _ in range(3))
        k = max(2, int(0.01 / per_op))
        rounds = max(12, int(secs / (k * per_op)))
        best_op = min(timed_block(op, k) / k for _ in range(rounds))

        breaker = engine._breaker
        assert breaker.state == faults.STATE_CLOSED
        check_ns = float("inf")
        inject_ns = float("inf")
        success_ns = float("inf")
        n_raw = 5000
        for _ in range(10):
            t0 = time.perf_counter()
            for _ in range(n_raw):
                breaker.allow_device()
            check_ns = min(check_ns, (time.perf_counter() - t0) / n_raw * 1e9)
            t0 = time.perf_counter()
            for _ in range(n_raw):
                faults.inject(faults.DEVICE_DISPATCH)
            inject_ns = min(inject_ns, (time.perf_counter() - t0) / n_raw * 1e9)
            t0 = time.perf_counter()
            for _ in range(n_raw):
                breaker.record_success()
            success_ns = min(
                success_ns, (time.perf_counter() - t0) / n_raw * 1e9
            )
        # informational: the deadline envelope's thread handoff per leg
        envelope_s = float("inf")
        for _ in range(30):
            t0 = time.perf_counter()
            faults.fetch_with_deadline(lambda: None, 30.0)
            envelope_s = min(envelope_s, time.perf_counter() - t0)
        # conservative per-launch budget: dispatch + mask fetch + harvest
        # each pay one inject; one allow_device; two breaker verdicts
        checks_per_launch = 3 * inject_ns + check_ns + 2 * success_ns
        pct = checks_per_launch / (best_op * 1e9) * 100.0 if best_op else 0.0
        return {
            "breaker_check_ns": round(check_ns, 1),
            "badger_disabled_check_ns": round(inject_ns, 1),
            "breaker_record_success_ns": round(success_ns, 1),
            "breaker_launch_cost_us": round(best_op * 1e6, 1),
            "fetch_envelope_us": round(envelope_s * 1e6, 1),
            "breaker_overhead_pct": round(pct, 3),
        }
    finally:
        if was_enabled:
            honey_badger.enable()
            arm = {
                "exception": honey_badger.set_exception,
                "delay": honey_badger.set_delay,
                "wedge": honey_badger.set_wedge,
                "terminate": honey_badger.set_termination,
            }
            for module, probes_armed in was_armed.items():
                for probe, effect in probes_armed.items():
                    arm[effect](module, probe)


def bench_admission_overhead(secs: float) -> dict:
    """Cost of the budget-plane admission gate on the UNCONTENDED produce
    path (resource_mgmt): what every admitted produce pays is exactly ONE
    ``try_admit`` (an account lock + two compares + a counter) and ONE
    ``release`` — the shed path is the degraded case and allowed to cost
    more. Derived like breaker_overhead: min-of-blocks per-pair cost over
    the min-of-blocks cost of a REAL acked produce op (a full client →
    broker → storage round trip on an in-process single-node broker),
    because wall-clock A/B cannot resolve sub-1% on a shared box.
    ``--assert-admission-overhead 1`` gates the quotient."""
    import asyncio

    from redpanda_tpu.resource_mgmt import AdmissionController, BudgetPlane

    plane = BudgetPlane(256 << 20)
    ctrl = AdmissionController(plane.account("kafka_produce"), "bench_adm")
    n_raw = 20000
    pair_ns = float("inf")
    for _ in range(10):
        t0 = time.perf_counter()
        for _ in range(n_raw):
            reserved, _r = ctrl.try_admit(4096)
            ctrl.release(reserved)
        pair_ns = min(pair_ns, (time.perf_counter() - t0) / n_raw * 1e9)

    async def produce_op_us() -> float:
        import tempfile

        from redpanda_tpu.kafka.client import KafkaClient
        from redpanda_tpu.kafka.server.broker import Broker, BrokerConfig
        from redpanda_tpu.kafka.server.protocol import KafkaServer
        from redpanda_tpu.storage.log_manager import StorageApi

        with tempfile.TemporaryDirectory(prefix="mb-adm-") as d:
            storage = await StorageApi(d).start()
            broker = Broker(BrokerConfig(data_dir=d), storage)
            server = await KafkaServer(broker, "127.0.0.1", 0).start()
            broker.config.advertised_port = server.port
            client = await KafkaClient(
                [("127.0.0.1", server.port)]
            ).connect()
            try:
                payload = [b"x" * 512] * 4
                for _ in range(8):  # warmup: topic create, first appends
                    await client.produce("bench", 0, payload, acks=-1)
                best = float("inf")
                k = 32
                rounds = max(6, int(secs / 0.05))
                for _ in range(rounds):
                    t0 = time.perf_counter()
                    for _ in range(k):
                        await client.produce("bench", 0, payload, acks=-1)
                    best = min(best, (time.perf_counter() - t0) / k)
                return best * 1e6
            finally:
                await client.close()
                await server.stop()
                await storage.stop()

    op_us = asyncio.run(produce_op_us())
    pct = pair_ns / (op_us * 1e3) * 100.0 if op_us else 0.0
    return {
        "admission_pair_ns": round(pair_ns, 1),
        "admission_produce_op_us": round(op_us, 1),
        "admission_overhead_pct": round(pct, 4),
    }


def bench_governor_overhead(secs: float) -> dict:
    """Cost of the governor's decision-plane hooks on the UNFAULTED coproc
    launch path.

    What a healthy launch pays the governor, per launch: two
    ``record_mode`` calls on their CLOSED path (harvest-path + seal
    verdicts unchanged -> one lock + one compare each) and a few
    ``policy_for`` lookups (cached adaptive deadline -> two dict lookups +
    an int compare). The journal append itself runs only when a verdict
    CHANGES — per-incident, not per-launch — but its cost is priced too
    (``governor_journal_append_ns``) on a throwaway DecisionJournal so the
    live process journal and the decision counters stay untouched.

    Same derived min-of-blocks discipline as the tracer/breaker/slo
    benches: wall-clock A/B cannot resolve sub-1% on a shared box, but the
    hooks are strictly additive straight-line code, so (per-call hook
    cost x conservative per-launch count) / (per-launch cost) IS their
    share of the hot path. --assert-governor-overhead gates it."""
    import json as _json

    from redpanda_tpu.coproc import TpuEngine, ProcessBatchRequest, faults
    from redpanda_tpu.coproc import governor as gov
    from redpanda_tpu.coproc.engine import ProcessBatchItem
    from redpanda_tpu.models import NTP, Record, RecordBatch
    from redpanda_tpu.ops.exprs import field
    from redpanda_tpu.ops.transforms import Int, Str, map_project, where

    # the denominator: a real columnar host launch over 512 records (the
    # same deterministic device-free shape as the breaker bench)
    engine = TpuEngine(
        row_stride=256, compress_threshold=10**9,
        force_mode="columnar_host", host_workers=0,
    )
    spec = where(field("level") == "error") | map_project(
        Int("code"), Str("msg", 16)
    )
    engine.enable_coprocessors([(1, spec.to_json(), ("orders",))])
    recs = [
        Record(
            offset_delta=i, timestamp_delta=i,
            value=_json.dumps(
                {"level": ["error", "info"][i % 2], "code": i, "msg": f"m{i}"},
                separators=(",", ":"),
            ).encode(),
        )
        for i in range(512)
    ]
    batch = RecordBatch.build(recs, base_offset=0, first_timestamp=1000)
    req = ProcessBatchRequest(
        [ProcessBatchItem(1, NTP.kafka("orders", 0), [batch])]
    )

    def op():
        engine.process_batch(req)

    def timed_block(fn, k: int) -> float:
        t0 = time.perf_counter()
        for _ in range(k):
            fn()
        return time.perf_counter() - t0

    op()  # warmup (plan compile, caches, first record_mode entries)
    per_op = min(timed_block(op, 2) / 2 for _ in range(3))
    k = max(2, int(0.01 / per_op))
    rounds = max(12, int(secs / (k * per_op)))
    best_op = min(timed_block(op, k) / k for _ in range(rounds))

    # per-call hook costs on PRIVATE instances: the scratch governor gets
    # its OWN journal (journal_override: its priming entries and any
    # deadline derivation must not land in the live process journal or
    # move coproc_governor_decisions_total), its own histogram source
    # (the live stage histograms must not drive a scratch DEADLINE entry),
    # and no gauges (register_gauges=False: it must not steal the live
    # engine's labeled series)
    from redpanda_tpu.utils.hdr import HdrHist

    journal = gov.DecisionJournal(capacity=256)
    scratch_hists: dict = {}
    scratch = gov.Governor(
        fault_policy=faults.FaultPolicy(),
        register_gauges=False,
        journal_override=gov.DecisionJournal(capacity=256),
        stage_hist=lambda s: scratch_hists.setdefault(s, HdrHist()),
    )
    scratch.record_mode("harvest_path", "gather", "bench prime")
    scratch.policy_for(faults.DEVICE_DISPATCH)
    append_ns = mode_ns = policy_ns = float("inf")
    n_raw = 5000
    for _ in range(10):
        t0 = time.perf_counter()
        for _ in range(n_raw):
            journal.append(
                "harvest_path", "gather", "bench append", {"rows": 512}
            )
        append_ns = min(append_ns, (time.perf_counter() - t0) / n_raw * 1e9)
        t0 = time.perf_counter()
        for _ in range(n_raw):
            scratch.record_mode("harvest_path", "gather", "bench prime")
        mode_ns = min(mode_ns, (time.perf_counter() - t0) / n_raw * 1e9)
        t0 = time.perf_counter()
        for _ in range(n_raw):
            scratch.policy_for(faults.DEVICE_DISPATCH)
        policy_ns = min(policy_ns, (time.perf_counter() - t0) / n_raw * 1e9)
    # conservative per-launch budget: harvest-path + seal record_mode on
    # the closed path, plus a policy_for per device leg (dispatch, mask
    # fetch, harvest)
    hooks_per_launch = 2 * mode_ns + 3 * policy_ns
    pct = hooks_per_launch / (best_op * 1e9) * 100.0 if best_op else 0.0
    engine.shutdown()
    return {
        "governor_journal_append_ns": round(append_ns, 1),
        "governor_record_mode_closed_ns": round(mode_ns, 1),
        "governor_policy_for_ns": round(policy_ns, 1),
        "governor_launch_cost_us": round(best_op * 1e6, 1),
        "governor_overhead_pct": round(pct, 3),
    }


def bench_pulse_overhead(secs: float) -> dict:
    """Cost of the pandapulse flight recorder on a real columnar launch.

    The recorder rides the tracer's commit path: with pulse OFF the
    marginal cost is one attribute check inside ``Tracer._commit``; with
    pulse ON it is one bounded-deque append (+ a counter lock) per
    committed span. The tracer itself is priced and gated separately
    (``tracer_overhead`` / ``trace_propagation_overhead``) — this bench
    answers the ISSUE 14 acceptance question: recorder-on vs recorder-off
    on the SAME traced launch.

    Derived min-of-blocks discipline (wall A/B can't resolve sub-1% on a
    shared box): (per-span sink cost x spans-per-launch, both measured) /
    (per-launch cost). ``pulse_overhead_with_tracer_pct`` reports the
    tracer-inclusive number for context — what a fully dark launch pays
    to become a timeline. Also pins the profiler-off posture: profile_hz=0
    must run NO sampler thread."""
    import json as _json
    import threading as _threading

    from redpanda_tpu.coproc import TpuEngine, ProcessBatchRequest
    from redpanda_tpu.coproc.engine import ProcessBatchItem
    from redpanda_tpu.models import NTP, Record, RecordBatch
    from redpanda_tpu.observability.pulse import FlightRecorder
    from redpanda_tpu.observability.trace import Tracer, tracer
    from redpanda_tpu.ops.exprs import field
    from redpanda_tpu.ops.transforms import Int, Str, map_project, where

    engine = TpuEngine(
        row_stride=256, compress_threshold=10**9,
        force_mode="columnar_host", host_workers=0,
    )
    spec = where(field("level") == "error") | map_project(
        Int("code"), Str("msg", 16)
    )
    engine.enable_coprocessors([(1, spec.to_json(), ("orders",))])
    recs = [
        Record(
            offset_delta=i, timestamp_delta=i,
            value=_json.dumps(
                {"level": ["error", "info"][i % 2], "code": i, "msg": f"m{i}"},
                separators=(",", ":"),
            ).encode(),
        )
        for i in range(512)
    ]
    batch = RecordBatch.build(recs, base_offset=0, first_timestamp=1000)
    req = ProcessBatchRequest(
        [ProcessBatchItem(1, NTP.kafka("orders", 0), [batch])]
    )

    def op():
        engine.process_batch(req)

    def timed_block(fn, k: int) -> float:
        t0 = time.perf_counter()
        for _ in range(k):
            fn()
        return time.perf_counter() - t0

    op()  # warmup
    per_op = min(timed_block(op, 2) / 2 for _ in range(3))
    k = max(2, int(0.01 / per_op))
    rounds = max(12, int(secs / (k * per_op)))
    best_op = min(timed_block(op, k) / k for _ in range(rounds))

    # spans one traced launch commits (recorder installed, fresh ring):
    # the multiplier in the derived overhead
    was_enabled = tracer.enabled
    was_sink = tracer._sink
    probe_rec = FlightRecorder()
    tracer.configure(enabled=True)
    tracer.set_sink(probe_rec.record)
    try:
        req.trace_id = tracer.new_trace_id()
        op()
    finally:
        tracer.set_sink(was_sink)
        tracer.configure(enabled=was_enabled)
        req.trace_id = None
    spans_per_launch = len(probe_rec.spans())

    # per-call costs on PRIVATE instances (the live tracer/recorder rings
    # must not absorb bench spam): the sink append alone (the recorder-on
    # delta) and the full enabled commit+sink (tracer-inclusive context)
    scratch_rec = FlightRecorder(capacity=4096)
    scratch_tr = Tracer(enabled=True, capacity=2048)
    span_dict = {
        "trace_id": 1, "name": "coproc.stage.bench", "start_us": 0,
        "dur_us": 5, "thread": "bench", "span_id": 1,
    }
    sink_ns = commit_ns = commit_dark_ns = float("inf")
    n_raw = 5000
    for _ in range(10):
        t0 = time.perf_counter()
        for _ in range(n_raw):
            scratch_rec.record(span_dict)
        sink_ns = min(sink_ns, (time.perf_counter() - t0) / n_raw * 1e9)
        scratch_tr._sink = scratch_rec.record
        t0 = time.perf_counter()
        for _ in range(n_raw):
            scratch_tr.record("coproc.stage.bench", 5.0, 1)
        commit_ns = min(commit_ns, (time.perf_counter() - t0) / n_raw * 1e9)
        scratch_tr._sink = None
        t0 = time.perf_counter()
        for _ in range(n_raw):
            scratch_tr.record("coproc.stage.bench", 5.0, 1)
        commit_dark_ns = min(
            commit_dark_ns, (time.perf_counter() - t0) / n_raw * 1e9
        )
    engine.shutdown()
    launch_ns = best_op * 1e9
    pct = spans_per_launch * sink_ns / launch_ns * 100.0 if launch_ns else 0.0
    with_tracer_pct = (
        spans_per_launch * commit_ns / launch_ns * 100.0 if launch_ns else 0.0
    )
    profiler_threads = sum(
        1 for t in _threading.enumerate()
        if t.name == "rptpu-pulse-profiler"
    )
    out = {
        "pulse_sink_append_ns": round(sink_ns, 1),
        "pulse_span_commit_sink_ns": round(commit_ns, 1),
        "pulse_span_commit_dark_ns": round(commit_dark_ns, 1),
        "pulse_spans_per_launch": spans_per_launch,
        "pulse_launch_cost_us": round(best_op * 1e6, 1),
        "pulse_overhead_pct": round(pct, 3),
        "pulse_overhead_with_tracer_pct": round(with_tracer_pct, 3),
    }
    if profiler_threads:
        # profiler-off steady state: NO sampler thread may exist. The key
        # only appears on violation (the assert flag reads .get(..., 0),
        # and the all-benches positivity smoke would trip on a good 0).
        out["pulse_profiler_off_threads"] = profiler_threads
    return out


def bench_history_overhead(secs: float) -> dict:
    """Cost of the pandatrend metrics-history recorder vs a real launch.

    The recorder never rides the launch path: it is one background thread
    calling ``sample_once()`` every ``history_interval_s``. Its steady-
    state tax on a running broker is therefore a duty cycle — per-sample
    cost over the sampling interval — and that is what the gate judges:
    during a launch of any length the recorder is expected to steal
    ``sample_ns / interval_ns`` of it. The per-sample cost is dominated
    by ``_cumulative()`` (one full registry scan + ``_hist_window`` per
    histogram), which is paid whether or not any series moved, so a quiet
    registry prices the scan honestly; the registry is first warmed by a
    real columnar launch so the scan walks the series a live broker has.

    Also pins the ISSUE 17 off posture: ``interval_s=0`` must run NO
    recorder thread (the violation-only ``history_recorder_off_threads``
    key, same contract as ``pulse_profiler_off_threads``)."""
    import json as _json
    import threading as _threading

    from redpanda_tpu.coproc import TpuEngine, ProcessBatchRequest
    from redpanda_tpu.coproc.engine import ProcessBatchItem
    from redpanda_tpu.models import NTP, Record, RecordBatch
    from redpanda_tpu.observability.history import (
        DEFAULT_INTERVAL_S, HistoryRecorder,
    )
    from redpanda_tpu.ops.exprs import field
    from redpanda_tpu.ops.transforms import Int, Str, map_project, where

    engine = TpuEngine(
        row_stride=256, compress_threshold=10**9,
        force_mode="columnar_host", host_workers=0,
    )
    spec = where(field("level") == "error") | map_project(
        Int("code"), Str("msg", 16)
    )
    engine.enable_coprocessors([(1, spec.to_json(), ("orders",))])
    recs = [
        Record(
            offset_delta=i, timestamp_delta=i,
            value=_json.dumps(
                {"level": ["error", "info"][i % 2], "code": i, "msg": f"m{i}"},
                separators=(",", ":"),
            ).encode(),
        )
        for i in range(512)
    ]
    batch = RecordBatch.build(recs, base_offset=0, first_timestamp=1000)
    req = ProcessBatchRequest(
        [ProcessBatchItem(1, NTP.kafka("orders", 0), [batch])]
    )

    def op():
        engine.process_batch(req)

    def timed_block(fn, k: int) -> float:
        t0 = time.perf_counter()
        for _ in range(k):
            fn()
        return time.perf_counter() - t0

    op()  # warmup (and: populates the live registry the recorder scans)
    per_op = min(timed_block(op, 2) / 2 for _ in range(3))
    k = max(2, int(0.01 / per_op))
    rounds = max(12, int(secs / (k * per_op)))
    best_op = min(timed_block(op, k) / k for _ in range(rounds))
    engine.shutdown()

    # per-sample cost on a PRIVATE recorder against the PROCESS registry
    # (reads only — sample_once never mutates the registry; a private ring
    # keeps bench windows out of any live /v1/history)
    rec = HistoryRecorder()
    rec.configure(windows=64)
    rec.sample_once()  # anchors the delta baseline; first call is free
    sample_ns = float("inf")
    n_raw = 200
    for _ in range(8):
        t0 = time.perf_counter()
        for _ in range(n_raw):
            rec.sample_once()
        sample_ns = min(sample_ns, (time.perf_counter() - t0) / n_raw * 1e9)
    series = len(rec.windows()[-1]["gauges"]) if rec.windows() else 0

    launch_ns = best_op * 1e9
    interval_ns = DEFAULT_INTERVAL_S * 1e9
    pct = sample_ns / interval_ns * 100.0
    # interval=0 posture: configure() with 0 must leave NO recorder thread
    rec.configure(interval_s=0.0)
    off_threads = sum(
        1 for t in _threading.enumerate()
        if t.name == "rptpu-history-recorder"
    )
    out = {
        "history_sample_ns": round(sample_ns, 1),
        "history_sample_cost_us": round(sample_ns / 1e3, 2),
        "history_gauge_series_scanned": series,
        "history_launch_cost_us": round(best_op * 1e6, 1),
        "history_sample_vs_launch_pct": round(
            sample_ns / launch_ns * 100.0, 3
        ) if launch_ns else 0.0,
        "history_overhead_pct": round(pct, 4),
    }
    if off_threads:
        # violation-only key, same contract as pulse_profiler_off_threads
        out["history_recorder_off_threads"] = off_threads
    return out


def bench_trace_propagation_overhead(secs: float) -> dict:
    """Cost of pandascope trace propagation on an rpc round trip.

    What a SAMPLED request pays beyond the pre-propagation wire: encoding
    the 17-byte TraceContext on the sender, decoding it on the receiver,
    and the receiver's JOINed rpc.handle span. Same derived min-of-blocks
    discipline as tracer_overhead: each piece is strictly additive
    straight-line code, so (per-call cost sum) / (per-RTT cost of a real
    loopback rpc) IS its share — wall-clock A/B on a shared box cannot
    resolve sub-1%.

    The denominator round trip carries a REPLICATE-REPRESENTATIVE payload
    (128 KiB, a quarter of the default 512 KiB recovery chunk): the only
    rpcs that are ever sampled are the coalesced-produce append_entries
    sends that join the submitter's trace — data-carrying by construction
    — while empty heartbeats and chatter never carry context and pay
    zero. Pricing the ctx against an empty echo would gate a cost against
    a request shape that never bears it; the one-process loopback echo
    already UNDERSTATES a real inter-broker round trip besides (no
    process switch, no NIC — the SLO harness measures real cross-process
    rpc means in the milliseconds). The acceptance bar (<1%) is asserted
    by --assert-propagation-overhead, which also FAILS if a disabled
    tracer adds even one byte to the wire
    (``propagation_disabled_extra_bytes`` must be 0 — the header is
    feature-flagged on trace_enabled)."""
    from redpanda_tpu.observability.trace import Tracer
    from redpanda_tpu.rpc import wire

    # real loopback RTT (tracer state untouched: whatever the process has)
    rtt_s = _rpc_echo_rtt_s(min(secs, 2.0), payload_bytes=128 * 1024)

    ctx = wire.TraceContext(0x1234_5678_9ABC, 0x42, True)
    blob = ctx.encode()
    encode_ns = float("inf")
    decode_ns = float("inf")
    join_ns = float("inf")
    scratch = Tracer(enabled=True, capacity=64)
    for _ in range(10):
        n = 2000
        t0 = time.perf_counter()
        for _ in range(n):
            ctx.encode()
        encode_ns = min(encode_ns, (time.perf_counter() - t0) / n * 1e9)
        t0 = time.perf_counter()
        for _ in range(n):
            wire.TraceContext.decode(blob)
        decode_ns = min(decode_ns, (time.perf_counter() - t0) / n * 1e9)
        t0 = time.perf_counter()
        for _ in range(n):
            with scratch.span("bench.join", trace_id=7):
                pass
        join_ns = min(join_ns, (time.perf_counter() - t0) / n * 1e9)
    per_rpc_ns = encode_ns + decode_ns + join_ns
    rtt_ns = rtt_s * 1e9
    pct = per_rpc_ns / rtt_ns * 100.0 if rtt_ns else 0.0
    # zero-wire-bytes invariant: no ctx -> byte-identical version-0 frame
    payload = b"x" * 128
    extra = len(wire.frame(payload, 1, 1)) - (wire.HEADER_SIZE + len(payload))
    return {
        "propagation_ctx_encode_ns": round(encode_ns, 1),
        "propagation_ctx_decode_ns": round(decode_ns, 1),
        "propagation_join_span_ns": round(join_ns, 1),
        "propagation_rpc_rtt_us": round(rtt_s * 1e6, 2),
        "propagation_overhead_pct": round(pct, 3),
        "propagation_disabled_extra_bytes": extra,
        "propagation_ctx_wire_bytes": wire.TRACE_CTX_SIZE,
    }


def _rpc_echo_rtt_s(secs: float, payload_bytes: int = 0) -> float:
    """Per-round-trip seconds of a real loopback rpc echo; the request
    carries ``payload_bytes`` of text (0 = the minimal chatter shape)."""
    from redpanda_tpu import rpc
    from redpanda_tpu.rpc.transport import Transport

    async def run() -> float:
        from redpanda_tpu.rpc import serde

        msg = serde.S(("text", serde.STRING))
        svc = rpc.ServiceDef(
            "bench", "echo_prop", [rpc.MethodDef("echo", msg, msg)]
        )

        class Impl:
            async def echo(self, req):
                return {"text": req["text"]}

        server = rpc.Server()
        proto = rpc.SimpleProtocol()
        proto.register_service(rpc.ServiceHandler(svc, Impl()))
        server.set_protocol(proto)
        await server.start()
        t = Transport("127.0.0.1", server.port)
        await t.connect()
        client = rpc.Client(svc, t)
        body = "r" * max(1, payload_bytes)
        await client.echo({"text": body})
        n = 0
        t0 = time.perf_counter()
        while time.perf_counter() - t0 < secs:
            await client.echo({"text": body})
            n += 1
        dt = time.perf_counter() - t0
        await t.close()
        await server.stop()
        return dt / max(1, n)

    return asyncio.run(run())


def bench_rpc_echo(secs: float) -> dict:
    """Loopback RPC round trips (rpc_bench shape) over the real stack."""
    from redpanda_tpu import rpc
    from redpanda_tpu.rpc.transport import Transport

    async def run() -> float:
        from redpanda_tpu.rpc import serde

        msg = serde.S(("text", serde.STRING))
        svc = rpc.ServiceDef("bench", "echo", [rpc.MethodDef("echo", msg, msg)])

        class Impl:
            async def echo(self, req):
                return {"text": req["text"]}

        server = rpc.Server()
        proto = rpc.SimpleProtocol()
        proto.register_service(rpc.ServiceHandler(svc, Impl()))
        server.set_protocol(proto)
        await server.start()
        t = Transport("127.0.0.1", server.port)
        await t.connect()
        client = rpc.Client(svc, t)
        await client.echo({"text": "warm"})
        n = 0
        t0 = time.perf_counter()
        while time.perf_counter() - t0 < secs:
            await client.echo({"text": "ping"})
            n += 1
        dt = time.perf_counter() - t0
        await t.close()
        await server.stop()
        return n / dt

    return {"rpc_echo_rtt_per_s": round(asyncio.run(run()), 1)}


BENCHES = {
    "crc32c": bench_crc32c,
    "xxhash": bench_xxhash,
    "zstd_stream": bench_zstd_stream,
    "batch_codec": bench_batch_codec,
    "explode_find": bench_explode_find,
    "host_pool_scaling": bench_host_pool_scaling,
    "mesh_scaling": bench_mesh_scaling,
    "harvest_path": bench_harvest_path,
    "compaction_index": bench_compaction_index,
    "allocation": bench_allocation,
    "rpc_echo": bench_rpc_echo,
    "tracer_overhead": bench_tracer_overhead,
    "trace_propagation_overhead": bench_trace_propagation_overhead,
    "breaker_overhead": bench_breaker_overhead,
    "slo_eval_overhead": bench_slo_eval_overhead,
    "governor_overhead": bench_governor_overhead,
    "admission_overhead": bench_admission_overhead,
    "pulse_overhead": bench_pulse_overhead,
    "history_overhead": bench_history_overhead,
}


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument(
        "benches", nargs="*", metavar="BENCH",
        help="bench names to run (default: all; same set as --only)",
    )
    p.add_argument("--secs", type=float, default=0.5, help="time budget per bench")
    p.add_argument("--only", help="comma-separated bench names")
    p.add_argument(
        "--metrics-snapshot",
        help="write {before, after} registry snapshots to this JSON file, so "
        "a bench run can be diffed against the probe counters it moved",
    )
    p.add_argument(
        "--assert-tracer-overhead",
        type=float,
        metavar="PCT",
        help="fail (exit 1) if the disabled-tracer overhead exceeds PCT "
        "percent; implies the tracer_overhead bench",
    )
    p.add_argument(
        "--assert-propagation-overhead",
        type=float,
        metavar="PCT",
        help="fail (exit 1) if the trace-context encode/decode + join-span "
        "share of an rpc round trip exceeds PCT percent, OR if a disabled "
        "tracer adds ANY bytes to the wire; implies the "
        "trace_propagation_overhead bench",
    )
    p.add_argument(
        "--assert-pool-speedup",
        type=float,
        metavar="RATIO",
        help="fail (exit 1) if the host-stage pool's best speedup over "
        "workers=1 falls below RATIO (e.g. 1.2); implies host_pool_scaling",
    )
    p.add_argument(
        "--assert-breaker-overhead",
        type=float,
        metavar="PCT",
        help="fail (exit 1) if the closed-breaker + disabled-honey-badger "
        "share of the launch path exceeds PCT percent; implies the "
        "breaker_overhead bench",
    )
    p.add_argument(
        "--assert-slo-overhead",
        type=float,
        metavar="PCT",
        help="fail (exit 1) if the always-on SLO/exemplar hook's share of "
        "a produce-shaped op exceeds PCT percent; implies the "
        "slo_eval_overhead bench",
    )
    p.add_argument(
        "--assert-governor-overhead",
        type=float,
        metavar="PCT",
        help="fail (exit 1) if the governor's closed-path decision hooks' "
        "share of a columnar launch exceeds PCT percent; implies the "
        "governor_overhead bench",
    )
    p.add_argument(
        "--assert-admission-overhead",
        type=float,
        metavar="PCT",
        help="fail (exit 1) if the uncontended budget-admission pair "
        "(try_admit + release) exceeds PCT percent of a real acked "
        "produce op; implies the admission_overhead bench",
    )
    p.add_argument(
        "--assert-pulse-overhead",
        type=float,
        metavar="PCT",
        help="fail (exit 1) if the pandapulse flight recorder's derived "
        "share of a real columnar launch exceeds PCT (e.g. 1 = 1%%), or "
        "if a profiler thread exists with profile_hz=0; implies the "
        "pulse_overhead bench",
    )
    p.add_argument(
        "--assert-history-overhead",
        type=float,
        metavar="PCT",
        help="fail (exit 1) if the pandatrend history recorder's steady-"
        "state duty cycle (per-sample cost over history_interval_s) "
        "exceeds PCT (e.g. 1 = 1%%), or if a recorder thread exists with "
        "history_interval_s=0; implies the history_overhead bench",
    )
    p.add_argument(
        "--assert-harvest-speedup",
        type=float,
        metavar="RATIO",
        help="fail (exit 1) if the gather harvest path's stage-time "
        "speedup over the padded path falls below RATIO (e.g. 1.33 = a "
        "25%% cut); implies the harvest_path bench",
    )
    p.add_argument(
        "--assert-mesh-speedup",
        type=float,
        metavar="RATIO",
        help="fail (exit 1) if the sharded CRC+vote step's best "
        "multi-device speedup over the 1-device mesh falls below RATIO "
        "(e.g. 1.2) on a >=2-device host-platform mesh; implies the "
        "mesh_scaling bench",
    )
    p.add_argument(
        "--assert-explode-speedup",
        type=float,
        metavar="RATIO",
        help="fail (exit 1) if the structural fused ladder's speedup over "
        "the staged rp_explode_find ladder on the stringified-JSON shape "
        "(the structural-index target shape) falls below RATIO (e.g. 2.0);"
        " implies the explode_find bench",
    )
    args = p.parse_args(argv)
    names = list(args.benches)
    if args.only:
        names.extend(n.strip() for n in args.only.split(","))
    if not names:
        names = list(BENCHES)
    unknown = [n for n in names if n not in BENCHES]
    if unknown:
        p.error(f"unknown bench(es) {unknown}; choose from {sorted(BENCHES)}")
    if args.assert_tracer_overhead is not None and "tracer_overhead" not in names:
        names.append("tracer_overhead")
    if (
        args.assert_propagation_overhead is not None
        and "trace_propagation_overhead" not in names
    ):
        names.append("trace_propagation_overhead")
    if args.assert_pool_speedup is not None and "host_pool_scaling" not in names:
        names.append("host_pool_scaling")
    if args.assert_mesh_speedup is not None and "mesh_scaling" not in names:
        names.append("mesh_scaling")
    if args.assert_breaker_overhead is not None and "breaker_overhead" not in names:
        names.append("breaker_overhead")
    if args.assert_harvest_speedup is not None and "harvest_path" not in names:
        names.append("harvest_path")
    if args.assert_explode_speedup is not None and "explode_find" not in names:
        names.append("explode_find")
    if args.assert_slo_overhead is not None and "slo_eval_overhead" not in names:
        names.append("slo_eval_overhead")
    if args.assert_pulse_overhead is not None and "pulse_overhead" not in names:
        names.append("pulse_overhead")
    if args.assert_history_overhead is not None and "history_overhead" not in names:
        names.append("history_overhead")
    if args.assert_governor_overhead is not None and "governor_overhead" not in names:
        names.append("governor_overhead")
    if args.assert_admission_overhead is not None and "admission_overhead" not in names:
        names.append("admission_overhead")
    snap_before = None
    if args.metrics_snapshot:
        from redpanda_tpu.metrics import registry

        snap_before = registry.snapshot()
    out: dict[str, float] = {}
    for name in names:
        out.update(BENCHES[name](args.secs))
    if args.metrics_snapshot:
        from redpanda_tpu.metrics import registry

        with open(args.metrics_snapshot, "w") as f:
            json.dump(
                {"before": snap_before, "after": registry.snapshot()},
                f, indent=2, sort_keys=True,
            )
    print(json.dumps(out))
    if args.assert_tracer_overhead is not None:
        pct = out.get("tracer_disabled_overhead_pct", 0.0)
        if pct > args.assert_tracer_overhead:
            print(
                f"tracer overhead {pct}% exceeds budget "
                f"{args.assert_tracer_overhead}%",
                file=sys.stderr,
            )
            return 1
    if args.assert_propagation_overhead is not None:
        pct = out.get("propagation_overhead_pct", 0.0)
        extra = out.get("propagation_disabled_extra_bytes", 0)
        if pct > args.assert_propagation_overhead:
            print(
                f"trace propagation overhead {pct}% exceeds budget "
                f"{args.assert_propagation_overhead}%",
                file=sys.stderr,
            )
            return 1
        if extra != 0:
            print(
                f"disabled tracer added {extra} byte(s) to the wire "
                f"(must be ZERO — header is feature-flagged on "
                f"trace_enabled)",
                file=sys.stderr,
            )
            return 1
    if args.assert_pool_speedup is not None:
        ratio = out.get("host_pool_speedup_best", 0.0)
        if ratio < args.assert_pool_speedup:
            print(
                f"host pool speedup {ratio}x below floor "
                f"{args.assert_pool_speedup}x",
                file=sys.stderr,
            )
            return 1
    if args.assert_mesh_speedup is not None:
        ratio = out.get("mesh_speedup_best", 0.0)
        if ratio < args.assert_mesh_speedup:
            print(
                f"mesh CRC+vote speedup {ratio}x below floor "
                f"{args.assert_mesh_speedup}x "
                f"({out.get('mesh_available_devices', 0)} devices)",
                file=sys.stderr,
            )
            return 1
    if args.assert_breaker_overhead is not None:
        pct = out.get("breaker_overhead_pct", 0.0)
        if pct > args.assert_breaker_overhead:
            print(
                f"breaker overhead {pct}% exceeds budget "
                f"{args.assert_breaker_overhead}%",
                file=sys.stderr,
            )
            return 1
    if args.assert_slo_overhead is not None:
        pct = out.get("slo_eval_overhead_pct", 0.0)
        if pct > args.assert_slo_overhead:
            print(
                f"slo hook overhead {pct}% exceeds budget "
                f"{args.assert_slo_overhead}%",
                file=sys.stderr,
            )
            return 1
    if args.assert_governor_overhead is not None:
        pct = out.get("governor_overhead_pct", 0.0)
        if pct > args.assert_governor_overhead:
            print(
                f"governor hook overhead {pct}% exceeds budget "
                f"{args.assert_governor_overhead}%",
                file=sys.stderr,
            )
            return 1
    if args.assert_pulse_overhead is not None:
        pct = out.get("pulse_overhead_pct", 0.0)
        if pct > args.assert_pulse_overhead:
            print(
                f"pulse recorder overhead {pct}% exceeds budget "
                f"{args.assert_pulse_overhead}%",
                file=sys.stderr,
            )
            return 1
        if out.get("pulse_profiler_off_threads", 0) != 0:
            print(
                "pulse profiler thread running with profile_hz=0 "
                "(disabled profiler must add ZERO hot-path work)",
                file=sys.stderr,
            )
            return 1
    if args.assert_history_overhead is not None:
        pct = out.get("history_overhead_pct", 0.0)
        if pct > args.assert_history_overhead:
            print(
                f"history recorder duty cycle {pct}% exceeds budget "
                f"{args.assert_history_overhead}%",
                file=sys.stderr,
            )
            return 1
        if out.get("history_recorder_off_threads", 0) != 0:
            print(
                "history recorder thread running with history_interval_s=0 "
                "(0 = off must mean NO thread)",
                file=sys.stderr,
            )
            return 1
    if args.assert_admission_overhead is not None:
        pct = out.get("admission_overhead_pct", 0.0)
        if pct > args.assert_admission_overhead:
            print(
                f"admission pair overhead {pct}% exceeds budget "
                f"{args.assert_admission_overhead}%",
                file=sys.stderr,
            )
            return 1
    if args.assert_harvest_speedup is not None:
        ratio = out.get("harvest_speedup", 0.0)
        if ratio < args.assert_harvest_speedup:
            print(
                f"harvest gather speedup {ratio}x below floor "
                f"{args.assert_harvest_speedup}x",
                file=sys.stderr,
            )
            return 1
    if args.assert_explode_speedup is not None:
        ratio = out.get("explode_find_speedup", 0.0)
        if ratio < args.assert_explode_speedup:
            print(
                f"structural explode+find+extract speedup {ratio}x below "
                f"floor {args.assert_explode_speedup}x",
                file=sys.stderr,
            )
            return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
