"""Closed-loop mixed-workload load generator gated on pandaprobe SLOs.

The ducktape/consistency-suite analogue (SURVEY §4.2-4.3) for the
"heavy traffic from millions of users" leg of the north star: simulated
clients drive produce → coproc-transform → fetch, consumer groups with
live rebalances, EOS consume-transform-produce transactions, and
tiered-storage reads against a real in-process broker (or an in-process
multi-node cluster over loopback RPC), then the run is *judged*: the
pandaprobe registry is snapshotted before and after, and the delta is
evaluated against the scenario's declarative SLO objectives
(observability/slo.py). The verdict — per-objective quantiles,
pass/fail, throughput, and breach exemplars that resolve to
/v1/trace/slow entries — lands in an ``SLO_r0N.json`` report alongside
the BENCH trajectory.

Arrival model: **open-loop arrival, closed-loop completion**. Each
producer client schedules arrivals on the wall clock (a slow broker does
not slow the offered load down — no coordinated omission) but awaits
every operation to completion, so the broker-side histograms see true
end-to-end latencies under the configured concurrency.

Chaos: ``--chaos`` arms the scenario's honey-badger probe (PR 4) through
the REAL admin API before the measured window — ``rpc.send`` delay
between the in-process cluster's nodes is the canonical one: every
replicate leg pays the injected delay, the rpc/produce objectives
breach, and each breach carries trace exemplars. The cluster-level
partition-tolerance suite over real broker *processes* lives in
tests/chaos/test_partition_tolerance.py; this tool is the load half.

Backends: ``--backend inproc`` (default) boots Applications inside this
process and judges the shared registry directly; ``--backend proc`` boots
REAL broker processes (the chaos harness's ProcCluster) and judges the
scenario from the FEDERATED /metrics scrape
(observability/federation.py) — the merged multi-node HdrHists, node
labels preserved — which removes the one-loop ceiling on offered load.

Usage:
    python tools/loadgen.py --scenario mixed_64p --report SLO_r06.json
    python tools/loadgen.py --scenario mixed_64p --chaos --report SLO_r06_chaos.json
    python tools/loadgen.py --scenario mixed_64p --backend proc --report SLO_r10.json
    python tools/loadgen.py --list

Scale: client counts multiply with ``--clients-scale`` (the default
sizes target a 2-core CI box; ``--clients-scale 8`` simulates thousands
of clients on real hardware).
"""

from __future__ import annotations

import argparse
import asyncio
import copy
import json
import os
import socket
import sys
import tempfile
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)
# the S3 imposter (tiered-storage scenarios) lives with the tests
sys.path.insert(0, os.path.join(REPO, "tests"))

os.environ.setdefault("JAX_PLATFORMS", "cpu")


# ================================================================ scenarios
# Objective threshold notes: clean runs must PASS on a busy shared box, so
# thresholds are generous against in-process latencies; the chaos delay is
# sized (see "chaos") to push the rpc/replicate tails well past them.
def _objectives(produce_ms, fetch_ms, append_ms, replicate_ms, rpc_ms,
                explode_ms, min_samples):
    return [
        {"name": "produce_p99", "metric": "kafka_produce_latency_us",
         "quantile": 99, "threshold_ms": produce_ms, "min_samples": min_samples},
        # fetch includes deliberate long-poll waits; judge on the error
        # budget (5% may ride the poll) instead of the raw quantile
        {"name": "fetch_p99", "metric": "kafka_fetch_latency_us",
         "quantile": 99, "threshold_ms": fetch_ms,
         "min_samples": min_samples, "budget_pct": 5.0},
        {"name": "append_p99", "metric": "storage_append_latency_us",
         "quantile": 99, "threshold_ms": append_ms, "min_samples": min_samples},
        {"name": "replicate_p99", "metric": "raft_replicate_latency_us",
         "quantile": 99, "threshold_ms": replicate_ms, "min_samples": 1},
        {"name": "rpc_p99", "metric": "rpc_request_latency_us",
         "quantile": 99, "threshold_ms": rpc_ms, "min_samples": 1},
        # the payload-plan parse stage: since PR 12 the filter transform
        # stages its rows off the per-batch pointer table
        # (t_explode_ptrs), so judging stage="explode" read NO_DATA on a
        # lane that no longer runs (caught by the PR 14 slodiff of
        # SLO_r14 vs SLO_r10 — the diff names idle objectives)
        {"name": "coproc_explode_p95", "metric": "coproc_stage_latency_us",
         "labels": {"stage": "explode_ptrs"}, "quantile": 95,
         "threshold_ms": explode_ms, "min_samples": 1},
    ]


SCENARIOS: dict[str, dict] = {
    # Tier-1 smoke: one broker, seconds long, deterministic PASS under
    # deliberately loose objectives (tests/slo/test_slo_smoke.py).
    "smoke": {
        "nodes": 1,
        "partitions": 4,
        "replication": 1,
        "duration_s": 2.0,
        "producers": 4,
        "produce_rate": 25.0,      # arrivals/s per producer client
        "records_per_op": 4,
        "record_bytes": 128,
        "group_members": 2,
        "rebalance_every_s": 0.0,  # off: the smoke run must be quiet
        "eos_pairs": 1,
        "eos_abort_every": 3,
        "transform_readers": 1,
        "tiered_readers": 0,
        "coproc": True,
        "objectives": _objectives(10_000, 20_000, 5_000, 10_000, 5_000,
                                  5_000, 20),
        "chaos": {"module": "rpc", "probe": "send", "effect": "delay",
                  "delay_ms": 800},
    },
    # The acceptance scenario: an in-process 3-node cluster, 64-partition
    # replicated topic, all four workload families at once. Clean run
    # passes; --chaos delays every inter-node rpc.send 800ms, breaching
    # the rpc (and usually replicate) objectives with trace exemplars.
    "mixed_64p": {
        "nodes": 3,
        "partitions": 64,
        "replication": 3,
        "duration_s": 12.0,
        "producers": 24,
        "produce_rate": 6.0,
        "records_per_op": 8,
        "record_bytes": 256,
        "group_members": 6,
        "rebalance_every_s": 3.0,
        "eos_pairs": 3,
        "eos_abort_every": 4,
        "transform_readers": 2,
        "tiered_readers": 2,
        "coproc": True,
        # thresholds sit in the clean/chaos separation band: the clean run
        # measures produce/replicate p99 ≈ 100ms and rpc p99 ≈ 40ms on a
        # 2-core box, while an 800ms rpc.send delay pushes rpc past 800ms
        # and produce/replicate into seconds — so clean PASSes with ~20x
        # margin and chaos breaches with exemplars, deterministically
        "objectives": _objectives(2_000, 30_000, 5_000, 2_000, 500,
                                  5_000, 100),
        "chaos": {"module": "rpc", "probe": "send", "effect": "delay",
                  "delay_ms": 800},
    },
    # Single-node heavy-partition variant: no replication rpc, coproc and
    # host-stage machinery under the full partition fan-out.
    "standalone_64p": {
        "nodes": 1,
        "partitions": 64,
        "replication": 1,
        "duration_s": 8.0,
        "producers": 16,
        "produce_rate": 10.0,
        "records_per_op": 8,
        "record_bytes": 256,
        "group_members": 4,
        "rebalance_every_s": 2.5,
        "eos_pairs": 2,
        "eos_abort_every": 4,
        "transform_readers": 2,
        "tiered_readers": 2,
        "coproc": True,
        "objectives": _objectives(15_000, 30_000, 8_000, 10_000, 5_000,
                                  8_000, 50),
        "chaos": {"module": "coproc", "probe": "device_dispatch",
                  "effect": "delay", "delay_ms": 800},
    },
    # Device-plane CRC chaos (ROADMAP item 2 follow-on c): a 3-node proc
    # cluster with follower batched-CRC validation ON; --chaos arms the
    # finjector CORRUPT probe so received append blobs arrive torn on
    # every node for its first N appends. The device plane must REJECT
    # them (raft_crc_rejected_batches_total moves in the federated
    # scrape), the leader's resend repairs each one, and acked writes
    # ride the healthy quorum meanwhile (workloads_ok requires both).
    "crc_chaos": {
        "nodes": 3,
        "partitions": 16,
        "replication": 3,
        "duration_s": 10.0,
        "producers": 8,
        "produce_rate": 6.0,
        "records_per_op": 8,
        "record_bytes": 256,
        "group_members": 0,
        "rebalance_every_s": 0.0,
        "eos_pairs": 1,
        "eos_abort_every": 4,
        "transform_readers": 0,
        "tiered_readers": 0,
        "coproc": False,
        "extra_config": {"raft_device_crc_validate": True},
        "objectives": _objectives(15_000, 30_000, 8_000, 15_000, 8_000,
                                  8_000, 20),
        "chaos": {"module": "raft", "probe": "append_blob",
                  "effect": "corrupt", "count": 30},
        "chaos_assert_metric": "raft_crc_rejected_batches_total",
    },
}

# Open-loop overload family (ROADMAP item 4 acceptance): arrivals are
# scheduled at overload_factor x the MEASURED closed-loop capacity and
# never wait for completions (coordinated-omission-safe: each acked op's
# latency is measured from its SCHEDULED arrival). The broker memory
# total is shrunk so the produce admission gate actually bites — the gate
# is that throughput plateaus at the knee, admitted p99 stays governed,
# sheds are counted (never lost: acked-write verification is EXACT), no
# account breaches its budget, and the decision journal reconstructs the
# shed episodes. Run via --scenario overload_* (run_overload_async).
OVERLOAD_SCENARIOS: dict[str, dict] = {
    # seconds-long single-broker smoke (tier-1: tests/slo/test_overload_smoke.py)
    "overload_smoke": {
        "nodes": 1,
        "partitions": 4,
        "replication": 1,
        "calibrate_s": 2.0,
        "duration_s": 4.0,
        "producers": 4,
        "records_per_op": 8,
        "record_bytes": 1024,
        "overload_factor": 2.0,
        "coproc": False,
        "admitted_p99_ms": 10_000,
        "plateau_floor": 0.5,
        "extra_config": {
            # small plane so the flood actually exhausts kafka_produce
            "resource_memory_total_mb": 4,
        },
    },
    # the acceptance scenario: a REAL broker process (proc backend),
    # 64-partition topic, >= 2x measured capacity — SLO_r13_overload.json
    "overload_64p": {
        "nodes": 1,
        "partitions": 64,
        "replication": 1,
        "calibrate_s": 6.0,
        "duration_s": 15.0,
        "producers": 8,
        "records_per_op": 8,
        "record_bytes": 1024,
        "overload_factor": 2.0,
        "coproc": False,
        "admitted_p99_ms": 10_000,
        "plateau_floor": 0.8,
        "extra_config": {
            "resource_memory_total_mb": 8,
        },
    },
}

TOPIC = "loadgen"
EOS_SRC_GROUP = "loadgen-eos"
EOS_DST = "loadgen-eos-out"
TIERED_TOPIC = "loadgen-tiered"
SCRIPT_NAME = "loadgen-filter"


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


# ================================================================ the stack
class Stack:
    """1..N in-process Applications sharing this process's registry,
    tracer, SLO engine and honey badger — which is exactly what lets the
    scenario snapshot/judge them directly while chaos arming still goes
    through the real admin API."""

    backend = "inproc"

    def __init__(self, scenario: dict, base_dir: str, imposter=None):
        self.scenario = scenario
        self.base_dir = base_dir
        self.imposter = imposter
        self.apps = []
        self.kafka_ports: list[int] = []
        self.admin_ports: list[int] = []

    def _configs(self):
        from redpanda_tpu.config import Configuration

        s = self.scenario
        n = s["nodes"]
        thresholds = [o["threshold_ms"] for o in s["objectives"]]
        slow_ms = max(1, int(min(thresholds)))
        rpc_ports = [_free_port() for _ in range(n)]
        # kafka ports are pre-allocated, not ephemeral: in clustered mode
        # the advertised port replicates through the controller's
        # register_node command, which reads the configured value
        kafka_ports = [_free_port() for _ in range(n)]
        seed_str = (
            ",".join(f"{i}@127.0.0.1:{p}" for i, p in enumerate(rpc_ports))
            if n > 1 else ""
        )
        configs = []
        for i in range(n):
            c = Configuration()
            sets = {
                "node_id": i,
                "data_directory": os.path.join(self.base_dir, f"n{i}"),
                "kafka_api_port": kafka_ports[i],
                "advertised_kafka_api_port": kafka_ports[i],
                "admin_api_port": 0,
                "rpc_server_port": rpc_ports[i],
                "seed_servers": seed_str,
                "default_topic_replication": s["replication"],
                # tolerate the injected rpc delay without election storms:
                # a heartbeat delayed by the chaos effect must still land
                # inside the election timeout
                "raft_election_timeout_ms": 2500,
                "raft_heartbeat_interval_ms": 250,
                "coproc_enable": bool(s.get("coproc")),
                # exemplars + /v1/trace/slow resolution need the tracer;
                # the slow ring threshold tracks the tightest objective so
                # every breach-sized span is resolvable afterwards
                "trace_enabled": True,
                "trace_slow_threshold_ms": slow_ms,
            }
            if self.imposter is not None:
                sets.update({
                    "cloud_storage_enabled": True,
                    "cloud_storage_bucket": "loadgen",
                    "cloud_storage_api_endpoint":
                        f"http://127.0.0.1:{self.imposter.port}",
                    "cloud_storage_access_key": "k",
                    "cloud_storage_secret_key": "s",
                    "cloud_storage_segment_max_upload_interval_sec": 1,
                })
            # per-scenario broker knobs (the overload family shrinks
            # resource_memory_total_mb so admission actually bites)
            sets.update(s.get("extra_config") or {})
            for k, v in sets.items():
                c.set(k, v)
            configs.append(c)
        return configs

    async def archival_run_once(self) -> int:
        """One reconcile+upload pass on every node; returns total uploads."""
        total = 0
        for a in self.apps:
            arch = getattr(a, "archival", None)
            if arch is not None:
                total += await arch.run_once()
        return total

    async def start(self) -> "Stack":
        from redpanda_tpu.app import Application

        configs = self._configs()
        # return_exceptions + assign-before-raise: if one node fails to
        # start (port bind race), the ones that DID start are recorded so
        # the caller's stack.stop() tears them down instead of leaking
        # live brokers into the process
        results = await asyncio.gather(
            *(Application(c).start() for c in configs),
            return_exceptions=True,
        )
        self.apps = [a for a in results if not isinstance(a, BaseException)]
        errors = [e for e in results if isinstance(e, BaseException)]
        if errors:
            raise errors[0]
        # the config property is integer milliseconds; re-apply the exact
        # float so every breach-sized span (possibly sub-ms in tests) is
        # guaranteed to land in the slow ring its exemplar points at
        from redpanda_tpu.observability import tracer

        tracer.configure(
            slow_threshold_ms=min(
                o["threshold_ms"] for o in self.scenario["objectives"]
            )
        )
        self.kafka_ports = [a.kafka_server.port for a in self.apps]
        self.admin_ports = [a.admin.port for a in self.apps]
        if len(self.apps) > 1:
            await self._wait_settled()
        return self

    async def _wait_settled(self, timeout: float = 60.0) -> None:
        """Same contract as the chaos harness's wait_for_settled_writes:
        two acks=-1 canary writes across an election-timeout margin."""
        from redpanda_tpu.kafka.client import KafkaClient

        deadline = time.monotonic() + timeout
        last = None
        while time.monotonic() < deadline:
            c = None
            try:
                c = await KafkaClient(self.bootstrap()).connect()
                try:
                    await c.create_topic(
                        "loadgen-canary", partitions=1,
                        replication=self.scenario["replication"],
                    )
                except Exception:
                    await c.refresh_metadata(["loadgen-canary"], auto_create=False)
                await c.produce("loadgen-canary", 0, [b"settle-1"], acks=-1)
                await asyncio.sleep(0.6)
                await c.produce("loadgen-canary", 0, [b"settle-2"], acks=-1)
                await c.close()
                return
            except Exception as e:  # noqa: BLE001 — retried until deadline
                last = e
                if c is not None:
                    try:
                        await c.close()
                    except Exception:
                        pass
                await asyncio.sleep(0.5)
        raise TimeoutError(f"cluster writes never settled: {last!r}")

    def bootstrap(self) -> list[tuple[str, int]]:
        return [("127.0.0.1", p) for p in self.kafka_ports]

    async def transforms_active(self, script: str) -> bool:
        return all(
            a.coproc is not None and script in a.coproc.active_scripts()
            for a in self.apps
        )

    async def stop(self) -> None:
        for a in self.apps:
            try:
                await a.stop()
            except Exception:
                pass


class ProcStack:
    """REAL broker processes (the chaos harness's ProcCluster): nothing is
    shared with this process, so scenario SLOs are judged from the
    FEDERATED /metrics scrape (observability/federation.py) instead of the
    in-process registry — removing the one-loop ceiling on offered load:
    the brokers burn their own cores, and the judged histograms live where
    the latency happened. Chaos arming and transform-activation polling go
    through each node's real admin API. Tiered-storage scenarios drive
    archival through the admin surface (POST /v1/archival/run_once), so
    ``tiered_readers`` work in this mode too — the S3 imposter runs in
    THIS process and the broker processes reach it over loopback."""

    backend = "proc"

    def __init__(self, scenario: dict, base_dir: str, imposter=None):
        self.scenario = scenario
        self.base_dir = base_dir
        self.imposter = imposter
        self.cluster = None
        self.kafka_ports: list[int] = []
        self.admin_ports: list[int] = []

    async def start(self) -> "ProcStack":
        from chaos.harness import ProcCluster

        s = self.scenario
        thresholds = [o["threshold_ms"] for o in s["objectives"]]
        extra = {
            "default_topic_replication": s["replication"],
            # same chaos posture as the in-process stack: an injected
            # rpc delay must not trigger election storms
            "raft_election_timeout_ms": 2500,
            "raft_heartbeat_interval_ms": 250,
            "coproc_enable": bool(s.get("coproc")),
            "trace_enabled": True,
            "trace_slow_threshold_ms": max(1, int(min(thresholds))),
        }
        if self.imposter is not None:
            extra.update({
                "cloud_storage_enabled": True,
                "cloud_storage_bucket": "loadgen",
                "cloud_storage_api_endpoint":
                    f"http://127.0.0.1:{self.imposter.port}",
                "cloud_storage_access_key": "k",
                "cloud_storage_secret_key": "s",
                "cloud_storage_segment_max_upload_interval_sec": 1,
            })
        extra.update(s.get("extra_config") or {})
        self.cluster = await ProcCluster(
            self.base_dir, n=s["nodes"], extra_config=extra
        ).start()
        self.kafka_ports = [n.ports["kafka"] for n in self.cluster.nodes]
        self.admin_ports = [n.ports["admin"] for n in self.cluster.nodes]
        return self

    def bootstrap(self) -> list[tuple[str, int]]:
        return [("127.0.0.1", p) for p in self.kafka_ports]

    def federation_targets(self) -> list[tuple[int, str]]:
        return [
            (i, f"http://127.0.0.1:{p}")
            for i, p in enumerate(self.admin_ports)
        ]

    async def transforms_active(self, script: str) -> bool:
        import aiohttp

        async with aiohttp.ClientSession() as sess:
            for port in self.admin_ports:
                try:
                    async with sess.get(
                        f"http://127.0.0.1:{port}/v1/coproc/status",
                        timeout=aiohttp.ClientTimeout(total=5),
                    ) as r:
                        doc = await r.json()
                except Exception:
                    return False
                if (
                    not doc.get("enabled")
                    or script not in (doc.get("scripts") or [])
                ):
                    return False
        return True

    async def archival_run_once(self) -> int:
        """Drive one archival pass per node through the admin surface."""
        import aiohttp

        total = 0
        async with aiohttp.ClientSession() as sess:
            for port in self.admin_ports:
                async with sess.post(
                    f"http://127.0.0.1:{port}/v1/archival/run_once",
                    timeout=aiohttp.ClientTimeout(total=60),
                ) as r:
                    if r.status == 200:
                        total += (await r.json()).get("uploads", 0)
        return total

    async def stop(self) -> None:
        if self.cluster is not None:
            await self.cluster.stop()


# ================================================================ workloads
def _payload(client_id: int, seq: int, j: int, size: int) -> bytes:
    level = ("error", "info", "warn")[(client_id + seq + j) % 3]
    doc = '{"level":"%s","code":%d,"msg":"c%d-%d-%d-' % (
        level, j, client_id, seq, j
    )
    pad = max(0, size - len(doc) - 2)
    return (doc + "x" * pad + '"}').encode()


async def _sleep_or_stop(stop: asyncio.Event, delay: float) -> bool:
    """True when the stop event fired during the wait. No shield: wait_for
    cancels the Event.wait() on timeout, which is harmless and leak-free
    (a shielded waiter would survive until stop.set(), thousands of them
    over a long scenario)."""
    if delay <= 0:
        return stop.is_set()
    try:
        await asyncio.wait_for(stop.wait(), delay)
        return True
    except asyncio.TimeoutError:
        return False


async def _producer(i, client, partitions, rate, k, size, stop, stats):
    loop = asyncio.get_event_loop()
    interval = 1.0 / rate
    # stagger client phases so arrivals spread over the interval
    next_t = loop.time() + (i % 16) / 16.0 * interval
    part = i % partitions
    seq = 0
    while not stop.is_set():
        now = loop.time()
        if next_t > now:
            if await _sleep_or_stop(stop, next_t - now):
                break
        # open loop: the schedule advances regardless of completion time
        next_t += interval
        part = (part + 1) % partitions
        values = [_payload(i, seq, j, size) for j in range(k)]
        seq += 1
        try:
            await client.produce(TOPIC, part, values, acks=-1)
            stats["produce_ops"] += 1
            stats["produced_records"] += k
        except Exception:
            stats["produce_errors"] += 1


async def _group_member(i, client, topics, stop, stats):
    from redpanda_tpu.kafka.client.consumer import GroupConsumer

    c = GroupConsumer(
        client, "loadgen-group", topics,
        session_timeout_ms=8000, heartbeat_interval_s=0.5,
    )
    try:
        await c.join()
        stats["group_joins"] += 1
        while not stop.is_set():
            try:
                out = await c.poll(max_records=500)
                n = sum(len(v) for v in out.values())
                stats["consumed_records"] += n
                await c.commit()
                if c.rejoin_needed:
                    stats["rebalances_seen"] += 1
                if not out:
                    await _sleep_or_stop(stop, 0.05)
            except Exception:
                stats["consume_errors"] += 1
                if await _sleep_or_stop(stop, 0.2):
                    break
    finally:
        try:
            await c.leave()
        except Exception:
            pass


async def _rebalancer(client, topics, every_s, stop, stats):
    """Forces group rebalances by cycling a transient member in and out —
    every join and leave bumps the generation for the whole group."""
    from redpanda_tpu.kafka.client.consumer import GroupConsumer

    while not stop.is_set():
        if await _sleep_or_stop(stop, every_s):
            break
        t = GroupConsumer(
            client, "loadgen-group", topics,
            session_timeout_ms=8000, heartbeat_interval_s=0.5,
        )
        try:
            await t.join()
            await _sleep_or_stop(stop, 0.3)
            await t.leave()
            stats["rebalances_forced"] += 1
        except Exception:
            stats["rebalance_errors"] += 1


async def _eos_pair(i, client, partitions, abort_every, stop, stats):
    """Consume-transform-produce with EOS: read the main topic, write the
    transform to EOS_DST inside a transaction with staged group offsets;
    every ``abort_every``-th transaction aborts. The end-of-run
    read_committed count over EOS_DST must equal exactly the committed
    records — the closed-loop exactly-once check."""
    from redpanda_tpu.kafka.client.producer import TransactionalProducer

    p = TransactionalProducer(client, f"loadgen-eos-{i}")
    await p.init()
    src_part = i % partitions
    pos = 0
    n_tx = 0
    while not stop.is_set():
        try:
            batches, hwm = await client.fetch(
                TOPIC, src_part, pos, max_wait_ms=100, max_bytes=64 * 1024
            )
        except Exception:
            stats["eos_errors"] += 1
            if await _sleep_or_stop(stop, 0.2):
                break
            continue
        values = []
        new_pos = pos
        for b in batches:
            for r in b.records():
                off = b.header.base_offset + r.offset_delta
                if off >= pos and r.value:
                    values.append(b"eos:" + r.value[:64])
                    new_pos = off + 1
        if not values:
            if await _sleep_or_stop(stop, 0.05):
                break
            continue
        values = values[:64]
        try:
            p.begin()
            await p.send(EOS_DST, i, values)
            await p.send_offsets(
                f"{EOS_SRC_GROUP}-{i}", {(TOPIC, src_part): new_pos}
            )
            if abort_every and n_tx % abort_every == abort_every - 1:
                await p.abort()
                stats["eos_aborted_tx"] += 1
            else:
                await p.commit()
                stats["eos_committed_tx"] += 1
                stats["eos_committed_records"] += len(values)
                pos = new_pos
            n_tx += 1
        except Exception:
            stats["eos_errors"] += 1
            try:
                await p.abort()
            except Exception:
                # a dead transaction epoch needs a fresh producer session
                try:
                    await p.init()
                except Exception:
                    pass
            if await _sleep_or_stop(stop, 0.2):
                break


async def _transform_reader(i, client, mat_topic, partitions, stop, stats):
    """Closes the produce → coproc → fetch loop: tails the materialized
    topic the deployed transform writes."""
    positions = {p: 0 for p in range(partitions)}
    part = i
    while not stop.is_set():
        part = (part + 1) % partitions
        try:
            batches, _ = await client.fetch(
                mat_topic, part, positions[part], max_wait_ms=20
            )
            n = sum(len(b.records()) for b in batches)
            if batches:
                positions[part] = batches[-1].last_offset + 1
            stats["transform_records_read"] += n
        except Exception:
            stats["transform_read_errors"] += 1
            if await _sleep_or_stop(stop, 0.25):
                break
        if await _sleep_or_stop(stop, 0.05):
            break


async def _tiered_reader(i, client, hi_offset, stop, stats):
    """Re-reads the archived-and-locally-evicted prefix: every fetch below
    the local log start falls through to the cloud read path."""
    off = 0
    while not stop.is_set():
        try:
            batches, _ = await client.fetch(
                TIERED_TOPIC, 0, off, max_wait_ms=10, max_bytes=32 * 1024
            )
            stats["tiered_reads"] += 1
            stats["tiered_records_read"] += sum(
                len(b.records()) for b in batches
            )
            off = batches[-1].last_offset + 1 if batches else 0
            if off >= hi_offset:
                off = 0
        except Exception:
            stats["tiered_read_errors"] += 1
            if await _sleep_or_stop(stop, 0.25):
                break
        if await _sleep_or_stop(stop, 0.05):
            break


# ================================================================ setup
async def _deploy_transform(stack, client) -> str:
    """Deploy the JSON-filter transform through the real wasm-event path
    (what `rpk wasm deploy` produces) and wait until every node's engine
    activated it."""
    from redpanda_tpu.coproc import wasm_event
    from redpanda_tpu.models.fundamental import COPROC_INTERNAL_TOPIC
    from redpanda_tpu.ops.transforms import filter_field_eq

    spec = filter_field_eq("level", "error")
    rec = wasm_event.make_deploy_record(
        SCRIPT_NAME, spec.to_json(), [TOPIC]
    )
    batch = wasm_event.deploy_batch([rec])
    deadline = time.monotonic() + 30.0
    while True:
        try:
            await client.produce_batches(COPROC_INTERNAL_TOPIC, 0, [batch])
            break
        except Exception:
            if time.monotonic() > deadline:
                raise
            await asyncio.sleep(0.5)
    while not await stack.transforms_active(SCRIPT_NAME):
        if time.monotonic() > deadline:
            raise TimeoutError("transform never activated on every node")
        await asyncio.sleep(0.1)
    return f"{TOPIC}.${SCRIPT_NAME}$"


async def _setup_tiered(stack: Stack, client) -> int:
    """Build a topic whose prefix lives ONLY in the bucket: produce across
    several small segments, archive the closed ones, then DeleteRecords
    the local prefix. Returns the high watermark readers cycle over."""
    from redpanda_tpu.kafka.protocol import messages as m

    await client.create_topic(
        TIERED_TOPIC, partitions=1, replication=1,
        configs={"segment.bytes": "8192"},
    )
    for seq in range(24):
        await client.produce(
            TIERED_TOPIC, 0,
            [_payload(999, seq, j, 512) for j in range(4)],
            acks=-1,
        )
    # archive the closed segments now (deterministic, no interval wait):
    # in-proc stacks call the scheduler directly, the proc backend goes
    # through POST /v1/archival/run_once on every node
    uploaded = await stack.archival_run_once()
    if uploaded == 0:
        raise RuntimeError("tiered setup: nothing archived")
    hwm = await client.latest_offset(TIERED_TOPIC, 0)
    evict_to = hwm // 2
    conn = await client.leader_connection(TIERED_TOPIC, 0)
    resp = await conn.request(m.DELETE_RECORDS, {
        "topics": [{
            "name": TIERED_TOPIC,
            "partitions": [{"partition_index": 0, "offset": evict_to}],
        }],
        "timeout_ms": 30_000,
    })
    pr = resp["topics"][0]["partitions"][0]
    if pr["error_code"] != 0:
        raise RuntimeError(f"tiered setup: delete_records error {pr}")
    if pr["low_watermark"] > 0:
        raise RuntimeError(
            "tiered setup: local eviction lost the archived prefix "
            f"(low_watermark {pr['low_watermark']})"
        )
    return hwm


async def _arm_chaos(stack, chaos: dict) -> dict:
    """Arm the scenario's failure probe through the real admin API (and
    size the injected delay), exactly like an operator with rpk. The probe
    is armed on EVERY node: in-process brokers share one honey badger so
    repeats are idempotent, while real broker processes each own theirs —
    one PUT per process is the only way the fault exists cluster-wide."""
    import aiohttp

    delay_ms = int(chaos.get("delay_ms", 50))
    params = []
    if chaos["effect"] == "delay":
        params.append(f"delay_ms={delay_ms}")
    if chaos.get("count"):
        params.append(f"count={int(chaos['count'])}")
    qs = ("?" + "&".join(params)) if params else ""
    body = None
    async with aiohttp.ClientSession() as s:
        for port in stack.admin_ports:
            url = (
                f"http://127.0.0.1:{port}/v1/failure-probes/"
                f"{chaos['module']}/{chaos['probe']}/{chaos['effect']}{qs}"
            )
            async with s.put(url) as resp:
                body = await resp.json()
                if resp.status != 200:
                    raise RuntimeError(
                        f"chaos arm failed on :{port}: {resp.status} {body}"
                    )
    return {**chaos, "armed": body.get("armed")}


async def _disarm_chaos(stack, chaos: dict) -> None:
    """Disarm on every node (the proc backend has one badger per broker
    process; honey_badger.disable() in this process reaches none of them)."""
    import aiohttp

    async with aiohttp.ClientSession() as s:
        for port in stack.admin_ports:
            url = (
                f"http://127.0.0.1:{port}/v1/failure-probes/"
                f"{chaos['module']}/{chaos['probe']}"
            )
            try:
                async with s.delete(url):
                    pass
            except Exception:
                pass  # a node lost mid-chaos: nothing to disarm there


async def _scrape_counter_total(stack, name: str) -> float:
    """Sum one counter series across every node's /metrics (uniform for
    both backends: in-process stacks expose admin /metrics too)."""
    import re

    import aiohttp

    # the registry renders with its exposition prefix (redpanda_tpu_...)
    pat = re.compile(
        rf"^(?:redpanda_tpu_)?{re.escape(name)}(?:\{{[^}}]*\}})? "
        rf"([0-9.eE+-]+)$",
        re.MULTILINE,
    )
    # in-process stacks share ONE registry: scraping every admin port
    # would multiply the same counter by the node count
    ports = (
        stack.admin_ports[:1]
        if stack.backend == "inproc"
        else stack.admin_ports
    )
    total = 0.0
    async with aiohttp.ClientSession() as sess:
        for port in ports:
            try:
                async with sess.get(
                    f"http://127.0.0.1:{port}/metrics",
                    timeout=aiohttp.ClientTimeout(total=10),
                ) as r:
                    text = await r.text()
            except Exception:
                continue
            total += sum(float(m) for m in pat.findall(text))
    return total


async def _resolve_exemplars(stack: Stack, report: dict) -> None:
    """Every breach exemplar must resolve to a /v1/trace/slow entry; the
    report says how many did, so a broken link is visible on its face."""
    import aiohttp

    trace_ids = {
        ex["trace_id"]
        for o in report["objectives"]
        for ex in o.get("exemplars") or []
    }
    report["exemplars_total"] = len(trace_ids)
    if not trace_ids:
        report["exemplars_resolved"] = 0
        return
    url = f"http://127.0.0.1:{stack.admin_ports[0]}/v1/trace/slow?limit=500"
    async with aiohttp.ClientSession() as s:
        async with s.get(url) as resp:
            doc = await resp.json()
    slow_ids = {sp["trace_id"] for sp in doc.get("spans", [])}
    report["exemplars_resolved"] = len(trace_ids & slow_ids)


async def _verify_eos(client, eos_pairs: int, stats: dict) -> dict:
    """read_committed count over EOS_DST must equal the committed records
    exactly: nothing aborted leaked, nothing committed lost."""
    visible = 0
    for p in range(eos_pairs):
        off = 0
        while True:
            batches, hwm = await client.fetch(
                EOS_DST, p, off, max_wait_ms=10, isolation_level=1
            )
            if not batches:
                if off >= hwm:
                    break
                off = hwm  # aborted-range hole: skip to the watermark
                continue
            visible += sum(len(b.records()) for b in batches)
            off = batches[-1].last_offset + 1
    return {
        "committed_records": stats["eos_committed_records"],
        "visible_read_committed": visible,
        "exact": visible == stats["eos_committed_records"],
    }


# ================================================================ scenario run
def _spec_for(scenario_name: str, s: dict):
    from redpanda_tpu.observability.slo import SloSpec

    return SloSpec.from_dict(
        {"name": scenario_name, "objectives": s["objectives"]}
    )


async def run_scenario_async(
    name: str,
    *,
    chaos: bool = False,
    duration_s: float | None = None,
    clients_scale: float = 1.0,
    overrides: dict | None = None,
    base_dir: str | None = None,
    backend: str = "inproc",
) -> dict:
    from redpanda_tpu.kafka.client import KafkaClient
    from redpanda_tpu.observability.slo import slo

    if backend not in ("inproc", "proc"):
        raise ValueError(f"unknown backend {backend!r}")
    s = copy.deepcopy(SCENARIOS[name])
    s.update(overrides or {})
    if duration_s is not None:
        s["duration_s"] = float(duration_s)
    for key in ("producers", "group_members", "eos_pairs",
                "transform_readers", "tiered_readers"):
        s[key] = max(0 if s[key] == 0 else 1, int(s[key] * clients_scale))

    tmp = None
    if base_dir is None:
        tmp = tempfile.TemporaryDirectory(prefix="loadgen-")
        base_dir = tmp.name

    from redpanda_tpu.finjector import honey_badger
    from redpanda_tpu.observability import tracer

    # A scenario reconfigures process-wide singletons (the injected delay,
    # the active SLO spec, the tracer slow threshold); in-process callers
    # (the pytest suite) must get every one of them back afterwards —
    # disable() clears probes but deliberately not the delay knob, and
    # nothing else restores itself
    saved_delay_ms = honey_badger.delay_ms
    saved_spec = slo.spec
    saved_slow_us = tracer.slow_threshold_us
    saved_trace_enabled = tracer.enabled
    spec = None

    imposter = None
    if s["tiered_readers"]:
        from s3_imposter import S3Imposter

        imposter = await S3Imposter().start()

    stack_cls = ProcStack if backend == "proc" else Stack
    stack = stack_cls(s, base_dir, imposter=imposter)
    stats: dict[str, int] = {
        k: 0 for k in (
            "produce_ops", "produced_records", "produce_errors",
            "consumed_records", "consume_errors", "group_joins",
            "rebalances_forced", "rebalances_seen", "rebalance_errors",
            "eos_committed_tx", "eos_aborted_tx", "eos_committed_records",
            "eos_errors", "transform_records_read", "transform_read_errors",
            "tiered_reads", "tiered_records_read", "tiered_read_errors",
        )
    }
    clients: list = []
    t_setup0 = time.monotonic()
    try:
        await stack.start()
        n_clients = max(
            2, min(8, s["producers"] + s["group_members"] + s["eos_pairs"])
        )
        clients = await asyncio.gather(*(
            KafkaClient(stack.bootstrap()).connect() for _ in range(n_clients)
        ))

        def client_for(i: int):
            return clients[i % len(clients)]

        admin = clients[0]
        await admin.create_topic(
            TOPIC, partitions=s["partitions"], replication=s["replication"]
        )
        await admin.create_topic(
            EOS_DST, partitions=max(1, s["eos_pairs"]),
            replication=s["replication"],
        )
        mat_topic = None
        if s.get("coproc"):
            mat_topic = await _deploy_transform(stack, admin)
        tiered_hwm = 0
        if s["tiered_readers"]:
            tiered_hwm = await _setup_tiered(stack, admin)

        # ---- warmup: touch every path once so the measured window holds
        # steady-state latencies, not first-op compiles and cache fills
        for p in range(s["partitions"]):
            await admin.produce(
                TOPIC, p, [_payload(0, 0, j, s["record_bytes"])
                            for j in range(2)], acks=-1
            )
        if mat_topic is not None:
            deadline = time.monotonic() + 20.0
            while time.monotonic() < deadline:
                try:
                    hv = await admin.latest_offset(mat_topic, 0)
                    if hv > 0:
                        break
                except Exception:
                    pass
                await asyncio.sleep(0.2)

        chaos_info = None
        if chaos:
            if not s.get("chaos"):
                raise ValueError(f"scenario {name} defines no chaos probe")
            chaos_info = await _arm_chaos(stack, s["chaos"])

        # ---- the measured window
        spec = _spec_for(name, s)
        fed = None
        if backend == "proc":
            # nothing broker-side lives in this process: judge the window
            # from the FEDERATED scrape of every broker's /metrics (the
            # merged HdrHists carry node labels for drill-down)
            from redpanda_tpu.observability.federation import FederatedSlo

            targets = stack.federation_targets()
            fed = FederatedSlo(lambda: targets)
            baseline = await fed.snapshot()
        else:
            slo.configure(spec)      # arms per-metric exemplar thresholds
            baseline = slo.snapshot()
        stop = asyncio.Event()
        tasks = []
        for i in range(s["producers"]):
            tasks.append(asyncio.create_task(_producer(
                i, client_for(i), s["partitions"], s["produce_rate"],
                s["records_per_op"], s["record_bytes"], stop, stats,
            )))
        group_topics = [TOPIC]
        for i in range(s["group_members"]):
            tasks.append(asyncio.create_task(_group_member(
                i, client_for(100 + i), group_topics, stop, stats
            )))
        if s["group_members"] and s["rebalance_every_s"] > 0:
            tasks.append(asyncio.create_task(_rebalancer(
                client_for(200), group_topics, s["rebalance_every_s"],
                stop, stats,
            )))
        for i in range(s["eos_pairs"]):
            tasks.append(asyncio.create_task(_eos_pair(
                i, client_for(300 + i), s["partitions"],
                s["eos_abort_every"], stop, stats,
            )))
        if mat_topic is not None:
            for i in range(s["transform_readers"]):
                tasks.append(asyncio.create_task(_transform_reader(
                    i, client_for(400 + i), mat_topic, s["partitions"],
                    stop, stats,
                )))
        for i in range(s["tiered_readers"]):
            tasks.append(asyncio.create_task(_tiered_reader(
                i, client_for(500 + i), tiered_hwm, stop, stats
            )))

        t0 = time.monotonic()
        await asyncio.sleep(s["duration_s"])
        stop.set()
        if tasks:
            done, pending = await asyncio.wait(tasks, timeout=20.0)
            for t in pending:
                t.cancel()
            if pending:
                await asyncio.gather(*pending, return_exceptions=True)
            for t in done:
                t.exception()  # consume; stats carry the error counts
        elapsed = time.monotonic() - t0

        if chaos_info is not None:
            # disarm before the closed-loop verification reads — through
            # the admin API on every node (real broker processes own their
            # badgers; the local disable only reaches the in-process one)
            await _disarm_chaos(stack, s["chaos"])
            honey_badger.disable()

        eos_check = (
            await _verify_eos(admin, s["eos_pairs"], stats)
            if s["eos_pairs"] else None
        )

        if fed is not None:
            report = await fed.evaluate(spec, baseline=baseline)
        else:
            report = slo.evaluate(spec, baseline=baseline)
        await _resolve_exemplars(stack, report)
        # scenario-declared fault-path proof: the chaos run must show its
        # counter moved (e.g. crc_chaos: corrupted appends REJECTED by the
        # follower CRC plane, visible in the federated scrape)
        chaos_metric = None
        if chaos_info is not None and s.get("chaos_assert_metric"):
            mname = s["chaos_assert_metric"]
            chaos_metric = {
                "name": mname,
                "total": await _scrape_counter_total(stack, mname),
            }
        report.update({
            "backend": stack.backend,
            "chaos": chaos_info,
            "duration_s": round(elapsed, 3),
            "setup_s": round(t0 - t_setup0, 3),
            "nodes": s["nodes"],
            "partitions": s["partitions"],
            "replication": s["replication"],
            "clients": {
                "producers": s["producers"],
                "group_members": s["group_members"],
                "eos_pairs": s["eos_pairs"],
                "transform_readers": s["transform_readers"],
                "tiered_readers": s["tiered_readers"],
            },
            "throughput": {
                **stats,
                "produce_ops_per_s": round(stats["produce_ops"] / elapsed, 1),
                "produced_records_per_s": round(
                    stats["produced_records"] / elapsed, 1
                ),
            },
            "eos_check": eos_check,
            "chaos_metric": chaos_metric,
            # the lossless-workload bar: EOS stays exactly-once always;
            # client-visible produce ERRORS (unacked, retriable) are
            # expected bounded degradation under chaos, but a CLEAN run
            # must not see any; a declared chaos metric must have MOVED
            # (the fault actually exercised its detection path)
            "workloads_ok": (
                (eos_check is None or eos_check["exact"])
                and (chaos_info is not None or stats["produce_errors"] == 0)
                and (chaos_metric is None or chaos_metric["total"] > 0)
            ),
        })
        return report
    finally:
        for c in clients:
            try:
                await c.close()
            except Exception:
                pass
        honey_badger.disable()
        honey_badger.delay_ms = saved_delay_ms
        # disarm the scenario's per-histogram exemplar thresholds before
        # restoring the spec: configure(arm_exemplars=False) restores the
        # OBJECT but would leave e.g. a 2000ms produce threshold silently
        # recording exemplars for the rest of the process (a later
        # in-process /v1/slo re-arms its own spec lazily)
        if spec is not None:
            from redpanda_tpu.observability import probes as _probes

            hists = slo.registry.histograms()
            for o in spec.objectives:
                h = hists.get(o.series)
                if h is not None:
                    _probes.disarm_exemplar_threshold(h)
        slo.configure(saved_spec, arm_exemplars=False)
        tracer.configure(
            enabled=saved_trace_enabled,
            slow_threshold_ms=saved_slow_us / 1000.0,
        )
        await stack.stop()
        if imposter is not None:
            await imposter.stop()
        if tmp is not None:
            tmp.cleanup()


def run_scenario(name: str, **kw) -> dict:
    return asyncio.run(run_scenario_async(name, **kw))


# ================================================================ overload
def _overload_payload(i: int, seq: int, j: int, size: int) -> bytes:
    """Unique, parseable key first so the verification sweep can extract
    it with a prefix scan instead of a JSON parse per record."""
    doc = '{"k":"%d-%d-%d","pad":"' % (i, seq, j)
    pad = max(0, size - len(doc) - 2)
    return (doc + "x" * pad + '"}').encode()


def _overload_keys(value: bytes) -> str | None:
    if not value.startswith(b'{"k":"'):
        return None
    end = value.find(b'"', 6)
    return value[6:end].decode() if end > 0 else None


async def _closed_loop_producer(i, client, partitions, k, size, stop, counter):
    """Calibration phase: back-to-back acked produces, no schedule — the
    aggregate acked rate IS the closed-loop knee the open-loop phase
    overloads against. Calibration keys use an id offset so the
    verification sweep never confuses them with measured-phase records."""
    part = i % partitions
    seq = 0
    while not stop.is_set():
        part = (part + 1) % partitions
        values = [
            _overload_payload(100_000 + i, seq, j, size) for j in range(k)
        ]
        seq += 1
        try:
            await client.produce(TOPIC, part, values, acks=-1)
            counter["records"] += k
        except Exception:
            counter["errors"] += 1


async def _open_loop_producer(
    i, client, partitions, op_rate, k, size, stop, ostats, lats,
    acked_keys, shed_keys, max_outstanding=256,
):
    """Open-loop overload: arrivals fire on a fixed schedule and NEVER
    wait for completions — each send runs as its own task, and an acked
    op's latency is measured from its SCHEDULED arrival time, so slow
    responses cannot suppress the arrivals that would have observed them
    (coordinated-omission-safe). A full outstanding window drops the
    arrival AT THE CLIENT and counts it (bounded client memory, no silent
    deferral of the schedule)."""
    from redpanda_tpu.kafka.protocol.errors import ErrorCode, KafkaError

    loop = asyncio.get_event_loop()
    interval = 1.0 / max(op_rate, 0.001)
    next_t = loop.time() + (i % 64) / 64.0 * interval
    outstanding: set = set()
    seq = 0

    async def one(part, values, keys, sched_t):
        try:
            await client.produce(TOPIC, part, values, acks=-1)
        except KafkaError as e:
            if e.code == ErrorCode.throttling_quota_exceeded:
                ostats["shed_ops"] += 1
                shed_keys.update(keys)
            else:
                ostats["error_ops"] += 1
            return
        except Exception:
            ostats["error_ops"] += 1
            return
        lats.append(loop.time() - sched_t)
        ostats["acked_ops"] += 1
        ostats["acked_records"] += len(values)
        acked_keys.update(keys)

    while not stop.is_set():
        now = loop.time()
        if next_t > now:
            if await _sleep_or_stop(stop, next_t - now):
                break
        sched_t = next_t
        next_t += interval
        if next_t < loop.time() - 2.0:
            # the event loop itself fell behind the schedule (client-side
            # saturation): re-anchor rather than emitting a burst that
            # would measure the CLIENT, not the broker
            skipped = int((loop.time() - next_t) / interval) + 1
            ostats["client_dropped"] += skipped
            next_t += skipped * interval
        if len(outstanding) >= max_outstanding:
            ostats["client_dropped"] += 1
            continue
        part = (i + seq) % partitions
        keys = [f"{i}-{seq}-{j}" for j in range(k)]
        values = [_overload_payload(i, seq, j, size) for j in range(k)]
        seq += 1
        t = asyncio.create_task(one(part, values, keys, sched_t))
        outstanding.add(t)
        t.add_done_callback(outstanding.discard)
    if outstanding:
        await asyncio.gather(*outstanding, return_exceptions=True)


def _quantile_ms(lats: list[float], q: float) -> float:
    if not lats:
        return 0.0
    xs = sorted(lats)
    idx = min(len(xs) - 1, int(q / 100.0 * len(xs)))
    return round(xs[idx] * 1e3, 3)


async def _overload_verify(client, partitions, acked_keys, shed_keys) -> dict:
    """End-of-run EXACT acked-write verification: every acked key appears
    exactly once (zero loss, zero duplicates), and no shed key is readable
    anywhere (shed-before-ack). Calibration/warmup records are ignored."""
    from collections import Counter as _Counter

    seen: _Counter = _Counter()
    for p in range(partitions):
        off = 0
        while True:
            batches, hwm = await client.fetch(
                TOPIC, p, off, max_wait_ms=10, max_bytes=1 << 20
            )
            if not batches:
                if off >= hwm:
                    break
                off = hwm
                continue
            for b in batches:
                for r in b.records():
                    key = _overload_keys(r.value or b"")
                    if key is not None:
                        seen[key] += 1
            off = batches[-1].last_offset + 1
    missing = sum(1 for k in acked_keys if seen[k] == 0)
    duplicated = sum(1 for k in acked_keys if seen[k] > 1)
    shed_visible = sum(1 for k in shed_keys if seen[k] > 0)
    return {
        "acked_keys": len(acked_keys),
        "missing": missing,
        "duplicated": duplicated,
        "shed_keys": len(shed_keys),
        "shed_visible": shed_visible,
        "exact": missing == 0 and duplicated == 0 and shed_visible == 0,
    }


async def _scrape_resources(stack) -> list[dict]:
    import aiohttp

    out = []
    async with aiohttp.ClientSession() as sess:
        for port in stack.admin_ports:
            try:
                async with sess.get(
                    f"http://127.0.0.1:{port}/v1/resources",
                    timeout=aiohttp.ClientTimeout(total=10),
                ) as r:
                    out.append(await r.json())
            except Exception as e:  # noqa: BLE001 — reported, judged below
                out.append({"error": repr(e)})
    return out


async def _scrape_admission_journal(stack) -> list[dict]:
    import aiohttp

    url = (
        f"http://127.0.0.1:{stack.admin_ports[0]}"
        f"/v1/governor?domain=admission&limit=256"
    )
    try:
        async with aiohttp.ClientSession() as sess:
            async with sess.get(
                url, timeout=aiohttp.ClientTimeout(total=10)
            ) as r:
                doc = await r.json()
        return doc.get("journal") or []
    except Exception:
        return []


async def run_overload_async(
    name: str,
    *,
    backend: str = "inproc",
    duration_s: float | None = None,
    base_dir: str | None = None,
    overrides: dict | None = None,
) -> dict:
    """The open-loop overload gate (ROADMAP item 4): calibrate the
    closed-loop knee, then schedule arrivals at overload_factor x that
    rate and judge survival — plateau (no collapse), governed admitted
    p99, counted sheds, EXACT acked-write verification, per-account peaks
    within budget, and an admission journal that reconstructs the run."""
    from redpanda_tpu.kafka.client import KafkaClient

    s = copy.deepcopy(OVERLOAD_SCENARIOS[name])
    s.update(overrides or {})
    if duration_s is not None:
        s["duration_s"] = float(duration_s)
    # the stack plumbing (configs, slow-ring threshold) reads these
    s.setdefault("objectives", _objectives(
        s["admitted_p99_ms"], 30_000, 8_000, 15_000, 8_000, 8_000, 20
    ))
    for key in ("group_members", "eos_pairs", "transform_readers",
                "tiered_readers"):
        s.setdefault(key, 0)

    tmp = None
    if base_dir is None:
        tmp = tempfile.TemporaryDirectory(prefix="loadgen-overload-")
        base_dir = tmp.name
    stack_cls = ProcStack if backend == "proc" else Stack
    stack = stack_cls(s, base_dir)
    clients: list = []
    k = s["records_per_op"]
    try:
        await stack.start()
        n_clients = max(2, min(8, s["producers"]))
        clients = await asyncio.gather(*(
            KafkaClient(stack.bootstrap()).connect() for _ in range(n_clients)
        ))
        admin = clients[0]
        await admin.create_topic(
            TOPIC, partitions=s["partitions"], replication=s["replication"]
        )
        for p in range(s["partitions"]):  # warmup: no first-op costs inside
            await admin.produce(
                TOPIC, p,
                [_overload_payload(200_000, 0, j, 64) for j in range(2)],
                acks=-1,
            )

        # ---- phase 1: the closed-loop knee
        counter = {"records": 0, "errors": 0}
        stop1 = asyncio.Event()
        tasks = [
            asyncio.create_task(_closed_loop_producer(
                i, clients[i % n_clients], s["partitions"], k,
                s["record_bytes"], stop1, counter,
            ))
            for i in range(s["producers"])
        ]
        t0 = time.monotonic()
        await asyncio.sleep(s["calibrate_s"])
        stop1.set()
        await asyncio.gather(*tasks, return_exceptions=True)
        calib_elapsed = time.monotonic() - t0
        capacity_rps = counter["records"] / calib_elapsed

        # ---- phase 2: open loop past the knee
        target_rps = capacity_rps * s["overload_factor"]
        op_rate = target_rps / k / s["producers"]
        ostats: dict[str, int] = {
            key: 0 for key in (
                "acked_ops", "acked_records", "shed_ops", "error_ops",
                "client_dropped",
            )
        }
        lats: list[float] = []
        acked_keys: set[str] = set()
        shed_keys: set[str] = set()
        stop2 = asyncio.Event()
        tasks = [
            asyncio.create_task(_open_loop_producer(
                i, clients[i % n_clients], s["partitions"], op_rate, k,
                s["record_bytes"], stop2, ostats, lats, acked_keys,
                shed_keys,
            ))
            for i in range(s["producers"])
        ]
        t0 = time.monotonic()
        await asyncio.sleep(s["duration_s"])
        stop2.set()
        await asyncio.gather(*tasks, return_exceptions=True)
        elapsed = time.monotonic() - t0
        admitted_rps = ostats["acked_records"] / elapsed

        # ---- verification + control-plane sweeps
        verify = await _overload_verify(
            admin, s["partitions"], acked_keys, shed_keys
        )
        resources = await _scrape_resources(stack)
        budgets_ok = True
        for node in resources:
            accounts = node.get("accounts")
            if not accounts:
                # an unreachable admin API or a plane-less broker is NOT
                # evidence the peaks stayed within budget — fail the gate
                # rather than pass it on missing data
                budgets_ok = False
                continue
            for acct in accounts.values():
                if acct["peak_bytes"] > acct["limit_bytes"]:
                    budgets_ok = False
        journal = await _scrape_admission_journal(stack)
        shed_total = await _scrape_counter_total(
            stack, "kafka_produce_admission_shed_total"
        )
        p99_ms = _quantile_ms(lats, 99.0)
        gates = {
            # the knee held: admitted throughput plateaus, never collapses
            "throughput_plateau": admitted_rps
            >= s["plateau_floor"] * capacity_rps,
            # ADMITTED requests stay governed (CO-safe client clock)
            "admitted_p99": p99_ms <= s["admitted_p99_ms"],
            # every client-observed shed is a counted server-side shed,
            # and the journal carries the episode(s)
            "shed_counted": ostats["shed_ops"] == 0 or (
                shed_total >= ostats["shed_ops"]
                and any(e["verdict"] == "shed" for e in journal)
            ),
            "verification_exact": verify["exact"],
            "budgets_respected": budgets_ok,
        }
        return {
            "scenario": name,
            "kind": "overload",
            "backend": stack.backend,
            "nodes": s["nodes"],
            "partitions": s["partitions"],
            "overload_factor": s["overload_factor"],
            "calibration": {
                "duration_s": round(calib_elapsed, 3),
                "capacity_records_per_s": round(capacity_rps, 1),
                "errors": counter["errors"],
            },
            "open_loop": {
                "duration_s": round(elapsed, 3),
                "offered_records_per_s": round(target_rps, 1),
                "admitted_records_per_s": round(admitted_rps, 1),
                "admitted_p50_ms": _quantile_ms(lats, 50.0),
                "admitted_p99_ms": p99_ms,
                "admitted_max_ms": _quantile_ms(lats, 100.0),
                **ostats,
            },
            "shed_total_server": shed_total,
            "verification": verify,
            "resources": resources,
            "admission_journal": journal,
            "gates": gates,
            "pass": all(gates.values()),
        }
    finally:
        for c in clients:
            try:
                await c.close()
            except Exception:
                pass
        await stack.stop()
        if tmp is not None:
            tmp.cleanup()


def run_overload(name: str, **kw) -> dict:
    return asyncio.run(run_overload_async(name, **kw))


# ================================================================ cli
def _diff_block(against_path: str, report: dict, band_pct) -> dict:
    """The release-flow judgment (ROADMAP item 6): this run's report
    diffed against a prior SLO artifact, objective-by-objective, with
    noise-band verdicts. Embedded in the written artifact so the verdict
    travels WITH the evidence; a broken baseline degrades to an error
    block, never a sunk run. Routed through tools/pulsediff.py (which
    delegates SLO/BENCH shapes to slodiff) so a timeline baseline judges
    too — one judge entry point for whatever the release flow hands it."""
    from tools import pulsediff

    try:
        baseline = pulsediff._load(against_path)
        d = pulsediff.diff_artifacts(baseline, report, band_pct)
        d["against"] = against_path
        return d
    except Exception as exc:  # noqa: BLE001 - the run itself succeeded
        return {"against": against_path, "error": repr(exc),
                "verdict": "NO_BASELINE"}


# latency objectives are noisier than the throughput skew the A/A bracket
# measures; a same-box band below this floor would misfire REGRESS on
# ordinary jitter, so the embedded judgments never judge tighter than this
AA_BAND_FLOOR_PCT = 5.0


def _aa_bracket(scenario: str, rounds: int, **run_kw) -> dict:
    """ROADMAP 7d same-session A/A bracket: run the scenario ``rounds``
    times back-to-back on the same code BEFORE the measured run, so the
    artifact carries its OWN noise band (max pairwise throughput skew,
    ``aa_band_pct``) instead of borrowing one measured on a different box
    on a different day — the exact aa_skew discipline BENCH artifacts
    already follow. The bracket also judges ITSELF (first vs last round
    through slodiff at the measured band): a bracket that cannot read
    PASS/WEATHER on its own same-code rounds has no business judging a
    release, and the embedded judgment says so on the artifact's face."""
    from tools import slodiff

    reports = [run_scenario(scenario, **run_kw) for _ in range(rounds)]
    rates = [
        r["throughput"]["produced_records_per_s"] for r in reports
    ]
    lo = min(rates)
    thr_skew = (max(rates) - lo) / lo * 100.0 if lo > 0 else 0.0
    # latency skew measured the same way, per objective across rounds:
    # same-code p99s on short windows jitter far more than throughput, and
    # a band that only priced throughput would misfire REGRESS on every
    # latency objective (observed live: 0.55% rate skew vs >5% p99 moves)
    by_name: dict[str, list[float]] = {}
    for r in reports:
        for o in r.get("objectives", []):
            v = o.get("observed_ms")
            if isinstance(v, (int, float)):
                by_name.setdefault(o["name"], []).append(float(v))
    lat_skews = [
        (max(vals) - min(vals)) / min(vals) * 100.0
        for vals in by_name.values()
        if len(vals) >= 2 and min(vals) > 0
    ]
    lat_skew = max(lat_skews) if lat_skews else 0.0
    band = max(thr_skew, lat_skew)
    block = {
        "rounds": rounds,
        "round_rates": [round(r, 1) for r in rates],
        "throughput_skew_pct": round(thr_skew, 2),
        "latency_skew_pct": round(lat_skew, 2),
        "aa_band_pct": round(band, 2),
        "band_floor_pct": AA_BAND_FLOOR_PCT,
    }
    if rounds >= 2:
        try:
            block["judgment"] = slodiff.diff_artifacts(
                reports[0], reports[-1], max(band, AA_BAND_FLOOR_PCT)
            )
        except Exception as exc:  # noqa: BLE001 - bracket stays advisory
            block["judgment"] = {"error": repr(exc), "verdict": "NO_DATA"}
    return block


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--scenario", default="smoke", help="see --list")
    p.add_argument("--report", default=None, metavar="SLO_r0N.json",
                   help="report path (default SLO_<scenario>.json)")
    p.add_argument("--chaos", action="store_true",
                   help="arm the scenario's honey-badger probe for the "
                        "measured window")
    p.add_argument("--backend", choices=("inproc", "proc"),
                   default="inproc",
                   help="inproc = 1..N Applications in this process "
                        "(judged off the shared registry); proc = REAL "
                        "broker processes judged from the federated "
                        "/metrics scrape — no one-loop ceiling on offered "
                        "load (tiered readers are inproc-only)")
    p.add_argument("--duration", type=float, default=None,
                   help="override the scenario's measured window (s)")
    p.add_argument("--clients-scale", type=float, default=1.0,
                   help="multiply every client count (8 ≈ thousands of "
                        "clients on real hardware)")
    p.add_argument("--list", action="store_true", help="list scenarios")
    p.add_argument(
        "--diff-against", default=None, metavar="SLO_r0N.json",
        help="ROADMAP item 6 release flow: after the run, judge this "
             "report against a prior artifact with tools/slodiff.py "
             "noise-band verdicts (PASS/WEATHER/REGRESS); the diff is "
             "embedded in the written report under 'slodiff'",
    )
    p.add_argument(
        "--diff-band-pct", type=float, default=None, metavar="PCT",
        help="noise band for --diff-against (default: the --ab-rounds "
             "measured band when bracketed, else slodiff's)",
    )
    p.add_argument(
        "--ab-rounds", type=int, default=0, metavar="K",
        help="same-session A/A bracket (ROADMAP 7d): run the scenario K "
             "extra times back-to-back BEFORE the measured run; the "
             "artifact then carries its OWN noise band (max pairwise "
             "throughput skew, 'aa_band_pct') plus the bracket's slodiff "
             "self-judgment, and --diff-against judges at that measured "
             "band instead of a borrowed default",
    )
    args = p.parse_args(argv)
    if args.list:
        for name, s in SCENARIOS.items():
            print(f"{name:<16} nodes={s['nodes']} partitions={s['partitions']} "
                  f"duration={s['duration_s']}s producers={s['producers']} "
                  f"chaos={s['chaos']['module']}.{s['chaos']['probe']}")
        for name, s in OVERLOAD_SCENARIOS.items():
            print(f"{name:<16} nodes={s['nodes']} partitions={s['partitions']} "
                  f"duration={s['duration_s']}s producers={s['producers']} "
                  f"open-loop x{s['overload_factor']} (overload gate)")
        return 0
    if args.ab_rounds and args.scenario in OVERLOAD_SCENARIOS:
        p.error("--ab-rounds brackets closed-loop scenarios only (the "
                "overload gate is judged against its own calibration run)")
    if args.scenario in OVERLOAD_SCENARIOS:
        report = run_overload(
            args.scenario, backend=args.backend, duration_s=args.duration,
        )
        out = args.report or f"SLO_{args.scenario}.json"
        if args.diff_against:
            report["slodiff"] = _diff_block(
                args.diff_against, report, args.diff_band_pct
            )
        with open(out, "w") as f:
            json.dump(report, f, indent=2, sort_keys=True)
        print(json.dumps({
            "scenario": report["scenario"],
            "verdict": "PASS" if report["pass"] else "FAIL",
            "gates": report["gates"],
            "capacity_records_per_s":
                report["calibration"]["capacity_records_per_s"],
            "admitted_records_per_s":
                report["open_loop"]["admitted_records_per_s"],
            "admitted_p99_ms": report["open_loop"]["admitted_p99_ms"],
            "shed_ops": report["open_loop"]["shed_ops"],
            "report": out,
        }))
        return 0 if report["pass"] else 1
    if args.scenario not in SCENARIOS:
        p.error(f"unknown scenario {args.scenario!r}; --list shows them")
    aa_block = None
    if args.ab_rounds:
        # A/A rounds run WITHOUT chaos even when the measured run arms it:
        # the band prices same-code weather, not the probe's damage
        aa_block = _aa_bracket(
            args.scenario, args.ab_rounds, chaos=False,
            duration_s=args.duration, clients_scale=args.clients_scale,
            backend=args.backend,
        )
    report = run_scenario(
        args.scenario, chaos=args.chaos, duration_s=args.duration,
        clients_scale=args.clients_scale, backend=args.backend,
    )
    out = args.report or f"SLO_{args.scenario}.json"
    if aa_block is not None:
        report["aa"] = aa_block
        # top-level so pulsediff/slodiff sniff it as the artifact's band
        report["aa_band_pct"] = aa_block["aa_band_pct"]
    if args.diff_against:
        band = args.diff_band_pct
        if band is None and aa_block is not None:
            band = max(aa_block["aa_band_pct"], AA_BAND_FLOOR_PCT)
        report["slodiff"] = _diff_block(args.diff_against, report, band)
    with open(out, "w") as f:
        json.dump(report, f, indent=2, sort_keys=True)
    verdict = "PASS" if report["pass"] else "FAIL"
    print(json.dumps({
        "scenario": report["scenario"],
        "verdict": verdict,
        **(
            {"slodiff": report["slodiff"]["verdict"],
             "slodiff_against": args.diff_against}
            if args.diff_against else {}
        ),
        **(
            {"aa_band_pct": aa_block["aa_band_pct"],
             "aa_judgment": (aa_block.get("judgment") or {}).get("verdict")}
            if aa_block is not None else {}
        ),
        "failed_objectives": report["failed"],
        "chaos": bool(report.get("chaos")),
        "exemplars": f"{report.get('exemplars_resolved', 0)}"
                     f"/{report.get('exemplars_total', 0)} resolved",
        "produced_records_per_s":
            report["throughput"]["produced_records_per_s"],
        "workloads_ok": report["workloads_ok"],
        "report": out,
    }))
    # a chaos run is EXPECTED to breach; its exit code reflects only that
    # the harness itself worked and the workloads stayed lossless
    if args.chaos:
        return 0 if report["workloads_ok"] else 1
    return 0 if (report["pass"] and report["workloads_ok"]) else 1


if __name__ == "__main__":
    sys.exit(main())
