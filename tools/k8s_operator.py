#!/usr/bin/env python3
"""Scale reconciler for k8s deployments (`rpk generate k8s-manifests`).

The one operator behavior a StatefulSet controller cannot provide: scale-in
must DRAIN the doomed ordinals through the cluster controller before their
pods (and PVCs) disappear. Point this at the admin API and the desired
replica count; it decommissions ordinals >= desired, waits for their
partitions to drain off, then you `kubectl scale`. Scale-out needs no
operator (new ordinals join via the seed list).

    python tools/k8s_operator.py --admin http://rp-0.rp:9644 --replicas 3

Logic lives in redpanda_tpu/cli/k8s.py reconcile_scale (transport-
parameterized; tested without k8s in tests/test_k8s.py).
"""

from __future__ import annotations

import argparse
import asyncio
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from redpanda_tpu.cli.k8s import reconcile_scale  # noqa: E402


class AdminHttp:
    def __init__(self, base: str):
        self.base = base.rstrip("/")

    async def _req(self, method: str, path: str):
        import json

        from redpanda_tpu.http import HttpClient

        async with HttpClient(self.base, request_timeout=10.0) as c:
            r = await c.request(method, path)
            if r.status >= 400:
                raise RuntimeError(f"{method} {path} -> {r.status}")
            return json.loads(r.body)

    async def brokers(self):
        return await self._req("GET", "/v1/brokers")

    async def decommission(self, node_id: int):
        return await self._req("PUT", f"/v1/brokers/{node_id}/decommission")


async def _wait_drained(template: str, node_ids: list[int], timeout_s: float) -> bool:
    """Poll each drained node's OWN admin (`template.format(n=id)`) until it
    hosts zero partition replicas. Returns True when all drained."""
    import time

    deadline = time.monotonic() + timeout_s
    pending = set(node_ids)
    while pending and time.monotonic() < deadline:
        for n in sorted(pending):
            try:
                node_admin = AdminHttp(template.format(n=n))
                parts = await node_admin._req("GET", "/v1/partitions")
                if not parts:
                    pending.discard(n)
                    print(f"node {n} drained")
            except Exception:
                pass  # node busy moving replicas; keep polling
        if pending:
            await asyncio.sleep(2.0)
    return not pending


async def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--admin", required=True, help="admin API base URL")
    ap.add_argument("--replicas", type=int, required=True)
    ap.add_argument(
        "--admin-template",
        help="per-node admin URL template, e.g. "
        "'http://rp-{n}.rp.default.svc.cluster.local:9644' — when given, "
        "block until the drained nodes host zero partitions",
    )
    ap.add_argument("--wait-timeout", type=float, default=600.0)
    args = ap.parse_args()
    admin = AdminHttp(args.admin)
    drained = await reconcile_scale(args.replicas, admin)
    if not drained:
        print("nothing to drain")
        return 0
    print(f"decommissioned node(s) {drained}")
    if args.admin_template:
        ok = await _wait_drained(args.admin_template, drained, args.wait_timeout)
        if not ok:
            print("ERROR: drain did not complete; do NOT scale down yet",
                  file=sys.stderr)
            return 1
        print(f"drain complete: kubectl scale statefulset --replicas={args.replicas}")
    else:
        print("wait until each drained node's /v1/partitions is empty, then "
              f"kubectl scale statefulset --replicas={args.replicas}")
    return 0


if __name__ == "__main__":
    sys.exit(asyncio.run(main()))
