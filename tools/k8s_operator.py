#!/usr/bin/env python3
"""Reconciling operator for k8s deployments (`rpk generate k8s-manifests`).

Runs the watch/reconcile controller (redpanda_tpu/cli/k8s.py Operator —
the reconciling twin of the reference's CRD controller,
src/go/k8s/controllers/redpanda/cluster_controller.go) against a real
cluster: kubectl for the StatefulSet/pods side, the admin API for the
broker side. Every pass converges one step of scale-up, drain-then-shrink
scale-down, or dead-pod replacement; `--once` runs a single pass (CI /
cron), the default loops forever.

    python tools/k8s_operator.py \
        --admin http://rp-0.rp:9644 \
        --admin-template http://rp-{n}.rp:9644 \
        --namespace default --statefulset rp --replicas 3

`--replicas` is the DESIRED size (the "cluster spec"); omit it to read
the desired size from the StatefulSet's `rptpu.dev/desired-replicas`
annotation so `kubectl annotate` is the scale control plane.
"""

from __future__ import annotations

import argparse
import asyncio
import json
import os
import subprocess
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from redpanda_tpu.cli.k8s import Operator  # noqa: E402

ANNOTATION = "rptpu.dev/desired-replicas"


class KubectlKube:
    """Operator kube transport over kubectl (no client library needed)."""

    def __init__(self, namespace: str, statefulset: str, desired: int | None):
        self.ns = namespace
        self.sts = statefulset
        self._desired = desired

    def _kubectl(self, *args: str) -> str:
        out = subprocess.run(
            ["kubectl", "-n", self.ns, *args],
            capture_output=True, text=True, check=True,
        )
        return out.stdout

    async def _json(self, *args: str):
        raw = await asyncio.to_thread(self._kubectl, *args, "-o", "json")
        return json.loads(raw)

    async def _get_sts(self):
        return await self._json("get", "statefulset", self.sts)

    async def get_desired_replicas(self) -> int:
        # one fetch serves this AND the get_sts_replicas call that the
        # operator makes immediately after (same object, same pass)
        self._sts_obj = await self._get_sts()
        if self._desired is not None:
            return self._desired
        ann = self._sts_obj["metadata"].get("annotations", {})
        return int(ann.get(ANNOTATION, self._sts_obj["spec"]["replicas"]))

    async def get_sts_replicas(self) -> int:
        sts = getattr(self, "_sts_obj", None) or await self._get_sts()
        self._sts_obj = None
        return int(sts["spec"]["replicas"])

    async def set_sts_replicas(self, n: int) -> None:
        await asyncio.to_thread(
            self._kubectl, "scale", "statefulset", self.sts, f"--replicas={n}"
        )

    async def list_pods(self):
        pods = await self._json("get", "pods", "-l", f"app={self.sts}")
        out = []
        for p in pods.get("items", []):
            name = p["metadata"]["name"]
            try:
                ordinal = int(name.rsplit("-", 1)[1])
            except ValueError:
                continue
            ready = any(
                c["type"] == "Ready" and c["status"] == "True"
                for c in p.get("status", {}).get("conditions", [])
            )
            out.append({"name": name, "ordinal": ordinal, "ready": ready})
        return out

    async def delete_pod(self, name: str) -> None:
        await asyncio.to_thread(self._kubectl, "delete", "pod", name, "--wait=false")


class AdminHttp:
    """Operator admin transport over the owned HTTP client."""

    def __init__(self, base: str, template: str | None):
        self.base = base.rstrip("/")
        self.template = template

    async def _req(self, base: str, method: str, path: str):
        from redpanda_tpu.http import HttpClient

        async with HttpClient(base, request_timeout=10.0) as c:
            r = await c.request(method, path)
            if r.status >= 400:
                raise RuntimeError(f"{method} {path} -> {r.status}")
            return json.loads(r.body)

    async def brokers(self):
        return await self._req(self.base, "GET", "/v1/brokers")

    async def decommission(self, node_id: int):
        return await self._req(
            self.base, "PUT", f"/v1/brokers/{node_id}/decommission"
        )

    async def partitions(self, node_id: int):
        """The doomed node's OWN admin reports what it still hosts —
        asking any other node would read the WRONG node's drain state."""
        if not self.template:
            raise RuntimeError("--admin-template required for drain checks")
        return await self._req(
            self.template.format(n=node_id), "GET", "/v1/partitions"
        )


async def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--admin", required=True, help="cluster admin API base URL")
    ap.add_argument(
        "--admin-template", required=True,
        help="per-node admin URL template, e.g. 'http://rp-{n}.rp:9644' "
        "(drain checks poll each doomed node's own admin)",
    )
    ap.add_argument("--namespace", default="default")
    ap.add_argument("--statefulset", default="rp")
    ap.add_argument(
        "--replicas", type=int, default=None,
        help=f"desired size; omitted -> read the {ANNOTATION} annotation",
    )
    ap.add_argument("--interval", type=float, default=10.0)
    ap.add_argument("--once", action="store_true", help="single reconcile pass")
    args = ap.parse_args()

    op = Operator(
        KubectlKube(args.namespace, args.statefulset, args.replicas),
        AdminHttp(args.admin, args.admin_template),
        poll_interval_s=args.interval,
    )
    if args.once:
        rep = await op.reconcile_once()
        print(
            f"desired={rep.desired} sts={rep.sts_replicas} "
            f"settled={rep.settled} actions={rep.actions}"
        )
        return 0 if rep.settled else 2  # 2 = converging, run me again
    print(f"operator loop: statefulset {args.statefulset} every {args.interval}s")
    await op.run()
    return 0


if __name__ == "__main__":
    sys.exit(asyncio.run(main()))
