"""Measure the host<->device link characteristics (RTT, bandwidth, overlap).

The coproc engine's performance ceiling is set by how the device link
charges for work: per round trip, per byte, or both — and whether JAX's
async dispatch actually overlaps transfers with compute on this backend.
This probe measures each axis directly and prints one JSON document. The
measurements drove the engine's execution-mode design
(redpanda_tpu/coproc/column_plan.py module docs) and are re-recorded in
every BENCH artifact (bench.run_link_profile); the produce-path CRC
backend makes its own runtime timing probe (redpanda_tpu/ops/crc_backend.py).

Run: python tools/link_probe.py            (whatever jax.devices() gives)
     JAX_PLATFORMS=cpu python tools/link_probe.py
"""

from __future__ import annotations

import json
import time

import numpy as np


def main():
    import jax
    import jax.numpy as jnp

    dev = jax.devices()[0]
    out = {"device": str(dev)}

    # --- RTT: tiny array round trip, H2D then D2H, fully synchronous.
    tiny = np.zeros(8, np.uint8)
    for _ in range(3):
        np.asarray(jax.device_put(tiny))  # warm
    t0 = time.perf_counter()
    reps = 10
    for _ in range(reps):
        np.asarray(jax.device_put(tiny))
    out["rtt_ms_put_get"] = round((time.perf_counter() - t0) / reps * 1e3, 2)

    # --- H2D bandwidth: device_put of increasing sizes (sync via block).
    h2d = {}
    for mb in (1, 4, 16, 64):
        arr = np.random.default_rng(0).integers(0, 255, mb << 20, np.uint8)
        jax.block_until_ready(jax.device_put(arr))  # warm path
        t0 = time.perf_counter()
        jax.block_until_ready(jax.device_put(arr))
        h2d[mb] = round(mb / (time.perf_counter() - t0), 1)
    out["h2d_mb_s"] = h2d

    # --- D2H bandwidth.
    d2h = {}
    for mb in (1, 4, 16, 64):
        darr = jax.block_until_ready(
            jax.device_put(np.zeros(mb << 20, np.uint8))
        )
        np.asarray(darr)  # warm
        t0 = time.perf_counter()
        np.asarray(darr)
        d2h[mb] = round(mb / (time.perf_counter() - t0), 1)
    out["d2h_mb_s"] = d2h

    # --- dispatch cost: jitted no-op-ish program on resident data.
    f = jax.jit(lambda x: x * 2 + 1)
    darr = jax.block_until_ready(jax.device_put(np.zeros(1 << 20, np.uint8)))
    jax.block_until_ready(f(darr))
    t0 = time.perf_counter()
    reps = 20
    r = darr
    for _ in range(reps):
        r = f(r)
    jax.block_until_ready(r)
    out["dispatch_chain_ms"] = round((time.perf_counter() - t0) / reps * 1e3, 2)

    # one dispatch with sync each time (cost of an isolated launch)
    t0 = time.perf_counter()
    for _ in range(reps):
        jax.block_until_ready(f(darr))
    out["dispatch_sync_ms"] = round((time.perf_counter() - t0) / reps * 1e3, 2)

    # --- end-to-end single launch: numpy arg -> jit -> fetch, 16MB.
    arr = np.random.default_rng(1).integers(0, 255, 16 << 20, np.uint8)
    g = jax.jit(lambda x: (x.astype(jnp.int32).sum(), x[:1024]))
    jax.block_until_ready(g(arr))
    t0 = time.perf_counter()
    reps = 4
    for _ in range(reps):
        s, head = g(arr)
        np.asarray(head)
    out["e2e_16mb_ms"] = round((time.perf_counter() - t0) / reps * 1e3, 1)

    # --- overlap: do N independent launches pipeline? compare serial sync
    # vs issue-all-then-drain for 8 x 8MB jobs.
    arrs = [
        np.random.default_rng(i).integers(0, 255, 8 << 20, np.uint8)
        for i in range(8)
    ]
    h = jax.jit(lambda x: x.astype(jnp.int32).sum())
    jax.block_until_ready(h(arrs[0]))
    t0 = time.perf_counter()
    for a in arrs:
        jax.block_until_ready(h(a))
    serial = time.perf_counter() - t0
    t0 = time.perf_counter()
    outs = [h(a) for a in arrs]
    jax.block_until_ready(outs)
    piped = time.perf_counter() - t0
    out["overlap_serial_ms"] = round(serial * 1e3, 1)
    out["overlap_piped_ms"] = round(piped * 1e3, 1)
    out["overlap_speedup"] = round(serial / piped, 2)

    # --- donation: update a device-resident buffer in place (scatter rows).
    big = jax.block_until_ready(
        jax.device_put(np.zeros((16384, 1160), np.uint8))
    )

    @jax.jit
    def scatter(buf, rows, idx):
        return buf.at[idx].set(rows)

    rows = np.ones((512, 1160), np.uint8)
    idx = np.arange(512, dtype=np.int32)
    big = jax.block_until_ready(scatter(big, rows, idx))
    t0 = time.perf_counter()
    for _ in range(10):
        big = scatter(big, rows, idx)
    jax.block_until_ready(big)
    out["scatter_512rows_ms"] = round((time.perf_counter() - t0) / 10 * 1e3, 2)

    print(json.dumps(out))


if __name__ == "__main__":
    main()
