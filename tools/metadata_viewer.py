#!/usr/bin/env python3
"""Offline decoder for on-disk state (tools/metadata_viewer parity).

Decodes, without a running broker:
- segment files: batch headers + records (viewer.py/storage.py analogue)
- kvstore snapshot + WAL (kvstore.py analogue)
- the controller log (controller commands decoded by type)

Usage:
  python tools/metadata_viewer.py segment <path/to/0-1-v1.log> [--records]
  python tools/metadata_viewer.py log <data_dir> <ns/topic/partition> [--records]
  python tools/metadata_viewer.py kvstore <base_dir>
  python tools/metadata_viewer.py controller <data_dir>
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

from redpanda_tpu.models.record import RecordBatch, RecordBatchType  # noqa: E402


def iter_batches(path: str):
    with open(path, "rb") as f:
        data = f.read()
    pos = 0
    while pos < len(data):
        try:
            batch, consumed = RecordBatch.decode_internal(data[pos:])
        except Exception as e:
            print(f"  !! decode stopped at byte {pos}: {e}", file=sys.stderr)
            return
        yield batch
        pos += consumed


def show_segment(path: str, show_records: bool) -> None:
    print(f"segment {path}")
    for batch in iter_batches(path):
        h = batch.header
        ok = "ok" if batch.verify_header_crc() and batch.verify_kafka_crc() else "CRC-MISMATCH"
        print(
            f"  batch base={h.base_offset} last={batch.last_offset} "
            f"type={RecordBatchType(h.type).name} records={h.record_count} "
            f"bytes={h.size_bytes} term={h.term if hasattr(h, 'term') else '-'} crc={ok}"
        )
        if show_records:
            for r in batch.records():
                print(
                    f"    off={h.base_offset + r.offset_delta} "
                    f"key={r.key!r} value={(r.value or b'')[:80]!r}"
                )


def show_log(data_dir: str, ntp_path: str, show_records: bool) -> None:
    d = os.path.join(data_dir, "data", ntp_path)
    if not os.path.isdir(d):
        d = os.path.join(data_dir, ntp_path)
    for name in sorted(os.listdir(d)):
        if name.endswith(".log"):
            show_segment(os.path.join(d, name), show_records)


def show_kvstore(base_dir: str) -> None:
    from redpanda_tpu.storage.kvstore import KeySpace, KvStore

    for entry in sorted(os.listdir(base_dir)):
        if not entry.startswith("kvstore"):
            continue
        kvs = KvStore(os.path.join(base_dir, entry))
        kvs.start()
        print(f"kvstore {entry}:")
        for space in KeySpace:
            for key in kvs.keys(space):
                value = kvs.get(space, key)
                shown = value[:60] if value else b""
                print(f"  [{space.name}] {key.decode('utf-8', 'replace')} = {shown!r}")
        kvs.stop()


def show_controller(data_dir: str) -> None:
    from redpanda_tpu.cluster.commands import Command

    show = os.path.join(data_dir, "data", "redpanda", "controller", "0")
    if not os.path.isdir(show):
        print(f"no controller log under {data_dir}", file=sys.stderr)
        return
    for name in sorted(os.listdir(show)):
        if not name.endswith(".log"):
            continue
        for batch in iter_batches(os.path.join(show, name)):
            t = RecordBatchType(batch.header.type)
            if t == RecordBatchType.raft_configuration:
                print(f"  @{batch.header.base_offset} raft_configuration")
                continue
            for rec in batch.records():
                try:
                    cmd = Command.from_record(rec)
                    print(
                        f"  @{batch.header.base_offset} {cmd.type.name} "
                        f"{json.dumps(cmd.data)[:120]}"
                    )
                except Exception:
                    print(f"  @{batch.header.base_offset} <{t.name}>")


def main() -> int:
    p = argparse.ArgumentParser(description=__doc__)
    sub = p.add_subparsers(dest="cmd", required=True)
    sp = sub.add_parser("segment")
    sp.add_argument("path")
    sp.add_argument("--records", action="store_true")
    lp = sub.add_parser("log")
    lp.add_argument("data_dir")
    lp.add_argument("ntp", help="ns/topic/partition")
    lp.add_argument("--records", action="store_true")
    kp = sub.add_parser("kvstore")
    kp.add_argument("base_dir")
    cp = sub.add_parser("controller")
    cp.add_argument("data_dir")
    args = p.parse_args()
    if args.cmd == "segment":
        show_segment(args.path, args.records)
    elif args.cmd == "log":
        show_log(args.data_dir, args.ntp, args.records)
    elif args.cmd == "kvstore":
        show_kvstore(args.base_dir)
    else:
        show_controller(args.data_dir)
    return 0


if __name__ == "__main__":
    sys.exit(main())
