"""Black-box verifiable producer/consumer.

Parity with the reference's tests/java/kafka-verifier (the ducktape
suites' verifiable_producer/verifiable_consumer pair): a standalone tool
that produces a self-describing sequenced workload over the Kafka API and
later verifies, purely from what a consumer reads back, that

1. every acked sequence number is present (no acked loss),
2. per partition, sequence numbers are strictly increasing in offset
   order (no reordering),
3. duplicates are reported (at-least-once retries are legal but counted).

Usage:
  python tools/kafka_verifier.py produce --brokers h:p --topic t \
      --partitions 4 --count 1000 --state /tmp/kv.json
  python tools/kafka_verifier.py verify --brokers h:p --topic t \
      --state /tmp/kv.json
Exit code 0 = invariants hold, 1 = violation (details on stderr).
"""

from __future__ import annotations

import argparse
import asyncio
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


from redpanda_tpu.cli.rpk import _parse_brokers as _parse


async def cmd_produce(args) -> int:
    from redpanda_tpu.kafka.client.client import KafkaClient

    c = await KafkaClient(_parse(args.brokers)).connect()
    acked: dict[str, list[int]] = {str(p): [] for p in range(args.partitions)}
    try:
        for seq in range(args.count):
            p = seq % args.partitions
            value = b"kv-%010d" % seq
            # acks=-1 with retry: an op only counts as acked when the
            # produce RETURNS (the verifier's loss invariant is about
            # acked writes, like the reference's verifiable producer).
            # The client caches leaders/connections, so a failed attempt
            # RECONNECTS before retrying — riding through failover is the
            # point of the tool.
            for attempt in range(8):
                try:
                    await c.produce(args.topic, p, [value], acks=-1)
                    acked[str(p)].append(seq)
                    break
                except Exception:
                    try:
                        await c.close()
                    except Exception:
                        pass
                    await asyncio.sleep(0.3 * (attempt + 1))
                    if attempt == 7:
                        raise
                    c = await KafkaClient(_parse(args.brokers)).connect()
    finally:
        try:
            await c.close()
        except Exception:
            pass
        # even on a fatal produce error, what WAS acked must be durable
        # state — otherwise the loss invariant can never be checked
        with open(args.state, "w") as f:
            json.dump({"topic": args.topic, "acked": acked}, f)
    n = sum(len(v) for v in acked.values())
    print(f"produced+acked {n}/{args.count} -> {args.state}")
    return 0


async def cmd_verify(args) -> int:
    from redpanda_tpu.kafka.client.client import KafkaClient

    with open(args.state) as f:
        state = json.load(f)
    if state["topic"] != args.topic:
        print(f"state is for topic {state['topic']!r}", file=sys.stderr)
        return 1
    c = await KafkaClient(_parse(args.brokers)).connect()
    errors: list[str] = []
    dupes = 0
    try:
        for p_str, acked in state["acked"].items():
            p = int(p_str)
            seen: list[int] = []
            offset = 0
            stalled = 0
            while True:
                batches, hwm = await c.fetch(args.topic, p, offset, max_wait_ms=50)
                for b in batches:
                    for r in b.records():
                        v = r.value or b""
                        if v.startswith(b"kv-"):
                            seen.append(int(v[3:]))
                if batches:
                    offset = batches[-1].last_offset + 1
                    stalled = 0
                else:
                    # a region of filtered control batches (or a transient
                    # empty response) must not spin forever
                    stalled += 1
                    if stalled > 40:
                        errors.append(
                            f"p{p}: fetch stalled at offset {offset} (hwm {hwm})"
                        )
                        break
                if offset >= hwm:
                    break
            seen_set = set(seen)
            missing = [s for s in acked if s not in seen_set]
            if missing:
                errors.append(
                    f"p{p}: {len(missing)} acked seq(s) lost, first {missing[:3]}"
                )
            # strictly increasing in offset order (dupes excepted, counted)
            last = -1
            for s in seen:
                if s < last:
                    errors.append(f"p{p}: reordering: {s} after {last}")
                    break
                last = s
            dupes += len(seen) - len(seen_set)
    finally:
        await c.close()
    if errors:
        for e in errors:
            print(f"VIOLATION: {e}", file=sys.stderr)
        return 1
    total = sum(len(v) for v in state["acked"].values())
    print(f"verified {total} acked seqs: OK ({dupes} duplicate deliveries)")
    return 0


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__)
    sub = p.add_subparsers(dest="cmd", required=True)
    for name in ("produce", "verify"):
        sp = sub.add_parser(name)
        sp.add_argument("--brokers", required=True)
        sp.add_argument("--topic", required=True)
        sp.add_argument("--state", required=True)
        if name == "produce":
            sp.add_argument("--partitions", type=int, default=1)
            sp.add_argument("--count", type=int, default=500)
    args = p.parse_args(argv)
    return asyncio.run({"produce": cmd_produce, "verify": cmd_verify}[args.cmd](args))


if __name__ == "__main__":
    sys.exit(main())
